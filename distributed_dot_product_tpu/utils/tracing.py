# -*- coding: utf-8 -*-
"""
Per-op tracing / profiling utilities.

TPU-native replacement for the reference ``measure`` decorator
(reference functions.py:24-41), which printed per-call wall time, operand
shapes and CUDA max-memory delta when the env var ``DISTRIBUTED_DOT_DEBUG``
was set (reference functions.py:21,30).

Differences, deliberate:

- **Honest timing.** The reference never called ``torch.cuda.synchronize()``
  before stopping the clock (noted in SURVEY §5 / BASELINE.md), so its GPU
  numbers are enqueue-biased. We fence with :func:`hard_sync` (a host
  readback — ``jax.block_until_ready`` alone is not a reliable fence on
  tunneled PJRT backends) before reading the clock.
- **Memory** comes from ``device.memory_stats()`` (TPU/GPU); on backends
  without stats (CPU) it is reported as ``None``.
- ``measure`` on a function *called inside jit/shard_map* times the trace,
  not the execution (the result is a tracer, which cannot be synced) — the
  printed line is tagged ``traced`` in that case. For execution numbers use
  :func:`time_fn` on the jitted callable, or ``jax.profiler.trace`` (see
  ``benchmark.py --profile-dir``).
"""

import bisect
import collections
import functools
import os
import threading
import time

import jax

# Same env-var name as the reference (functions.py:21) so users can flip the
# identical switch.
DEBUG_ENV_VAR = 'DISTRIBUTED_DOT_DEBUG'


def _debug_enabled():
    return bool(os.environ.get(DEBUG_ENV_VAR))


def device_peak_bytes(device=None):
    """Peak device-memory bytes, or None when the backend has no stats
    (replaces ``torch.cuda.max_memory_allocated``, reference functions.py:28)."""
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats()
    except (AttributeError, NotImplementedError, RuntimeError, TypeError):
        # Backend without memory stats (CPU, some PJRT plugins) — the
        # narrowed set is every "stats unsupported here" shape observed;
        # anything else (a real runtime fault) propagates.
        return None
    if not stats:
        return None
    return stats.get('peak_bytes_in_use', stats.get('bytes_in_use'))


def _shape_of(x):
    return tuple(getattr(x, 'shape', ())) or None


def measure(fn):
    """Decorator: when ``DISTRIBUTED_DOT_DEBUG`` is set, print wall time,
    operand shapes and peak device memory per call (reference
    functions.py:24-41). Zero overhead when disabled.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not _debug_enabled():
            return fn(*args, **kwargs)
        peak_before = device_peak_bytes()
        start = time.perf_counter()
        result = fn(*args, **kwargs)
        traced = ''
        try:
            hard_sync(result)
        except (jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError):
            # Tracer under jit/shard_map: only trace time is observable.
            # (Real runtime errors — OOM, RPC failures — propagate.)
            # Both types named: on jax 0.4.x TracerArrayConversionError
            # is NOT a ConcretizationTypeError subclass, and the sync
            # probe's np.asarray raises it.
            traced = ' (traced)'
        elapsed = time.perf_counter() - start
        shapes = [_shape_of(a) for a in args if _shape_of(a) is not None]
        # Peak-memory DELTA across the call (before/after readings of
        # the monotonic peak), matching the reference semantics
        # (reference functions.py:28 reports max-memory growth per
        # call) — an absolute peak says nothing about THIS op once any
        # larger op has run in the process.
        peak_after = device_peak_bytes()
        if peak_before is None or peak_after is None:
            peak_s = 'n/a'
        else:
            delta = peak_after - peak_before
            peak_s = f'+{delta / 2 ** 30:.3f} GiB'
        print(f'[{DEBUG_ENV_VAR}] {fn.__name__}: {elapsed * 1000:.3f} ms'
              f'{traced} shapes={shapes} peak_mem_delta={peak_s}')
        return result

    return wrapper


def log_exception(context, exc, registry=None):
    """Record a swallowed-but-survivable exception so fault paths stay
    observable: bumps ``exceptions_swallowed`` (total + per-context)
    in the metrics registry — a health endpoint or operator sees the
    count move even when nothing prints — and prints the exception
    under the ``DISTRIBUTED_DOT_DEBUG`` switch.

    This is the logging half of the ``silent-except`` lint contract
    (analysis/astlint.py): a broad handler must re-raise, narrow its
    type, or route through here. ``context`` is a short dotted site
    name (e.g. ``'health.on_stall_callback'``).

    When an observability event log is active (obs/events.py), the
    exception also lands there as an ``exception`` event — swallowed
    failures share the durable JSONL stream with the serve/train
    lifecycle they interrupted."""
    reg = registry if registry is not None else _DEFAULT_REGISTRY
    reg.counter('exceptions_swallowed').inc()
    reg.counter(f'exceptions_swallowed.{context}').inc()
    _emit_event('exception', context=context,
                type=type(exc).__name__, message=str(exc))
    if _debug_enabled():
        print(f'[{DEBUG_ENV_VAR}] swallowed exception in {context}: '
              f'{type(exc).__name__}: {exc}', flush=True)


def _emit_event(event, **fields):
    """Route into the active observability event log, if any. Lazy
    import: utils.tracing is imported by nearly everything, so it must
    not pull the obs package (and its jax import) at module load."""
    from distributed_dot_product_tpu.obs import events as _events
    if _events.get_active() is not None:
        _events.emit(event, **fields)


def log_step(step, loss, grad_norm=None, bad=False, seconds=None,
             extra='', force=False):
    """One-line per-step training log, gated by the same
    ``DISTRIBUTED_DOT_DEBUG`` switch as :func:`measure` (``force=True``
    prints unconditionally — the driver uses it for its periodic log
    cadence). The resilient train loop feeds its per-step
    ``{loss, bad_step, grad_norm}`` records through here.

    Independently of the print gate, every record is routed into the
    active observability event log (obs/events.py) when one exists —
    training history lands in the same durable JSONL stream as the
    serving lifecycle (``train.step`` + ``train.bad_step``)."""
    _emit_event('train.step', step=int(step), loss=float(loss),
                grad_norm=(None if grad_norm is None
                           else float(grad_norm)),
                bad=bool(bad), seconds=seconds, extra=extra or None)
    if bad:
        _emit_event('train.bad_step', step=int(step), loss=float(loss))
    if not (force or _debug_enabled()):
        return
    parts = [f'step {step}: loss={loss:.6f}']
    if grad_norm is not None:
        parts.append(f'grad_norm={grad_norm:.4g}')
    if bad:
        parts.append('BAD (non-finite; update skipped)')
    if seconds is not None:
        parts.append(f'({seconds * 1000:.1f} ms)')
    if extra:
        parts.append(extra)
    print(' '.join(parts), flush=True)


class timed:
    """Context manager for honest block timing:

    with timed() as t:
        out = step(x)
    print(t.seconds)
    """

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._start
        return False


@jax.jit
def _sync_probe(leaves):
    # One scalar depending on EVERY leaf, so a single host readback fences
    # all dispatches that produced them (multi-output computations may come
    # from separate executables — probing only the first leaf would
    # under-synchronize). Retraces per pytree structure; cached after.
    import jax.numpy as jnp
    acc = jnp.zeros((), jnp.float32)
    for leaf in leaves:
        acc = acc + leaf.ravel()[0].astype(jnp.float32)
    return acc


def hard_sync(out):
    """Synchronize with the device by reading one element of every leaf
    back to the host (as a single fused scalar → one RPC).

    ``jax.block_until_ready`` alone is not a reliable fence on remote /
    tunneled PJRT backends (observed: it returns in ~0.1 ms while the
    computation is still in flight); a host readback is. The probe is a
    cached tiny jit so steady-state cost is one small RPC.
    """
    leaves = [x for x in jax.tree.leaves(out)
              if getattr(x, 'size', 1)]  # drop zero-size leaves
    if not leaves:
        return  # nothing to sync on (fn returned None / empty pytree)
    import numpy as np
    np.asarray(_sync_probe(leaves))


def time_fn(fn, *args, iters=5, warmup=2, inner=None, max_inner=512,
            **kwargs):
    """Honest wall-clock timing of ``fn(*args)``: returns
    ``(best_seconds, mean_seconds)`` per call.

    The reference's ``measure()`` never synchronized the device (reference
    benchmark.py:56-67), so its GPU numbers are enqueue-biased. Here each
    sample queues ``inner`` async dispatches (the device executes them
    serially), hard-syncs once via a host readback, and subtracts the
    separately-measured sync overhead. ``inner=None`` auto-scales so the
    measured window dominates that overhead (~70 ms on a tunneled TPU) —
    without this, sub-millisecond ops disappear into sync noise.
    """
    out = fn(*args, **kwargs)
    hard_sync(out)
    for _ in range(max(warmup - 1, 0)):
        out = fn(*args, **kwargs)
    hard_sync(out)
    # Steady-state sync overhead on an already-materialized result.
    overhead = min(_timed_sync(out) for _ in range(3))
    if inner is None:
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        hard_sync(out)
        est = max(time.perf_counter() - t0 - overhead, 1e-5)
        inner = max(1, min(max_inner, int(8 * overhead / est)))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn(*args, **kwargs)
        hard_sync(out)
        dt = time.perf_counter() - t0 - overhead
        times.append(max(dt, 1e-9) / inner)
    return min(times), sum(times) / len(times)


def _timed_sync(out):
    t0 = time.perf_counter()
    hard_sync(out)
    return time.perf_counter() - t0


# ---------------------------------------------------------------------------
# Lightweight metrics registry (serving observability)
#
# The serving scheduler (serve/scheduler.py) needs queue depth, admissions,
# rejections-by-reason, evictions and step-latency percentiles exported
# somewhere a health endpoint / operator can read them. No external metrics
# dependency is available in the image, so this is the minimal honest core:
# monotonic counters, last-value gauges, and a bounded-reservoir histogram
# with nearest-rank percentiles. Thread-safe (the watchdog thread reads
# while the scheduler loop writes).
# ---------------------------------------------------------------------------


class Counter:
    """Monotonic event counter."""

    def __init__(self):
        self._value = 0         # guarded-by: self._lock
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """Last-value gauge (queue depth, active slots, readiness code)."""

    def __init__(self):
        self._value = 0.0       # guarded-by: self._lock
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = float(value)

    @property
    def value(self):
        with self._lock:
            return self._value


# Default cumulative-bucket bounds (seconds): spans the sub-ms decode
# dispatch floor through multi-second compile phases. A Prometheus
# scraping several replicas can SUM _bucket series across them — the
# one aggregation the reservoir quantiles cannot support.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class Histogram:
    """Bounded reservoir of the most recent ``maxlen`` observations with
    nearest-rank percentiles — enough for honest p50/p99 step latency
    without an external metrics stack. Older observations age out, so
    the percentiles track CURRENT behavior (what a readiness probe
    wants), not the run's whole history.

    Independently, LIFETIME cumulative bucket counts are kept over
    ``buckets`` (upper bounds, ``le`` semantics; default
    :data:`DEFAULT_BUCKETS`, ``()`` disables) — these never age out,
    which is what lets an external Prometheus aggregate histograms
    across replicas (sum of cumulative counters is meaningful; merged
    reservoir quantiles are not)."""

    def __init__(self, maxlen=4096, buckets=DEFAULT_BUCKETS):
        self._values = collections.deque(maxlen=maxlen)  # guarded-by: self._lock
        self._count = 0         # guarded-by: self._lock
        self._sum = 0.0         # guarded-by: self._lock
        # _bounds is immutable after construction — reads need no lock.
        self._bounds = (tuple(sorted({float(b) for b in buckets}))
                        if buckets else ())
        self._bucket_counts = [0] * len(self._bounds)  # guarded-by: self._lock
        self._lock = threading.Lock()

    def observe(self, value):
        with self._lock:
            v = float(value)
            self._values.append(v)
            self._count += 1
            self._sum += v
            if self._bounds:
                i = bisect.bisect_left(self._bounds, v)
                if i < len(self._bounds):
                    self._bucket_counts[i] += 1

    @property
    def bucket_bounds(self):
        return self._bounds

    def _cumulative(self, counts):
        """Per-bucket counts → cumulative ``[(le, count), ...]`` (the
        ONE place the le accumulation rule lives — buckets() and
        summary() both render through it)."""
        out, cum = [], 0
        for le, c in zip(self._bounds, counts):
            cum += c
            out.append((le, cum))
        return out

    def buckets(self):
        """Cumulative ``[(le, count), ...]`` over the lifetime counts
        (ascending bounds; observations above the last bound appear
        only in ``total_count`` — the exporter's ``+Inf`` line)."""
        with self._lock:
            counts = list(self._bucket_counts)
        return self._cumulative(counts)

    @property
    def count(self):
        with self._lock:
            return self._count

    def percentile(self, p):
        """Nearest-rank percentile over the reservoir (NaN when empty)."""
        with self._lock:
            vals = sorted(self._values)
        if not vals:
            return float('nan')
        idx = min(len(vals) - 1, max(0, int(round(
            (p / 100.0) * (len(vals) - 1)))))
        return vals[idx]

    @property
    def total_count(self):
        """Lifetime observation count (never ages out)."""
        with self._lock:
            return self._count

    @property
    def total_sum(self):
        """Lifetime observation sum (never ages out)."""
        with self._lock:
            return self._sum

    def summary(self):
        """Reservoir-local ``count``/``mean``/``p50``/``p99``/``max``
        — ALL five describe the same aged window, so they are mutually
        consistent (a lifetime mean next to reservoir percentiles would
        describe two different distributions once anything has aged
        out) — plus the lifetime ``total_count``/``total_sum`` the
        Prometheus exporter needs for its cumulative _count/_sum
        series. Histograms with bucket bounds additionally carry
        ``'buckets'`` (the cumulative lifetime counts) for the
        exporter's real ``_bucket{le=...}`` lines."""
        with self._lock:
            vals = sorted(self._values)
            count, total = self._count, self._sum
            # Bucket counts read in the SAME locked snapshot as
            # total_count: a cumulative bucket exceeding the +Inf line
            # (rendered from total_count) is corrupt data to a
            # Prometheus consumer.
            bucket_counts = list(self._bucket_counts)
        buckets = ({'buckets': [[le, n] for le, n
                                in self._cumulative(bucket_counts)]}
                   if self._bounds else {})
        if not vals:
            return {'count': 0, 'mean': float('nan'),
                    'p50': float('nan'), 'p99': float('nan'),
                    'max': float('nan'),
                    'total_count': count, 'total_sum': total, **buckets}

        def _pct(p):
            return vals[min(len(vals) - 1,
                            max(0, int(round((p / 100.0)
                                             * (len(vals) - 1)))))]

        return {'count': len(vals), 'mean': sum(vals) / len(vals),
                'p50': _pct(50), 'p99': _pct(99), 'max': vals[-1],
                'total_count': count, 'total_sum': total, **buckets}


def _metric_key(name, labels):
    """Internal storage key: the bare name, or ``(name, ((k, v), ...))``
    with sorted stringified label pairs for labeled metrics."""
    if not labels:
        return name
    return (name, tuple(sorted((str(k), str(v))
                               for k, v in labels.items())))


def _flat_name(key):
    """Display/JSON form of a storage key: ``name`` or
    ``name{k=v,...}``."""
    if isinstance(key, str):
        return key
    name, items = key
    return name + '{' + ','.join(f'{k}={v}' for k, v in items) + '}'


class MetricsRegistry:
    """Named metric store with one-call :meth:`snapshot`. Get-or-create
    accessors, so call sites never coordinate registration order.

    ``labels`` (optional dict on every accessor) keys a separate series
    per label set under one family name — the Prometheus exporter
    (obs/exporter.py) renders them as real labels with value escaping;
    :meth:`snapshot` flattens them to ``name{k=v,...}`` strings."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}     # guarded-by: self._lock
        self._gauges = {}       # guarded-by: self._lock
        self._histograms = {}   # guarded-by: self._lock

    def counter(self, name, labels=None) -> Counter:
        with self._lock:
            return self._counters.setdefault(
                _metric_key(name, labels), Counter())

    def gauge(self, name, labels=None) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(
                _metric_key(name, labels), Gauge())

    def histogram(self, name, maxlen=4096, labels=None,
                  buckets=None) -> Histogram:
        """``buckets``: cumulative-bucket upper bounds for this series
        (None → :data:`DEFAULT_BUCKETS`, ``()`` disables). Get-or-create
        semantics: the first registration's bounds win."""
        with self._lock:
            key = _metric_key(name, labels)
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram(
                    maxlen,
                    buckets=DEFAULT_BUCKETS if buckets is None
                    else buckets)
            return h

    def peek(self, kind, name, labels=None):
        """The EXISTING metric of ``kind`` (``'counter'``/``'gauge'``/
        ``'histogram'``) under ``name``/``labels``, or None — read-only
        probing that never creates a series. The anomaly watchdog
        (obs/anomaly.py) polls metric streams other layers may not have
        created yet; the get-or-create accessors would materialize an
        empty series and teach its detectors a phantom zero."""
        with self._lock:
            table = {'counter': self._counters, 'gauge': self._gauges,
                     'histogram': self._histograms}[kind]
            return table.get(_metric_key(name, labels))

    def iter_metrics(self):
        """Structured iteration for exporters: yields ``(kind, name,
        labels_dict, value)`` with ``value`` the counter/gauge value or
        the histogram :meth:`~Histogram.summary` dict. Metric names are
        iterated from a snapshot of the key tables; each value read is
        atomic (counters/gauges) or lock-consistent (histograms), so a
        concurrent writer can never produce a torn read."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        for table, kind in ((counters, 'counter'), (gauges, 'gauge'),
                            (histograms, 'histogram')):
            for key in sorted(table, key=_flat_name):
                name = key if isinstance(key, str) else key[0]
                labels = {} if isinstance(key, str) else dict(key[1])
                value = (table[key].summary() if kind == 'histogram'
                         else table[key].value)
                yield kind, name, labels, value

    def snapshot(self):
        """Plain-dict view: ``{'counters': {name: int}, 'gauges':
        {name: float}, 'histograms': {name: {count, mean, p50, p99,
        max, total_count, total_sum}}}`` — JSON-serializable, safe to
        hand to a health endpoint. Labeled series flatten to
        ``name{k=v,...}`` keys."""
        out = {'counters': {}, 'gauges': {}, 'histograms': {}}
        for kind, name, labels, value in self.iter_metrics():
            key = _flat_name(_metric_key(name, labels))
            out[kind + 's'][key] = value
        return out

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_DEFAULT_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-default registry (the serving layer's default sink)."""
    return _DEFAULT_REGISTRY


def metrics():
    """Snapshot of the process-default registry."""
    return _DEFAULT_REGISTRY.snapshot()
