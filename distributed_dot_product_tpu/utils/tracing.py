# -*- coding: utf-8 -*-
"""
Per-op tracing / profiling utilities.

TPU-native replacement for the reference ``measure`` decorator
(reference functions.py:24-41), which printed per-call wall time, operand
shapes and CUDA max-memory delta when the env var ``DISTRIBUTED_DOT_DEBUG``
was set (reference functions.py:21,30).

Differences, deliberate:

- **Honest timing.** The reference never called ``torch.cuda.synchronize()``
  before stopping the clock (noted in SURVEY §5 / BASELINE.md), so its GPU
  numbers are enqueue-biased. We fence with :func:`hard_sync` (a host
  readback — ``jax.block_until_ready`` alone is not a reliable fence on
  tunneled PJRT backends) before reading the clock.
- **Memory** comes from ``device.memory_stats()`` (TPU/GPU); on backends
  without stats (CPU) it is reported as ``None``.
- ``measure`` on a function *called inside jit/shard_map* times the trace,
  not the execution (the result is a tracer, which cannot be synced) — the
  printed line is tagged ``traced`` in that case. For execution numbers use
  :func:`time_fn` on the jitted callable, or ``jax.profiler.trace`` (see
  ``benchmark.py --profile-dir``).
"""

import collections
import functools
import os
import threading
import time

import jax

# Same env-var name as the reference (functions.py:21) so users can flip the
# identical switch.
DEBUG_ENV_VAR = 'DISTRIBUTED_DOT_DEBUG'


def _debug_enabled():
    return bool(os.environ.get(DEBUG_ENV_VAR))


def device_peak_bytes(device=None):
    """Peak device-memory bytes, or None when the backend has no stats
    (replaces ``torch.cuda.max_memory_allocated``, reference functions.py:28)."""
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats()
    except (AttributeError, NotImplementedError, RuntimeError, TypeError):
        # Backend without memory stats (CPU, some PJRT plugins) — the
        # narrowed set is every "stats unsupported here" shape observed;
        # anything else (a real runtime fault) propagates.
        return None
    if not stats:
        return None
    return stats.get('peak_bytes_in_use', stats.get('bytes_in_use'))


def _shape_of(x):
    return tuple(getattr(x, 'shape', ())) or None


def measure(fn):
    """Decorator: when ``DISTRIBUTED_DOT_DEBUG`` is set, print wall time,
    operand shapes and peak device memory per call (reference
    functions.py:24-41). Zero overhead when disabled.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not _debug_enabled():
            return fn(*args, **kwargs)
        start = time.perf_counter()
        result = fn(*args, **kwargs)
        traced = ''
        try:
            hard_sync(result)
        except jax.errors.ConcretizationTypeError:
            # Tracer under jit/shard_map: only trace time is observable.
            # (Real runtime errors — OOM, RPC failures — propagate.)
            traced = ' (traced)'
        elapsed = time.perf_counter() - start
        shapes = [_shape_of(a) for a in args if _shape_of(a) is not None]
        peak = device_peak_bytes()
        peak_s = f'{peak / 2 ** 30:.3f} GiB' if peak is not None else 'n/a'
        print(f'[{DEBUG_ENV_VAR}] {fn.__name__}: {elapsed * 1000:.3f} ms'
              f'{traced} shapes={shapes} peak_mem={peak_s}')
        return result

    return wrapper


def log_exception(context, exc, registry=None):
    """Record a swallowed-but-survivable exception so fault paths stay
    observable: bumps ``exceptions_swallowed`` (total + per-context)
    in the metrics registry — a health endpoint or operator sees the
    count move even when nothing prints — and prints the exception
    under the ``DISTRIBUTED_DOT_DEBUG`` switch.

    This is the logging half of the ``silent-except`` lint contract
    (analysis/astlint.py): a broad handler must re-raise, narrow its
    type, or route through here. ``context`` is a short dotted site
    name (e.g. ``'health.on_stall_callback'``)."""
    reg = registry if registry is not None else _DEFAULT_REGISTRY
    reg.counter('exceptions_swallowed').inc()
    reg.counter(f'exceptions_swallowed.{context}').inc()
    if _debug_enabled():
        print(f'[{DEBUG_ENV_VAR}] swallowed exception in {context}: '
              f'{type(exc).__name__}: {exc}', flush=True)


def log_step(step, loss, grad_norm=None, bad=False, seconds=None,
             extra='', force=False):
    """One-line per-step training log, gated by the same
    ``DISTRIBUTED_DOT_DEBUG`` switch as :func:`measure` (``force=True``
    prints unconditionally — the driver uses it for its periodic log
    cadence). The resilient train loop feeds its per-step
    ``{loss, bad_step, grad_norm}`` records through here."""
    if not (force or _debug_enabled()):
        return
    parts = [f'step {step}: loss={loss:.6f}']
    if grad_norm is not None:
        parts.append(f'grad_norm={grad_norm:.4g}')
    if bad:
        parts.append('BAD (non-finite; update skipped)')
    if seconds is not None:
        parts.append(f'({seconds * 1000:.1f} ms)')
    if extra:
        parts.append(extra)
    print(' '.join(parts), flush=True)


class timed:
    """Context manager for honest block timing:

    with timed() as t:
        out = step(x)
    print(t.seconds)
    """

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._start
        return False


@jax.jit
def _sync_probe(leaves):
    # One scalar depending on EVERY leaf, so a single host readback fences
    # all dispatches that produced them (multi-output computations may come
    # from separate executables — probing only the first leaf would
    # under-synchronize). Retraces per pytree structure; cached after.
    import jax.numpy as jnp
    acc = jnp.zeros((), jnp.float32)
    for leaf in leaves:
        acc = acc + leaf.ravel()[0].astype(jnp.float32)
    return acc


def hard_sync(out):
    """Synchronize with the device by reading one element of every leaf
    back to the host (as a single fused scalar → one RPC).

    ``jax.block_until_ready`` alone is not a reliable fence on remote /
    tunneled PJRT backends (observed: it returns in ~0.1 ms while the
    computation is still in flight); a host readback is. The probe is a
    cached tiny jit so steady-state cost is one small RPC.
    """
    leaves = [x for x in jax.tree.leaves(out)
              if getattr(x, 'size', 1)]  # drop zero-size leaves
    if not leaves:
        return  # nothing to sync on (fn returned None / empty pytree)
    import numpy as np
    np.asarray(_sync_probe(leaves))


def time_fn(fn, *args, iters=5, warmup=2, inner=None, max_inner=512,
            **kwargs):
    """Honest wall-clock timing of ``fn(*args)``: returns
    ``(best_seconds, mean_seconds)`` per call.

    The reference's ``measure()`` never synchronized the device (reference
    benchmark.py:56-67), so its GPU numbers are enqueue-biased. Here each
    sample queues ``inner`` async dispatches (the device executes them
    serially), hard-syncs once via a host readback, and subtracts the
    separately-measured sync overhead. ``inner=None`` auto-scales so the
    measured window dominates that overhead (~70 ms on a tunneled TPU) —
    without this, sub-millisecond ops disappear into sync noise.
    """
    out = fn(*args, **kwargs)
    hard_sync(out)
    for _ in range(max(warmup - 1, 0)):
        out = fn(*args, **kwargs)
    hard_sync(out)
    # Steady-state sync overhead on an already-materialized result.
    overhead = min(_timed_sync(out) for _ in range(3))
    if inner is None:
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        hard_sync(out)
        est = max(time.perf_counter() - t0 - overhead, 1e-5)
        inner = max(1, min(max_inner, int(8 * overhead / est)))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn(*args, **kwargs)
        hard_sync(out)
        dt = time.perf_counter() - t0 - overhead
        times.append(max(dt, 1e-9) / inner)
    return min(times), sum(times) / len(times)


def _timed_sync(out):
    t0 = time.perf_counter()
    hard_sync(out)
    return time.perf_counter() - t0


# ---------------------------------------------------------------------------
# Lightweight metrics registry (serving observability)
#
# The serving scheduler (serve/scheduler.py) needs queue depth, admissions,
# rejections-by-reason, evictions and step-latency percentiles exported
# somewhere a health endpoint / operator can read them. No external metrics
# dependency is available in the image, so this is the minimal honest core:
# monotonic counters, last-value gauges, and a bounded-reservoir histogram
# with nearest-rank percentiles. Thread-safe (the watchdog thread reads
# while the scheduler loop writes).
# ---------------------------------------------------------------------------


class Counter:
    """Monotonic event counter."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge:
    """Last-value gauge (queue depth, active slots, readiness code)."""

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = float(value)

    @property
    def value(self):
        return self._value


class Histogram:
    """Bounded reservoir of the most recent ``maxlen`` observations with
    nearest-rank percentiles — enough for honest p50/p99 step latency
    without an external metrics stack. Older observations age out, so
    the percentiles track CURRENT behavior (what a readiness probe
    wants), not the run's whole history."""

    def __init__(self, maxlen=4096):
        self._values = collections.deque(maxlen=maxlen)
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value):
        with self._lock:
            self._values.append(float(value))
            self._count += 1
            self._sum += float(value)

    @property
    def count(self):
        return self._count

    def percentile(self, p):
        """Nearest-rank percentile over the reservoir (NaN when empty)."""
        with self._lock:
            vals = sorted(self._values)
        if not vals:
            return float('nan')
        idx = min(len(vals) - 1, max(0, int(round(
            (p / 100.0) * (len(vals) - 1)))))
        return vals[idx]

    def summary(self):
        with self._lock:
            vals = sorted(self._values)
            count, total = self._count, self._sum
        if not vals:
            return {'count': count, 'mean': float('nan'),
                    'p50': float('nan'), 'p99': float('nan'),
                    'max': float('nan')}

        def _pct(p):
            return vals[min(len(vals) - 1,
                            max(0, int(round((p / 100.0)
                                             * (len(vals) - 1)))))]

        return {'count': count, 'mean': total / max(count, 1),
                'p50': _pct(50), 'p99': _pct(99), 'max': vals[-1]}


class MetricsRegistry:
    """Named metric store with one-call :meth:`snapshot`. Get-or-create
    accessors, so call sites never coordinate registration order."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        self._histograms = {}

    def counter(self, name) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def histogram(self, name, maxlen=4096) -> Histogram:
        with self._lock:
            return self._histograms.setdefault(name, Histogram(maxlen))

    def snapshot(self):
        """Plain-dict view: ``{'counters': {name: int}, 'gauges':
        {name: float}, 'histograms': {name: {count, mean, p50, p99,
        max}}}`` — JSON-serializable, safe to hand to a health
        endpoint."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            'counters': {k: c.value for k, c in counters.items()},
            'gauges': {k: g.value for k, g in gauges.items()},
            'histograms': {k: h.summary() for k, h in histograms.items()},
        }

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_DEFAULT_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-default registry (the serving layer's default sink)."""
    return _DEFAULT_REGISTRY


def metrics():
    """Snapshot of the process-default registry."""
    return _DEFAULT_REGISTRY.snapshot()
