# -*- coding: utf-8 -*-
"""
Per-op tracing / profiling utilities.

TPU-native replacement for the reference ``measure`` decorator
(reference functions.py:24-41), which printed per-call wall time, operand
shapes and CUDA max-memory delta when the env var ``DISTRIBUTED_DOT_DEBUG``
was set (reference functions.py:21,30).

Differences, deliberate:

- **Honest timing.** The reference never called ``torch.cuda.synchronize()``
  before stopping the clock (noted in SURVEY §5 / BASELINE.md), so its GPU
  numbers are enqueue-biased. We call ``jax.block_until_ready`` on the
  result before reading the clock.
- **Memory** comes from ``device.memory_stats()`` (TPU/GPU); on backends
  without stats (CPU) it is reported as ``None``.
- Tracing a *jitted* function measures whole-call latency, including compile
  on first hit; we report ``compiled=False`` on a call where tracing
  happened so the first (compile) sample can be discarded.
- For deep kernel profiles use ``jax.profiler.trace`` (see
  ``benchmark.py --profile-dir``); this decorator is the lightweight,
  print-based path matching the reference's ergonomics.
"""

import functools
import os
import time

import jax

# Same env-var name as the reference (functions.py:21) so users can flip the
# identical switch.
DEBUG_ENV_VAR = 'DISTRIBUTED_DOT_DEBUG'


def _debug_enabled():
    return bool(os.environ.get(DEBUG_ENV_VAR))


def device_peak_bytes(device=None):
    """Peak device-memory bytes, or None when the backend has no stats
    (replaces ``torch.cuda.max_memory_allocated``, reference functions.py:28)."""
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return stats.get('peak_bytes_in_use', stats.get('bytes_in_use'))


def _shape_of(x):
    return tuple(getattr(x, 'shape', ())) or None


def measure(fn):
    """Decorator: when ``DISTRIBUTED_DOT_DEBUG`` is set, print wall time,
    operand shapes and peak device memory per call (reference
    functions.py:24-41). Zero overhead when disabled.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not _debug_enabled():
            return fn(*args, **kwargs)
        start = time.perf_counter()
        result = fn(*args, **kwargs)
        result = jax.block_until_ready(result)
        elapsed = time.perf_counter() - start
        shapes = [_shape_of(a) for a in args if _shape_of(a) is not None]
        peak = device_peak_bytes()
        peak_s = f'{peak / 2 ** 30:.3f} GiB' if peak is not None else 'n/a'
        print(f'[{DEBUG_ENV_VAR}] {fn.__name__}: {elapsed * 1000:.3f} ms '
              f'shapes={shapes} peak_mem={peak_s}')
        return result

    return wrapper


class timed:
    """Context manager for honest block timing:

    with timed() as t:
        out = step(x)
    print(t.seconds)
    """

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._start
        return False


def time_fn(fn, *args, iters=10, warmup=2, **kwargs):
    """Run ``fn`` ``warmup`` + ``iters`` times, blocking on results, and
    return (best_seconds, mean_seconds). The benchmark harness's honest
    replacement for the reference's ``measure()`` (reference
    benchmark.py:56-67)."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return min(times), sum(times) / len(times)
