# -*- coding: utf-8 -*-
"""
Checkpoint / resume for sharded training state (orbax-backed).

The reference has NO checkpoint subsystem — its only use of ``state_dict``
is the rank-0 weight broadcast inside a test (SURVEY §5 "Checkpoint /
resume: none"; reference test_gradient.py:48), so a crashed multi-day run
restarts from scratch. This module closes that gap the TPU-native way:
`orbax.checkpoint` writes each device's shards in parallel (OCDBT), works
unchanged on one host or a multi-host pod (every process calls
``save``/``restore`` collectively), and restores arrays onto whatever
sharding the provided template carries — so a checkpoint taken on one mesh
can resume on another.

Paths go through ``etils.epath``, so ``path`` may be a POSIX directory OR
an object-store URL (``gs://bucket/run1`` — where real TPU pods
checkpoint): listing, existence checks and the overwrite-backup dance all
use epath's backend-portable operations, and orbax itself writes through
the same abstraction. (On object stores a directory "rename" is
per-object copy+delete — the backup dance costs one checkpoint's worth of
copies there; orbax's own temp-write + commit-marker finalization is what
makes the write itself atomic on every backend.)

Durability: orbax finalizes a checkpoint only after all shards land
(rename on POSIX, commit marker on object stores); ``latest_step`` asks
orbax whether a step directory is finalized, so a crash mid-save is never
selected for restore. Overwriting an existing step keeps the old
checkpoint as ``step_N.replaced`` until the new one is finalized.

Usage::

    state = TrainState(step=0, params=params, opt_state=opt_state)
    save(ckpt_dir, state)                       # atomic, collective
    state = restore(ckpt_dir, state)            # template = like-shaped state
    step = latest_step(ckpt_dir)                # None if no checkpoint
"""

import os
from typing import Any, NamedTuple, Optional

import jax

from distributed_dot_product_tpu.utils.comm import synchronize

__all__ = ['TrainState', 'save', 'restore', 'latest_step', 'wait']


class TrainState(NamedTuple):
    """Minimal training state: a step counter plus arbitrary pytrees.

    A NamedTuple (not a dataclass) so it is a pytree out of the box and
    orbax round-trips it without custom registration.
    """
    step: int
    params: Any
    opt_state: Any


_CKPTR = None


def _checkpointer():
    # One long-lived checkpointer: StandardCheckpointer owns async-write
    # machinery (threads), so constructing one per call would leak it
    # across a training loop.
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _root(path):
    """The run directory as an epath.Path, absolutized for local paths
    (orbax requires absolute paths; URLs are absolute by construction)."""
    from etils import epath
    s = os.fspath(path)
    if '://' not in s:
        s = os.path.abspath(s)
    return epath.Path(s)


def _step_dir(path, step):
    return _root(path) / f'step_{step:09d}'


def _is_finalized(path):
    try:
        from orbax.checkpoint import utils as ocp_utils
        return bool(ocp_utils.is_checkpoint_finalized(path))
    except Exception:
        # Fallback if the orbax util is missing/renamed: never assume YES —
        # a crash-truncated directory must not be selected for restore.
        # Orbax in-progress dirs carry an '.orbax-checkpoint-tmp' suffix,
        # and a finalized StandardCheckpointer dir contains its metadata
        # files; require positive evidence of the latter.
        if '.orbax-checkpoint-tmp' in path.name:
            return False
        try:
            entries = {p.name for p in path.iterdir()}
        except OSError:
            return False
        return bool(entries & {'_CHECKPOINT_METADATA', '_METADATA'})


# Backups whose removal is deferred until their (async) save finalizes,
# and whether ANY async save is outstanding (a non-overwrite async save
# leaves no backup but must still be waited on before the next save's
# filesystem inspection — its target directory may not exist yet).
_PENDING_BACKUPS = []
_ASYNC_PENDING = False


def wait():
    """Block until every outstanding ``save(..., blocking=False)`` has
    finalized, then remove the overwrite backups it deferred. Collective
    on multi-host (same contract as ``save``). A no-op when nothing is
    pending."""
    global _ASYNC_PENDING
    if _CKPTR is not None:
        _CKPTR.wait_until_finished()
    synchronize()
    if jax.process_index() == 0:
        for backup in _PENDING_BACKUPS:
            if backup.is_dir():
                backup.rmtree()
    _PENDING_BACKUPS.clear()
    _ASYNC_PENDING = False


def save(path, state: TrainState, *, force: bool = True,
         blocking: bool = True) -> str:
    """Write ``state`` under ``path/step_<step>/``; returns that directory.

    ``path``: POSIX directory or object-store URL (``gs://...``) — see
    the module docstring. Atomic: orbax writes to a temporary name and
    finalizes it afterwards. If the step already exists and ``force`` is
    set, the old checkpoint is kept as ``step_<step>.replaced`` until the
    new write finalizes, so a crash mid-overwrite never destroys the only
    copy of a step.

    ``blocking=False`` returns as soon as the device arrays are snapshot
    and lets orbax flush to storage in the background — the training loop
    keeps stepping while the previous checkpoint lands (call
    :func:`wait` before exiting, and note ``latest_step`` simply skips a
    still-unfinalized save). A new ``save`` first waits for any pending
    one, so overlapping saves serialize instead of colliding.

    Collective on multi-host: every process must call this with its view
    of the same global arrays (directory juggling runs on process 0 only;
    process 0's filesystem view decides the overwrite branch for
    everyone).
    """
    global _ASYNC_PENDING
    if _ASYNC_PENDING:
        wait()
    target = _step_dir(path, int(state.step))
    backup = target.parent / (target.name + '.replaced')
    exists = target.is_dir()
    if jax.process_count() > 1:
        # Every process must take the same branch below (the orbax save is
        # collective; one process raising while others enter it would hang
        # at its barrier). Filesystem views can differ across hosts —
        # process 0's view decides for everyone.
        from jax.experimental import multihost_utils
        exists = bool(multihost_utils.broadcast_one_to_all(
            jax.numpy.asarray(exists)))
    if exists and not force:
        raise FileExistsError(
            f'{target} already exists; pass force=True to replace it')
    if exists and jax.process_index() == 0:
        if backup.is_dir():
            backup.rmtree()
        target.rename(backup)
    synchronize()
    ckptr = _checkpointer()
    ckptr.save(target, state)
    if not blocking:
        _ASYNC_PENDING = True
        if exists:
            _PENDING_BACKUPS.append(backup)
        return os.fspath(target)
    ckptr.wait_until_finished()
    synchronize()
    if exists and jax.process_index() == 0 and backup.is_dir():
        backup.rmtree()
    return os.fspath(target)


def latest_step(path) -> Optional[int]:
    """Highest step with a FINALIZED checkpoint under ``path``, or None —
    a crash mid-save leaves an unfinalized directory, which is skipped."""
    root = _root(path)
    if not root.is_dir():
        return None
    steps = []
    for child in root.iterdir():
        name = child.name
        if not name.startswith('step_') or name.endswith('.replaced'):
            continue
        try:
            step = int(name[len('step_'):])
        except ValueError:
            continue
        if _is_finalized(child):
            steps.append(step)
    return max(steps) if steps else None


def restore(path, template: TrainState, *, step: Optional[int] = None
            ) -> TrainState:
    """Restore the checkpoint at ``step`` (default: latest finalized)
    using ``template`` for structure/shardings: every restored array
    adopts the sharding of the corresponding template leaf, so resuming
    on a different mesh layout re-shards transparently.
    """
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f'no checkpoint under {path!r}')
    restored = _checkpointer().restore(_step_dir(path, step), template)
    # orbax returns the same pytree type; ensure the step is a python int
    # (templates often carry traced/array steps).
    return restored._replace(step=int(jax.device_get(restored.step)))
