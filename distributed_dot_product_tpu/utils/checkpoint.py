# -*- coding: utf-8 -*-
"""
Checkpoint / resume for sharded training state (orbax-backed).

The reference has NO checkpoint subsystem — its only use of ``state_dict``
is the rank-0 weight broadcast inside a test (SURVEY §5 "Checkpoint /
resume: none"; reference test_gradient.py:48), so a crashed multi-day run
restarts from scratch. This module closes that gap the TPU-native way:
`orbax.checkpoint` writes each device's shards in parallel (OCDBT), works
unchanged on one host or a multi-host pod (every process calls
``save``/``restore`` collectively), and restores arrays onto whatever
sharding the provided template carries — so a checkpoint taken on one mesh
can resume on another.

Paths go through ``etils.epath``, so ``path`` may be a POSIX directory OR
an object-store URL (``gs://bucket/run1`` — where real TPU pods
checkpoint): listing, existence checks and the overwrite-backup dance all
use epath's backend-portable operations, and orbax itself writes through
the same abstraction. (On object stores a directory "rename" is
per-object copy+delete — the backup dance costs one checkpoint's worth of
copies there; orbax's own temp-write + commit-marker finalization is what
makes the write itself atomic on every backend.)

Durability: orbax finalizes a checkpoint only after all shards land
(rename on POSIX, commit marker on object stores); ``latest_step`` asks
orbax whether a step directory is finalized, so a crash mid-save is never
selected for restore. Overwriting an existing step keeps the old
checkpoint as ``step_N.replaced`` until the new one is finalized.
:func:`recover_interrupted` cleans up after a crash mid-save (removes
partial writes, restores an orphaned ``.replaced`` backup whose original
vanished) and :func:`gc_old_steps` implements ``keep_last=N`` retention.

Pending async-save bookkeeping is scoped PER CHECKPOINT ROOT: two runs
(or two ``tmp_path`` tests) sharing one process never interleave each
other's deferred-backup cleanup — ``wait(path)`` finalizes and cleans one
root, ``wait()`` all of them.

Usage::

    state = TrainState(step=0, params=params, opt_state=opt_state)
    save(ckpt_dir, state)                       # atomic, collective
    state = restore(ckpt_dir, state)            # template = like-shaped state
    step = latest_step(ckpt_dir)                # None if no checkpoint
"""

import os
from typing import Any, NamedTuple, Optional

import jax

from distributed_dot_product_tpu.utils.comm import synchronize
from distributed_dot_product_tpu.utils.tracing import log_exception

__all__ = ['TrainState', 'save', 'restore', 'latest_step', 'wait',
           'gc_old_steps', 'recover_interrupted', 'CheckpointMismatchError']


class TrainState(NamedTuple):
    """Minimal training state: a step counter plus arbitrary pytrees.

    A NamedTuple (not a dataclass) so it is a pytree out of the box and
    orbax round-trips it without custom registration.
    """
    step: int
    params: Any
    opt_state: Any


class CheckpointMismatchError(ValueError):
    """A checkpoint exists but its on-disk tree does not match the restore
    template (typically: ``TrainState`` fields, the model architecture, or
    the optimizer changed since the checkpoint was written)."""


_CKPTR = None

# Fault-injection seam (see utils/faults.py): when set, called as
# ``hook(target_dir)`` at the top of ``save`` and may raise to simulate
# transient I/O failure or a crash mid-save. Never set in production.
_SAVE_FAULT_HOOK = None


def _checkpointer():
    # One long-lived checkpointer: StandardCheckpointer owns async-write
    # machinery (threads), so constructing one per call would leak it
    # across a training loop.
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _root(path):
    """The run directory as an epath.Path, absolutized for local paths
    (orbax requires absolute paths; URLs are absolute by construction)."""
    from etils import epath
    s = os.fspath(path)
    if '://' not in s:
        s = os.path.abspath(s)
    return epath.Path(s)


def _step_dir(path, step):
    return _root(path) / f'step_{step:09d}'


_FINALIZED_UTIL = None          # unresolved; False once known-absent


def _resolve_finalized_util():
    """The orbax is_checkpoint_finalized util, or False — resolved ONCE
    (a missing/renamed util is a permanent property of the installed
    orbax, not a per-call anomaly worth a metric per scanned dir)."""
    global _FINALIZED_UTIL
    if _FINALIZED_UTIL is None:
        try:
            from orbax.checkpoint import utils as ocp_utils
            _FINALIZED_UTIL = ocp_utils.is_checkpoint_finalized
        except (ImportError, AttributeError):
            _FINALIZED_UTIL = False
    return _FINALIZED_UTIL


def _is_finalized(path):
    util = _resolve_finalized_util()
    if util:
        try:
            return bool(util(path))
        except Exception as e:
            # A REAL probe failure (the util exists but raised) is
            # anomalous — unlike a merely-absent util, it is worth a
            # metric — and the structural fallback below still decides.
            log_exception('checkpoint.is_finalized_fallback', e)
    # Fallback when the orbax util is missing/renamed (or its probe
    # failed): never assume YES — a crash-truncated directory must not
    # be selected for restore. Orbax in-progress dirs carry an
    # '.orbax-checkpoint-tmp' suffix, and a finalized
    # StandardCheckpointer dir contains its metadata files; require
    # positive evidence of the latter.
    if '.orbax-checkpoint-tmp' in path.name:
        return False
    try:
        entries = {p.name for p in path.iterdir()}
    except OSError:
        return False
    return bool(entries & {'_CHECKPOINT_METADATA', '_METADATA'})


class _RootPending:
    """Deferred async-save state for ONE checkpoint root: overwrite
    backups whose removal waits for their save to finalize, and whether
    any async save against this root is outstanding (a non-overwrite
    async save leaves no backup but must still be waited on before the
    next save's filesystem inspection — its target directory may not
    exist yet)."""

    __slots__ = ('backups', 'async_pending')

    def __init__(self):
        self.backups = []
        self.async_pending = False


# Keyed by absolutized root path so e.g. two tmp_path test runs in one
# process never touch each other's deferred cleanup.
_PENDING_ROOTS = {}


def _pending(path) -> _RootPending:
    return _PENDING_ROOTS.setdefault(str(_root(path)), _RootPending())


def wait(path=None):
    """Block until outstanding ``save(..., blocking=False)`` writes have
    finalized, then remove the overwrite backups they deferred.

    ``path=None`` (the default) finalizes every root this process has
    saved to; passing a checkpoint root restricts the deferred-backup
    cleanup to that root (other roots' bookkeeping stays pending, to be
    cleaned by their own ``wait``/next ``save``). Collective on
    multi-host (same contract as ``save``). A no-op when nothing is
    pending.
    """
    states = ([_pending(path)] if path is not None
              else list(_PENDING_ROOTS.values()))
    if not any(st.async_pending or st.backups for st in states):
        return
    if _CKPTR is not None:
        # One shared checkpointer: this fences EVERY in-flight async save,
        # which is conservative but safe — only the selected roots'
        # bookkeeping is cleaned below.
        _CKPTR.wait_until_finished()
    synchronize()
    for st in states:
        if jax.process_index() == 0:
            for backup in st.backups:
                if backup.is_dir():
                    _resolve_backup(backup)
        st.backups.clear()
        st.async_pending = False


def _resolve_backup(backup):
    """Decide the fate of one ``step_N.replaced`` overwrite backup: if
    the replacement finalized, the backup is stale — remove it; if not
    (crash/failed flush mid-overwrite), the backup is the ONLY surviving
    copy of the step — remove the partial replacement and restore the
    backup. Shared by :func:`wait` and :func:`recover_interrupted`.
    Returns ``(action, name)`` pairs describing what was done."""
    orig = backup.parent / backup.name[:-len('.replaced')]
    if orig.is_dir() and _is_finalized(orig):
        backup.rmtree()
        return [('removed-stale-backup', backup.name)]
    actions = []
    if orig.is_dir():
        orig.rmtree()
        actions.append(('removed-partial', orig.name))
    backup.rename(orig)
    actions.append(('restored-backup', orig.name))
    return actions


def discard_pending(path):
    """Abandon the deferred bookkeeping for ``path`` WITHOUT touching
    disk. For use after a failed async flush: the write never finalized,
    so its overwrite backups must stay on disk (``recover_interrupted``
    restores them on the next run start); only the in-memory pending
    state is dropped so the caller can proceed to a fresh blocking save.
    """
    st = _pending(path)
    st.async_pending = False
    st.backups.clear()


def save(path, state: TrainState, *, force: bool = True,
         blocking: bool = True) -> str:
    """Write ``state`` under ``path/step_<step>/``; returns that directory.

    ``path``: POSIX directory or object-store URL (``gs://...``) — see
    the module docstring. Atomic: orbax writes to a temporary name and
    finalizes it afterwards. If the step already exists and ``force`` is
    set, the old checkpoint is kept as ``step_<step>.replaced`` until the
    new write finalizes, so a crash mid-overwrite never destroys the only
    copy of a step.

    ``blocking=False`` returns as soon as the device arrays are snapshot
    and lets orbax flush to storage in the background — the training loop
    keeps stepping while the previous checkpoint lands (call
    :func:`wait` before exiting, and note ``latest_step`` simply skips a
    still-unfinalized save). A new ``save`` first waits for any pending
    one, so overlapping saves serialize instead of colliding.

    Collective on multi-host: every process must call this with its view
    of the same global arrays (directory juggling runs on process 0 only;
    process 0's filesystem view decides the overwrite branch for
    everyone).
    """
    if _SAVE_FAULT_HOOK is not None:
        _SAVE_FAULT_HOOK(_step_dir(path, int(state.step)))
    st = _pending(path)
    if st.async_pending:
        wait(path)
    target = _step_dir(path, int(state.step))
    backup = target.parent / (target.name + '.replaced')
    exists = target.is_dir()
    if jax.process_count() > 1:
        # Every process must take the same branch below (the orbax save is
        # collective; one process raising while others enter it would hang
        # at its barrier). Filesystem views can differ across hosts —
        # process 0's view decides for everyone.
        from jax.experimental import multihost_utils
        exists = bool(multihost_utils.broadcast_one_to_all(
            jax.numpy.asarray(exists)))
    if exists and not force:
        raise FileExistsError(
            f'{target} already exists; pass force=True to replace it')
    if exists and jax.process_index() == 0:
        if backup.is_dir():
            backup.rmtree()
        target.rename(backup)
    synchronize()
    ckptr = _checkpointer()
    ckptr.save(target, state)
    if not blocking:
        st.async_pending = True
        if exists:
            st.backups.append(backup)
        return os.fspath(target)
    ckptr.wait_until_finished()
    synchronize()
    if exists and jax.process_index() == 0 and backup.is_dir():
        backup.rmtree()
    return os.fspath(target)


def _finalized_steps(path):
    """Sorted list of steps with a finalized checkpoint under ``path``."""
    root = _root(path)
    if not root.is_dir():
        return []
    steps = []
    for child in root.iterdir():
        name = child.name
        if not name.startswith('step_') or name.endswith('.replaced'):
            continue
        try:
            step = int(name[len('step_'):])
        except ValueError:
            continue
        if _is_finalized(child):
            steps.append(step)
    return sorted(steps)


def latest_step(path) -> Optional[int]:
    """Highest step with a FINALIZED checkpoint under ``path``, or None —
    a crash mid-save leaves an unfinalized directory, which is skipped."""
    steps = _finalized_steps(path)
    return steps[-1] if steps else None


def gc_old_steps(path, keep_last: int):
    """Retention policy: delete all but the ``keep_last`` NEWEST finalized
    step directories (and their stale ``.replaced`` backups). Unfinalized
    (in-flight or crash-partial) directories are never touched — an async
    save still flushing must not lose its predecessor count. Returns the
    list of deleted step numbers. Collective on multi-host."""
    if keep_last is None or keep_last < 1:
        return []
    doomed = _finalized_steps(path)[:-keep_last]
    if doomed and jax.process_index() == 0:
        root = _root(path)
        for step in doomed:
            for suffix in ('', '.replaced'):
                victim = root / f'step_{step:09d}{suffix}'
                if victim.is_dir():
                    victim.rmtree()
    # Unconditional barrier: filesystem views can diverge across hosts
    # (a process listing AFTER process 0's deletions sees doomed=[]), so
    # gating the collective on the local listing would deadlock.
    synchronize()
    return doomed


def recover_interrupted(path):
    """Clean up after a crash mid-save, before resuming a run:

    - remove ``*.orbax-checkpoint-tmp*`` partial writes (a crash between
      orbax's temp write and its finalizing rename);
    - for each ``step_N.replaced`` backup: if ``step_N`` is missing or
      unfinalized (a crash mid-overwrite destroyed/never-finished the
      replacement), the backup is the only surviving copy — restore it
      to ``step_N``; otherwise the overwrite finalized and the stale
      backup is removed.

    Returns a list of ``(action, name)`` pairs describing what was done.
    Call only when no async save is in flight (run start, not mid-loop).
    Collective on multi-host (process 0 mutates, all synchronize).
    """
    root = _root(path)
    if not root.is_dir():
        return []
    actions = []
    if jax.process_index() == 0:
        for child in list(root.iterdir()):
            if '.orbax-checkpoint-tmp' in child.name:
                child.rmtree()
                actions.append(('removed-partial', child.name))
        for child in list(root.iterdir()):
            name = child.name
            if not (name.startswith('step_') and name.endswith('.replaced')):
                continue
            actions.extend(_resolve_backup(child))
    synchronize()
    return actions


def restore(path, template: TrainState, *, step: Optional[int] = None
            ) -> TrainState:
    """Restore the checkpoint at ``step`` (default: latest finalized)
    using ``template`` for structure/shardings: every restored array
    adopts the sharding of the corresponding template leaf, so resuming
    on a different mesh layout re-shards transparently.

    Raises :class:`CheckpointMismatchError` (with the step directory, the
    expected vs. on-disk tree structure, and a hint) instead of an opaque
    orbax error when the template does not match what is on disk.
    """
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f'no checkpoint under {path!r}')
    step_dir = _step_dir(path, step)
    try:
        restored = _checkpointer().restore(step_dir, template)
    except (KeyboardInterrupt, SystemExit):
        raise
    except OSError:
        # Transient I/O (permissions, network, missing files) is NOT a
        # structure mismatch: keep the original type so callers can
        # classify/retry it.
        raise
    except Exception as e:
        raise CheckpointMismatchError(
            _mismatch_message(step_dir, template, e)) from e
    # orbax returns the same pytree type; ensure the step is a python int
    # (templates often carry traced/array steps).
    return restored._replace(step=int(jax.device_get(restored.step)))


def _tree_summary(tree):
    """Compact, order-stable description of a pytree's structure: the
    key paths of its leaves (shapes elided — structure is what mismatches
    on a TrainState/model change)."""
    try:
        paths = [jax.tree_util.keystr(p)
                 for p, _ in jax.tree_util.tree_flatten_with_path(tree)[0]]
        return f'{len(paths)} leaves: ' + ', '.join(paths[:20]) + (
            ', ...' if len(paths) > 20 else '')
    except Exception as e:
        log_exception('checkpoint.tree_summary', e)
        return str(jax.tree.structure(tree))


def _mismatch_message(step_dir, template, err):
    found = 'unreadable'
    try:
        meta = _checkpointer().metadata(step_dir)
        if meta is not None:
            found = _tree_summary(meta)
    except Exception as e:
        # The mismatch diagnostic is best-effort ('unreadable' stands in)
        # but the metadata failure itself must stay observable.
        log_exception('checkpoint.mismatch_metadata', e)
    return (
        f'failed to restore checkpoint {step_dir}: the on-disk tree does '
        f'not match the restore template.\n'
        f'  expected (template): {_tree_summary(template)}\n'
        f'  found (on disk):     {found}\n'
        f'  hint: if TrainState fields, the model architecture, or the '
        f'optimizer changed since this checkpoint was written, restore '
        f'with a template built from the OLD structure (then migrate), '
        f'or start a fresh run directory.\n'
        f'  original error: {err}')
