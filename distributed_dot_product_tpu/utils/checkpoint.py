# -*- coding: utf-8 -*-
"""
Checkpoint / resume for sharded training state (orbax-backed).

The reference has NO checkpoint subsystem — its only use of ``state_dict``
is the rank-0 weight broadcast inside a test (SURVEY §5 "Checkpoint /
resume: none"; reference test_gradient.py:48), so a crashed multi-day run
restarts from scratch. This module closes that gap the TPU-native way:
`orbax.checkpoint` writes each device's shards in parallel (OCDBT), works
unchanged on one host or a multi-host pod (every process calls
``save``/``restore`` collectively), and restores arrays onto whatever
sharding the provided template carries — so a checkpoint taken on one mesh
can resume on another.

Durability: orbax finalizes a checkpoint only after all shards land
(rename on POSIX, commit marker on object stores); ``latest_step`` asks
orbax whether a step directory is finalized, so a crash mid-save is never
selected for restore. Overwriting an existing step keeps the old
checkpoint as ``step_N.replaced`` until the new one is finalized.

Usage::

    state = TrainState(step=0, params=params, opt_state=opt_state)
    save(ckpt_dir, state)                       # atomic, collective
    state = restore(ckpt_dir, state)            # template = like-shaped state
    step = latest_step(ckpt_dir)                # None if no checkpoint
"""

import os
import shutil
from typing import Any, NamedTuple, Optional

import jax

from distributed_dot_product_tpu.utils.comm import synchronize

__all__ = ['TrainState', 'save', 'restore', 'latest_step']


class TrainState(NamedTuple):
    """Minimal training state: a step counter plus arbitrary pytrees.

    A NamedTuple (not a dataclass) so it is a pytree out of the box and
    orbax round-trips it without custom registration.
    """
    step: int
    params: Any
    opt_state: Any


_CKPTR = None


def _checkpointer():
    # One long-lived checkpointer: StandardCheckpointer owns async-write
    # machinery (threads), so constructing one per call would leak it
    # across a training loop.
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _step_dir(path, step):
    return os.path.join(os.fspath(path), f'step_{step:09d}')


def _is_finalized(path):
    try:
        from orbax.checkpoint import utils as ocp_utils
        return bool(ocp_utils.is_checkpoint_finalized(path))
    except Exception:
        # Fallback if the orbax util is missing/renamed: never assume YES —
        # a crash-truncated directory must not be selected for restore.
        # Orbax in-progress dirs carry an '.orbax-checkpoint-tmp' suffix,
        # and a finalized StandardCheckpointer dir contains its metadata
        # files; require positive evidence of the latter.
        if '.orbax-checkpoint-tmp' in os.path.basename(os.fspath(path)):
            return False
        try:
            entries = set(os.listdir(path))
        except OSError:
            return False
        return bool(entries & {'_CHECKPOINT_METADATA', '_METADATA'})


def save(path, state: TrainState, *, force: bool = True) -> str:
    """Write ``state`` under ``path/step_<step>/``; returns that directory.

    Atomic: orbax writes to a temporary name and finalizes it afterwards.
    If the step already exists and ``force`` is set, the old checkpoint is
    kept as ``step_<step>.replaced`` until the new write finalizes, so a
    crash mid-overwrite never destroys the only copy of a step.

    Collective on multi-host: every process must call this with its view
    of the same global arrays (directory juggling runs on process 0 only).
    ``path`` must be a local/POSIX filesystem visible to process 0 — the
    backup rename dance uses ``os.rename``/``shutil.rmtree``; object-store
    URLs (``gs://`` etc.) are rejected up front (use orbax directly there).
    """
    if '://' in os.fspath(path):
        raise ValueError(
            f'save() supports POSIX paths only, got {path!r} — the '
            'overwrite-backup rename is a filesystem operation; for '
            'object stores call orbax.checkpoint directly')
    target = _step_dir(path, int(state.step))
    backup = target + '.replaced'
    exists = os.path.isdir(target)
    if jax.process_count() > 1:
        # Every process must take the same branch below (the orbax save is
        # collective; one process raising while others enter it would hang
        # at its barrier). Filesystem views can differ across hosts —
        # process 0's view decides for everyone.
        from jax.experimental import multihost_utils
        exists = bool(multihost_utils.broadcast_one_to_all(
            jax.numpy.asarray(exists)))
    if exists and not force:
        raise FileExistsError(
            f'{target} already exists; pass force=True to replace it')
    if exists and jax.process_index() == 0:
        if os.path.isdir(backup):
            shutil.rmtree(backup)
        os.rename(target, backup)
    synchronize()
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(target), state)
    ckptr.wait_until_finished()
    synchronize()
    if exists and jax.process_index() == 0 and os.path.isdir(backup):
        shutil.rmtree(backup)
    return target


def latest_step(path) -> Optional[int]:
    """Highest step with a FINALIZED checkpoint under ``path``, or None —
    a crash mid-save leaves an unfinalized directory, which is skipped."""
    path = os.fspath(path)
    if not os.path.isdir(path):
        return None
    steps = []
    for name in os.listdir(path):
        if not name.startswith('step_') or name.endswith('.replaced'):
            continue
        try:
            step = int(name[len('step_'):])
        except ValueError:
            continue
        if _is_finalized(os.path.join(path, name)):
            steps.append(step)
    return max(steps) if steps else None


def restore(path, template: TrainState, *, step: Optional[int] = None
            ) -> TrainState:
    """Restore the checkpoint at ``step`` (default: latest finalized)
    using ``template`` for structure/shardings: every restored array
    adopts the sharding of the corresponding template leaf, so resuming
    on a different mesh layout re-shards transparently.
    """
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f'no checkpoint under {path!r}')
    target = os.path.abspath(_step_dir(path, step))
    restored = _checkpointer().restore(target, template)
    # orbax returns the same pytree type; ensure the step is a python int
    # (templates often carry traced/array steps).
    return restored._replace(step=int(jax.device_get(restored.step)))
