# -*- coding: utf-8 -*-
"""
determlint: the seeded bit-reproducible-replay contract, machine-checked
— the servelint family guarding the virtual-clock tick paths.

The serving layer's replay story (loadgen → scheduler → router) rests on
one invariant: inside a tick path, every observable value derives from
the injected clock and the seeded trace, never from the host's wall
clock, the ``random`` module, or the process environment. A single
``time.time()`` in a tick path silently turns "same seed, identical
goodput report" into "same seed, usually identical".

Mechanics:

- A module DECLARES its tick roots with a module-level literal::

      GRAPHLINT_TICK_ROOTS = ('Scheduler.step', 'Scheduler.submit')

  (function names, or ``Class.method`` qualnames). determlint computes
  the intra-module call closure of those roots — ``self._foo()`` to
  methods of the same class, bare calls to module functions — and
  flags, anywhere in the closure:

  * real-time reads: ``time.time/monotonic/perf_counter/process_time``
    and ``time.sleep`` (a sleep additionally blocks the loop);
  * ``random.*`` and ``np.random.*`` calls (unseeded host randomness —
    seeded generators are constructed OUTSIDE the tick and passed in);
  * ``os.environ`` reads / ``os.getenv`` (config resolution belongs at
    construction time, where it is recorded, not per tick).

- Modules that are intentionally REAL-TIME (the health watchdog judges
  liveness in wall time by contract; devmon polls; flight throttles;
  anomaly cooldowns) are declared in :data:`REAL_TIME_CONTRACT` below —
  a per-module table with reasons, not scattered pragmas. ``'*'``
  exempts the whole module (it must then declare no tick roots);
  a ``{qualname: reason}`` dict waives individual functions inside a
  tick closure (the scheduler's step-duration histogram measures the
  REAL cost of the compiled step — that is the point of the metric).

- Any module that declares tick roots is additionally swept for
  ``time.sleep`` OUTSIDE the closure too: a sleep anywhere in a
  tick-path module stalls the loop that module drives.

Suppression: the contract table is the intended mechanism; a trailing
``# graphlint: allow[tick-determinism]`` pragma still works for
one-off sites (see analysis/base.py).
"""

import ast
import os

from distributed_dot_product_tpu.analysis.base import (
    Violation, allowed_by_pragma,
)

__all__ = ['DETERM_RULES', 'REAL_TIME_CONTRACT', 'lint_file',
           'lint_paths']

DETERM_RULES = ('tick-determinism',)

_SCOPE_FRAGMENTS = ('distributed_dot_product_tpu' + os.sep,
                    'graphlint_fixtures')

# The per-module real-time contract (repo-relative path suffix, '/'
# separators). '*' = the whole module is real-time BY DESIGN (it must
# not declare tick roots); {qualname: reason} = these functions inside
# a tick closure may read real time, for the stated reason. This table
# is the allowlist the README documents — adding to it is a reviewed
# design decision, not a pragma sprinkle.
REAL_TIME_CONTRACT = {
    'serve/health.py': '*',     # the watchdog judges liveness in REAL
    #   time independently of the scheduler clock — a virtual-clock
    #   test must not self-trigger stalls (module docstring contract)
    'obs/devmon.py': '*',       # device polling + profiler capture
    #   windows are wall-time by nature
    'obs/flight.py': '*',       # ring sample throttle and per-trigger
    #   dump cooldowns are REAL seconds (storm control)
    'obs/anomaly.py': '*',      # detector tick throttle and breach
    #   cooldowns are REAL seconds
    'obs/spans.py': '*',        # spans measure host wall time — that
    #   is their one job
    'serve/scheduler.py': {
        'Scheduler._step_impl':
            'serve.step_seconds measures the REAL cost of the compiled '
            'decode dispatch (time.perf_counter) — virtual ticks would '
            'record the simulation, not the hardware',
        'Scheduler._maybe_profile':
            'the adaptive-profile cooldown is REAL time by design '
            '(captures are real however the scheduler clock runs)',
    },
    'serve/loadgen.py': {
        'run_trace':
            'wall_seconds is reporting-only wall-clock accounting '
            '(time.perf_counter) — it never feeds control flow or the '
            'virtual timeline',
    },
    'serve/router.py': {
        'Router._handoff':
            'the prefill.handoff build/transfer split measures the '
            'REAL cost of KV compute vs page movement '
            '(time.perf_counter) — reporting-only additive event '
            'fields, never control flow or the virtual timeline',
    },
}

_TIME_FNS = {'time', 'monotonic', 'sleep', 'perf_counter',
             'process_time', 'thread_time'}


def _module_key(rel):
    """Normalized '/'-separated repo-relative path for table lookup."""
    return rel.replace(os.sep, '/')


def _contract_for(rel):
    key = _module_key(rel)
    for suffix, entry in REAL_TIME_CONTRACT.items():
        if key.endswith(suffix):
            return entry
    return None


def _tick_roots(tree):
    """``(roots, bad_lineno)``: the module's ``GRAPHLINT_TICK_ROOTS``
    literal, or ``((), lineno)`` when the declaration exists but is not
    a literal — the caller reports that, because a computed declaration
    silently disabling the whole check would be the worst failure mode
    this rule can have."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) \
                        and tgt.id == 'GRAPHLINT_TICK_ROOTS':
                    try:
                        val = ast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        return (), node.lineno
                    return tuple(str(v) for v in val), None
    return (), None


def _functions_by_qualname(tree):
    """``{qualname: FunctionDef}`` for module functions and class
    methods (one level of class nesting — the shape this codebase
    uses)."""
    out = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    out[f'{node.name}.{sub.name}'] = sub
    return out


def _callees(qualname, fn_node, functions):
    """Intra-module callees of one function: ``self._foo()`` resolves
    into the same class, bare ``foo()`` into module functions."""
    cls = qualname.split('.')[0] if '.' in qualname else None
    found = set()
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (cls is not None and isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == 'self'):
            cand = f'{cls}.{fn.attr}'
            if cand in functions:
                found.add(cand)
        elif isinstance(fn, ast.Name) and fn.id in functions:
            found.add(fn.id)
    return found


def _closure(roots, functions):
    """Transitive intra-module call closure of the declared roots."""
    seen, stack = set(), [r for r in roots if r in functions]
    while stack:
        qn = stack.pop()
        if qn in seen:
            continue
        seen.add(qn)
        stack.extend(_callees(qn, functions[qn], functions))
    return seen


def _nondeterministic_call(node):
    """(kind, detail) when ``node`` is a forbidden call, else None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        root = fn.value.id if isinstance(fn.value, ast.Name) else None
        inner = (fn.value.attr if isinstance(fn.value, ast.Attribute)
                 else None)
        inner_root = (fn.value.value.id
                      if isinstance(fn.value, ast.Attribute)
                      and isinstance(fn.value.value, ast.Name) else None)
        if root == 'time' and fn.attr in _TIME_FNS:
            return ('real-time read', f'time.{fn.attr}()')
        if root == 'random':
            return ('host randomness', f'random.{fn.attr}()')
        if inner == 'random' and inner_root in ('np', 'numpy'):
            return ('host randomness', f'{inner_root}.random.{fn.attr}()')
        if root == 'os' and fn.attr == 'getenv':
            return ('environment read', 'os.getenv()')
        if (fn.attr == 'get' and inner == 'environ'
                and inner_root == 'os'):
            return ('environment read', 'os.environ.get()')
    return None


def _environ_subscript(node):
    """``os.environ[...]`` reads (not calls)."""
    return (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == 'environ'
            and isinstance(node.value.value, ast.Name)
            and node.value.value.id == 'os')


def lint_file(path, repo_root=None, rules=None):
    """Run determlint over one file; returns a Violation list. Files
    outside the package / fixture scope, and modules declared wholly
    real-time in :data:`REAL_TIME_CONTRACT`, return []."""
    rules = set(rules or DETERM_RULES)
    if 'tick-determinism' not in rules:
        return []
    rel = (os.path.relpath(path, repo_root) if repo_root
           else os.fspath(path))
    if not any(frag in rel for frag in _SCOPE_FRAGMENTS):
        return []
    with open(path, encoding='utf-8') as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError:
        return []       # astlint owns parse-error reporting
    roots, bad_decl = _tick_roots(tree)
    contract = _contract_for(rel)
    out = []
    lines = src.splitlines()
    if bad_decl is not None:
        return [Violation(
            rule='tick-determinism', file=rel, line=bad_decl,
            message='GRAPHLINT_TICK_ROOTS must be a literal tuple/list '
                    'of function qualnames — a computed declaration '
                    'cannot be read statically and would silently '
                    'disable determinism checking for this module')]
    if contract == '*':
        if roots:
            out.append(Violation(
                rule='tick-determinism', file=rel, line=1,
                message=f'{_module_key(rel)} declares tick roots '
                        f'{roots} but is listed as wholly real-time in '
                        f'REAL_TIME_CONTRACT — a module cannot be '
                        f'both; fix the contract table'))
        return out
    if not roots:
        return []
    allow = contract if isinstance(contract, dict) else {}
    functions = _functions_by_qualname(tree)
    unknown = [r for r in roots if r not in functions]
    for r in unknown:
        out.append(Violation(
            rule='tick-determinism', file=rel, line=1,
            message=f'GRAPHLINT_TICK_ROOTS names {r!r} but no such '
                    f'function/method exists in this module — the '
                    f'declaration rotted'))
    closure = _closure(roots, functions)

    def flag(node, qualname, kind, detail):
        if allowed_by_pragma(lines, node.lineno, 'tick-determinism'):
            return
        out.append(Violation(
            rule='tick-determinism', file=rel, line=node.lineno,
            message=f'{detail}: {kind} inside the virtual-clock tick '
                    f'path ({qualname}, reachable from '
                    f'{"/".join(sorted(roots))}) breaks seeded '
                    f'bit-reproducible replay — derive it from the '
                    f'injected clock/trace, hoist it to construction '
                    f'time, or add a reviewed REAL_TIME_CONTRACT entry'))

    for qualname in sorted(closure):
        if qualname in allow:
            continue
        fn_node = functions[qualname]
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Call):
                hit = _nondeterministic_call(node)
                if hit:
                    flag(node, qualname, *hit)
            elif _environ_subscript(node):
                flag(node, qualname, 'environment read',
                     'os.environ[...]')

    # Module-wide sleep sweep: a sleep ANYWHERE in a tick-path module
    # stalls the loop that module drives, closure or not.
    flagged = {v.line for v in out}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == 'sleep'
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == 'time'
                and node.lineno not in flagged
                and not allowed_by_pragma(lines, node.lineno,
                                          'tick-determinism')):
            out.append(Violation(
                rule='tick-determinism', file=rel, line=node.lineno,
                message='time.sleep() in a module that declares '
                        'virtual-clock tick roots blocks the serving '
                        'loop — use the injected clock / event waits'))
    return out


def lint_paths(paths, repo_root=None, rules=None):
    from distributed_dot_product_tpu.analysis.astlint import (
        iter_python_files,
    )
    out = []
    for path in iter_python_files(paths):
        out.extend(lint_file(path, repo_root=repo_root, rules=rules))
    return out
