# -*- coding: utf-8 -*-
"""
Retrace sentinel: trace-count budgets for jitted serving/decode
entrypoints.

The hazard class this automates: a jitted per-token step that silently
re-traces every call. One concrete instance already happened here — an
unhashable module field made ``decode_seq_parallel`` rebuild and
re-trace its compiled step EVERY token (caught by hand in round 5, see
ADVICE.md; the LRU step cache + warn-once in models/attention.py is the
fix). Nothing mechanical guarded against the next instance: a retrace
storm shows up only as mysterious slowness, because each trace produces
a *correct* program.

The sentinel closes that gap. Wrap the **pre-jit python callable** with
:func:`watch_traces` — ``jax.jit`` executes the wrapped body exactly
once per cache miss, so the wrapper's call count IS the trace count —
and the wrapper raises :class:`RetraceBudgetExceeded` the moment a
function traces more often than its declared budget.

Enablement: the ``DDP_TPU_RETRACE_SENTINEL`` env var (1/0). Unset, the
sentinel is ON under pytest (``PYTEST_CURRENT_TEST`` present — every
decode/serve suite then runs under its budgets, which is the point:
retrace storms become test failures, not perf mysteries) and OFF
otherwise (production keeps counting — the counters are cheap and
:func:`snapshot` exposes them — but never raises).

Budget semantics: a budget of ``n`` allows ``n`` traces over the
wrapper's lifetime. Per-token loops own ONE wrapper per compiled step
(e.g. ``make_decode_step`` wraps at build time), so legitimate
shape-driven retraces of a *new* step get a fresh budget while the
per-token storm on a single step trips immediately.
"""

import functools
import os
import threading
import weakref

__all__ = ['RetraceBudgetExceeded', 'TraceCounter', 'watch_traces',
           'sentinel_enabled', 'snapshot', 'total', 'totals', 'reset',
           'ENV_VAR']

ENV_VAR = 'DDP_TPU_RETRACE_SENTINEL'


class RetraceBudgetExceeded(RuntimeError):
    """A watched entrypoint traced more often than its declared budget."""


def sentinel_enabled():
    """Raise-on-exceed policy: the env var wins; unset, on under pytest
    (so the suites enforce budgets) and off elsewhere (counters still
    count — see :func:`snapshot`)."""
    v = os.environ.get(ENV_VAR)
    if v is not None:
        return v.strip().lower() in ('1', 'true', 'on', 'yes')
    return 'PYTEST_CURRENT_TEST' in os.environ


class TraceCounter:
    """Count + budget for one watched callable (thread-safe: serving
    watchdog threads may trigger traces)."""

    __slots__ = ('name', 'budget', 'count', '_lock', '__weakref__')

    def __init__(self, name, budget):
        if budget < 1:
            raise ValueError(f'trace budget must be >= 1, got {budget}')
        self.name = name
        self.budget = budget
        self.count = 0
        self._lock = threading.Lock()

    def __del__(self):
        # Fold the final count into the per-name retired total so
        # total() stays exact however the GC times wrapper teardown
        # (the rebuild-storm path discards one wrapper per token).
        try:
            with _COUNTERS_LOCK:
                _RETIRED[self.name] = (_RETIRED.get(self.name, 0)
                                       + self.count)
        except Exception:  # graphlint: allow[silent-except]
            pass           # interpreter shutdown: globals may be gone

    def hit(self):
        with self._lock:
            self.count += 1
            count = self.count
        if count > self.budget and sentinel_enabled():
            raise RetraceBudgetExceeded(
                f'retrace budget exceeded: {self.name!r} traced {count} '
                f'times (budget {self.budget}). A jitted decode/serve '
                f'step re-tracing per call is a silent throughput '
                f'collapse — hold ONE compiled step across calls (check '
                f'for unhashable static args, python-object keys, or a '
                f'step rebuilt inside the token loop).')


# Counter registry for snapshot()/total()/reset() and the pytest
# fixture: WEAK references, so a counter lives exactly as long as its
# wrapper (the pathological case the sentinel observes — a step rebuilt
# per token — discards one wrapper per token; holding them strongly
# here would turn the observer into its own leak). A dying counter
# folds its count into the per-name _RETIRED total (TraceCounter.
# __del__), so total() is exact regardless of GC timing, and reset()
# always reaches every counter that could still raise.
_COUNTERS = []                   # weakref.ref(TraceCounter)
_RETIRED = {}                    # name -> folded count from dead
_COUNTERS_LOCK = threading.Lock()


def _live_counters():
    """Strong refs to the live counters; prunes dead weakrefs in place.
    Callers must hold _COUNTERS_LOCK."""
    live, refs = [], []
    for ref in _COUNTERS:
        c = ref()
        if c is not None:
            live.append(c)
            refs.append(ref)
    _COUNTERS[:] = refs
    return live


def watch_traces(fn, name, budget=2):
    """Wrap a **pre-jit** python callable so every trace of the jitted
    result counts against ``budget``. Returns the wrapped callable;
    pass THAT to ``jax.jit`` / ``shard_map``::

        step = jax.jit(watch_traces(step_fn, 'decode_step', budget=2))

    The counter rides the wrapper as ``_graphlint_counter`` (tests and
    budget assertions read it)."""
    counter = TraceCounter(name, budget)
    with _COUNTERS_LOCK:
        _live_counters()             # prune dead refs opportunistically
        _COUNTERS.append(weakref.ref(counter))

    @functools.wraps(fn)
    def counted(*args, **kwargs):
        counter.hit()
        return fn(*args, **kwargs)

    counted._graphlint_counter = counter
    return counted


def snapshot():
    """``{name: (count, budget)}`` over every live counter (names can
    repeat across instances; later registrations win the key — use the
    per-wrapper ``_graphlint_counter`` for exact assertions)."""
    with _COUNTERS_LOCK:
        return {c.name: (c.count, c.budget) for c in _live_counters()}


def total(name):
    """Cumulative trace count across EVERY counter registered under
    ``name`` (live + folded-at-death). Per-instance budgets can't see
    the rebuild-storm variant (a step rebuilt per token gets a fresh
    counter each time — each counts 1); the name total exposes it: N
    tokens through a properly cached step total 1 trace, through a
    rebuilt-per-token step they total N. tests/test_graphlint.py pins
    both numbers for decode_seq_parallel's LRU step cache."""
    with _COUNTERS_LOCK:
        return (_RETIRED.get(name, 0)
                + sum(c.count for c in _live_counters()
                      if c.name == name))


def totals():
    """``{name: cumulative count}`` over EVERY name ever watched —
    live counters plus the folded-at-death totals. Unlike
    :func:`snapshot` (live only), the key set is stable across GC
    timing, which is what lets a before/after diff of this mapping
    (obs/perf.py's snapshot accounting) be deterministic regardless of
    what the process traced — and retired — earlier."""
    with _COUNTERS_LOCK:
        out = dict(_RETIRED)
        for c in _live_counters():
            out[c.name] = out.get(c.name, 0) + c.count
        return out


def reset():
    """Zero every live counter and the folded totals (test isolation —
    the pytest fixture calls this so one test's traces never charge
    another's budget; weak registration means every counter that could
    still raise is reachable here)."""
    with _COUNTERS_LOCK:
        for c in _live_counters():
            c.count = 0
        _RETIRED.clear()
