# -*- coding: utf-8 -*-
"""
protolint: static checks of the event-log PROTOCOL at every emit call
site — the servelint family that turns an EVENT_SCHEMA violation from
a ``ValueError`` mid-incident into a lint error at PR time.

``obs/events.py`` owns the closed vocabulary (:data:`EVENT_SCHEMA`) and
already validates every record at runtime; this module checks the same
contract *statically* against the call sites sprinkled through serve/,
obs/, utils/ and the train loop. Three rules:

- ``event-vocab``   — a LITERAL event kind passed to ``emit(...)`` /
  ``log.emit(...)`` / ``self._emit(...)`` must exist in EVENT_SCHEMA.
- ``event-fields``  — when the payload is statically complete (keyword
  arguments only, no ``**kwargs`` forwarding), every field the schema
  requires for that kind must be present. ``_log=`` is transport, not
  payload.
- ``reject-reason`` — the ``reason`` of a ``serve.reject`` must be a
  :class:`~distributed_dot_product_tpu.serve.admission.RejectReason`
  member: a literal string must be one of the enum VALUES, and a
  ``RejectReason.X`` attribute must name a real member and end in
  ``.value`` (emitting the enum object would serialize as its repr).

Scope: the package itself (``distributed_dot_product_tpu/``) plus the
negative-fixture tree (``graphlint_fixtures``) when its files are named
explicitly — tests legitimately emit malformed events on purpose to
exercise the runtime validator, so tests/ stays out of the sweep.

The schema and the enum are imported at lint time from the modules that
own them — the write-side contract, the offline validator and this
linter can never drift apart.

Suppression: ``# graphlint: allow[<rule>]`` on the line or the line
above (see analysis/base.py).
"""

import ast
import os

from distributed_dot_product_tpu.analysis.base import (
    Violation, allowed_by_pragma,
)

__all__ = ['PROTO_RULES', 'lint_file', 'lint_paths']

PROTO_RULES = ('event-vocab', 'event-fields', 'reject-reason')

# Files protolint judges: the package plus explicitly-named fixtures.
# The analysis subtree is excluded — its AST checkers have their own
# internal `_emit(rule, ...)` helpers that are not event emits.
_SCOPE_FRAGMENTS = ('distributed_dot_product_tpu' + os.sep,
                    'graphlint_fixtures')
_EXCLUDE_FRAGMENTS = ('distributed_dot_product_tpu' + os.sep
                      + 'analysis' + os.sep,)

# Transport-level keywords of the emit surfaces — never payload fields.
_TRANSPORT_KWARGS = {'_log'}


def _schema():
    """The closed vocabulary, read from its owner at lint time."""
    from distributed_dot_product_tpu.obs.events import EVENT_SCHEMA
    return EVENT_SCHEMA


def _reject_reasons():
    """``{member_name: value}`` of the typed-reject taxonomy."""
    from distributed_dot_product_tpu.serve.admission import RejectReason
    return {r.name: r.value for r in RejectReason}


def _is_emit_call(node):
    """``emit('kind', ...)`` / ``<anything>.emit('kind', ...)`` /
    ``self._emit('kind', ...)`` with a LITERAL first argument — the
    wrapper definitions themselves forward a variable and are never
    judged."""
    fn = node.func
    name = (fn.id if isinstance(fn, ast.Name)
            else fn.attr if isinstance(fn, ast.Attribute) else None)
    if name not in ('emit', '_emit'):
        return False
    return (bool(node.args)
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str))


def _attr_chain(node):
    """Dotted name of an attribute expression (``RejectReason.X.value``
    → ``['RejectReason', 'X', 'value']``), or None when any link is not
    a plain Name/Attribute."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return list(reversed(parts))


def _check_reject_reason(node, kw, reasons, emitv):
    """Judge the ``reason=`` keyword of a serve.reject emit."""
    val = kw.value
    if isinstance(val, ast.Constant):
        if isinstance(val.value, str) and val.value not in reasons.values():
            emitv('reject-reason', val,
                  f'serve.reject reason {val.value!r} is not a '
                  f'RejectReason value — the typed-reject taxonomy is '
                  f'{sorted(reasons.values())}')
        return
    chain = _attr_chain(val)
    if not chain or 'RejectReason' not in chain:
        return      # a variable / expression: runtime validation owns it
    i = chain.index('RejectReason')
    tail = chain[i + 1:]
    if not tail or tail[0] not in reasons:
        emitv('reject-reason', val,
              f'RejectReason has no member '
              f'{tail[0] if tail else "<none>"!r}')
    elif tail[-1] != 'value':
        emitv('reject-reason', val,
              f'serve.reject reason must emit RejectReason.'
              f'{tail[0]}.value — the bare enum member would '
              f'serialize as its repr, not the typed string')


def lint_file(path, repo_root=None, rules=None):
    """Run the protolint ruleset over one file; returns a Violation
    list. Files outside the package / fixture scope return []."""
    rules = set(rules or PROTO_RULES)
    rel = (os.path.relpath(path, repo_root) if repo_root
           else os.fspath(path))
    if not any(frag in rel for frag in _SCOPE_FRAGMENTS) \
            or any(frag in rel for frag in _EXCLUDE_FRAGMENTS):
        return []
    with open(path, encoding='utf-8') as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError:
        return []       # astlint owns parse-error reporting
    lines = src.splitlines()
    schema = _schema()
    reasons = _reject_reasons()
    out = []

    def emitv(rule, node, msg):
        if rule in rules and not allowed_by_pragma(lines, node.lineno,
                                                   rule):
            out.append(Violation(rule=rule, message=msg, file=rel,
                                 line=node.lineno))

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_emit_call(node)):
            continue
        kind = node.args[0].value
        if kind not in schema:
            emitv('event-vocab', node,
                  f'emit of unknown event kind {kind!r} — the closed '
                  f'vocabulary is EVENT_SCHEMA (obs/events.py); this '
                  f'call raises ValueError at runtime')
            continue        # field checks need a known kind
        kwargs = {kw.arg for kw in node.keywords if kw.arg is not None}
        has_star = (any(kw.arg is None for kw in node.keywords)
                    or len(node.args) > 1)
        required = set(schema[kind])
        missing = required - (kwargs - _TRANSPORT_KWARGS)
        if missing and not has_star:
            emitv('event-fields', node,
                  f'emit of {kind!r} is missing required field'
                  f'{"s" if len(missing) != 1 else ""} '
                  f'{sorted(missing)} (EVENT_SCHEMA) — this call '
                  f'raises ValueError at runtime')
        if kind == 'serve.reject':
            for kw in node.keywords:
                if kw.arg == 'reason':
                    _check_reject_reason(node, kw, reasons, emitv)
    return out


def lint_paths(paths, repo_root=None, rules=None):
    from distributed_dot_product_tpu.analysis.astlint import (
        iter_python_files,
    )
    out = []
    for path in iter_python_files(paths):
        out.extend(lint_file(path, repo_root=repo_root, rules=rules))
    return out
