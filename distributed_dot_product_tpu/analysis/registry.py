# -*- coding: utf-8 -*-
"""
Central entrypoint registry: the single place where every public
computation of the package declares *example abstract shapes and
meshes* so the jaxpr linter (analysis/jaxpr_rules.py) can trace it
without running it.

The shapes live NEXT TO the code they describe: each layer module
(``ops/functions.py``, ``ops/pallas_attention.py``,
``models/attention.py``, ``models/decode.py``, ``models/lm.py``,
``serve/engine.py``, ``train.py``) exposes a ``graphlint_entrypoints()``
hook returning ``{name: builder}``; this module aggregates them. A new
public entrypoint ships with its registration in the same diff, and the
tier-1 gate test (tests/test_graphlint.py) fails if any registered
entrypoint violates a rule — that is how the contracts survive growth.

Builders are lazy (constructing flax params or meshes costs real work)
and run on whatever devices are visible; mesh-using entries need >= 2
devices (the CLI forces an 8-device CPU platform — see
analysis/__main__.py — and the test suite already runs on one).

Precision convention for examples: every projection matmul is the
OWNED dense (models/dense.py — explicit ``preferred_element_type``
accumulation), so module-level entries register at the serving dtype
(bf16, plus int8-weight twins) right alongside the raw-op entries
(flash kernels, decode steps, the LM head einsum) — the
fp32/i32-accumulation contract is enforced end to end with zero
waivers (the flax ``linen.Dense`` debt that used to force f32
registration is retired).
"""

import dataclasses
from collections import OrderedDict
from typing import Any, Callable, Optional, Tuple

__all__ = ['TraceSpec', 'default_entrypoints', 'resolve_registry_arg',
           'LAYER_HOOKS']


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """One traceable entrypoint example.

    ``fn``/``args``: the callable and example arguments (concrete
    arrays or ShapeDtypeStructs — tracing never executes).
    ``mesh_axes``: mesh axis names this entrypoint is DECLARED to run
    over; collectives naming anything else violate ``collective-axis``.
    ``cache_in``/``cache_out``: identity selectors — given ``args`` /
    the ``eval_shape`` output, return the cache-buffer leaves, pairwise
    aligned — driving ``cache-alias`` and ``cache-upcast``.
    ``expect_donation``: run the ``donation`` rule. ``prejitted``: the
    fn already carries its jit (lower it directly); otherwise the rule
    jits with ``donate_argnums``. ``min_donated``: least number of
    aliased/donor arguments the lowered module must show.
    ``allow``: rule ids whose violations on THIS entry are known,
    documented debt — reported with ``allowed=True`` (visible in
    ``--format json``) but never failing the CLI or the gate. The
    registration line should carry a matching ``# graphlint:
    allow[...]`` comment so the waiver stays greppable. Currently
    UNUSED: the last waivers (the flax ``linen.Dense``
    bf16-accumulation debt) were retired by the owned dense
    (models/dense.py), and the gate test asserts the waiver set stays
    empty — adding one is a reviewed decision, not a default.
    """
    name: str
    fn: Callable
    args: Tuple[Any, ...]
    mesh_axes: Tuple[str, ...] = ()
    cache_in: Optional[Callable] = None
    cache_out: Optional[Callable] = None
    expect_donation: bool = False
    prejitted: bool = False
    donate_argnums: Tuple[int, ...] = ()
    static_argnums: Tuple[int, ...] = ()
    min_donated: int = 1
    allow: Tuple[str, ...] = ()

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)


# (module path, hook name) for every layer that registers entrypoints.
LAYER_HOOKS = (
    'distributed_dot_product_tpu.ops.functions',
    'distributed_dot_product_tpu.ops.pallas_attention',
    'distributed_dot_product_tpu.models.attention',
    'distributed_dot_product_tpu.models.decode',
    'distributed_dot_product_tpu.models.lm',
    'distributed_dot_product_tpu.serve.engine',
    'distributed_dot_product_tpu.train',
    'distributed_dot_product_tpu.obs',
)


def resolve_registry_arg(arg):
    """``MODULE:ATTR`` → a ``{name: builder}`` mapping (callables are
    called) — the shared ``--registry`` escape hatch of the graphlint
    and perf CLIs, in one place so the contract cannot drift. Raises
    ValueError on a malformed argument."""
    import importlib
    modpath, _, attr = arg.partition(':')
    if not attr:
        raise ValueError('--registry takes MODULE:ATTR')
    obj = getattr(importlib.import_module(modpath), attr)
    return obj() if callable(obj) else obj


def default_entrypoints():
    """Aggregate every layer's ``graphlint_entrypoints()`` hook into one
    ordered ``{name: builder}`` registry. Name collisions are an error —
    the registry is the namespace the gate test and CLI report against."""
    import importlib
    registry = OrderedDict()
    for modpath in LAYER_HOOKS:
        mod = importlib.import_module(modpath)
        hook = getattr(mod, 'graphlint_entrypoints', None)
        if hook is None:
            raise AttributeError(
                f'{modpath} is listed in LAYER_HOOKS but defines no '
                f'graphlint_entrypoints() hook')
        for name, builder in hook().items():
            if name in registry:
                raise ValueError(f'duplicate entrypoint registration: '
                                 f'{name!r} (from {modpath})')
            registry[name] = builder
    return registry
