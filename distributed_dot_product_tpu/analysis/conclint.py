# -*- coding: utf-8 -*-
"""
conclint: lock-discipline and thread-discipline for the serving/obs
concurrency surface — the servelint family that machine-checks the
convention the EventLog tee, SpanCollector, MetricsRegistry and
HealthMonitor already follow by hand.

- ``guarded-by`` — a field ANNOTATED at its assignment site with a
  trailing ``# guarded-by: self._lock`` comment may only be read or
  written inside a ``with self._lock:`` block of the same class.
  Exemptions, by convention:

  * ``__init__`` (construction happens before the object is shared);
  * methods whose name ends in ``_locked`` (the caller holds the lock
    — ``EventLog._rotate_locked`` is the canonical case);
  * an explicit ``# graphlint: allow[guarded-by]`` pragma for the
    deliberate torn-read sites (the scheduler's watchdog-thread
    introspection documents exactly why it reads without locks).

  The annotation is declarative: it rides the line that assigns the
  field (usually in ``__init__``), so the lock contract lives NEXT TO
  the state it protects and a new method touching the field off-lock
  fails CI instead of racing in production.

- ``thread-discipline`` — every ``threading.Thread(...)`` construction
  must pass ``daemon=True`` (a non-daemon worker blocks interpreter
  shutdown when a compiled step wedges — the exact situation the
  watchdog exists for) and a ``name=`` (anonymous threads are
  unidentifiable in the flight recorder's ``stacks.json``).

Scope: the package (``distributed_dot_product_tpu/``) plus explicitly
named ``graphlint_fixtures`` files — tests spawn short-lived helper
threads that legitimately join before teardown.

Suppression: ``# graphlint: allow[<rule>]`` on the line or the line
above (see analysis/base.py).
"""

import ast
import os
import re

from distributed_dot_product_tpu.analysis.base import (
    Violation, allowed_by_pragma,
)

__all__ = ['CONC_RULES', 'lint_file', 'lint_paths']

CONC_RULES = ('guarded-by', 'thread-discipline')

_SCOPE_FRAGMENTS = ('distributed_dot_product_tpu' + os.sep,
                    'graphlint_fixtures')

_GUARDED_BY = re.compile(r'#\s*guarded-by:\s*(self\.[A-Za-z_][\w.]*)')


def _annotations(cls_node, lines):
    """``{field: lock_expr}`` from ``self.<field> = ...`` assignment
    lines carrying a ``# guarded-by:`` comment anywhere in the class
    body (typically ``__init__``)."""
    guarded = {}
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        else:
            continue
        m = _GUARDED_BY.search(lines[node.lineno - 1]) \
            if node.lineno <= len(lines) else None
        if not m:
            continue
        for tgt in targets:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == 'self'):
                guarded[tgt.attr] = m.group(1)
    return guarded


class _LockScopeChecker(ast.NodeVisitor):
    """Walk one method tracking which annotated locks are held (via
    ``with self._lock:`` nesting) and flag annotated-field accesses
    made while their lock is not."""

    def __init__(self, guarded, rel, lines, out):
        self.guarded = guarded          # field -> lock expr string
        self.rel = rel
        self.lines = lines
        self.out = out
        self.held = []                  # stack of held lock exprs

    # A function DEFINED inside a `with self._lock:` block does not
    # RUN there — the classic deferred race is exactly a closure built
    # under the lock and executed later as a thread target. Its body
    # is judged with an empty held stack.
    def visit_FunctionDef(self, node):
        inner = _LockScopeChecker(self.guarded, self.rel, self.lines,
                                  self.out)
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        inner = _LockScopeChecker(self.guarded, self.rel, self.lines,
                                  self.out)
        inner.visit(node.body)

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            try:
                expr = ast.unparse(item.context_expr)
            except Exception:   # graphlint: allow[silent-except] ast-only
                expr = ''
            acquired.append(expr)
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(acquired):]

    visit_AsyncWith = visit_With

    def visit_Attribute(self, node):
        if (isinstance(node.value, ast.Name) and node.value.id == 'self'
                and node.attr in self.guarded
                and self.guarded[node.attr] not in self.held
                and not allowed_by_pragma(self.lines, node.lineno,
                                          'guarded-by')):
            lock = self.guarded[node.attr]
            kind = ('write' if isinstance(node.ctx,
                                          (ast.Store, ast.Del))
                    else 'read')
            self.out.append(Violation(
                rule='guarded-by', file=self.rel, line=node.lineno,
                message=f'{kind} of self.{node.attr} (annotated '
                        f'guarded-by: {lock}) outside a `with {lock}:` '
                        f'block — another thread can observe torn '
                        f'state; take the lock or rename the method '
                        f'*_locked if the caller holds it'))
        self.generic_visit(node)


def _check_guarded(tree, rel, lines, out):
    for cls in [n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef)]:
        guarded = _annotations(cls, lines)
        if not guarded:
            continue
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            if method.name == '__init__' \
                    or method.name.endswith('_locked'):
                continue
            checker = _LockScopeChecker(guarded, rel, lines, out)
            for stmt in method.body:
                checker.visit(stmt)


def _kw(node, name):
    for kw in node.keywords:
        if kw.arg == name:
            return kw
    return None


def _check_threads(tree, rel, lines, out):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = (fn.attr if isinstance(fn, ast.Attribute)
                else getattr(fn, 'id', None))
        root = (fn.value.id if isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name) else None)
        if name != 'Thread' or (root is not None
                                and root != 'threading'):
            continue
        if allowed_by_pragma(lines, node.lineno, 'thread-discipline'):
            continue
        problems = []
        daemon = _kw(node, 'daemon')
        if daemon is None or not (isinstance(daemon.value, ast.Constant)
                                  and daemon.value.value is True):
            problems.append('daemon=True (a non-daemon worker blocks '
                            'interpreter shutdown on a wedged step)')
        if _kw(node, 'name') is None:
            problems.append('name= (anonymous threads are invisible '
                            'in flight-recorder stack dumps)')
        if problems:
            out.append(Violation(
                rule='thread-discipline', file=rel, line=node.lineno,
                message='threading.Thread(...) must pass '
                        + ' and '.join(problems)))


def lint_file(path, repo_root=None, rules=None):
    """Run the conclint ruleset over one file; returns a Violation
    list. Files outside the package / fixture scope return []."""
    rules = set(rules or CONC_RULES)
    rel = (os.path.relpath(path, repo_root) if repo_root
           else os.fspath(path))
    if not any(frag in rel for frag in _SCOPE_FRAGMENTS):
        return []
    with open(path, encoding='utf-8') as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError:
        return []       # astlint owns parse-error reporting
    lines = src.splitlines()
    out = []
    if 'guarded-by' in rules:
        _check_guarded(tree, rel, lines, out)
    if 'thread-discipline' in rules:
        _check_threads(tree, rel, lines, out)
    return out


def lint_paths(paths, repo_root=None, rules=None):
    from distributed_dot_product_tpu.analysis.astlint import (
        iter_python_files,
    )
    out = []
    for path in iter_python_files(paths):
        out.extend(lint_file(path, repo_root=repo_root, rules=rules))
    return out
