# -*- coding: utf-8 -*-
"""
AST ruleset: project-specific hazard patterns that a jaxpr can't show
(either because the code never traces — host branches, exception
handlers — or because the hazard *prevents* tracing).

Pure ``ast``, no third-party dependency: this is deliberately NOT a
generic style linter (ruff owns hygiene — see pyproject.toml); every
rule here encodes a contract this repo has already been burned by or
explicitly designed around. Scope is per rule: the traced-value rules
(``host-pull``, ``traced-bool-branch``) only police the jit hot paths
(``ops/``, ``models/``, ``serve/``, ``obs/``); ``clock-in-jit`` and
``silent-except`` apply package-wide plus ``scripts/``.

"Traced value" is approximated statically and conservatively: a local
name is *jax-derived* when it was assigned from a ``jnp.* / jax.* /
lax.*`` call (or an attribute/index of one) inside the same function.
Only jax-derived names and direct jnp-predicate calls trigger the
traced-value rules, so static-config idioms (``float(scale)`` on a
kwarg, ``jnp.asarray`` coercion) stay clean — zero false positives on
the current tree is a design requirement, because the clean-tree gate
runs in tier-1.

Suppression: ``# graphlint: allow[<rule>]`` on the line or the line
above (see analysis/base.py).
"""

import ast
import os

from distributed_dot_product_tpu.analysis.base import (
    Violation, allowed_by_pragma,
)

__all__ = ['lint_file', 'lint_paths', 'iter_python_files', 'AST_RULES']

AST_RULES = ('host-pull', 'traced-bool-branch', 'clock-in-jit',
             'silent-except')

# Rules whose scope is the jit hot paths only (path fragments matched
# against the repo-relative file path). serve/ and obs/ joined the
# sweep in PR 13: the serving tick and the obs sampling paths dispatch
# compiled programs per token, so a host pull of a jnp-derived value
# there stalls the same hot loop the kernel rules protect.
_HOT_PATH_FRAGMENTS = (os.sep + 'ops' + os.sep,
                       os.sep + 'models' + os.sep,
                       os.sep + 'serve' + os.sep,
                       os.sep + 'obs' + os.sep)

_JAX_ROOTS = {'jnp', 'jax', 'lax'}
_PREDICATE_FNS = {'any', 'all', 'isfinite', 'isnan', 'allclose',
                  'array_equal', 'isin'}
_HOST_CASTS = {'float', 'int', 'bool'}
_CLOCK_FNS = {'time', 'perf_counter', 'monotonic', 'process_time',
              'thread_time'}
_LOGGY_NAMES = {'log_exception', 'warn', 'warning', 'error', 'exception',
                'print', 'log', 'log_step', 'debug', 'info'}


def _root_name(node):
    """Leftmost Name of a dotted/indexed expression, or None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_jax_call(node):
    """``jnp.foo(...)`` / ``jax.lax.bar(...)`` / ``lax.baz(...)``."""
    return (isinstance(node, ast.Call)
            and _root_name(node.func) in _JAX_ROOTS)


# The obs spans layer (obs/spans.py): roots its calls may appear under.
_SPAN_ROOTS = {'obs', 'spans', 'obs_spans'}
_SPAN_NAMES = {'span', 'spanned'}


def _is_span_call(node):
    """``span(...)`` / ``spanned(...)`` / ``obs.span(...)`` /
    ``spans.span(...)`` — the obs layer's clock-reading context
    managers. A bare name matches only the exact identifiers (so a
    regex ``m.span()`` attribute on a non-obs object never fires: its
    root is the match object, not an obs module)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id in _SPAN_NAMES
    if isinstance(fn, ast.Attribute):
        return fn.attr in _SPAN_NAMES and _root_name(fn) in _SPAN_ROOTS
    return False


def _is_jnp_predicate_call(node):
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _PREDICATE_FNS
            and _root_name(node.func) in _JAX_ROOTS)


def _jit_decorated(fn_node):
    """Does this function's decorator list mention jit?  Covers
    ``@jax.jit``, ``@jit``, ``@partial(jax.jit, ...)``,
    ``@functools.partial(jit, ...)``."""
    for dec in fn_node.decorator_list:
        target = dec
        if isinstance(dec, ast.Call):
            # partial(jax.jit, ...): the jitted callable is arg 0.
            if (getattr(dec.func, 'attr', None) == 'partial'
                    or getattr(dec.func, 'id', None) == 'partial'):
                if dec.args:
                    target = dec.args[0]
            else:
                target = dec.func
        name = (target.attr if isinstance(target, ast.Attribute)
                else getattr(target, 'id', None))
        if name == 'jit':
            return True
    return False


class _FunctionChecker(ast.NodeVisitor):
    """Per-function pass: infer jax-derived locals, then flag host
    pulls and traced-bool branches on them."""

    def __init__(self, fn_node, rel, src_lines, out, hot, in_jit):
        self.fn = fn_node
        self.rel = rel
        self.lines = src_lines
        self.out = out
        self.hot = hot
        self.in_jit = in_jit or _jit_decorated(fn_node)
        self.jax_locals = set()
        # Pass 1: names assigned from jax calls anywhere in this
        # function body (order-insensitive — good enough statically,
        # and reassignment to host values is rare in kernel code).
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign) and _is_jax_value(node.value):
                for tgt in node.targets:
                    for el in _name_targets(tgt):
                        self.jax_locals.add(el)
            elif (isinstance(node, (ast.AugAssign, ast.AnnAssign))
                  and node.value is not None
                  and _is_jax_value(node.value)):
                for el in _name_targets(node.target):
                    self.jax_locals.add(el)

    def _emit(self, rule, node, msg):
        if not allowed_by_pragma(self.lines, node.lineno, rule):
            self.out.append(Violation(rule=rule, message=msg,
                                      file=self.rel, line=node.lineno))

    def _is_traced_expr(self, node):
        if _is_jnp_predicate_call(node):
            return True
        if isinstance(node, ast.Name) and node.id in self.jax_locals:
            return True
        if isinstance(node, ast.UnaryOp):
            return self._is_traced_expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._is_traced_expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # Identity checks (`x is None` / `x is not None`) are host
            # predicates even on arrays — never traced.
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return any(self._is_traced_expr(n)
                       for n in [node.left, *node.comparators])
        return False

    # -- nested functions get their own checker (jit context inherits) --
    def visit_FunctionDef(self, node):
        if node is self.fn:
            self.generic_visit(node)
        else:
            _FunctionChecker(node, self.rel, self.lines, self.out,
                             self.hot, self.in_jit).visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        if self.hot:
            # .item() — always a host pull of a device value.
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == 'item' and not node.args):
                self._emit('host-pull', node,
                           '.item() forces a device readback (or a '
                           'tracer error under jit) — keep the value '
                           'on device or read it back once outside the '
                           'hot path')
            # float/int/bool/np.asarray/np.array on a jax-derived local.
            target = None
            if (isinstance(node.func, ast.Name)
                    and node.func.id in _HOST_CASTS and node.args):
                target = node.args[0]
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in ('asarray', 'array')
                  and _root_name(node.func) in ('np', 'numpy')
                  and node.args):
                target = node.args[0]
            if (target is not None
                    and (self._is_traced_expr(target)
                         or _is_jax_value(target))):
                self._emit('host-pull', node,
                           f'host conversion of a traced value '
                           f'(`{ast.unparse(node)[:60]}`) blocks or '
                           f'crashes the jit hot path — use jnp/lax '
                           f'equivalents')
        if self.in_jit and isinstance(node.func, ast.Attribute):
            if (node.func.attr in _CLOCK_FNS
                    and _root_name(node.func) == 'time'):
                self._emit('clock-in-jit', node,
                           f'time.{node.func.attr}() inside a jitted '
                           f'function reads the clock at TRACE time '
                           f'and bakes a constant into the compiled '
                           f'program — time outside the jit boundary')
        if self.in_jit and _is_span_call(node):
            # The obs layer's spans read the host clock: inside a
            # jitted function they time the TRACE, not the execution,
            # and the recorded span silently describes compilation.
            # Spans wrap host-side dispatch — never traced code.
            self._emit('clock-in-jit', node,
                       'obs span inside a jitted function reads the '
                       'host clock at TRACE time — wrap the dispatch '
                       'of the compiled step, not its traced body')
        self.generic_visit(node)

    def visit_If(self, node):
        if self.hot and self._is_traced_expr(node.test):
            self._emit('traced-bool-branch', node,
                       'python `if` on a traced predicate fixes the '
                       'branch at trace time (or raises under jit) — '
                       'use lax.cond / jnp.where')
        self.generic_visit(node)

    def visit_While(self, node):
        if self.hot and self._is_traced_expr(node.test):
            self._emit('traced-bool-branch', node,
                       'python `while` on a traced predicate cannot '
                       'trace — use lax.while_loop')
        self.generic_visit(node)


def _is_jax_value(node):
    """Expression that produces a jax array: a jnp/lax call, or an
    attribute/index/binop over one."""
    if _is_jax_call(node):
        return True
    if isinstance(node, (ast.Attribute, ast.Subscript)):
        return _is_jax_value(node.value)
    if isinstance(node, ast.BinOp):
        return _is_jax_value(node.left) or _is_jax_value(node.right)
    return False


def _name_targets(tgt):
    if isinstance(tgt, ast.Name):
        yield tgt.id
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for el in tgt.elts:
            yield from _name_targets(el)


def _outermost_functions(tree):
    """Functions not nested inside another function (module-level and
    method definitions; recursion stops at each found function)."""
    found = []

    def scan(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                found.append(child)
            else:
                scan(child)

    scan(tree)
    return found


def _is_broad_handler(handler):
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        name = n.attr if isinstance(n, ast.Attribute) else \
            getattr(n, 'id', None)
        if name in ('Exception', 'BaseException'):
            return True
    return False


def _handler_is_silent(handler):
    """No raise and no logging-ish call anywhere in the handler body."""
    for node in ast.walk(ast.Module(body=handler.body,
                                    type_ignores=[])):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            name = (node.func.attr if isinstance(node.func, ast.Attribute)
                    else getattr(node.func, 'id', None))
            if name in _LOGGY_NAMES:
                return False
    return True


def _check_silent_except(tree, rel, lines, out):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if _is_broad_handler(handler) and _handler_is_silent(handler):
                if not allowed_by_pragma(lines, handler.lineno,
                                         'silent-except'):
                    out.append(Violation(
                        rule='silent-except',
                        message='broad except that neither re-raises '
                                'nor logs swallows real failures — '
                                'log through utils.tracing.'
                                'log_exception or narrow the type',
                        file=rel, line=handler.lineno))


def lint_file(path, repo_root=None, rules=None):
    """Run the AST ruleset over one file; returns a Violation list."""
    rules = set(rules or AST_RULES)
    rel = (os.path.relpath(path, repo_root) if repo_root
           else os.fspath(path))
    with open(path, encoding='utf-8') as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        # Deliberately NOT subject to the rules filter: a file that
        # doesn't parse can hide any violation, so it always surfaces.
        return [Violation(rule='parse-error', file=rel,
                          line=e.lineno or 0,
                          message=f'file does not parse: {e.msg}')]
    lines = src.splitlines()
    hot = any(frag in os.sep + rel for frag in _HOT_PATH_FRAGMENTS)
    out = []
    if rules & {'host-pull', 'traced-bool-branch', 'clock-in-jit'}:
        # Checker roots are OUTERMOST functions only — nested defs are
        # reached through visit_FunctionDef's recursion, which is also
        # the only path that propagates the enclosing jit context.
        for node in _outermost_functions(tree):
            _FunctionChecker(node, rel, lines, out, hot,
                             in_jit=False).visit(node)
    if 'silent-except' in rules:
        _check_silent_except(tree, rel, lines, out)
    return [v for v in out if v.rule in rules]


def iter_python_files(paths, exclude_fragments=('graphlint_fixtures',
                                                '__pycache__')):
    """Yield .py files under the given files/directories, skipping
    deliberate-violation fixture trees and caches."""
    for p in paths:
        if os.path.isfile(p) and p.endswith('.py'):
            yield p          # explicitly-named files are never excluded
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if not any(f in d for f in exclude_fragments)]
            for fn in sorted(filenames):
                if fn.endswith('.py'):
                    yield os.path.join(dirpath, fn)


def lint_paths(paths, repo_root=None, rules=None):
    out = []
    for path in iter_python_files(paths):
        out.extend(lint_file(path, repo_root=repo_root, rules=rules))
    return out
