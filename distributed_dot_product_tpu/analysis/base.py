# -*- coding: utf-8 -*-
"""
Shared vocabulary of the analysis subsystem: the :class:`Violation`
record every engine emits, the rule catalog (id → what the rule guards
and which PR's contract it encodes), and the suppression pragma.

A violation is always anchored: ``file:line`` for AST rules, the
registered entrypoint name (plus the traced source line when jaxpr
equation metadata carries one) for jaxpr rules. The CLI and the tier-1
gate test both render these records, so an analyzer finding is
actionable from its one-line form.

Suppression: a trailing ``# graphlint: allow[<rule-id>]`` comment on
the offending line (or the line directly above) waives that rule for
that site — deliberate exceptions stay visible and greppable in the
source instead of accumulating in a config file. The flowlint family
spells the same pragma ``# flowlint: allow[<rule-id>]``; both prefixes
parse identically.
"""

import dataclasses
import re
from typing import Optional

__all__ = ['Violation', 'RULES', 'allowed_by_pragma',
           'active_violations', 'format_violations']

# Rule catalog. Jaxpr rules (J*) trace registered entrypoints and walk
# the ClosedJaxpr; AST rules (A*) parse source; R* is enforced at
# runtime by the retrace sentinel (analysis/retrace.py) under pytest.
RULES = {
    'f32-accum': (
        'every dot_general on low-precision (bf16/f16/int8) operands '
        'must request a wide accumulator via preferred_element_type '
        '(f32, or i32 for int8) — encodes the fp32-accumulation '
        'contract of the matmul-heavy paths (PR 3: LM head; the Pallas '
        'kernels carry it throughout)'),
    'donation': (
        'entrypoints declared as donating (KV-cache serving steps) '
        'must actually alias their donated buffers in the lowered '
        'module — without donation every token copies the full cache '
        '(PR 3: in-place KV-cache aliasing)'),
    'cache-alias': (
        'cache buffers must flow input→output through surgical writes '
        'only (dynamic_update_slice / masked select / kernel '
        'input_output_aliases); a full-shape copy or re-materialization '
        'degrades the in-place append into a per-token cache copy '
        '(PR 3: aliased append contract)'),
    'cache-upcast': (
        'no convert_element_type may widen a cache-shaped tensor: '
        'upcasting the KV buffer (e.g. bf16→f32 before a matmul) '
        'materializes a full-size copy every step — request the wide '
        'accumulator on the dot instead (PR 3: cache streaming '
        'contract)'),
    'collective-axis': (
        'collectives inside shard_map must name axes that exist on the '
        "entrypoint's declared mesh — a stray axis name means the "
        'program is being built against the wrong topology (PR 0/2: '
        'mesh discipline)'),
    'trace-error': (
        'a registered entrypoint failed to trace at its declared '
        'example shapes — the registration or the entrypoint itself '
        'regressed'),
    'host-pull': (
        'float()/int()/bool()/np.asarray()/.item() on a value produced '
        'by jnp/lax in ops/ or models/ hot paths forces a device '
        'readback (or a tracer error) mid-graph'),
    'traced-bool-branch': (
        'python `if`/`while` on a traced predicate (jnp.any/all/'
        'isfinite/...) in ops/ or models/ either crashes under jit or '
        'silently fixes the branch at trace time — use lax.cond/'
        'jnp.where'),
    'clock-in-jit': (
        'time.time()/perf_counter()/monotonic() inside a jitted '
        'function reads the clock at TRACE time and bakes the constant '
        'into the program (PR 2: the health watchdog reads real time '
        'outside compiled code for exactly this reason)'),
    'parse-error': (
        'a scanned file does not parse as python — reported regardless '
        'of any --rule filter (a broken file can hide any violation)'),
    'silent-except': (
        'a broad except (bare / Exception / BaseException) that '
        'neither re-raises nor logs swallows real failures — log '
        'through utils.tracing.log_exception or narrow the type '
        '(PR 1/2: fault paths must stay observable)'),
    'retrace-budget': (
        'runtime rule (analysis/retrace.py): a watched decode/serve '
        'entrypoint may not trace more often than its declared budget '
        '— automates the round-5 decode_seq_parallel retrace-storm '
        'finding (ADVICE.md)'),
    # -- servelint: protocol / concurrency / determinism (PR 13) --------
    'event-vocab': (
        'protolint (analysis/protolint.py): a literal event kind at an '
        'emit() call site must exist in the closed obs/events.py '
        'EVENT_SCHEMA vocabulary — an unknown kind raises mid-incident '
        'at runtime; here it fails at PR time'),
    'event-fields': (
        'protolint: a literal emit() payload must carry every field '
        'EVENT_SCHEMA requires for its kind (calls forwarding **kwargs '
        'are skipped — only statically-complete payloads are judged)'),
    'reject-reason': (
        'protolint: a serve.reject `reason` must be a RejectReason '
        'member — a literal string must be one of the enum values, and '
        'a RejectReason attribute must name a member and emit its '
        '.value (the enum object would serialize as its repr)'),
    'guarded-by': (
        'conclint (analysis/conclint.py): a field annotated '
        '`# guarded-by: self._lock` may only be read or written inside '
        'a `with self._lock:` block (exempt: __init__, methods named '
        '*_locked — the caller holds the lock by convention)'),
    'thread-discipline': (
        'conclint: every threading.Thread(...) must be daemon=True and '
        'carry a name= — a non-daemon thread blocks interpreter '
        'shutdown on a wedged step, and an unnamed one is anonymous in '
        'the flight recorder\'s stack dumps'),
    'tick-determinism': (
        'determlint (analysis/determlint.py): no real-time reads '
        '(time.time/monotonic/sleep/perf_counter), `random` module '
        'calls, np.random, or os.environ reads inside a declared '
        'virtual-clock tick path (GRAPHLINT_TICK_ROOTS and their '
        'intra-module call closure) — the seeded bit-reproducible '
        'replay contract; intentional real-time sites live in '
        'determlint\'s REAL_TIME_CONTRACT table'),
    # -- flowlint: interprocedural typed-failure flow (PR 19) -----------
    'typed-escape': (
        'flowlint (analysis/flowlint.py): every exception class that '
        'can escape a declared serving root (SERVING_ROOTS — '
        'Scheduler.step/submit, Router.step/submit, KernelEngine.step/'
        'prefill/verify_step, run_trace) must be in the typed failure '
        'contract (TYPED_CONTRACT: RejectedError, PageCorruptionError, '
        'shard-exhaustion RuntimeError, ServeContractError, '
        'UnknownReplicaError) — a raw KeyError/IndexError/ValueError '
        'escape flags with its propagation chain file:line → file:line '
        '(the PR 17 deque.remove bug class, mechanized)'),
    'handler-totality': (
        'flowlint: an `except` of a typed serving error (RejectedError/'
        'PageCorruptionError or a subclass) must re-raise, route the '
        'failure into the event/metric ladder (emit/log_exception/'
        'count_reject/reject — directly or transitively), or consume '
        'the typed payload (.reason/.pages/.site) — silently dropping '
        'a typed failure un-types it'),
    'reason-coverage': (
        'flowlint: every RejectReason member needs ≥ 1 raise/convert '
        'reference site plus serve.reject emit and per-reason counter '
        'coverage — a dead enum member is taxonomy the operator '
        'dashboards promise but no code path can produce'),
    'shard-ownership': (
        'flowlint: host code outside models/decode.py must reach '
        'ShardedPageTable geometry through its helpers (gpage/gsplit/'
        'page_shard/owner/owned_range/tracked_pages), never raw '
        '`pages_per_shard + 1` stride arithmetic — the PR 18 '
        'contiguous-ownership layout has exactly one home'),
}

_PRAGMA = re.compile(
    r'#\s*(?:graphlint|flowlint):\s*allow\[([a-z0-9_,\s-]+)\]')


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str                       # id from RULES
    message: str
    file: Optional[str] = None      # repo-relative where possible
    line: Optional[int] = None
    entrypoint: Optional[str] = None  # registry name (jaxpr rules)
    # Waived-but-visible: a registration-level allowance (TraceSpec
    # .allow — the flax Dense bf16-accum debt) keeps the record in
    # `--format json` output without failing the CLI or the gate, so
    # known debt stays enumerable instead of disappearing into a
    # pragma. flowlint pragma waivers ride the same flag — a waived
    # failure-flow site is debt, not absence.
    allowed: bool = False
    # typed-escape only: the propagation chain root → origin raise as
    # ('file:line', ...) hops — the `--format json` shape README
    # documents (rule/file/line/chain are the stable keys).
    chain: Optional[tuple] = None

    def render(self):
        where = f'{self.file}:{self.line}' if self.file else '<registry>'
        entry = f' [{self.entrypoint}]' if self.entrypoint else ''
        mark = ' (allowed)' if self.allowed else ''
        return f'{where}: {self.rule}{entry}{mark}: {self.message}'


def allowed_by_pragma(source_lines, lineno, rule):
    """True when the 1-based ``lineno`` (or the line above) carries a
    ``# graphlint: allow[rule]`` pragma naming ``rule``."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(source_lines):
            m = _PRAGMA.search(source_lines[ln - 1])
            if m and rule in {r.strip() for r in m.group(1).split(',')}:
                return True
    return False


def active_violations(violations):
    """The violations that FAIL a run (``allowed=False``) — the CLI
    exit code and the tier-1 gate both judge this subset; allowed
    records stay visible in the rendered output."""
    return [v for v in violations if not v.allowed]


def format_violations(violations, fmt='text'):
    """Render a violation list for the CLI: ``text`` (one line each),
    ``json`` (a list of plain dicts, ``allowed`` records included), or
    ``sarif`` (a minimal SARIF 2.1.0 log — one run, ruleId/level/
    message/location per result — so CI can annotate findings inline;
    ``allowed`` records carry level ``note``, active ones ``error``)."""
    if fmt == 'json':
        import json
        return json.dumps([dataclasses.asdict(v) for v in violations],
                          indent=2)
    if fmt == 'sarif':
        import json
        results = []
        for v in violations:
            entry = f' [{v.entrypoint}]' if v.entrypoint else ''
            res = {
                'ruleId': v.rule,
                'level': 'note' if v.allowed else 'error',
                'message': {'text': f'{v.message}{entry}'},
            }
            if v.file:
                res['locations'] = [{'physicalLocation': {
                    'artifactLocation': {
                        'uri': v.file.replace('\\', '/')},
                    'region': {'startLine': int(v.line or 1)},
                }}]
            results.append(res)
        used = sorted({v.rule for v in violations})
        log = {
            '$schema': 'https://json.schemastore.org/sarif-2.1.0.json',
            'version': '2.1.0',
            'runs': [{
                'tool': {'driver': {
                    'name': 'graphlint',
                    'rules': [{'id': r,
                               'shortDescription':
                                   {'text': RULES.get(r, r)}}
                              for r in used],
                }},
                'results': results,
            }],
        }
        return json.dumps(log, indent=2)
    act = active_violations(violations)
    n_allowed = len(violations) - len(act)
    lines = [v.render() for v in violations]
    if not act:
        lines.append('graphlint: no violations'
                     + (f' ({n_allowed} allowed by registration)'
                        if n_allowed else ''))
    else:
        lines.append(f'graphlint: {len(act)} violation'
                     f'{"s" if len(act) != 1 else ""}'
                     + (f' (+{n_allowed} allowed)' if n_allowed else ''))
    return '\n'.join(lines)
