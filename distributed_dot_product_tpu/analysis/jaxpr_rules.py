# -*- coding: utf-8 -*-
"""
Jaxpr linter: trace a registered entrypoint at its example abstract
shapes (``jax.make_jaxpr`` — no execution, no device memory) and walk
the ClosedJaxpr enforcing the repo's compiled-graph contracts:

- ``f32-accum``   — every ``dot_general`` on low-precision operands
  (bf16/f16 → f32, int8 → i32) requests a wide accumulator via
  ``preferred_element_type``. The Pallas kernels carry this everywhere
  (ops/pallas_attention.py, ops/pallas_decode.py); the LM head einsum
  requests it explicitly (models/lm.py). This rule is what keeps the
  next refactor from silently dropping it.
- ``cache-alias`` — each declared cache buffer must flow input→output
  through *surgical* writes only: ``dynamic_update_slice`` (appends),
  ``select_n`` (masked slot writes), sub-operand ``scatter`` (the paged
  pool's page-write spine), same-dtype ``convert_element_type``, and
  kernel ``input_output_aliases`` — across ``pjit``/``shard_map``/
  custom-vjp boundaries. A buffer that is re-materialized (arithmetic,
  gather, full-shape copy) or overwritten by a full-buffer-shaped
  ``dynamic_update_slice``/full-operand ``scatter`` breaks the in-place
  append contract and degrades every decode step into a cache copy.
- ``cache-upcast`` — no ``convert_element_type`` widens a cache-shaped
  tensor (e.g. ``cache.k.astype(f32)`` before a matmul): that
  materializes a full-size high-precision copy per step. Request the
  wide accumulator on the dot instead.
- ``collective-axis`` — collectives only name axes on the entrypoint's
  DECLARED mesh (``TraceSpec.mesh_axes``); inner ``shard_map`` meshes
  must agree with the declaration.
- ``donation``     — entrypoints declared as donating actually alias
  their buffers in the lowered module (``tf.aliasing_output`` /
  ``jax.buffer_donor`` argument attributes).

Tracing failures are reported as ``trace-error`` violations rather than
crashing the whole run, so one broken registration never hides the
others' findings.
"""

import jax
import jax.numpy as jnp

from distributed_dot_product_tpu.analysis.base import Violation

__all__ = ['JAXPR_RULES', 'lint_spec', 'lint_entrypoints']

JAXPR_RULES = ('f32-accum', 'cache-alias', 'cache-upcast',
               'collective-axis', 'donation', 'trace-error')

_LOW_FLOAT = (jnp.bfloat16, jnp.float16)
_LOW_INT = (jnp.int8, jnp.uint8)

# Collective primitives; their named axes ride in either the 'axes' or
# the 'axis_name' param (both are read — see _check_axes).
_COLLECTIVES = frozenset({
    'psum', 'pmax', 'pmin', 'all_gather', 'all_to_all', 'ppermute',
    'pbroadcast', 'reduce_scatter', 'axis_index', 'psum_scatter',
})


def _src(eqn):
    """(file, line) of the user frame that traced this equation, or
    (None, None) — best-effort, jaxpr source_info is optional."""
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name, frame.start_line
    except Exception:  # graphlint: allow[silent-except] best-effort
        pass       # (source info is optional metadata; None is the API)
    return None, None


def _sub_jaxprs(eqn):
    """Every sub-jaxpr carried in an eqn's params (pjit's ClosedJaxpr,
    shard_map's open Jaxpr, custom-vjp call_jaxpr, pallas_call jaxpr,
    scan/while/cond bodies — found generically)."""
    for val in eqn.params.values():
        items = val if isinstance(val, (tuple, list)) else (val,)
        for item in items:
            # ClosedJaxpr forwards .eqns, so unwrap .jaxpr FIRST.
            if hasattr(getattr(item, 'jaxpr', None), 'eqns'):
                yield item.jaxpr                # ClosedJaxpr
            elif hasattr(item, 'eqns'):
                yield item                      # open Jaxpr


def _iter_eqns(jaxpr):
    """Depth-first over every equation, descending through call-like
    primitives."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _iter_eqns(sub)


def _axis_strs(val):
    """Normalize an axes param to the set of *named* axes (positional
    ints from vmap are not mesh axes)."""
    if val is None:
        return set()
    items = val if isinstance(val, (tuple, list, set, frozenset)) \
        else (val,)
    return {a for a in items if isinstance(a, str)}


# -- rule: f32-accum ----------------------------------------------------

def _check_dots(spec, jaxpr, out):
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name != 'dot_general':
            continue
        dtypes = [v.aval.dtype for v in eqn.invars
                  if hasattr(v.aval, 'dtype')]
        pref = eqn.params.get('preferred_element_type')
        low_f = any(d in _LOW_FLOAT for d in dtypes)
        low_i = any(d in _LOW_INT for d in dtypes)
        if not (low_f or low_i):
            continue
        ok = pref is not None and (
            (low_i and jnp.issubdtype(pref, jnp.integer)
             and jnp.dtype(pref).itemsize >= 4)
            or (not low_i and jnp.issubdtype(pref, jnp.floating)
                and jnp.dtype(pref).itemsize >= 4))
        if not ok:
            f, ln = _src(eqn)
            shown = pref if pref is None else jnp.dtype(pref).name
            out.append(Violation(
                rule='f32-accum', file=f, line=ln,
                entrypoint=spec.name,
                message=f'dot_general on '
                        f'{"/".join(str(d) for d in dtypes)} operands '
                        f'accumulates at preferred_element_type='
                        f'{shown} — request '
                        f'{"int32" if low_i else "float32"} '
                        f'(preferred_element_type) so the contraction '
                        f'accumulates wide on every backend'))


# -- rule: cache-upcast -------------------------------------------------

def _check_upcasts(spec, jaxpr, cache_shapes, out):
    if not cache_shapes:
        return
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name != 'convert_element_type':
            continue
        aval = eqn.invars[0].aval
        if getattr(aval, 'shape', None) not in cache_shapes:
            continue
        new = eqn.params.get('new_dtype')
        if new is None:
            continue
        if jnp.dtype(new).itemsize > jnp.dtype(aval.dtype).itemsize:
            f, ln = _src(eqn)
            out.append(Violation(
                rule='cache-upcast', file=f, line=ln,
                entrypoint=spec.name,
                message=f'cache-shaped {aval.shape} tensor upcast '
                        f'{aval.dtype} → {jnp.dtype(new).name}: this '
                        f'materializes a full-size copy of the cache '
                        f'every step — keep the buffer narrow and '
                        f'request the wide accumulator on the dot '
                        f'(preferred_element_type) instead'))


# -- rule: collective-axis ----------------------------------------------

def _check_axes(spec, jaxpr, out):
    declared = set(spec.mesh_axes)
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name == 'shard_map':
            mesh = eqn.params.get('mesh')
            axes = set(getattr(mesh, 'axis_names', ()) or ())
            bad = axes - declared
            if bad:
                f, ln = _src(eqn)
                out.append(Violation(
                    rule='collective-axis', file=f, line=ln,
                    entrypoint=spec.name,
                    message=f'shard_map over mesh axes '
                            f'{sorted(axes)} but the entrypoint '
                            f'declares mesh_axes='
                            f'{sorted(declared) or "()"} — declaration '
                            f'and program disagree about the topology'))
            continue
        if name not in _COLLECTIVES:
            continue
        used = _axis_strs(eqn.params.get('axes')) \
            | _axis_strs(eqn.params.get('axis_name'))
        bad = used - declared
        if bad:
            f, ln = _src(eqn)
            out.append(Violation(
                rule='collective-axis', file=f, line=ln,
                entrypoint=spec.name,
                message=f'{name} over axis {sorted(bad)} which is not '
                        f'on the declared mesh '
                        f'(mesh_axes={sorted(declared) or "()"})'))


# -- rule: cache-alias --------------------------------------------------

# Spine-preserving primitives: ops through which a cache buffer may
# legitimately flow from input to output without being re-materialized.
# `reshape` is a layout view (the kernel path folds (B, H, T, d) to
# (B·H, T, d) around its pallas_call); `transpose` is NOT — it moves
# every byte on TPU, so it stays off-spine and gets reported.
# `scatter` (operand position only) is the PAGED pool's page-write
# spine: per-slot appends and freed-page zeroing are drop-mode
# scatters into the pool operand — a full-operand-sized scatter (the
# degenerate rewrite) is blocked like a full-shape DUS.
_SPINE_WALK = {
    'dynamic_update_slice': lambda eqn: [eqn.invars[0]],
    'select_n': lambda eqn: list(eqn.invars[1:]),
    'convert_element_type': lambda eqn: [eqn.invars[0]],
    'reshape': lambda eqn: [eqn.invars[0]],
    'scatter': lambda eqn: [eqn.invars[0]],
    'copy_p': lambda eqn: [],               # explicit copy breaks it
}


def _inner_jaxpr(eqn):
    """The single callee jaxpr of a call-like eqn, or None."""
    for key in ('jaxpr', 'call_jaxpr'):
        item = eqn.params.get(key)
        # ClosedJaxpr forwards .eqns, so unwrap .jaxpr FIRST.
        if hasattr(getattr(item, 'jaxpr', None), 'eqns'):
            return item.jaxpr
        if hasattr(item, 'eqns'):
            return item
    return None


def _spine_sources(jaxpr, out_var, blockers):
    """All jaxpr INVARS reachable from ``out_var`` through
    spine-preserving ops. Disallowed producers are recorded in
    ``blockers`` as (primitive_name, file, line)."""
    produced = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            produced[v] = eqn
    invar_set = set(jaxpr.invars)
    sources, seen, stack = set(), set(), [out_var]
    while stack:
        v = stack.pop()
        if v in seen:
            continue
        seen.add(v)
        if v in invar_set:
            sources.add(v)
            continue
        eqn = produced.get(v)
        if eqn is None:
            continue                      # literal / constvar
        name = eqn.primitive.name
        if name in _SPINE_WALK:
            if name == 'dynamic_update_slice':
                op, upd = eqn.invars[0].aval, eqn.invars[1].aval
                if getattr(op, 'shape', None) == getattr(upd, 'shape',
                                                         None):
                    f, ln = _src(eqn)
                    blockers.append(('full-shape dynamic_update_slice',
                                     f, ln))
                    continue
            if name == 'scatter':
                op, upd = eqn.invars[0].aval, eqn.invars[-1].aval
                if getattr(upd, 'size', 0) >= getattr(op, 'size', 1):
                    f, ln = _src(eqn)
                    blockers.append(('full-operand scatter', f, ln))
                    continue
            if name == 'convert_element_type':
                src_aval = eqn.invars[0].aval
                if eqn.params.get('new_dtype') != src_aval.dtype:
                    # dtype-changing convert re-materializes the buffer
                    f, ln = _src(eqn)
                    blockers.append((f'convert_element_type to '
                                     f'{eqn.params.get("new_dtype")}',
                                     f, ln))
                    continue
            stack.extend(_SPINE_WALK[name](eqn))
            continue
        if name == 'pallas_call':
            aliases = eqn.params.get('input_output_aliases') or ()
            out_idx = eqn.outvars.index(v)
            hit = [in_idx for in_idx, o in aliases if o == out_idx]
            if not hit:
                f, ln = _src(eqn)
                blockers.append(('pallas_call output without an '
                                 'input_output_alias', f, ln))
            for in_idx in hit:
                stack.append(eqn.invars[in_idx])
            continue
        inner = _inner_jaxpr(eqn)
        if inner is not None and len(inner.outvars) == len(eqn.outvars):
            # Call boundary (pjit/shard_map/custom-vjp/remat): map the
            # outer outvar to the callee outvar, recurse, and map the
            # reachable callee invars back to outer operands. Callee
            # invars align with the TRAILING outer invars (leading
            # outer invars may be consts).
            out_idx = eqn.outvars.index(v)
            inner_sources = _spine_sources(inner, inner.outvars[out_idx],
                                           blockers)
            offset = len(eqn.invars) - len(inner.invars)
            for i, iv in enumerate(inner.invars):
                if iv in inner_sources and 0 <= offset + i:
                    stack.append(eqn.invars[offset + i])
            continue
        f, ln = _src(eqn)
        blockers.append((name, f, ln))
    return sources


def _check_cache_alias(spec, closed, flat_in_idx, flat_out_idx, out):
    jaxpr = closed.jaxpr
    for in_idx, out_idx in zip(flat_in_idx, flat_out_idx):
        blockers = []
        sources = _spine_sources(jaxpr, jaxpr.outvars[out_idx], blockers)
        if jaxpr.invars[in_idx] in sources:
            continue
        detail = ''
        if blockers:
            name, f, ln = blockers[0]
            where = f' at {f}:{ln}' if f else ''
            detail = f' (first off-spine producer: {name}{where})'
        out.append(Violation(
            rule='cache-alias', entrypoint=spec.name,
            message=f'cache buffer (flat arg {in_idx} → flat output '
                    f'{out_idx}) does not flow through surgical writes '
                    f'— it is re-materialized, so the in-place append '
                    f'degrades into a full cache copy per '
                    f'step{detail}'))


# -- rule: donation -----------------------------------------------------

def _check_donation(spec, out):
    try:
        if spec.prejitted:
            lowered = spec.fn.lower(*spec.args)
        else:
            lowered = jax.jit(
                spec.fn,
                donate_argnums=spec.donate_argnums or (),
                static_argnums=spec.static_argnums or (),
            ).lower(*spec.args)
        text = lowered.as_text()
    except Exception as e:  # graphlint: allow[silent-except]
        out.append(Violation(   # reported AS a violation, not swallowed
            rule='trace-error', entrypoint=spec.name,
            message=f'lowering for the donation check failed: {e}'))
        return
    n_alias = text.count('tf.aliasing_output') \
        + text.count('jax.buffer_donor')
    needed = max(1, spec.min_donated)
    if n_alias < needed:
        out.append(Violation(
            rule='donation', entrypoint=spec.name,
            message=f'entrypoint declares donated buffers but the '
                    f'lowered module aliases {n_alias} argument(s) '
                    f'(expected >= {needed}) — without donation every '
                    f'step copies the full buffers before writing '
                    f'(check donate_argnums on the jit)'))


# -- driver -------------------------------------------------------------

def _flat_indices(tree, selected):
    """Flat-leaf indices (tree_flatten order) of the identity-selected
    leaves."""
    leaves = jax.tree_util.tree_leaves(tree)
    idx = []
    for leaf in selected:
        matches = [i for i, l in enumerate(leaves) if l is leaf]
        if not matches:
            raise ValueError('cache selector returned a leaf that is '
                             'not in the tree')
        idx.append(matches[0])
    return idx


def lint_spec(spec, rules=None):
    """Lint one TraceSpec; returns a Violation list."""
    rules = set(rules or JAXPR_RULES)
    out = []
    try:
        # return_shape=True: ONE trace yields both the jaxpr and the
        # output pytree (a separate eval_shape would trace the most
        # expensive entrypoints a second time and burn a unit of the
        # prejitted entries' retrace budget for nothing).
        closed, out_tree = jax.make_jaxpr(
            spec.fn, return_shape=True)(*spec.args)
    except Exception as e:  # graphlint: allow[silent-except]
        msg = str(e).splitlines()[0] if str(e) else repr(e)
        return [Violation(rule='trace-error', entrypoint=spec.name,
                          message=f'entrypoint failed to trace at its '
                                  f'registered shapes: {msg}')]
    jaxpr = closed.jaxpr

    cache_shapes = set()
    flat_in = flat_out = ()
    if spec.cache_in is not None:
        in_leaves = spec.cache_in(spec.args)
        cache_shapes = {tuple(l.shape) for l in in_leaves}
        flat_in = _flat_indices(spec.args, in_leaves)
        out_leaves = spec.cache_out(out_tree)
        flat_out = _flat_indices(out_tree, out_leaves)
        if len(flat_in) != len(flat_out):
            raise ValueError(f'{spec.name}: cache_in/cache_out '
                             f'selector arity mismatch')

    if 'f32-accum' in rules:
        _check_dots(spec, jaxpr, out)
    if 'cache-upcast' in rules:
        _check_upcasts(spec, jaxpr, cache_shapes, out)
    if 'collective-axis' in rules:
        _check_axes(spec, jaxpr, out)
    if 'cache-alias' in rules and flat_in:
        _check_cache_alias(spec, closed, flat_in, flat_out, out)
    if 'donation' in rules and (spec.expect_donation):
        _check_donation(spec, out)
    if spec.allow:
        # Registration-level waiver (TraceSpec.allow): the violation
        # stays in the output as visible debt, flagged allowed so the
        # CLI exit code and the clean-tree gate ignore it.
        import dataclasses
        out = [dataclasses.replace(v, allowed=True)
               if v.rule in spec.allow else v for v in out]
    return out


def lint_entrypoints(entrypoints, rules=None):
    """Lint a registry mapping ``{name: builder}``; builder errors are
    reported as trace-error violations, never raised."""
    out = []
    for name, build in entrypoints.items():
        try:
            spec = build()
            if spec.name != name:
                spec = spec.replace(name=name)
        except Exception as e:  # graphlint: allow[silent-except]
            msg = str(e).splitlines()[0] if str(e) else repr(e)
            out.append(Violation(  # reported AS a violation, not swallowed
                rule='trace-error', entrypoint=name,
                message=f'entrypoint builder failed: {msg}'))
            continue
        out.extend(lint_spec(spec, rules=rules))
    return out
