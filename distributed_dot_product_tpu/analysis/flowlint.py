# -*- coding: utf-8 -*-
"""
flowlint — interprocedural typed-failure-flow lint for the serving
stack (the third AST engine, next to graphlint's jaxpr/ast rules and
servelint's protocol/concurrency/determinism families).

The repo's load-bearing production invariant is that every request
entering the serving stack leaves with a CLOSED-VOCABULARY event and a
TYPED reason. Runtime soaks exercise it; nothing before this pass
*proved* statically that an exception cannot escape a tick root
untyped. PR 17's drive-found bug — ``deque.remove`` hitting
``Request.__eq__`` on numpy prompts and throwing an untyped
``ValueError`` out of ``Scheduler.step`` — is exactly the defect class
this engine mechanizes away.

Four rules:

- **typed-escape**: build the intra-package call graph, compute each
  function's MAY-RAISE set (raise sites plus callee escapes, minus
  classes caught on the path — ``except`` clauses that re-raise are
  transparent), and require every class escaping a declared serving
  root (:data:`SERVING_ROOTS`) to be in the typed contract
  (:data:`TYPED_CONTRACT`, hierarchy-aware). A raw ``KeyError`` /
  ``IndexError`` / ``ValueError`` escape flags with its propagation
  chain rendered ``file:line → file:line``. Unresolvable attribute
  calls named ``remove``/``index`` count as implicit ``ValueError``
  raisers (:data:`IMPLICIT_RAISES`) — the PR 17 shape — unless the
  receiver is an imported module (``os.remove``).
- **handler-totality**: every ``except`` of a typed serving error
  (``RejectedError`` / ``PageCorruptionError`` or an in-tree subclass)
  must re-raise, route the failure into the event/metric ladder
  (``emit`` / ``log_exception`` / ``count_reject`` / ``reject`` —
  directly or through an intra-package call), or consume the typed
  payload (``e.reason`` / ``e.pages`` / ``e.site``). A handler doing
  none of those swallows a typed failure silently.
- **reason-coverage**: every ``RejectReason`` member needs ≥ 1
  raise/convert reference site, and the tree needs a ``serve.reject``
  emit plus per-reason counter coverage (literal
  ``...rejected.<value>`` names, or the canonical dynamic
  ``f'serve.rejected.{r.value}'`` loop which covers all members). A
  dead enum member — a reason no code path can produce — flags.
- **shard-ownership**: host code outside ``models/decode.py`` must
  reach :class:`ShardedPageTable` geometry through its helpers
  (``gpage`` / ``gsplit`` / ``page_shard`` / ``owner`` /
  ``owned_range`` / ``tracked_pages``), never raw
  ``pages_per_shard + 1`` stride arithmetic — the PR 18 contiguous-
  ownership contract has exactly one home.

Scope: the installed package (minus ``analysis/`` itself — the linter
does not lint the linter) is ALWAYS parsed in full as the
interprocedural universe, whatever path subset was requested, so
``--changed-only`` keeps whole-graph soundness; violations are then
reported only when they touch a requested file. Files under
``graphlint_fixtures`` are each analyzed as a standalone universe
(their ``FLOWLINT_ROOTS`` / ``FLOWLINT_CONTRACT`` module literals
stand in for the central tables).

Suppression: ``# flowlint: allow[<rule>]`` on the flagged line or the
line above (``# graphlint: allow[...]`` is accepted too — one pragma
grammar). Unlike the other families, a pragma-waived flowlint site
stays VISIBLE as an ``allowed`` record — waived failure-flow debt is
enumerable in ``--format json``/``sarif`` and the clean-tree gate
asserts the set stays empty.
"""

import ast
import os
import re

from distributed_dot_product_tpu.analysis.astlint import (
    iter_python_files,
)
from distributed_dot_product_tpu.analysis.base import (
    Violation, allowed_by_pragma,
)

__all__ = ['FLOW_RULES', 'SERVING_ROOTS', 'TYPED_CONTRACT',
           'IMPLICIT_RAISES', 'lint_paths', 'lint_file']

FLOW_RULES = ('typed-escape', 'handler-totality', 'reason-coverage',
              'shard-ownership')

# Declared serving roots: the host-surface entrypoints a request's
# whole lifecycle flows through. Keyed by path suffix; values are the
# qualnames whose may-raise sets are judged against TYPED_CONTRACT.
SERVING_ROOTS = {
    'serve/scheduler.py': ('Scheduler.step', 'Scheduler.submit'),
    'serve/router.py': ('Router.step', 'Router.submit'),
    'serve/engine.py': ('KernelEngine.step', 'KernelEngine.prefill',
                        'KernelEngine.verify_step'),
    'serve/loadgen.py': ('run_trace',),
}

# The typed failure contract at those roots (hierarchy-aware: a
# subclass of a contract class is covered). RejectedError carries the
# RejectReason taxonomy; PageCorruptionError the integrity verdicts;
# RuntimeError is the declared shard/pool-exhaustion shape ("size the
# pool larger" — an operator capacity fact, not a request fault);
# ServeContractError/UnknownReplicaError are the typed narrowings of
# the caller-contract ValueError/KeyError raises this pass forced out
# of the bare builtins (they subclass them, so callers keep catching
# the builtin).
TYPED_CONTRACT = ('RejectedError', 'PageCorruptionError',
                  'RuntimeError', 'ServeContractError',
                  'UnknownReplicaError')

# Unresolvable attribute calls that may raise UNTYPED builtins by
# value-equality semantics: list/deque `.remove`/`.index` walk
# `__eq__` and raise ValueError on no-match — the PR 17 regression
# shape (numpy-array fields make the walk itself throw). Calls whose
# receiver resolves to an imported module (os.remove) are exempt.
IMPLICIT_RAISES = {
    'remove': ('ValueError', 'container .remove() raises untyped '
                             'ValueError when the value is missing '
                             '(and walks __eq__ — the PR 17 '
                             'deque.remove shape); delete by index'),
    'index': ('ValueError', 'container .index() raises untyped '
                            'ValueError when the value is missing; '
                            'guard membership or delete by index'),
}

# `self.<attr>` receiver types the constructor cannot infer (the attr
# is assigned from a parameter): (class, attr) -> receiver class.
TYPE_BINDINGS = {
    ('Scheduler', 'engine'): ('KernelEngine',),
    ('Router', 'pool'): ('ReplicaPool',),
}

# handler-totality: an except of one of these (or an in-universe
# subclass) must route the failure onward.
TOTALITY_BASES = ('RejectedError', 'PageCorruptionError')

# Routing a failure into the observability ladder: these call names
# (directly, or transitively through intra-package calls) satisfy
# handler-totality.
EMITISH_NAMES = frozenset({
    'emit', '_emit', 'log_exception', 'count_reject', '_count_reject',
    'reject', '_reject',
})

# Reading the typed payload off the caught exception also satisfies
# totality — the reason/verdict is consumed, not dropped.
PAYLOAD_ATTRS = frozenset({'reason', 'pages', 'site', 'args'})

# Builtin exception hierarchy (name -> base name), enough to make both
# the catch filter and the contract check subclass-aware.
_BUILTIN_BASES = {
    'KeyError': 'LookupError', 'IndexError': 'LookupError',
    'LookupError': 'Exception', 'ValueError': 'Exception',
    'TypeError': 'Exception', 'AttributeError': 'Exception',
    'RuntimeError': 'Exception', 'NotImplementedError': 'RuntimeError',
    'RecursionError': 'RuntimeError', 'ArithmeticError': 'Exception',
    'ZeroDivisionError': 'ArithmeticError',
    'OverflowError': 'ArithmeticError',
    'FloatingPointError': 'ArithmeticError',
    'OSError': 'Exception', 'IOError': 'OSError',
    'FileNotFoundError': 'OSError', 'FileExistsError': 'OSError',
    'PermissionError': 'OSError', 'TimeoutError': 'OSError',
    'ConnectionError': 'OSError', 'BrokenPipeError': 'ConnectionError',
    'StopIteration': 'Exception', 'StopAsyncIteration': 'Exception',
    'AssertionError': 'Exception', 'ImportError': 'Exception',
    'ModuleNotFoundError': 'ImportError', 'NameError': 'Exception',
    'UnboundLocalError': 'NameError', 'MemoryError': 'Exception',
    'BufferError': 'Exception', 'ReferenceError': 'Exception',
    'SystemError': 'Exception', 'EOFError': 'Exception',
    'UnicodeError': 'ValueError', 'UnicodeDecodeError': 'UnicodeError',
    'UnicodeEncodeError': 'UnicodeError',
    'Exception': 'BaseException', 'KeyboardInterrupt': 'BaseException',
    'SystemExit': 'BaseException', 'GeneratorExit': 'BaseException',
}

_PKG_PREFIX = 'distributed_dot_product_tpu.'
_MAX_HOPS = 64


# -- per-file collection ------------------------------------------------

class _Handler:
    """One except clause: what it catches, whether it re-raises, how
    its body behaves (for handler-totality)."""

    __slots__ = ('caught', 'transparent', 'lineno', 'name',
                 'raises_any', 'call_names', 'payload_read')

    def __init__(self, caught, transparent, lineno, name):
        self.caught = caught            # tuple of class names ('BaseException' = bare)
        self.transparent = transparent  # contains a bare re-raise
        self.lineno = lineno
        self.name = name                # `as e` binding (or None)
        self.raises_any = False         # any raise statement in body
        self.call_names = set()         # call names made in the body
        self.payload_read = False       # reads e.reason/e.pages/...


class _Func:
    __slots__ = ('rel', 'path', 'qual', 'cls', 'lineno', 'raises',
                 'calls', 'handlers', 'emitish', 'local_types')

    def __init__(self, rel, path, qual, cls, lineno):
        self.rel = rel
        self.path = path
        self.qual = qual
        self.cls = cls                  # enclosing class name or None
        self.lineno = lineno
        self.raises = []                # (exc_name, lineno, guards)
        self.calls = []                 # (kind, data, lineno, guards)
        self.handlers = []              # _Handler
        self.emitish = False
        self.local_types = {}           # local var -> set of class names


class _FileInfo:
    __slots__ = ('path', 'rel', 'lines', 'tree', 'modules',
                 'from_imports', 'functions', 'classes', 'literals')

    def __init__(self, path, rel):
        self.path = path
        self.rel = rel
        self.lines = []
        self.tree = None
        self.modules = set()        # `import os` / `import numpy as np` aliases
        self.from_imports = {}      # name -> package module rel path
        self.functions = {}         # qualname -> _Func
        self.classes = {}           # class name -> _Class
        self.literals = {}          # module-level UPPERCASE literal decls


class _Class:
    __slots__ = ('name', 'rel', 'bases', 'lineno', 'methods',
                 'attr_types', 'enum_members')

    def __init__(self, name, rel, bases, lineno):
        self.name = name
        self.rel = rel
        self.bases = bases          # base name strings
        self.lineno = lineno
        self.methods = set()
        self.attr_types = {}        # self.<attr> -> set of class names
        self.enum_members = {}      # member name -> (lineno, value literal)


def _name_of(node):
    """Rightmost identifier of a Name/Attribute, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_enum_class(node):
    for b in node.bases:
        if _name_of(b) in ('Enum', 'IntEnum', 'StrEnum'):
            return True
    return False


def _parse_file(path, rel):
    info = _FileInfo(path, rel)
    try:
        with open(path, encoding='utf-8') as f:
            src = f.read()
        info.lines = src.splitlines()
        info.tree = ast.parse(src, filename=path)
    except (OSError, SyntaxError, ValueError):
        return None     # astlint owns parse-error reporting
    for node in info.tree.body:
        if isinstance(node, ast.Import):
            for a in node.names:
                info.modules.add(a.asname or a.name.split('.')[0])
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.startswith(
                    _PKG_PREFIX.rstrip('.')):
                target = node.module.replace('.', '/') + '.py'
                for a in node.names:
                    info.from_imports[a.asname or a.name] = target
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.isupper():
            try:
                info.literals[node.targets[0].id] = \
                    ast.literal_eval(node.value)
            except ValueError:
                pass
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.functions[node.name] = _collect_function(
                node, info, cls=None)
        elif isinstance(node, ast.ClassDef):
            _collect_class(node, info)
    return info


def _collect_class(node, info):
    ci = _Class(node.name, info.rel,
                tuple(n for n in (_name_of(b) for b in node.bases) if n),
                node.lineno)
    info.classes[node.name] = ci
    if _is_enum_class(node):
        for st in node.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                val = None
                if isinstance(st.value, ast.Constant):
                    val = st.value.value
                ci.enum_members[st.targets[0].id] = (st.lineno, val)
        return
    for st in node.body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ci.methods.add(st.name)
            qual = f'{node.name}.{st.name}'
            info.functions[qual] = _collect_function(
                st, info, cls=node.name)
            if st.name == '__init__':
                _infer_attr_types(st, ci)


def _infer_attr_types(init_node, ci):
    """``self.x = ClassName(...)`` (anywhere in the value expression —
    conditional constructions included) types the attribute for
    ``self.x.m()`` resolution."""
    for st in ast.walk(init_node):
        if not (isinstance(st, ast.Assign) and len(st.targets) == 1):
            continue
        tgt = st.targets[0]
        if not (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == 'self'):
            continue
        names = {n.func.id for n in ast.walk(st.value)
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Name)
                 and n.func.id[:1].isupper()}
        if names:
            ci.attr_types.setdefault(tgt.attr, set()).update(names)


def _collect_function(node, info, cls):
    fn = _Func(info.rel, info.path,
               f'{cls}.{node.name}' if cls else node.name,
               cls, node.lineno)
    # Local aliases: `eng = self.engine` / `p = PagePool(...)`.
    for st in ast.walk(node):
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            v = st.value
            if isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == 'self':
                fn.local_types[st.targets[0].id] = ('self-attr', v.attr)
            elif isinstance(v, ast.Call) \
                    and isinstance(v.func, ast.Name) \
                    and v.func.id[:1].isupper():
                fn.local_types[st.targets[0].id] = ('class', v.func.id)
    _walk_body(node.body, fn, info, guards=(), handler=None)
    return fn


def _parse_handlers(try_node, info):
    out = []
    for h in try_node.handlers:
        if h.type is None:
            caught = ('BaseException',)
        elif isinstance(h.type, ast.Tuple):
            caught = tuple(n for n in (_name_of(e) for e in h.type.elts)
                           if n)
        else:
            caught = tuple(n for n in (_name_of(h.type),) if n)
        transparent = any(
            isinstance(n, ast.Raise)
            and (n.exc is None
                 or (isinstance(n.exc, ast.Name) and h.name
                     and n.exc.id == h.name))
            for n in _walk_no_nested(h.body))
        out.append(_Handler(caught or ('BaseException',), transparent,
                            h.lineno, h.name))
    return tuple(out)


def _walk_no_nested(stmts):
    """Every node under ``stmts``, not descending into nested
    function/class scopes."""
    stack = list(stmts)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _walk_body(stmts, fn, info, guards, handler):
    for node in stmts:
        _walk_node(node, fn, info, guards, handler)


def _walk_node(node, fn, info, guards, handler):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda, ast.ClassDef)):
        return      # nested scope: raises there don't fire here
    if isinstance(node, ast.Try):
        hs = _parse_handlers(node, info)
        inner = guards + (hs,)
        _walk_body(node.body, fn, info, inner, handler)
        for h, hnode in zip(hs, node.handlers):
            fn.handlers.append(h)
            for st in _walk_no_nested(hnode.body):
                if isinstance(st, ast.Raise):
                    h.raises_any = True
                if isinstance(st, ast.Call):
                    nm = _name_of(st.func)
                    if nm:
                        h.call_names.add(nm)
                if h.name and isinstance(st, ast.Attribute) \
                        and isinstance(st.value, ast.Name) \
                        and st.value.id == h.name \
                        and st.attr in PAYLOAD_ATTRS:
                    h.payload_read = True
            # Handler bodies run unprotected by their own try.
            _walk_body(hnode.body, fn, info, guards, h)
        _walk_body(node.orelse, fn, info, guards, handler)
        _walk_body(node.finalbody, fn, info, guards, handler)
        return
    if isinstance(node, ast.Raise):
        exc = node.exc
        if exc is None or (handler is not None and handler.name
                           and isinstance(exc, ast.Name)
                           and exc.id == handler.name):
            pass    # bare re-raise: modeled by handler transparency
        else:
            if isinstance(exc, ast.Call):
                exc = exc.func
            name = _name_of(exc)
            if name:
                fn.raises.append((name, node.lineno, guards))
        # fall through: raise operands may contain calls
    if isinstance(node, ast.Call):
        _record_call(node, fn, info, guards)
    for child in ast.iter_child_nodes(node):
        _walk_node(child, fn, info, guards, handler)


def _record_call(node, fn, info, guards):
    f = node.func
    if isinstance(f, ast.Name):
        if f.id in EMITISH_NAMES:
            fn.emitish = True
        fn.calls.append(('bare', f.id, node.lineno, guards))
        return
    if not isinstance(f, ast.Attribute):
        return
    if f.attr in EMITISH_NAMES:
        fn.emitish = True
    base = f.value
    if isinstance(base, ast.Name):
        if base.id == 'self':
            fn.calls.append(('self', f.attr, node.lineno, guards))
            return
        if base.id in info.modules:
            return      # module-attr call (os.remove, np.asarray): external
        local = fn.local_types.get(base.id)
        if local is not None:
            fn.calls.append(('local', (local, f.attr), node.lineno,
                             guards))
            return
        fn.calls.append(('unknown', f.attr, node.lineno, guards))
        return
    if isinstance(base, ast.Attribute) \
            and isinstance(base.value, ast.Name) \
            and base.value.id == 'self':
        fn.calls.append(('self-attr', (base.attr, f.attr), node.lineno,
                         guards))
        return
    fn.calls.append(('unknown', f.attr, node.lineno, guards))


# -- the universe -------------------------------------------------------

class _Universe:
    def __init__(self, files):
        self.files = files                      # rel -> _FileInfo
        self.functions = {}                     # (rel, qual) -> _Func
        self.classes = {}                       # name -> [_Class]
        self.bases = dict(_BUILTIN_BASES)       # exc name -> base name
        for fi in files.values():
            for qual, fn in fi.functions.items():
                self.functions[(fi.rel, qual)] = fn
            for name, ci in fi.classes.items():
                self.classes.setdefault(name, []).append(ci)
                if ci.bases:
                    self.bases.setdefault(name, ci.bases[0])

    def ancestry(self, exc):
        """``exc`` and its base chain. Unknown classes are assumed to
        sit directly under Exception."""
        chain, seen = [exc], {exc}
        cur = exc
        while True:
            nxt = self.bases.get(cur)
            if nxt is None:
                if cur not in ('BaseException',):
                    chain.append('Exception')
                    chain.append('BaseException')
                break
            if nxt in seen:
                break
            chain.append(nxt)
            seen.add(nxt)
            cur = nxt
        return chain

    def catches(self, exc, caught_names):
        anc = self.ancestry(exc)
        return any(c in anc for c in caught_names)

    def resolve_method(self, cls_name, meth, _depth=0):
        """(rel, qual) of ``cls_name.meth``, following in-universe base
        classes; None when the universe doesn't define it."""
        if _depth > 8:
            return None
        for ci in self.classes.get(cls_name, ()):
            if meth in ci.methods:
                return (ci.rel, f'{ci.name}.{meth}')
            for b in ci.bases:
                hit = self.resolve_method(b, meth, _depth + 1)
                if hit is not None:
                    return hit
        return None

    def attr_candidates(self, cls_name, attr):
        out = set()
        for ci in self.classes.get(cls_name, ()):
            out.update(ci.attr_types.get(attr, ()))
        out.update(TYPE_BINDINGS.get((cls_name, attr), ()))
        return out

    def resolve_call(self, fn, kind, data):
        """Resolve one recorded call site to ``[(rel, qual), ...]``
        universe functions; ``None`` marks 'unresolved' (a candidate
        for IMPLICIT_RAISES)."""
        fi = self.files[fn.rel]
        if kind == 'bare':
            if data in fi.functions and fi.functions[data].cls is None:
                return [(fn.rel, data)]
            if data in fi.classes:
                return self._init_of(data)
            target = fi.from_imports.get(data)
            if target is not None:
                for rel, tfi in self.files.items():
                    if rel.replace(os.sep, '/').endswith(target):
                        if data in tfi.functions \
                                and tfi.functions[data].cls is None:
                            return [(rel, data)]
                        if data in tfi.classes:
                            return self._init_of(data)
            if data in self.classes:
                return self._init_of(data)
            return []       # builtins (len, int, ...): no raises tracked
        if kind == 'self':
            if fn.cls is None:
                return None
            hit = self.resolve_method(fn.cls, data)
            return [hit] if hit else None
        if kind == 'self-attr':
            attr, meth = data
            if fn.cls is None:
                return None
            cands = self.attr_candidates(fn.cls, attr)
            out = []
            for c in sorted(cands):
                hit = self.resolve_method(c, meth)
                if hit:
                    out.append(hit)
            return out or None
        if kind == 'local':
            (lk, lv), meth = data
            if lk == 'class':
                hit = self.resolve_method(lv, meth)
                return [hit] if hit else None
            if lk == 'self-attr' and fn.cls is not None:
                out = []
                for c in sorted(self.attr_candidates(fn.cls, lv)):
                    hit = self.resolve_method(c, meth)
                    if hit:
                        out.append(hit)
                return out or None
            return None
        return None     # 'unknown'

    def _init_of(self, cls_name):
        hit = self.resolve_method(cls_name, '__init__')
        return [hit] if hit else []


def _package_universe_paths():
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = []
    for base, dirs, names in os.walk(pkg):
        dirs[:] = [d for d in dirs
                   if d not in ('__pycache__', 'analysis')]
        for n in sorted(names):
            if n.endswith('.py'):
                out.append(os.path.join(base, n))
    return out


def _build_universe(paths, repo_root):
    files = {}
    for p in paths:
        rel = (os.path.relpath(p, repo_root) if repo_root
               else p).replace(os.sep, '/')
        fi = _parse_file(p, rel)
        if fi is not None:
            files[fi.rel] = fi
    return _Universe(files)


# -- may-raise fixpoint -------------------------------------------------

def _escapes_guards(uni, exc, guards):
    """Does ``exc`` raised under ``guards`` (outer→inner handler
    levels) leave the function? First matching clause per level wins:
    transparent → keeps propagating, else absorbed."""
    for level in reversed(guards):
        for h in level:
            if uni.catches(exc, h.caught):
                if not h.transparent:
                    return False
                break
    return True


def _may_raise_fixpoint(uni):
    """``{(rel, qual): {exc: (lineno, callee_key|None, note)}}`` —
    witness-carrying may-raise sets. The witness is the FIRST site
    observed (deterministic: sites are walked in source order)."""
    may = {k: {} for k in uni.functions}
    changed = True
    while changed:
        changed = False
        for key, fn in uni.functions.items():
            cur = may[key]
            for exc, lineno, guards in fn.raises:
                if exc not in cur and _escapes_guards(uni, exc, guards):
                    cur[exc] = (lineno, None, 'raise')
                    changed = True
            for kind, data, lineno, guards in fn.calls:
                callees = uni.resolve_call(fn, kind, data)
                if callees is None:
                    meth = data[1] if isinstance(data, tuple) else data
                    imp = IMPLICIT_RAISES.get(meth)
                    if imp and imp[0] not in cur \
                            and _escapes_guards(uni, imp[0], guards):
                        cur[imp[0]] = (lineno, None, imp[1])
                        changed = True
                    continue
                for ck in callees:
                    for exc in may.get(ck, ()):
                        if exc not in cur \
                                and _escapes_guards(uni, exc, guards):
                            cur[exc] = (lineno, ck, 'call')
                            changed = True
    return may


def _witness_chain(uni, may, key, exc):
    """Call-site hops from ``key`` down to the origin raise, as
    ``(rel, lineno, note)`` triples."""
    chain = []
    for _ in range(_MAX_HOPS):
        fn = uni.functions[key]
        lineno, callee, note = may[key][exc]
        chain.append((fn.rel, lineno, note))
        if callee is None:
            return chain
        if exc not in may.get(callee, ()):
            return chain
        key = callee
    return chain


# -- rules --------------------------------------------------------------

def _v(rule, msg, fi, lineno, chain=None):
    waived = allowed_by_pragma(fi.lines, lineno, rule)
    return Violation(rule=rule, message=msg, file=fi.rel, line=lineno,
                     allowed=waived, chain=chain)


def _roots_of(uni, fixture):
    """``[(rel, qual), ...]`` declared roots present in the universe."""
    out = []
    for rel, fi in uni.files.items():
        quals = ()
        if fixture:
            decl = fi.literals.get('FLOWLINT_ROOTS')
            if decl:
                quals = tuple(decl)
        else:
            for suffix, names in SERVING_ROOTS.items():
                if rel.endswith(suffix):
                    quals = names
        for q in quals:
            if (rel, q) in uni.functions:
                out.append((rel, q))
    return out


def _contract_of(uni, fixture):
    if fixture:
        for fi in uni.files.values():
            decl = fi.literals.get('FLOWLINT_CONTRACT')
            if decl:
                return tuple(decl)
    return TYPED_CONTRACT


def _check_typed_escape(uni, may, fixture, out):
    contract = _contract_of(uni, fixture)
    for rel, qual in _roots_of(uni, fixture):
        root_fi = uni.files[rel]
        root_fn = uni.functions[(rel, qual)]
        for exc in sorted(may[(rel, qual)]):
            if any(c in uni.ancestry(exc) for c in contract):
                continue
            chain = _witness_chain(uni, may, (rel, qual), exc)
            origin_rel, origin_line, note = chain[-1]
            origin_fi = uni.files[origin_rel]
            rendered = ' → '.join(f'{r}:{ln}' for r, ln, _ in chain)
            detail = ('' if note in ('raise', 'call')
                      else f' ({note})')
            msg = (f'{qual} may leak untyped {exc} — {rendered}'
                   f'{detail}; raise a TYPED_CONTRACT class '
                   f'({", ".join(contract)}) or convert it inside the '
                   f'serving stack')
            waived = (allowed_by_pragma(origin_fi.lines, origin_line,
                                        'typed-escape')
                      or allowed_by_pragma(root_fi.lines,
                                           root_fn.lineno,
                                           'typed-escape'))
            out.append(Violation(
                rule='typed-escape', message=msg, file=origin_rel,
                line=origin_line, allowed=waived,
                chain=tuple(f'{r}:{ln}' for r, ln, _ in chain)))


def _may_emit_fixpoint(uni):
    emits = {k for k, fn in uni.functions.items() if fn.emitish}
    changed = True
    while changed:
        changed = False
        for key, fn in uni.functions.items():
            if key in emits:
                continue
            for kind, data, _lineno, _guards in fn.calls:
                callees = uni.resolve_call(fn, kind, data) or ()
                if any(c in emits for c in callees):
                    emits.add(key)
                    changed = True
                    break
    return emits


def _typed_handler_names(uni):
    names = set(TOTALITY_BASES)
    changed = True
    while changed:
        changed = False
        for name, base in list(uni.bases.items()):
            if base in names and name not in names:
                names.add(name)
                changed = True
    return names


def _check_handler_totality(uni, out):
    typed = _typed_handler_names(uni)
    emits = _may_emit_fixpoint(uni)
    for key, fn in uni.functions.items():
        fi = uni.files[fn.rel]
        for h in fn.handlers:
            if not any(c in typed for c in h.caught):
                continue
            if h.transparent or h.raises_any or h.payload_read:
                continue
            if h.call_names & EMITISH_NAMES:
                continue
            routed = False
            for nm in sorted(h.call_names):
                for kind in ('self', 'bare'):
                    callees = uni.resolve_call(fn, kind, nm)
                    if callees and any(c in emits for c in callees):
                        routed = True
                        break
                if routed:
                    break
            if routed:
                continue
            caught = '/'.join(h.caught)
            out.append(_v(
                'handler-totality',
                f'{fn.qual} catches typed serving error {caught} and '
                f'drops it — emit a closed-vocab event, route '
                f'log_exception/count_reject, consume the typed '
                f'payload (e.g. .reason), or re-raise',
                fi, h.lineno))


_REJECTED_COUNTER = re.compile(r'rejected\.([a-z0-9_]+)$')


def _check_reason_coverage(uni, out):
    enums = [(fi, ci) for fi in uni.files.values()
             for ci in fi.classes.values()
             if ci.name == 'RejectReason' and ci.enum_members]
    if not enums:
        return
    refs = {}           # member -> count of reference sites
    counter_lits = set()
    counter_dynamic = False
    emit_reject = False
    for fi in uni.files.values():
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == 'RejectReason':
                refs[node.attr] = refs.get(node.attr, 0) + 1
            if isinstance(node, ast.Call):
                nm = _name_of(node.func)
                if nm in ('emit', '_emit') and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and node.args[0].value == 'serve.reject':
                    emit_reject = True
                if nm == 'counter' and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        m = _REJECTED_COUNTER.search(arg.value)
                        if m:
                            counter_lits.add(m.group(1))
                    elif isinstance(arg, ast.JoinedStr) and arg.values:
                        head = arg.values[0]
                        if isinstance(head, ast.Constant) \
                                and isinstance(head.value, str) \
                                and head.value.endswith('rejected.'):
                            counter_dynamic = True
    for fi, ci in enums:
        if not emit_reject:
            out.append(_v(
                'reason-coverage',
                'RejectReason declared but no serve.reject emit site '
                'exists — typed rejects would leave no event',
                fi, ci.lineno))
        for member, (lineno, value) in ci.enum_members.items():
            missing = []
            if not refs.get(member):
                missing.append('no raise/convert site references it')
            if not counter_dynamic and (
                    value is None or str(value) not in counter_lits):
                missing.append('no per-reason counter covers it')
            if missing:
                out.append(_v(
                    'reason-coverage',
                    f'RejectReason.{member} is dead taxonomy — '
                    f'{"; ".join(missing)} — wire it into the '
                    f'reject ladder or delete the member',
                    fi, lineno))


def _check_shard_ownership(uni, anchor_rels, out):
    for rel, fi in uni.files.items():
        if rel.endswith('models/decode.py'):
            continue    # the geometry's one home
        if anchor_rels is not None and rel not in anchor_rels:
            continue
        flagged = set()
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.BinOp):
                continue
            hit = any(isinstance(n, ast.Attribute)
                      and n.attr == 'pages_per_shard'
                      for n in ast.walk(node))
            if hit and node.lineno not in flagged:
                flagged.add(node.lineno)
                out.append(_v(
                    'shard-ownership',
                    'raw pages_per_shard stride arithmetic outside '
                    'models/decode.py — go through the '
                    'ShardedPageTable helpers (gpage/gsplit/'
                    'page_shard/owner/owned_range) so the contiguous-'
                    'ownership layout has exactly one home',
                    fi, node.lineno))


# -- entry points -------------------------------------------------------

def _lint_universe(uni, fixture, anchor_rels, rules):
    out = []
    run = (lambda r: rules is None or r in rules)
    if run('typed-escape') or run('handler-totality'):
        may = _may_raise_fixpoint(uni) if run('typed-escape') else None
        if run('typed-escape'):
            _check_typed_escape(uni, may, fixture, out)
        if run('handler-totality'):
            _check_handler_totality(uni, out)
    if run('reason-coverage'):
        _check_reason_coverage(uni, out)
    if run('shard-ownership'):
        _check_shard_ownership(uni, anchor_rels, out)
    if anchor_rels is not None:
        out = [v for v in out
               if v.file in anchor_rels
               or (v.chain is not None
                   and any(h.rsplit(':', 1)[0] in anchor_rels
                           for h in v.chain))]
    return out


def _in_package(path):
    norm = os.path.abspath(path).replace(os.sep, '/')
    return f'/{_PKG_PREFIX.rstrip(".")}/' in norm \
        and '/analysis/' not in norm


def lint_paths(paths, repo_root=None, rules=None):
    """Run flowlint over ``paths``. Fixture files (under
    ``graphlint_fixtures``) are standalone universes; package files are
    judged against the full-package universe (interprocedural
    soundness survives ``--changed-only``), with findings filtered to
    the requested set. Non-package files (tests/, scripts/) are out of
    scope — the serving stack is the contract surface."""
    if rules is not None and not set(rules) & set(FLOW_RULES):
        return []
    violations = []
    package_anchor = set()
    for path in iter_python_files(paths):
        if 'graphlint_fixtures' in path.replace(os.sep, '/'):
            uni = _build_universe(
                [path], repo_root or os.path.dirname(path))
            violations.extend(
                _lint_universe(uni, fixture=True, anchor_rels=None,
                               rules=rules))
        elif _in_package(path):
            package_anchor.add(path)
    if package_anchor:
        pkg_paths = _package_universe_paths()
        root = repo_root
        if root is None:
            pkg = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            root = os.path.dirname(pkg)
        uni = _build_universe(pkg_paths, root)
        anchor_rels = {os.path.relpath(p, root).replace(os.sep, '/')
                       for p in package_anchor}
        violations.extend(
            _lint_universe(uni, fixture=False, anchor_rels=anchor_rels,
                           rules=rules))
    return violations


def lint_file(path, repo_root=None, rules=None):
    return lint_paths([path], repo_root=repo_root, rules=rules)
