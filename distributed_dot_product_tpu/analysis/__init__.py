# -*- coding: utf-8 -*-
"""
``graphlint`` — static analysis that mechanically enforces the repo's
performance and correctness contracts.

Five engines (see README "Static analysis"):

- **Jaxpr linter** (:mod:`.jaxpr_rules` over :mod:`.registry`): traces
  every registered entrypoint at example abstract shapes and walks the
  ClosedJaxpr — fp32 accumulation on low-precision dots, surgical
  (aliased) KV-cache writes + real donation, no cache-shaped upcasts,
  collectives only on declared mesh axes.
- **Retrace sentinel** (:mod:`.retrace`): runtime trace-count budgets
  on jitted decode/serve entrypoints; on by default under pytest.
- **AST ruleset** (:mod:`.astlint`): pure-``ast`` hazard patterns —
  host pulls of traced values and traced-bool branching in hot paths,
  clock reads inside jit, silent broad excepts.
- **servelint** (:mod:`.protolint` / :mod:`.conclint` /
  :mod:`.determlint`): the serving/obs layer's contracts — emit call
  sites vs the closed EVENT_SCHEMA vocabulary and the RejectReason
  taxonomy, ``# guarded-by:`` lock discipline plus daemon/named thread
  discipline, and real-time/random/environ reads inside declared
  virtual-clock tick paths (``GRAPHLINT_TICK_ROOTS`` closures, with the
  intentional real-time modules in determlint's REAL_TIME_CONTRACT).
- **flowlint** (:mod:`.flowlint`): interprocedural typed-failure flow
  — per-function may-raise sets over the intra-package call graph
  judged against the typed contract at the declared serving roots
  (typed-escape, with ``file:line → file:line`` propagation chains),
  handler totality on typed serving errors, RejectReason taxonomy
  liveness, and ShardedPageTable stride-ownership.

CLI: ``python -m distributed_dot_product_tpu.analysis`` (exit 0 = no
violations). The tier-1 gate test (tests/test_graphlint.py) asserts a
clean tree, so a contract break fails CI before it ships.

This ``__init__`` stays import-light (no jax): serving code imports
:mod:`.retrace` at build time, and pulling the whole linter (which
imports every layer) along with it would be an import cycle.
"""

from distributed_dot_product_tpu.analysis.base import (     # noqa: F401
    RULES, Violation, active_violations, format_violations,
)
from distributed_dot_product_tpu.analysis.retrace import (  # noqa: F401
    RetraceBudgetExceeded, watch_traces,
)

__all__ = ['RULES', 'Violation', 'active_violations',
           'format_violations', 'watch_traces',
           'RetraceBudgetExceeded', 'run_analysis']


def run_analysis(paths=None, rules=None, repo_root=None,
                 jaxpr=True, ast_rules=True, entrypoints=None):
    """Run the full analyzer; returns a list of
    :class:`~distributed_dot_product_tpu.analysis.base.Violation`.

    ``paths``: files/dirs for the AST pass (default: the installed
    package plus ``scripts/`` and ``tests/`` when resolvable).
    ``rules``: restrict to these rule ids (default: all).
    ``entrypoints``: a ``{name: builder}`` mapping for the jaxpr pass
    (default: the central registry).
    """
    import os
    violations = []
    if ast_rules:
        from distributed_dot_product_tpu.analysis import (
            astlint, conclint, determlint, flowlint, protolint,
        )
        if paths is None:
            pkg = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            root = os.path.dirname(pkg)
            paths = [pkg]
            for extra in ('scripts', 'tests'):
                p = os.path.join(root, extra)
                if os.path.isdir(p):
                    paths.append(p)
            repo_root = repo_root or root
        # 'parse-error' is emitted by the AST pass (unconditionally, on
        # unparseable files) — requesting it must run that pass.
        ast_rule_set = None if rules is None else \
            [r for r in rules
             if r in astlint.AST_RULES or r == 'parse-error']
        if ast_rule_set is None or ast_rule_set:
            violations.extend(astlint.lint_paths(
                paths, repo_root=repo_root, rules=ast_rule_set))
        # servelint families ride the same AST pass and path set.
        for mod, fam in ((protolint, protolint.PROTO_RULES),
                         (conclint, conclint.CONC_RULES),
                         (determlint, determlint.DETERM_RULES),
                         (flowlint, flowlint.FLOW_RULES)):
            fam_rules = None if rules is None else \
                [r for r in rules if r in fam]
            if fam_rules is None or fam_rules:
                violations.extend(mod.lint_paths(
                    paths, repo_root=repo_root, rules=fam_rules))
    if jaxpr:
        from distributed_dot_product_tpu.analysis import jaxpr_rules
        jaxpr_rule_set = None if rules is None else \
            [r for r in rules if r in jaxpr_rules.JAXPR_RULES]
        if jaxpr_rule_set is None or jaxpr_rule_set:
            if entrypoints is None:
                from distributed_dot_product_tpu.analysis.registry import (
                    default_entrypoints,
                )
                entrypoints = default_entrypoints()
            violations.extend(jaxpr_rules.lint_entrypoints(
                entrypoints, rules=jaxpr_rule_set))
    return violations
