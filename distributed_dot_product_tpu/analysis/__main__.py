# -*- coding: utf-8 -*-
"""
``python -m distributed_dot_product_tpu.analysis`` — the graphlint CLI.

Exit status: 0 when clean, 1 when any violation (each rendered as
``file:line: rule [entrypoint]: message``), 2 on usage errors.

The jaxpr pass traces on a forced 8-virtual-device CPU platform
(tracing needs devices for meshes but never executes), so the CLI is
hermetic: same result on a TPU host, a CI runner, or a laptop.
"""

import argparse
import os
import sys


def main(argv=None):
    from distributed_dot_product_tpu.analysis.base import (
        RULES, format_violations,
    )
    parser = argparse.ArgumentParser(
        prog='python -m distributed_dot_product_tpu.analysis',
        description='graphlint: jaxpr/AST static analysis enforcing '
                    'the repo\'s perf and correctness contracts')
    parser.add_argument('paths', nargs='*',
                        help='files/dirs for the AST pass (default: '
                             'the package + scripts/ + tests/)')
    parser.add_argument('--rule', action='append', dest='rules',
                        metavar='ID', choices=sorted(RULES),
                        help='run only this rule (repeatable)')
    parser.add_argument('--format', choices=('text', 'json'),
                        default='text')
    parser.add_argument('--no-jaxpr', action='store_true',
                        help='skip the (slower) jaxpr/registry pass')
    parser.add_argument('--no-ast', action='store_true',
                        help='skip the AST pass')
    parser.add_argument('--registry', metavar='MODULE:ATTR',
                        help='lint this {name: builder} mapping instead '
                             'of the central registry (the negative-'
                             'fixture tests drive the CLI through '
                             'seeded regressions this way)')
    parser.add_argument('--list-rules', action='store_true',
                        help='print the rule catalog and exit')
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f'{rid}:\n    {RULES[rid]}')
        return 0

    if args.rules:
        from distributed_dot_product_tpu.analysis.astlint import AST_RULES
        from distributed_dot_product_tpu.analysis.jaxpr_rules import (
            JAXPR_RULES,
        )
        static = set(AST_RULES) | set(JAXPR_RULES) | {'parse-error'}
        runtime_only = [r for r in args.rules if r not in static]
        if runtime_only:
            parser.error(
                f'{", ".join(runtime_only)}: enforced at RUNTIME by the '
                f'retrace sentinel (analysis/retrace.py; on under '
                f'pytest), not statically — there is nothing for this '
                f'command to check')

    if not args.no_jaxpr:
        # Force the hermetic 8-device CPU platform BEFORE jax commits
        # to a backend (tracing needs mesh devices, never execution).
        os.environ.setdefault('JAX_PLATFORMS', 'cpu')
        from distributed_dot_product_tpu._compat import (
            ensure_cpu_devices,
        )
        ensure_cpu_devices(8)

    entrypoints = None
    if args.registry:
        from distributed_dot_product_tpu.analysis.registry import (
            resolve_registry_arg,
        )
        try:
            entrypoints = resolve_registry_arg(args.registry)
        except ValueError as e:
            parser.error(str(e))

    from distributed_dot_product_tpu.analysis import run_analysis
    violations = run_analysis(
        paths=args.paths or None, rules=args.rules,
        jaxpr=not args.no_jaxpr, ast_rules=not args.no_ast,
        entrypoints=entrypoints)
    print(format_violations(violations, fmt=args.format))
    return 1 if violations else 0


if __name__ == '__main__':
    try:
        sys.exit(main())
    except BrokenPipeError:     # `... | head` closed the pipe: not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
