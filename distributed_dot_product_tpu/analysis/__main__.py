# -*- coding: utf-8 -*-
"""
``python -m distributed_dot_product_tpu.analysis`` — the graphlint CLI.

Exit status: 0 when clean, 1 when any ACTIVE violation (each rendered
as ``file:line: rule [entrypoint]: message``), 2 on usage errors.
Registration-waived records (``TraceSpec.allow`` — the flax Dense
bf16-accum debt) render with an ``(allowed)`` mark and never fail the
run; ``--format json`` carries them with ``"allowed": true``.

The jaxpr pass traces on a forced 8-virtual-device CPU platform
(tracing needs devices for meshes but never executes), so the CLI is
hermetic: same result on a TPU host, a CI runner, or a laptop.
"""

import argparse
import os
import sys


def main(argv=None):
    from distributed_dot_product_tpu.analysis.base import (
        RULES, format_violations,
    )
    parser = argparse.ArgumentParser(
        prog='python -m distributed_dot_product_tpu.analysis',
        description='graphlint: jaxpr/AST static analysis enforcing '
                    'the repo\'s perf and correctness contracts')
    parser.add_argument('paths', nargs='*',
                        help='files/dirs for the AST pass (default: '
                             'the package + scripts/ + tests/)')
    parser.add_argument('--changed-only', nargs='?', const='HEAD',
                        metavar='REF', default=None,
                        help='lint only .py files changed vs the git '
                             'ref (default HEAD) plus untracked ones — '
                             'the fast pre-commit mode. The jaxpr/'
                             'registry pass still runs when a changed '
                             'file can affect a registered entrypoint '
                             '(ops/, models/, parallel/, obs/, '
                             'serve/engine.py, train.py, analysis/), '
                             'else it is skipped')
    parser.add_argument('--rule', action='append', dest='rules',
                        metavar='ID', choices=sorted(RULES),
                        help='run only this rule (repeatable)')
    parser.add_argument('--format', choices=('text', 'json', 'sarif'),
                        default='text',
                        help='text (one line each), json (stable '
                             'rule/file/line/chain dicts), or sarif '
                             '(SARIF 2.1.0 for inline CI annotation)')
    parser.add_argument('--no-jaxpr', action='store_true',
                        help='skip the (slower) jaxpr/registry pass')
    parser.add_argument('--no-ast', action='store_true',
                        help='skip the AST pass')
    parser.add_argument('--registry', metavar='MODULE:ATTR',
                        help='lint this {name: builder} mapping instead '
                             'of the central registry (the negative-'
                             'fixture tests drive the CLI through '
                             'seeded regressions this way)')
    parser.add_argument('--list-rules', action='store_true',
                        help='print the rule catalog and exit')
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f'{rid}:\n    {RULES[rid]}')
        return 0

    if args.rules:
        from distributed_dot_product_tpu.analysis.astlint import AST_RULES
        from distributed_dot_product_tpu.analysis.conclint import (
            CONC_RULES,
        )
        from distributed_dot_product_tpu.analysis.determlint import (
            DETERM_RULES,
        )
        from distributed_dot_product_tpu.analysis.flowlint import (
            FLOW_RULES,
        )
        from distributed_dot_product_tpu.analysis.jaxpr_rules import (
            JAXPR_RULES,
        )
        from distributed_dot_product_tpu.analysis.protolint import (
            PROTO_RULES,
        )
        static = (set(AST_RULES) | set(JAXPR_RULES) | set(PROTO_RULES)
                  | set(CONC_RULES) | set(DETERM_RULES)
                  | set(FLOW_RULES) | {'parse-error'})
        runtime_only = [r for r in args.rules if r not in static]
        if runtime_only:
            parser.error(
                f'{", ".join(runtime_only)}: enforced at RUNTIME by the '
                f'retrace sentinel (analysis/retrace.py; on under '
                f'pytest), not statically — there is nothing for this '
                f'command to check')

    if args.changed_only is not None:
        if args.paths:
            parser.error('--changed-only computes its own file set — '
                         'drop the explicit paths')
        try:
            changed = changed_files(args.changed_only)
        except RuntimeError as e:
            parser.error(str(e))
        if not changed:
            # Notices go to stderr: --format json owns stdout.
            print(f'graphlint: no .py files changed vs '
                  f'{args.changed_only} — nothing to lint',
                  file=sys.stderr)
            if args.format != 'text':
                print(format_violations([], fmt=args.format))
            return 0
        args.paths = changed
        if not args.no_jaxpr and not any(
                _affects_registry(p) for p in changed):
            print(f'graphlint: changed files cannot affect registered '
                  f'entrypoints — skipping the jaxpr pass '
                  f'({len(changed)} files, AST rules only)',
                  file=sys.stderr)
            args.no_jaxpr = True

    if not args.no_jaxpr:
        # Force the hermetic 8-device CPU platform BEFORE jax commits
        # to a backend (tracing needs mesh devices, never execution).
        os.environ.setdefault('JAX_PLATFORMS', 'cpu')
        from distributed_dot_product_tpu._compat import (
            ensure_cpu_devices,
        )
        ensure_cpu_devices(8)

    entrypoints = None
    if args.registry:
        from distributed_dot_product_tpu.analysis.registry import (
            resolve_registry_arg,
        )
        try:
            entrypoints = resolve_registry_arg(args.registry)
        except ValueError as e:
            parser.error(str(e))

    from distributed_dot_product_tpu.analysis import (
        active_violations, run_analysis,
    )
    violations = run_analysis(
        paths=args.paths or None, rules=args.rules,
        # Explicit (absolute) changed-file paths still render
        # repo-relative in violations.
        repo_root=_repo_root() if args.changed_only is not None
        else None,
        jaxpr=not args.no_jaxpr, ast_rules=not args.no_ast,
        entrypoints=entrypoints)
    print(format_violations(violations, fmt=args.format))
    # `allowed` records (registration-level debt, e.g. the flax Dense
    # bf16-accum entries) are rendered but never fail the run.
    return 1 if active_violations(violations) else 0


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _affects_registry(path):
    """Can a change to ``path`` alter a registered entrypoint's jaxpr?
    Conservative path heuristic over the LAYER_HOOKS modules plus the
    analysis subsystem itself."""
    norm = os.path.abspath(path).replace(os.sep, '/')
    return any(frag in norm for frag in (
        '/ops/', '/models/', '/parallel/', '/analysis/',
        '/serve/engine.py', '/train.py', '/obs/'))


def changed_files(ref='HEAD'):
    """The .py files changed vs ``ref`` (tracked diff + untracked),
    as absolute paths of files that still exist. RuntimeError when git
    cannot resolve the ref — the CLI maps it to a usage error."""
    import subprocess
    root = _repo_root()

    def _git(*argv):
        res = subprocess.run(['git', *argv], cwd=root,
                             capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(
                f'--changed-only: git {" ".join(argv)} failed: '
                f'{res.stderr.strip() or res.stdout.strip()}')
        return res.stdout.splitlines()

    names = _git('diff', '--name-only', '--diff-filter=d', ref,
                 '--', '*.py')
    names += _git('ls-files', '--others', '--exclude-standard',
                  '--', '*.py')
    out = []
    for name in dict.fromkeys(n.strip() for n in names if n.strip()):
        # The deliberate-violation fixture tree is excluded from the
        # full walk (iter_python_files); explicitly-named files bypass
        # that exclusion, so a changed-files sweep must apply it here
        # or any PR touching a fixture fails its own pre-commit lint.
        if 'graphlint_fixtures' in name or '__pycache__' in name:
            continue
        path = os.path.join(root, name)
        if os.path.isfile(path):
            out.append(path)
    return out


if __name__ == '__main__':
    try:
        sys.exit(main())
    except BrokenPipeError:     # `... | head` closed the pipe: not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
