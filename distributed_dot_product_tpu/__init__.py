# -*- coding: utf-8 -*-
"""
distributed_dot_product_tpu — a TPU-native (JAX/XLA/shard_map) framework for
operator-level sequence (context) parallelism of dot-product attention.

Brand-new implementation with the capabilities of the reference library
``andfoy/py-distributed-dot-product`` (PyTorch + Horovod/NCCL/MPI): the three
distributed sequence matmuls ``A·Bᵀ`` ("nt"), ``A·B`` ("all") and ``Aᵀ·B``
("tn") with a chunk-size (``offset``) memory/time knob, their custom
gradients, and a multi-head ``DistributedDotProductAttn`` module that shards
the time axis ``T`` across ``N`` devices so each holds a ``(*, T/N, d)``
slice (reference README.md:4-15).

Architecture (TPU-first, not a port):

- one compiled SPMD program over a 1-D ``jax.sharding.Mesh`` axis ``'seq'``
  replaces the reference's N OS processes + eager named collectives
  (reference comm.py:6-10, functions.py:95);
- ``lax.all_gather`` / ``lax.psum_scatter`` / ``lax.ppermute`` over ICI
  replace Horovod allgather/allreduce over NCCL/MPI (reference
  functions.py:95,143-147);
- ``jax.custom_vjp`` replaces ``torch.autograd.Function`` (reference ops.py);
- single-process multi-device CPU simulation
  (``--xla_force_host_platform_device_count``) replaces
  ``horovodrun -np N --mpi pytest`` (reference README.md:171-177).

Version parity note: the reference exposes ``VERSION_INFO`` in its
``__init__.py`` (reference __init__.py:9-10); we keep the same convention.
"""

from distributed_dot_product_tpu import _compat  # noqa: F401  (shims first)
from distributed_dot_product_tpu._version import (  # noqa: F401
    VERSION_INFO, __version__,
)

from distributed_dot_product_tpu.utils.comm import (  # noqa: F401
    SEQ_AXIS, get_rank, get_world_size, is_main_process, synchronize, init,
)
from distributed_dot_product_tpu.parallel.mesh import (  # noqa: F401
    seq_mesh, seq_spec, replicated_spec, shard_seq,
)
from distributed_dot_product_tpu.ops.functions import (  # noqa: F401
    distributed_matmul_nt, distributed_matmul_tn, distributed_matmul_all,
)
from distributed_dot_product_tpu.ops.ops import (  # noqa: F401
    matmul_nt, matmul_all, matmul_tn,
    RightTransposeMultiplication, FullMultiplication,
    LeftTransposeMultiplication,
)
from distributed_dot_product_tpu.models.attention import (  # noqa: F401
    DistributedDotProductAttn, apply_seq_parallel, decode_seq_parallel,
    make_decode_step,
)
from distributed_dot_product_tpu.models.ring_attention import (  # noqa: F401
    local_attention_reference, ring_attention,
)
from distributed_dot_product_tpu.models.decode import (  # noqa: F401
    DecodeCache, append_kv, append_kv_sharded, append_kv_slots,
    decode_attention, decode_kernel_eligible, decode_step, init_cache,
    init_slot_cache, reset_slot, slots_all_finite,
)
from distributed_dot_product_tpu.models.dense import (  # noqa: F401
    OwnedDense, quantize_dense_params, quantize_kernel,
)
from distributed_dot_product_tpu.models.transformer import (  # noqa: F401
    TransformerBlock, TransformerStack,
)
from distributed_dot_product_tpu.models.lm import (  # noqa: F401
    TransformerLM, greedy_generate, lm_targets,
)
from distributed_dot_product_tpu.models.ulysses_attention import (  # noqa: F401
    ulysses_attention,
)
from distributed_dot_product_tpu.ops.pallas_attention import (  # noqa: F401
    flash_attention,
)
from distributed_dot_product_tpu.ops.rope import (  # noqa: F401
    rope, rope_seq_parallel,
)
from distributed_dot_product_tpu.utils.checkpoint import (  # noqa: F401
    CheckpointMismatchError, TrainState, gc_old_steps, latest_step,
    recover_interrupted, restore, save, wait,
)
from distributed_dot_product_tpu.train_loop import (  # noqa: F401
    TrainLoopConfig, TrainLoopResult, run_training,
)
from distributed_dot_product_tpu.serve import (  # noqa: F401
    HealthMonitor, KernelEngine, Readiness, RejectReason, RejectedError,
    Scheduler, ServeConfig,
)
