# -*- coding: utf-8 -*-
"""
Unified observability layer: spans, the structured event log, request
timelines, and the Prometheus exporter.

Grown from the reference's ``measure`` decorator (reference
functions.py:24-41) and the in-process ``MetricsRegistry``
(utils/tracing.py) into a real subsystem — see each submodule:

- :mod:`~distributed_dot_product_tpu.obs.spans` — nestable host-side
  wall-time spans with a zero-overhead disabled path.
- :mod:`~distributed_dot_product_tpu.obs.events` — append-only
  schema-versioned JSONL event log (serve/train/health/fault lifecycle
  vocabulary), crash-safe flushing, size-based rotation.
- :mod:`~distributed_dot_product_tpu.obs.timeline` — per-request
  lifecycle reconstruction over the event log (multi-replica log sets
  merge through ``events.merge_events``).
- :mod:`~distributed_dot_product_tpu.obs.slo` — goodput-under-SLO
  accounting from the event log alone (SloSpec, per-tenant breakdowns,
  the ``slo check`` CI gate against ``SLO_BASELINE.json``).
- :mod:`~distributed_dot_product_tpu.obs.exporter` — Prometheus-text
  rendering of the metrics registry plus the optional ``/metrics`` +
  ``/healthz`` + ``/profile`` HTTP thread (off by default).
- :mod:`~distributed_dot_product_tpu.obs.perf` — compiled-program
  cost/roofline accounting over the analysis registry and the
  perf-regression gate (``python -m distributed_dot_product_tpu.obs.
  perf {snapshot,check,report}``; scripts/ci.sh stage [5/5]).
- :mod:`~distributed_dot_product_tpu.obs.devmon` — live device-memory
  telemetry gauges and guarded on-demand ``jax.profiler`` captures.
- :mod:`~distributed_dot_product_tpu.obs.flight` — the incident flight
  recorder: a hard-bounded black-box ring teeing the event log +
  metric/device samples, dumped as schema-versioned post-mortem
  bundles on stall / exception / NaN-storm / anomaly / SIGTERM /
  ``GET /dump``.
- :mod:`~distributed_dot_product_tpu.obs.anomaly` — pluggable online
  detectors (EWMA z-score, static threshold, rate-of-change) over the
  registry's metric streams, emitting ``anomaly.detected`` events and
  chaining profile captures / flight dumps.
- :mod:`~distributed_dot_product_tpu.obs.doctor` — post-mortem bundle
  diagnosis (``python -m distributed_dot_product_tpu.obs doctor
  BUNDLE``): classify the incident and name affected tenants/requests
  from the bundle alone.

CLI: ``python -m distributed_dot_product_tpu.obs validate <log.jsonl>``
schema-checks a log offline; ``... stats <log.jsonl>`` summarizes it
operationally; ``... timeline <log.jsonl> <request-id>`` prints one
request's reconstructed lifecycle (scripts/ci.sh and
scripts/smoke_serve.sh drive them).
"""

from distributed_dot_product_tpu.obs.devmon import (  # noqa: F401
    CaptureInFlight, DeviceMonitor, ProfileCapture,
    device_stats_snapshot,
)
from distributed_dot_product_tpu.obs.anomaly import (  # noqa: F401
    AnomalyWatchdog, EwmaZScore, RateOfChange, StaticThreshold, Watch,
    default_watches,
)
from distributed_dot_product_tpu.obs.events import (  # noqa: F401
    EVENT_SCHEMA, SCHEMA_VERSION, EventLog, activate, emit, get_active,
    merge_events, open_from_env, read_events, remove_log, set_active,
    validate_file,
)
from distributed_dot_product_tpu.obs.flight import (  # noqa: F401
    FlightRecorder, load_bundle,
)
from distributed_dot_product_tpu.obs.slo import (  # noqa: F401
    SloReport, SloSpec, check_baseline, goodput,
)
from distributed_dot_product_tpu.obs.exporter import (  # noqa: F401
    MetricsServer, render_prometheus,
)
from distributed_dot_product_tpu.obs.spans import (  # noqa: F401
    SpanCollector, SpanRecord, collecting, enable, enabled,
    get_collector, span, spanned,
)
from distributed_dot_product_tpu.obs.timeline import (  # noqa: F401
    Timeline, reconstruct, timeline,
)

__all__ = [
    'EVENT_SCHEMA', 'SCHEMA_VERSION', 'EventLog', 'activate', 'emit',
    'get_active', 'merge_events', 'open_from_env', 'read_events',
    'remove_log', 'set_active', 'validate_file', 'SloReport', 'SloSpec',
    'check_baseline', 'goodput', 'MetricsServer', 'render_prometheus',
    'SpanCollector', 'SpanRecord', 'collecting', 'enable', 'enabled',
    'get_collector', 'span', 'spanned', 'Timeline', 'reconstruct',
    'timeline', 'CaptureInFlight', 'DeviceMonitor', 'ProfileCapture',
    'device_stats_snapshot', 'FlightRecorder', 'load_bundle',
    'AnomalyWatchdog', 'EwmaZScore', 'RateOfChange', 'StaticThreshold',
    'Watch', 'default_watches',
]


def graphlint_entrypoints():
    """Static-analysis registration hook (analysis/registry.py): trace
    the serving engine's decode program THROUGH a host-side span — the
    supported composition — and require the cache-alias / precision
    contracts to hold unchanged. A span that leaked ops or constants
    into the traced program (the clock-in-jit hazard the AST rule
    rejects in jitted bodies) would surface here as a rule violation or
    a jaxpr diff against the engine's own entry."""

    def spanned_decode():
        import jax.numpy as jnp

        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        from distributed_dot_product_tpu.obs.spans import span
        from distributed_dot_product_tpu.serve.engine import KernelEngine

        eng = KernelEngine(slots=2, t_max=16, decode_impl='xla')
        tokens = jnp.zeros((2,), jnp.int32)
        active = jnp.ones((2,), bool)
        poison = jnp.zeros((2,), bool)

        def dispatch(cache, tokens, active, poison):
            # The span wraps the dispatch from the HOST side; the traced
            # body below it must come out identical to the unspanned
            # engine entry (serve.engine_decode).
            with span('obs.decode_dispatch'):
                return eng._decode_impl(cache, tokens, active, poison)

        return TraceSpec(
            name='obs.spanned_decode', fn=dispatch,
            args=(eng.cache, tokens, active, poison),
            cache_in=lambda a: [a[0].k, a[0].v],
            cache_out=lambda o: [o[0].k, o[0].v])

    return {'obs.spanned_decode': spanned_decode}
