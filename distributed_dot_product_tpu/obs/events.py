# -*- coding: utf-8 -*-
"""
Append-only, schema-versioned JSONL event log — the durable record of
everything the serving and training loops DO, next to the metrics that
record what they COST.

Design:

- **One line per event**, JSON, schema-versioned: every record carries
  ``{"schema": 1, "seq": N, "ts": <unix>, "event": "<name>", ...}``.
  ``seq`` is a per-log monotonic counter, the authoritative order (and
  the tiebreak for equal timestamps); ``ts`` comes from an injectable
  wall clock.
- **Closed vocabulary**: :data:`EVENT_SCHEMA` names every event and its
  required fields. Emitting an unknown event or dropping a required
  field raises immediately — the log is an audited contract, not a
  printf stream, and ``python -m distributed_dot_product_tpu.obs
  validate`` re-checks the same schema offline (scripts/ci.sh runs it
  over the smoke-serve run).
- **Crash-safe flushing**: each emit writes one complete line and
  flushes the stream, so a crash loses at most the event being written
  mid-line (a torn tail line is detected, not silently absorbed, by the
  readers). ``fsync=True`` additionally fsyncs per emit for logs that
  must survive power loss.
- **Size-based rotation**: past ``rotate_bytes`` the file rotates
  through ``path.1 .. path.<keep_rotations>`` (newest = ``.1``);
  :func:`read_events` reassembles the rotated set in order.

The *active log* is a process-wide slot (:func:`set_active` /
:func:`activate`): the serving scheduler, the health monitor, the fault
injectors, and ``utils.tracing.log_step`` / ``log_exception`` all emit
through :func:`emit`, which no-ops when no log is active — so wiring
observability into a run is one ``with activate(EventLog(path)):``.
"""

import contextlib
import json
import os
import threading
import time
from typing import Optional

__all__ = ['SCHEMA_VERSION', 'SUPPORTED_SCHEMAS', 'EVENT_SCHEMA',
           'EventLog', 'emit', 'get_active', 'set_active', 'activate',
           'open_from_env', 'read_events', 'merge_events',
           'remove_log', 'validate_record', 'validate_file', 'ENV_VAR']

# v2 added the required `tenant` field on serve.admit / serve.reject
# (multi-tenant SLO accounting); v1 logs predate tenancy and stay
# readable — validation exempts them from the v2-only fields.
SCHEMA_VERSION = 2
SUPPORTED_SCHEMAS = (1, 2)

ENV_VAR = 'DDP_TPU_EVENT_LOG'

# The complete lifecycle vocabulary: event name -> required fields
# (beyond the envelope fields schema/seq/ts/event). Extra fields are
# allowed; missing required fields or unknown names raise at emit AND
# fail offline validation.
EVENT_SCHEMA = {
    # -- serving lifecycle (serve/scheduler.py, serve/admission.py) ----
    # `reason` values come from admission.RejectReason: queue_full,
    # deadline_exceeded, prompt_too_long, cache_exhausted (paged
    # KV-pool exhaustion — static impossibility at submit, or spent
    # preemption retries stamped on the terminal evict/retire),
    # prefix_unregistered (unknown/unregistered shared prefix),
    # no_replica (router-level shed), replica_lost (in-flight stream's
    # replica died and recovery could not re-place it).
    # `tenant` (schema >= 2): the tenant label load/SLO accounting
    # groups by — every admit/reject carries it, so per-tenant goodput
    # is derivable from the log alone (obs/slo.py).
    'serve.admit': ('request_id', 'slot', 'tenant'),
    'serve.reject': ('request_id', 'reason', 'tenant'),
    'serve.evict': ('request_id', 'slot'),
    'serve.prefill': ('request_id', 'slot', 'pos'),
    'serve.decode': ('request_id', 'slot', 'token_index'),
    'serve.retire': ('request_id', 'status'),
    'serve.quarantine': ('request_id', 'slot', 'requeued'),
    # Paged pool ran dry under this slot mid-stream: slot freed, request
    # requeued (True) or terminally evicted CACHE_EXHAUSTED (False).
    # A controller drain (serve/control.py) emits the same arc with an
    # extra `drain: true` — the request requeues onto ANOTHER replica.
    'serve.preempt': ('request_id', 'slot', 'requeued'),
    # The degradation rung engaged: the request was admitted with a
    # CAPPED token budget because pressure crossed `watermark`
    # (`reason` names the source: queue / page_pool). State-exempt in
    # the timeline automaton — it precedes the admit/reject verdict.
    'serve.degrade': ('request_id', 'watermark', 'reason', 'tenant'),
    # -- disaggregated serving (serve/router.py, serve/replica.py) -----
    # The router placed a request on a decode replica: `target` names
    # it, `policy` how it was chosen (prefix / session / load). Lives
    # in the ROUTER's log; the request's admit→retire lifecycle lives
    # in the named replica's — reconstruct over the merged labeled set
    # follows the request across both. (`target`, not `replica`: the
    # multi-log merge annotates every record with its SOURCE under
    # `replica`.) A router shed (every replica queue full) is a
    # `serve.reject` with reason `no_replica`.
    'router.route': ('request_id', 'target'),
    # The prefill pool computed a prompt's KV sequence-sharded and
    # handed it to `target` as whole pool pages
    # (KernelEngine.adopt_prefix): `pages` moved, `rows` of KV they
    # cover. Lives in the PREFILL pool's log.
    'prefill.handoff': ('request_id', 'target', 'pages'),
    # -- replica failure domains (serve/router.py, serve/replica.py) ---
    # The router declared a decode replica dead: `target` names it,
    # `reason` how the loss surfaced (crash / probe_timeout /
    # handoff_crash), `in_flight` how many ledger entries were live on
    # it at declaration time. Lives in the ROUTER's log — the dead
    # replica's own log is torn at the crash point and closes nothing.
    'replica.lost': ('target', 'reason', 'in_flight'),
    # One router liveness probe verdict for `target`: `state` is
    # 'ok' (answered, clears the miss streak) or 'missed' (no answer;
    # an extra `misses` field carries the consecutive-miss count that
    # drives the bounded exponential backoff toward declaration).
    'replica.probe': ('target', 'state'),
    # A (restarted) replica rejoined the pool through add_replica with
    # a fresh pool: `target` is its NEW name (names are never reused),
    # an extra `replicas` field carries the post-join pool size.
    'replica.rejoin': ('target',),
    # A stream that was in flight on a lost replica was resolved by the
    # recovery ledger: requeued=True → re-dispatched to a survivor via
    # replay-prefill (`target` names it; original-submit TTFT/deadline
    # anchors preserved, so the survivor's terminal closes the arc);
    # requeued=False → recovery budget/survivor set exhausted, a
    # terminal serve.reject reason=replica_lost follows in this log.
    # Always returns the request to 'queued' in the timeline automaton:
    # its slot died with the replica.
    'request.recovered': ('request_id', 'from_replica', 'requeued'),
    # KV page integrity (router-side verdict): pool page(s) of `target`
    # (a decode replica or the prefill pool) failed checksum
    # verification at `site` ('scrub' / 'attach' / 'fork' /
    # 'handoff_src' / 'handoff_copy'); `pages` lists them. The pages
    # are quarantined and every prefix built on them invalidated
    # cluster-wide; request.recovered events (reason=kv_corrupt) for
    # the victim streams follow in this log. No request_id: corruption
    # is a page-level event — per-request arcs close through the
    # recovered/terminal records.
    'kv.corrupt': ('target', 'pages', 'site'),
    # The router declared the shared prefill pool dead (probe timeout,
    # same observational discipline as replica.lost): `target` names
    # it, `reason` how the loss surfaced. Routing falls back to flat
    # prefill on the decode replicas — no stream blocks on a dead
    # pool; rebuild_prefill() restores offload under a fresh name.
    'prefill.lost': ('target', 'reason'),
    # -- speculative decoding (serve/scheduler.py spec ticks) ----------
    # A proposer guessed `proposed` continuation tokens for the slot
    # this tick (`proposer` names which: ngram/draft/custom).
    'spec.propose': ('request_id', 'slot', 'proposed'),
    # One fused verify step resolved the guesses: `accepted` of the
    # `proposed` survived greedy verification; accepted + 1 tokens
    # committed (the free token) unless a terminal condition truncated
    # the commit — the serve.decode events alongside carry the tokens.
    'spec.verify': ('request_id', 'slot', 'proposed', 'accepted'),
    # -- training driver (train_loop.py via utils.tracing.log_step) ----
    'train.step': ('step', 'loss'),
    'train.bad_step': ('step',),
    'train.checkpoint_save': ('step', 'seconds'),
    'train.restore': ('step',),
    'train.rollback': ('step',),
    # -- health surface (serve/health.py) ------------------------------
    'health.liveness': ('state',),
    'health.readiness': ('state',),
    # -- fault injection (utils/faults.py) -----------------------------
    'fault.inject': ('kind',),
    # -- perf observatory (obs/perf.py, obs/devmon.py) -----------------
    # One bounded jax.profiler capture began (manual /profile hit or
    # the scheduler's adaptive ttft-p99 trigger — `trigger` names it).
    'profile.capture': ('trigger', 'seconds', 'path'),
    # `perf check` found a per-entry tolerance violation against the
    # committed baseline (entry = registry name, metric = which gate).
    'perf.regression': ('entry', 'metric'),
    # Dispatch-floor accounting: one record per decode tick that ran a
    # device program. `tick_seconds` is the REAL wall time of the whole
    # scheduler tick body, `device_seconds` the slice spent inside
    # compiled-program invocations (engine.program_seconds delta), so
    # `overhead = tick_seconds - device_seconds` is the host-loop share
    # ROADMAP item 5 targets. `tokens` counts tokens committed by the
    # tick. Carries NO request_id: the floor is a per-tick property of
    # the loop, not of any one stream — timeline reconstruction skips
    # it, `obs critpath` aggregates it into the dispatch-floor section.
    'serve.dispatch': ('step', 'tick_seconds', 'device_seconds'),
    # -- incident layer (obs/anomaly.py, obs/flight.py) ----------------
    # An online detector flagged a metric stream: `metric` is the
    # registry family watched, `detector` the detector class that
    # tripped, `value` the observation that breached. Extra fields
    # (watch name, threshold/mean/sigma) ride along per detector.
    'anomaly.detected': ('metric', 'detector', 'value'),
    # The flight recorder wrote a post-mortem bundle: `trigger` names
    # the cause (stall / exception / nan_storm / anomaly / sigterm /
    # http / manual), `path` the bundle directory.
    'postmortem.dump': ('trigger', 'path'),
    # -- control plane (serve/control.py) ------------------------------
    # The controller moved a scheduler knob: `knob` names it
    # (degrade_watermark / queue_limit), `value` the new setting,
    # `reason` why (breach:<watch> / pressure:<source>:<val> with
    # source queue|page_pool / sustained_headroom). Extra fields:
    # `previous` (the old value),
    # `target` (the replica, in pool mode) — a run's control history
    # reconstructs from these records alone.
    'control.adjust': ('knob', 'value', 'reason'),
    # The controller resized the decode pool: `direction` up/down,
    # `replicas` the NEW pool size, `reason` the signal. A scale-down
    # is always preceded by a control.drain of the victim.
    'control.scale': ('direction', 'replicas', 'reason'),
    # A decode replica was drained for removal: every in-flight and
    # queued request preempted (serve.preempt, requeued=true, in the
    # TARGET replica's log) and resubmitted through the router —
    # `requeued` counts them; no stream drops without a typed reason.
    'control.drain': ('target', 'requeued'),
    # -- SLO observatory (obs/slo.py) ----------------------------------
    # `slo check` found goodput below the committed SLO_BASELINE.json
    # tolerance (`metric` names the gate; `tenant` is present on
    # per-tenant violations, None on the aggregate one).
    'slo.violation': ('metric',),
    # -- swallowed exceptions (utils.tracing.log_exception) ------------
    'exception': ('context', 'type'),
}


# Flight-recorder tee (obs/flight.py installs it): called with every
# record an EventLog emits, as ``(record, encoded_line)``. None when no
# recorder is installed — the disabled path costs exactly one global
# None-check per emit, no allocation (the spans contract).
_TEE = None


# Fields that became REQUIRED at schema v2: records stamped with an
# older version are exempt (a pre-tenancy log stays schema-clean), new
# emits are not.
_V2_FIELDS = {
    'serve.admit': ('tenant',),
    'serve.reject': ('tenant',),
}


def validate_record(rec):
    """Schema-check one decoded record; returns a list of error strings
    (empty = valid). Shared by :meth:`EventLog.emit` and the offline
    validator CLI, so the write-side and read-side contracts cannot
    drift apart. Records from any :data:`SUPPORTED_SCHEMAS` version
    validate against THAT version's requirements — old logs don't rot
    when the vocabulary grows."""
    errors = []
    if not isinstance(rec, dict):
        return [f'record is not an object: {rec!r}']
    schema = rec.get('schema')
    if schema not in SUPPORTED_SCHEMAS:
        errors.append(f'unknown schema version {schema!r} '
                      f'(supported: {SUPPORTED_SCHEMAS})')
    event = rec.get('event')
    if event not in EVENT_SCHEMA:
        errors.append(f'unknown event {event!r}')
        return errors
    for field in ('seq', 'ts'):
        if field not in rec:
            errors.append(f'{event}: missing envelope field {field!r}')
    exempt = (_V2_FIELDS.get(event, ())
              if isinstance(schema, int) and schema < 2 else ())
    for field in EVENT_SCHEMA[event]:
        if field not in rec and field not in exempt:
            errors.append(f'{event}: missing required field {field!r}')
    return errors


def _json_safe(value):
    """Strict-JSON field values: non-finite floats become the strings
    ``'nan'``/``'inf'``/``'-inf'`` (bare ``NaN`` tokens are Python-only
    — jq / Go / BigQuery consumers reject them, and the bad-step
    records a fault log exists for are exactly the NaN-bearing ones).
    Containers are sanitized recursively."""
    if isinstance(value, float):
        if value != value:
            return 'nan'
        if value in (float('inf'), float('-inf')):
            return 'inf' if value > 0 else '-inf'
        return value
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    if isinstance(value, dict):
        return {k: _json_safe(v) for k, v in value.items()}
    return value


class EventLog:
    """Append-only JSONL event sink (see module docstring).

    ``clock`` is injectable (virtual-time tests); ``ts`` is a wall
    timestamp for operators — ``seq`` is the ordering contract.
    """

    def __init__(self, path, *, rotate_bytes=16 * 2 ** 20,
                 keep_rotations=3, fsync=False, clock=time.time):
        self.path = os.fspath(path)
        self.rotate_bytes = int(rotate_bytes)
        self.keep_rotations = int(keep_rotations)
        self.fsync = fsync
        self.clock = clock
        self._lock = threading.Lock()
        self._rotations = 0             # guarded-by: self._lock
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        # Reopening an existing log continues its seq series: seq is
        # the authoritative order, so a second run appending to the
        # same file must not restart at 0 (read_events sorts by seq —
        # duplicated values would interleave the two runs' records).
        self._seq = self._resume_seq()  # guarded-by: self._lock
        self._fh = open(self.path, 'a', encoding='utf-8')  # guarded-by: self._lock
        self._size = self._fh.tell()    # guarded-by: self._lock

    def _resume_seq(self):
        if not os.path.exists(self.path):
            return 0
        # A crash-torn tail has no trailing newline; appending onto it
        # would merge the next record into the torn fragment MID-file,
        # where readers rightly refuse it. Drop the fragment (it was
        # never a complete record) before appending.
        with open(self.path, 'rb+') as f:
            data = f.read()
            if data and not data.endswith(b'\n'):
                last_nl = data.rfind(b'\n')
                f.truncate(last_nl + 1 if last_nl >= 0 else 0)
        last = -1
        with open(self.path, encoding='utf-8') as f:
            for line in f:
                try:
                    seq = json.loads(line).get('seq')
                except json.JSONDecodeError:
                    continue        # complete-but-corrupt line
                if isinstance(seq, int):
                    last = max(last, seq)
        return last + 1

    # -- write side -----------------------------------------------------
    def emit(self, event, **fields):
        """Append one schema-validated event; returns the full record
        (envelope included) for callers that also want it in-process."""
        rec = {'schema': SCHEMA_VERSION, 'seq': None,
               'ts': self.clock(), 'event': event}
        rec.update({k: _json_safe(v) for k, v in fields.items()})
        with self._lock:
            rec['seq'] = self._seq
            errors = validate_record(rec)
            if errors:
                raise ValueError(
                    f'invalid event {event!r}: ' + '; '.join(errors))
            line = json.dumps(rec, separators=(',', ':'),
                              allow_nan=False, default=str)
            self._seq += 1
            self._fh.write(line + '\n')
            # Flush per line: a crash loses at most the line being
            # written, and readers (smoke audits tailing a live run)
            # always see complete records.
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
            self._size += len(line) + 1
            # Tee into the flight recorder's ring (already-encoded line
            # — no second serialization). Inside the lock so the ring
            # sees records in the same order the file does.
            tee = _TEE
            if tee is not None:
                tee(rec, line)
            if self._size >= self.rotate_bytes:
                self._rotate_locked()
        return rec

    def _rotate_locked(self):
        self._fh.close()
        oldest = f'{self.path}.{self.keep_rotations}'
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.keep_rotations - 1, 0, -1):
            src = f'{self.path}.{i}'
            if os.path.exists(src):
                os.replace(src, f'{self.path}.{i + 1}')
        os.replace(self.path, f'{self.path}.1')
        self._fh = open(self.path, 'a', encoding='utf-8')
        self._size = 0
        self._rotations += 1

    @property
    def rotations(self):
        with self._lock:
            return self._rotations

    def files(self):
        """Existing log files, oldest first (rotated set then the live
        file) — the read order that makes ``seq`` non-decreasing."""
        out = [f'{self.path}.{i}'
               for i in range(self.keep_rotations, 0, -1)
               if os.path.exists(f'{self.path}.{i}')]
        if os.path.exists(self.path):
            out.append(self.path)
        return out

    def flush(self):
        with self._lock:
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())

    def close(self):
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()
                self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# -- the process-wide active log ----------------------------------------

_ACTIVE: Optional[EventLog] = None
_ACTIVE_LOCK = threading.Lock()


def get_active() -> Optional[EventLog]:
    return _ACTIVE


def set_active(log: Optional[EventLog]) -> Optional[EventLog]:
    """Install ``log`` as the process-wide sink; returns the previous
    one (for restoration)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        prev, _ACTIVE = _ACTIVE, log
    return prev


@contextlib.contextmanager
def activate(log: EventLog):
    """Scoped :func:`set_active` (the normal way to wire a run)."""
    prev = set_active(log)
    try:
        yield log
    finally:
        set_active(prev)


def emit(event, _log: Optional[EventLog] = None, **fields):
    """Emit through ``_log``, or the active log, or nowhere (no-op when
    neither exists) — the call sites sprinkled through serve/train/fault
    code pay one None-check when logging is off."""
    log = _log if _log is not None else _ACTIVE
    if log is None:
        return None
    return log.emit(event, **fields)


def remove_log(path):
    """Delete a log AND its rotated set — the fresh-file guarantee a
    one-shot run wants before opening its EventLog (which otherwise
    APPENDS, resuming the seq series; a stale previous run would then
    double every reconstructed timeline). Owns the rotation naming so
    callers don't hardcode it."""
    path = os.fspath(path)
    for p in _log_files(path):
        os.remove(p)


def open_from_env(environ=None) -> Optional[EventLog]:
    """An :class:`EventLog` at ``$DDP_TPU_EVENT_LOG``, or None when the
    knob is unset — how shell drivers (scripts/smoke_serve.sh) attach a
    log without touching python."""
    env = os.environ if environ is None else environ
    path = env.get(ENV_VAR)
    return EventLog(path) if path else None


# -- read side ------------------------------------------------------------

def _log_files(path):
    """Rotated set for ``path`` (oldest first), accepting either the
    live file or a directory-less prefix."""
    path = os.fspath(path)
    rotated = []
    i = 1
    while os.path.exists(f'{path}.{i}'):
        rotated.append(f'{path}.{i}')
        i += 1
    out = list(reversed(rotated))
    if os.path.exists(path):
        out.append(path)
    return out


def read_events(source):
    """Decode every event from ``source`` — an :class:`EventLog`, a path
    (its rotated set is reassembled), or an iterable of already-decoded
    records. Returns records sorted by ``seq``. A torn tail line (crash
    mid-write) is tolerated on the LAST line of the newest file only;
    anywhere else it raises."""
    if isinstance(source, EventLog):
        files = source.files()
    elif isinstance(source, (str, os.PathLike)):
        files = _log_files(source)
    else:
        return sorted(source, key=lambda r: r.get('seq', 0))
    records = []
    for fi, fname in enumerate(files):
        with open(fname, encoding='utf-8') as f:
            lines = f.read().splitlines()
        for li, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                last = (fi == len(files) - 1 and li == len(lines) - 1)
                if not last:
                    raise ValueError(
                        f'{fname}:{li + 1}: corrupt event line '
                        f'(not the crash-torn tail): {line[:80]!r}')
    return sorted(records, key=lambda r: r.get('seq', 0))


def merge_events(sources):
    """Merge the event streams of several logs — one per serving
    replica (ROADMAP item 2: a request's prefill and decode happen in
    different pools, so its lifecycle spans two JSONL files) — into ONE
    seq-consistent record list.

    ``sources`` is an iterable of log paths (each read through
    :func:`read_events`, so rotated sets and a crash-torn tail on any
    source are handled) or ``(replica, path)`` pairs naming the source;
    bare paths get ``r0, r1, ...`` labels. Every returned record is
    annotated with its ``replica`` label.

    Ordering contract: within one source, per-source ``seq`` stays
    authoritative (records of a source never reorder relative to each
    other, whatever their timestamps — a replica's own clock can
    stutter). Across sources, heads are merged by ``(ts, source
    index)`` — a stable k-way merge, so equal timestamps resolve in
    source order and the merge is deterministic."""
    streams = []
    seen_labels = set()
    for i, src in enumerate(sources):
        if isinstance(src, (tuple, list)) and len(src) == 2:
            label, path = src
        else:
            label, path = f'r{i}', src
        if str(label) in seen_labels:
            # Two sources under one label would collapse into one
            # indistinguishable replica (and silently interleave their
            # seq series) — a mislabeled merge is a typed error, not a
            # corrupted timeline.
            raise ValueError(
                f'duplicate replica label {str(label)!r} in '
                f'merge_events sources — label each source uniquely '
                f'(replica=path)')
        seen_labels.add(str(label))
        recs = read_events(path)
        for rec in recs:
            rec.setdefault('replica', str(label))
        streams.append(recs)
    merged = []
    heads = [0] * len(streams)
    while True:
        best = None
        for si, recs in enumerate(streams):
            if heads[si] >= len(recs):
                continue
            key = (recs[heads[si]].get('ts', 0), si)
            if best is None or key < best:
                best, bi = key, si
        if best is None:
            return merged
        merged.append(streams[bi][heads[bi]])
        heads[bi] += 1


def validate_file(path):
    """Offline schema validation over a log's rotated set: returns
    ``(records, errors)`` where ``errors`` is a list of strings (empty
    = the log is schema-clean)."""
    errors = []
    try:
        records = read_events(path)
    except ValueError as e:
        return [], [str(e)]
    for rec in records:
        for err in validate_record(rec):
            errors.append(f'seq={rec.get("seq")}: {err}')
    return records, errors
