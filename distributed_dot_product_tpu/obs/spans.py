# -*- coding: utf-8 -*-
"""
Hierarchical host-side wall-time spans — the structured successor of the
reference ``measure`` decorator (reference functions.py:24-41), grown
from a per-call print into a nestable tree an operator can read.

Contract (the part graphlint enforces — see analysis/astlint.py):

- Spans time HOST-side work: dispatch, readback, scheduling, I/O. A
  ``span`` inside a jitted function would read the clock at TRACE time
  and bake a constant into the compiled program, so the ``clock-in-jit``
  rule rejects ``span(...)`` calls in jit-decorated functions (negative
  fixture: tests/graphlint_fixtures/fx_span_in_jit.py). Wrap the
  *dispatch* of a compiled step, never its body.
- **Zero-overhead disabled path**: when collection is off (the default),
  :func:`span` returns a shared null context manager — no allocation,
  no lock, no clock read. Production code can leave spans in place.
- When enabled, each span additionally enters a
  ``jax.profiler.TraceAnnotation`` scope, so a ``jax.profiler.trace``
  capture shows the same names on the host timeline (the annotation is
  a no-op outside an active capture).
- Thread-safe: nesting is tracked per thread (thread-local stacks), the
  finished-span buffer is shared and lock-protected.

Usage::

    from distributed_dot_product_tpu.obs import span, spanned, enable

    enable(True)                      # or DDP_TPU_SPANS=1
    with span('train.step', step=i):
        record = step_fn(...)         # host dispatch + readback

    @spanned('benchmark.compile')
    def compile_phase(...): ...

    for rec in get_collector().records():
        print(rec.path, rec.seconds)
"""

import collections
import dataclasses
import functools
import os
import threading
import time
from typing import Optional, Tuple

__all__ = ['span', 'spanned', 'enable', 'enabled', 'collecting',
           'get_collector', 'SpanCollector', 'SpanRecord']

ENV_VAR = 'DDP_TPU_SPANS'


@dataclasses.dataclass(frozen=True)
class SpanRecord:
    """One finished span. ``path`` is the slash-joined ancestry on this
    thread (``'serve.tick/engine.decode_step'``), ``depth`` its nesting
    level, ``start`` a ``perf_counter`` timestamp (comparable within the
    process only)."""
    name: str
    path: str
    start: float
    seconds: float
    depth: int
    thread: str
    attrs: Tuple[Tuple[str, object], ...] = ()
    ok: bool = True

    def as_dict(self):
        return {'name': self.name, 'path': self.path,
                'start': self.start, 'seconds': self.seconds,
                'depth': self.depth, 'thread': self.thread,
                'attrs': dict(self.attrs), 'ok': self.ok}


class SpanCollector:
    """Bounded buffer of finished spans plus per-thread nesting stacks.

    ``registry``: when set, every finished span also observes its
    duration into ``registry.histogram('span.<name>.seconds')`` — so a
    metrics snapshot / the Prometheus exporter carries span latency
    percentiles without a separate pipeline."""

    def __init__(self, *, registry=None, maxlen=65536):
        self.enabled = False
        self.registry = registry
        self._lock = threading.Lock()
        self._records = collections.deque(maxlen=maxlen)  # guarded-by: self._lock
        self._tls = threading.local()

    def _stack(self):
        stack = getattr(self._tls, 'stack', None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def add(self, record: SpanRecord):
        with self._lock:
            self._records.append(record)
        reg = self.registry
        if reg is not None:
            reg.histogram(f'span.{record.name}.seconds').observe(
                record.seconds)

    def records(self):
        with self._lock:
            return list(self._records)

    def clear(self):
        with self._lock:
            self._records.clear()

    def summary(self):
        """``{name: {'count', 'total_seconds', 'max_seconds'}}`` over the
        buffered records — the compact form ``benchmark.py
        --metrics-out`` serializes."""
        out = {}
        for rec in self.records():
            agg = out.setdefault(rec.name, {'count': 0,
                                            'total_seconds': 0.0,
                                            'max_seconds': 0.0})
            agg['count'] += 1
            agg['total_seconds'] += rec.seconds
            agg['max_seconds'] = max(agg['max_seconds'], rec.seconds)
        return out

    def render(self):
        """Indented one-line-per-span text tree (records are in finish
        order; depth carries the nesting)."""
        return '\n'.join(
            f'{"  " * rec.depth}{rec.name}: {rec.seconds * 1e3:.3f} ms'
            + ('' if rec.ok else ' [raised]')
            for rec in self.records())


_COLLECTOR = SpanCollector()
_COLLECTOR.enabled = bool(os.environ.get(ENV_VAR))


def get_collector() -> SpanCollector:
    return _COLLECTOR


def enable(on=True, *, registry=None):
    """Turn span collection on/off process-wide. ``registry`` (optional)
    mirrors span durations into that metrics registry's histograms."""
    _COLLECTOR.enabled = bool(on)
    if registry is not None:
        _COLLECTOR.registry = registry
    return _COLLECTOR


def enabled() -> bool:
    return _COLLECTOR.enabled


class collecting:
    """Scoped enablement (tests, ``--metrics-out`` runs)::

        with collecting() as col:
            ...
        col.records()
    """

    def __init__(self, *, registry=None):
        self._registry = registry

    def __enter__(self):
        self._prev = (_COLLECTOR.enabled, _COLLECTOR.registry)
        enable(True, registry=self._registry)
        return _COLLECTOR

    def __exit__(self, *exc):
        _COLLECTOR.enabled, _COLLECTOR.registry = self._prev
        return False


class _NullSpan:
    """The disabled path: a shared, stateless context manager. Also
    usable as a decorator (``@span('name')`` at import time with spans
    off): the wrapper re-checks enablement per call, so enabling later
    still records — the span NAME then falls back to the function's
    qualname (use :func:`spanned` to pin an explicit name)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __call__(self, fn):
        return spanned()(fn)


_NULL_SPAN = _NullSpan()


def _trace_annotation(name):
    """A ``jax.profiler.TraceAnnotation`` for ``name``, or None when jax
    (or the annotation API) is unavailable. Imported lazily: the spans
    layer must stay importable without pulling jax at module load."""
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except (ImportError, AttributeError):
        return None


class _LiveSpan:
    """The enabled path. Created only by :func:`span` after the
    enablement check."""

    __slots__ = ('name', 'attrs', '_col', '_start', '_path', '_depth',
                 '_ann')

    def __init__(self, name, attrs, col):
        self.name = name
        self.attrs = attrs
        self._col = col

    def __enter__(self):
        stack = self._col._stack()
        self._depth = len(stack)
        stack.append(self.name)
        self._path = '/'.join(stack)
        self._ann = _trace_annotation(self.name)
        if self._ann is not None:
            self._ann.__enter__()
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        seconds = time.perf_counter() - self._start
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        stack = self._col._stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        self._col.add(SpanRecord(
            name=self.name, path=self._path, start=self._start,
            seconds=seconds, depth=self._depth,
            thread=threading.current_thread().name,
            attrs=tuple(sorted(self.attrs.items())),
            ok=exc_type is None))
        return False

    def __call__(self, fn):
        return spanned(self.name, **self.attrs)(fn)


def span(name, **attrs):
    """Nestable span context manager (see the module docstring).
    ``attrs`` are free-form key/values recorded on the span (kept small
    — they are materialized per finished span)."""
    col = _COLLECTOR
    if not col.enabled:
        return _NULL_SPAN
    return _LiveSpan(name, attrs, col)


def spanned(name=None, **attrs):
    """Decorator form: wrap every call of ``fn`` in a span. Enablement
    is re-checked per call, so decorating at import time is free until
    spans are switched on."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            col = _COLLECTOR
            if not col.enabled:
                return fn(*args, **kwargs)
            with _LiveSpan(label, attrs, col):
                return fn(*args, **kwargs)

        return wrapper

    return deco
