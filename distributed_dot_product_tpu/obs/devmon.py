# -*- coding: utf-8 -*-
"""
Live device telemetry and on-demand profiler capture — the runtime half
of the perf observatory (obs/perf.py is the static, compiler half).

Two pieces:

- :class:`DeviceMonitor` polls ``device.memory_stats()`` for every
  visible device into labeled gauges (``device.memory.bytes_in_use
  {device="tpu:0"}`` …) on a background thread, so the ``/metrics``
  endpoint answers "how full is each chip RIGHT NOW" without any run
  touching the devices itself. Backends without stats (CPU, some
  tunneled PJRT plugins) simply report no gauges — the monitor records
  how many devices answered in ``device.memory.devices_reporting``.
- :class:`ProfileCapture` owns bounded on-demand ``jax.profiler``
  trace captures: one at a time (a second request while one is in
  flight raises :class:`CaptureInFlight` — the ``/profile`` endpoint
  maps it to HTTP 409), each clamped to ``max_seconds``, each recorded
  as a ``profile.capture`` event in the active event log. The spans
  layer already wraps every serve/train phase in a
  ``jax.profiler.TraceAnnotation``, so the captured trace shows those
  names on the host timeline.

The serving scheduler uses :class:`ProfileCapture` for its adaptive
trigger: when the ``serve.ttft`` p99 crosses a configured threshold it
captures one trace (with a cooldown) — the profile of a latency
regression gets taken WHILE it is happening, not re-created later.
"""

import os
import threading
import time
from typing import Optional

from distributed_dot_product_tpu.utils import tracing

__all__ = ['DeviceMonitor', 'device_stats_snapshot', 'ProfileCapture',
           'CaptureInFlight']

# memory_stats() keys worth exporting, when present (PJRT backends vary).
_STAT_KEYS = ('bytes_in_use', 'peak_bytes_in_use', 'bytes_limit',
              'largest_free_block_bytes', 'bytes_reserved',
              'num_allocs')


def _device_label(device):
    plat = getattr(device, 'platform', 'dev')
    return f'{plat}:{getattr(device, "id", 0)}'


def _safe_memory_stats(device):
    """``device.memory_stats()`` or None — the narrowed exception set is
    every "stats unsupported here" shape observed (see
    utils.tracing.device_peak_bytes)."""
    try:
        return device.memory_stats() or None
    except (AttributeError, NotImplementedError, RuntimeError, TypeError):
        return None


def device_stats_snapshot(devices=None):
    """One-shot plain-dict view of every device's memory stats (None on
    backends without them) — the form ``benchmark.py --metrics-out``
    embeds in its JSON artifact."""
    if devices is None:
        import jax
        devices = jax.devices()
    return [{'device': _device_label(d),
             'platform': getattr(d, 'platform', None),
             'device_kind': getattr(d, 'device_kind', None),
             'memory_stats': _safe_memory_stats(d)}
            for d in devices]


class DeviceMonitor:
    """Poll device memory stats into labeled gauges.

    ``devices`` is injectable (tests use fakes; default: all visible
    jax devices, resolved lazily at first poll so constructing a
    monitor never initializes a backend). ``interval`` is the polling
    period of the background thread; :meth:`poll_once` works without
    the thread for callers that poll on their own cadence."""

    def __init__(self, registry: Optional[tracing.MetricsRegistry] = None,
                 *, devices=None, interval=5.0, prefix='device.memory'):
        self.registry = registry or tracing.get_registry()
        self.interval = float(interval)
        self.prefix = prefix
        self._devices = devices
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # label -> keys set on the last poll: lets a later poll mark a
        # device's gauges NaN when it STOPS reporting, instead of
        # serving its last value as if it were live forever.
        self._last_keys = {}
        self._polls = self.registry.counter(f'{prefix}.polls')
        self._reporting = self.registry.gauge(
            f'{prefix}.devices_reporting')

    def _resolve_devices(self):
        if self._devices is None:
            import jax
            self._devices = jax.devices()
        return self._devices

    def poll_once(self):
        """One polling pass; returns ``{device_label: stats_dict}`` for
        the devices that reported (and updates the gauges). A device
        (or stat key) that previously reported and now does not gets
        its gauge set to NaN — a frozen last value would be
        indistinguishable from a live reading at ``/metrics``."""
        out = {}
        seen_keys = {}
        for dev in self._resolve_devices():
            stats = _safe_memory_stats(dev)
            label = _device_label(dev)
            if not stats:
                seen_keys[label] = set()
                continue
            out[label] = stats
            exported = set()
            for key in _STAT_KEYS:
                val = stats.get(key)
                if isinstance(val, (int, float)):
                    exported.add(key)
                    self.registry.gauge(
                        f'{self.prefix}.{key}',
                        labels={'device': label}).set(val)
            seen_keys[label] = exported
        for label, prev in self._last_keys.items():
            for key in prev - seen_keys.get(label, set()):
                self.registry.gauge(f'{self.prefix}.{key}',
                                    labels={'device': label}
                                    ).set(float('nan'))
        self._last_keys = {k: v for k, v in seen_keys.items() if v}
        self._polls.inc()
        self._reporting.set(len(out))
        return out

    # -- background thread ---------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name='obs-devmon', daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:
                tracing.log_exception('devmon.poll', e,
                                      registry=self.registry)
            self._stop.wait(self.interval)

    def stop(self):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class CaptureInFlight(RuntimeError):
    """A trace capture was requested while one is already running."""


class ProfileCapture:
    """Guarded, bounded ``jax.profiler`` trace captures.

    One capture at a time process-wide per instance: :meth:`start`
    raises :class:`CaptureInFlight` while a capture is in flight (the
    ``/profile`` endpoint answers 409; the scheduler's adaptive trigger
    just skips). Durations are clamped to ``(0, max_seconds]`` — an
    unbounded capture would grow without limit and stall the profiler
    for every later request.

    Captures run on a worker thread: ``start`` returns immediately with
    the trace directory (``base_dir/trace-<n>``), emits a
    ``profile.capture`` event, and bumps the ``profile.captures``
    counter. ``join()`` blocks until the in-flight capture (if any)
    lands — tests and shutdown paths use it."""

    def __init__(self, base_dir, *, max_seconds=60.0,
                 default_seconds=3.0,
                 registry: Optional[tracing.MetricsRegistry] = None,
                 clock=time.sleep):
        self.base_dir = os.fspath(base_dir)
        self.max_seconds = float(max_seconds)
        self.default_seconds = float(default_seconds)
        self.registry = registry or tracing.get_registry()
        self._sleep = clock
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        # Explicit in-flight flag, flipped under the lock: a freshly
        # CREATED thread is not yet alive, so Thread.is_alive() alone
        # would let two concurrent start() calls both pass the guard.
        self._in_flight = False     # guarded-by: self._lock
        self._n = 0                 # guarded-by: self._lock
        self._warmed = False
        self._captures = self.registry.counter('profile.captures')
        self._g_busy = self.registry.gauge('profile.capture_in_flight')

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._in_flight

    @property
    def warmed(self) -> bool:
        return self._warmed

    def warmup(self):
        """Pay the profiler's one-time native init NOW (the first
        ``start_trace`` in a process costs ~14 s on this container —
        PR 6's measurement; every later capture is milliseconds). An
        anomaly- or ttft-triggered capture taken before warmup would
        spend its whole bounded window inside init and record nothing
        of the regression it fired on. Synchronous, idempotent
        (returns False when already warmed), guarded like a capture
        (raises :class:`CaptureInFlight` while one runs — warming
        would wedge the active trace). The throwaway trace lands in
        ``base_dir/warmup``; no ``profile.capture`` event or counter —
        it observed nothing."""
        if self._warmed:
            return False
        with self._lock:
            if self._in_flight:
                raise CaptureInFlight(
                    'cannot warm up while a capture is in flight')
            self._in_flight = True
            self._g_busy.set(1)
        path = os.path.join(self.base_dir, 'warmup')
        try:
            os.makedirs(path, exist_ok=True)
            import jax
            jax.profiler.start_trace(path)
            jax.profiler.stop_trace()
            self._warmed = True
        except Exception as e:
            # A backend without a profiler must not fail startup —
            # the later real capture will report its own failure.
            tracing.log_exception('profile.warmup', e,
                                  registry=self.registry)
        finally:
            with self._lock:
                self._in_flight = False
                self._g_busy.set(0)
        return self._warmed

    def start(self, seconds=None, *, trigger='manual', event_log=None,
              **extra):
        """Begin one bounded capture; returns ``{'path', 'seconds',
        'trigger'}``. Raises :class:`CaptureInFlight` when one is
        already running. ``extra`` fields ride on the emitted
        ``profile.capture`` event (the adaptive trigger stamps the p99
        that tripped it)."""
        seconds = (self.default_seconds if seconds is None
                   else float(seconds))
        if not (seconds > 0):
            raise ValueError(f'capture seconds must be > 0, '
                             f'got {seconds}')
        seconds = min(seconds, self.max_seconds)
        with self._lock:
            if self._in_flight:
                raise CaptureInFlight(
                    'a profiler capture is already in flight — one '
                    'trace at a time (retry after it lands)')
            self._in_flight = True
            # Never hand out a directory that already has contents: a
            # restarted process reusing base_dir would otherwise return
            # a path holding the PREVIOUS run's trace, and a consumer
            # reading it mid-capture would load the wrong profile.
            while True:
                self._n += 1
                path = os.path.join(self.base_dir,
                                    f'trace-{self._n:04d}')
                if not os.path.exists(path):
                    break
        try:
            os.makedirs(path, exist_ok=False)
            thread = threading.Thread(
                target=self._capture, args=(path, seconds),
                name='obs-profile-capture', daemon=True)
            self._thread = thread
            # Gauge updates happen under the SAME lock as _in_flight
            # flips (here and in _capture's finally): a finishing
            # worker's set(0) must not land after a newer capture's
            # set(1) and report an in-flight capture as idle.
            with self._lock:
                self._g_busy.set(1)
            thread.start()
        except BaseException:
            # The capture never began: release the guard so the next
            # request isn't refused (409) forever.
            with self._lock:
                self._in_flight = False
                self._g_busy.set(0)
            raise
        # Accounting only after the worker is really running — a
        # failed start must not leave a phantom capture in the metrics
        # or the event log.
        self._captures.inc()
        from distributed_dot_product_tpu.obs import events
        events.emit('profile.capture', _log=event_log,
                    trigger=trigger, seconds=seconds, path=path, **extra)
        return {'path': path, 'seconds': seconds, 'trigger': trigger}

    def _capture(self, path, seconds):
        import jax
        try:
            jax.profiler.start_trace(path)
            self._warmed = True     # the native init is paid now
            try:
                self._sleep(seconds)
            finally:
                jax.profiler.stop_trace()
        except Exception as e:
            # A failed capture must never wedge the guard (the next
            # request would 409 forever) or crash the server thread.
            tracing.log_exception('profile.capture', e,
                                  registry=self.registry)
        finally:
            with self._lock:
                self._in_flight = False
                self._g_busy.set(0)

    def join(self, timeout=None):
        t = self._thread
        if t is not None:
            t.join(timeout)
        return not self.busy
