# -*- coding: utf-8 -*-
"""
Offline event-log tooling::

    python -m distributed_dot_product_tpu.obs validate LOG [LOG...]
        [--require event[,event...]] [--timelines]
    python -m distributed_dot_product_tpu.obs stats LOG [LOG...] [--json]
    python -m distributed_dot_product_tpu.obs timeline LOG REQUEST_ID
        [--json]

``validate`` schema-checks every record of each log's rotated set
against :data:`~distributed_dot_product_tpu.obs.events.EVENT_SCHEMA`
(exit 1 on any violation). ``--require`` additionally demands that the
named events appear at least once — how scripts/smoke_serve.sh asserts
the injected fault cocktail actually landed in the log. ``--timelines``
reconstructs every request and fails on incomplete lifecycles.

``stats`` summarizes a log operationally: per-event-type counts, the
wall-clock span and sustained events/sec, and the rotated-file
accounting (which files exist, their sizes and record counts) —
``--json`` emits the same as one machine-readable object.

``timeline`` prints one request's reconstructed lifecycle; ``--json``
switches to compact machine-readable output with the FULL event
records (the default renders ``(seq, event)`` pairs for humans).

Runs on plain files — no devices touched, safe in any CI stage.
"""

import argparse
import collections
import json
import os
import sys

from distributed_dot_product_tpu.obs.events import (
    _log_files, read_events, validate_file,
)
from distributed_dot_product_tpu.obs.timeline import reconstruct, timeline


def _cmd_validate(args):
    rc = 0
    for path in args.logs:
        records, errors = validate_file(path)
        counts = collections.Counter(r.get('event') for r in records)
        for err in errors:
            print(f'{path}: SCHEMA: {err}')
            rc = 1
        missing = [ev for ev in args.require if not counts.get(ev)]
        for ev in missing:
            print(f'{path}: REQUIRED event never recorded: {ev}')
            rc = 1
        if args.timelines:
            for rid, tl in sorted(reconstruct(records).items()):
                for err in tl.errors:
                    print(f'{path}: TIMELINE {rid}: {err}')
                    rc = 1
        summary = ' '.join(f'{ev}={n}' for ev, n in sorted(counts.items()))
        print(f'{path}: {len(records)} events '
              f'({"OK" if rc == 0 else "INVALID"}) {summary}')
    return rc


def _cmd_stats(args):
    rc = 0
    reports = []
    for path in args.logs:
        if not _log_files(path):
            print(f'{path}: no such log (nor rotated set)',
                  file=sys.stderr)
            rc = 1
            continue
        try:
            records = read_events(path)
        except (ValueError, OSError) as e:
            print(f'{path}: UNREADABLE: {e}', file=sys.stderr)
            rc = 1
            continue
        counts = collections.Counter(r.get('event') for r in records)
        ts = [r['ts'] for r in records if isinstance(
            r.get('ts'), (int, float))]
        span_s = (max(ts) - min(ts)) if len(ts) >= 2 else 0.0
        files = []
        for fname in _log_files(path):
            with open(fname, encoding='utf-8') as f:
                n_lines = sum(1 for line in f if line.strip())
            # `lines` is the RAW non-empty line count — it can exceed
            # the parsed `events` total by one when the newest file
            # ends in a crash-torn tail line (which read_events
            # tolerates and skips).
            files.append({'path': fname,
                          'bytes': os.path.getsize(fname),
                          'lines': n_lines})
        reports.append({
            'log': path, 'events': len(records),
            'wall_span_seconds': span_s,
            'events_per_second': (len(records) / span_s if span_s
                                  else None),
            'first_ts': min(ts) if ts else None,
            'last_ts': max(ts) if ts else None,
            'by_event': dict(sorted(counts.items(),
                                    key=lambda kv: str(kv[0]))),
            'files': files,
        })
    if args.json:
        # Always a list — one element per readable log — so consumers
        # get a stable shape regardless of how many paths were passed.
        print(json.dumps(reports, indent=2, default=str))
        return rc
    for rep in reports:
        rate = (f'{rep["events_per_second"]:.1f}/s'
                if rep['events_per_second'] else 'n/a')
        print(f'{rep["log"]}: {rep["events"]} events over '
              f'{rep["wall_span_seconds"]:.2f}s ({rate}) in '
              f'{len(rep["files"])} file(s)')
        for ev, n in rep['by_event'].items():
            print(f'  {ev:24} {n}')
        for fi in rep['files']:
            print(f'  file {fi["path"]}: {fi["lines"]} lines, '
                  f'{fi["bytes"]} bytes')
    return rc


def _cmd_timeline(args):
    tl = timeline(args.request_id, args.log)
    payload = {
        'request_id': tl.request_id, 'status': tl.status,
        'reason': tl.reason, 'complete': tl.complete,
        'errors': tl.errors, 'phases': tl.phases(),
        'admits': tl.admits, 'quarantines': tl.quarantines,
        'tokens': tl.tokens,
    }
    if args.json:
        # Machine-readable: full event records, compact encoding.
        payload['events'] = tl.events
        print(json.dumps(payload, separators=(',', ':'), default=str))
    else:
        payload['events'] = [(r['seq'], r['event']) for r in tl.events]
        print(json.dumps(payload, indent=2, default=str))
    return 0 if tl.complete else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m distributed_dot_product_tpu.obs',
        description=__doc__)
    sub = parser.add_subparsers(dest='cmd', required=True)

    v = sub.add_parser('validate', help='schema-check JSONL event logs')
    v.add_argument('logs', nargs='+')
    v.add_argument('--require', default='',
                   type=lambda s: [e for e in s.split(',') if e],
                   help='comma-separated events that must appear')
    v.add_argument('--timelines', action='store_true',
                   help='also require every request lifecycle complete')
    v.set_defaults(fn=_cmd_validate)

    s = sub.add_parser('stats', help='operational summary of a log '
                                     '(counts, rate, rotation files)')
    s.add_argument('logs', nargs='+')
    s.add_argument('--json', action='store_true',
                   help='one machine-readable JSON object instead of '
                        'the human table')
    s.set_defaults(fn=_cmd_stats)

    t = sub.add_parser('timeline', help='print one request lifecycle')
    t.add_argument('log')
    t.add_argument('request_id')
    t.add_argument('--json', action='store_true',
                   help='compact machine-readable output with full '
                        'event records')
    t.set_defaults(fn=_cmd_timeline)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == '__main__':
    sys.exit(main())
