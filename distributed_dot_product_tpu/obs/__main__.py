# -*- coding: utf-8 -*-
"""
Offline event-log tooling::

    python -m distributed_dot_product_tpu.obs validate LOG [LOG...]
        [--require event[,event...]] [--timelines]
    python -m distributed_dot_product_tpu.obs stats LOG [LOG...]
        [--percentiles] [--json]
    python -m distributed_dot_product_tpu.obs timeline LOG REQUEST_ID
        [--json]
    python -m distributed_dot_product_tpu.obs slo report LOG [LOG...]
        [--ttft S] [--per-token S] [--e2e S] [--spec SPEC.json]
        [--baseline-out SLO_BASELINE.json] [--json]
    python -m distributed_dot_product_tpu.obs slo check LOG [LOG...]
        --against SLO_BASELINE.json [--json]
    python -m distributed_dot_product_tpu.obs doctor BUNDLE
        [BUNDLE...] [--json]
    python -m distributed_dot_product_tpu.obs critpath LOG
        [replica=LOG ...] [--json]
    python -m distributed_dot_product_tpu.obs trace export LOG
        [replica=LOG ...] -o trace.json

``validate`` schema-checks every record of each log's rotated set
against :data:`~distributed_dot_product_tpu.obs.events.EVENT_SCHEMA`
(exit 1 on any violation). ``--require`` additionally demands that the
named events appear at least once — how scripts/smoke_serve.sh asserts
the injected fault cocktail actually landed in the log. ``--timelines``
reconstructs every request and fails on incomplete lifecycles.

``stats`` summarizes a log operationally: per-event-type counts, the
wall-clock span and sustained events/sec, and the rotated-file
accounting (which files exist, their sizes and record counts) —
``--json`` emits the same as one machine-readable object.
``--percentiles`` additionally reconstructs every request and prints
p50/p95/p99 of TTFT, queue wait and inter-token gap — latency
distributions without writing python.

``slo`` is the goodput observatory (obs/slo.py): ``report`` classifies
every submitted request against an :class:`~distributed_dot_product_tpu
.obs.slo.SloSpec` (met / missed_ttft / missed_token / missed_e2e /
rejected / incomplete) with per-tenant breakdowns; ``check`` gates a
log against the committed ``SLO_BASELINE.json`` with tolerances (exit 1
on violation, each naming the metric and tenant) — scripts/ci.sh runs
it over the seeded serve-load smoke. Multi-replica log sets merge:
pass several paths, optionally labeled ``replica=path``.

``critpath`` is the latency-attribution observatory (obs/critpath.py):
per-request causal phase chains (queue / handoff / prefill / decode /
stall / commit) whose durations PARTITION each request's e2e latency
exactly, aggregated into per-tenant / per-replica profiles and the
p99 tail cohorts, plus the dispatch-floor split folded from
``serve.dispatch`` records — exit 1 when any completed request fails
the partition check (scripts/smoke_router.sh gates on it).

``trace export`` emits Chrome-trace/Perfetto JSON from the same merged
sources (obs/trace.py): one process track per replica, one thread per
slot, phase slices per request, instant markers for faults / preempts /
anomalies / handoffs — load the file in ``ui.perfetto.dev``.

``timeline`` prints one request's reconstructed lifecycle; ``--json``
switches to compact machine-readable output with the FULL event
records (the default renders ``(seq, event)`` pairs for humans).

``doctor`` diagnoses flight-recorder post-mortem bundle(s)
(obs/flight.py) FROM THE BUNDLES ALONE: classifies the incident
(stuck_step / nan_storm / cache_exhaustion / deadline_storm /
overload) from the ring's events, the metric samples and the thread
stacks, and names the affected tenants and request ids — exit 1 only
on an unreadable/invalid bundle (scripts/smoke_serve.sh greps its
classification against the injected fault cocktail). Several bundles
(one per serving replica, optionally labeled ``replica=path``) merge
into one diagnosis whose verdict names the replica the incident
happened on and prefixes affected request ids with their replica.

Runs on plain files — no devices touched, safe in any CI stage.
"""

import argparse
import collections
import json
import os
import sys

from distributed_dot_product_tpu.obs import slo as obs_slo
from distributed_dot_product_tpu.obs.events import (
    _log_files, read_events, validate_file,
)
from distributed_dot_product_tpu.obs.timeline import reconstruct, timeline


def _parse_labeled(items):
    """``replica=path`` CLI args → ``([(label, path), ...], labeled)``
    — bare paths get positional ``r0, r1, ...`` labels. The ONE place
    the label grammar lives (log sets and bundle sets share it)."""
    parsed = []
    labeled = False
    for i, arg in enumerate(items):
        if '=' in arg and not os.path.exists(arg):
            label, path = arg.split('=', 1)
            labeled = True
        else:
            label, path = f'r{i}', arg
        parsed.append((label, path))
    return parsed, labeled


def _parse_log_args(logs):
    """CLI log args → a reconstruct() source: one bare path stays a
    path; several (or any ``replica=path`` labeled one) become a
    multi-source list with per-replica labels."""
    parsed, labeled = _parse_labeled(logs)
    if len(parsed) == 1 and not labeled:
        return parsed[0][1]
    return parsed


def _cmd_validate(args):
    rc = 0
    for path in args.logs:
        records, errors = validate_file(path)
        counts = collections.Counter(r.get('event') for r in records)
        for err in errors:
            print(f'{path}: SCHEMA: {err}')
            rc = 1
        missing = [ev for ev in args.require if not counts.get(ev)]
        for ev in missing:
            print(f'{path}: REQUIRED event never recorded: {ev}')
            rc = 1
        if args.timelines:
            for rid, tl in sorted(reconstruct(records).items()):
                for err in tl.errors:
                    print(f'{path}: TIMELINE {rid}: {err}')
                    rc = 1
        summary = ' '.join(f'{ev}={n}' for ev, n in sorted(counts.items()))
        print(f'{path}: {len(records)} events '
              f'({"OK" if rc == 0 else "INVALID"}) {summary}')
    return rc


def _cmd_stats(args):
    rc = 0
    reports = []
    parsed, labeled = _parse_labeled(args.logs)
    multi = labeled or len(parsed) > 1
    for label, path in parsed:
        if not _log_files(path):
            print(f'{path}: no such log (nor rotated set)',
                  file=sys.stderr)
            rc = 1
            continue
        try:
            records = read_events(path)
        except (ValueError, OSError) as e:
            print(f'{path}: UNREADABLE: {e}', file=sys.stderr)
            rc = 1
            continue
        counts = collections.Counter(r.get('event') for r in records)
        ts = [r['ts'] for r in records if isinstance(
            r.get('ts'), (int, float))]
        span_s = (max(ts) - min(ts)) if len(ts) >= 2 else 0.0
        files = []
        for fname in _log_files(path):
            with open(fname, encoding='utf-8') as f:
                n_lines = sum(1 for line in f if line.strip())
            # `lines` is the RAW non-empty line count — it can exceed
            # the parsed `events` total by one when the newest file
            # ends in a crash-torn tail line (which read_events
            # tolerates and skips).
            files.append({'path': fname,
                          'bytes': os.path.getsize(fname),
                          'lines': n_lines})
        rep = {
            'log': path, 'replica': label, 'events': len(records),
            'wall_span_seconds': span_s,
            'events_per_second': (len(records) / span_s if span_s
                                  else None),
            'first_ts': min(ts) if ts else None,
            'last_ts': max(ts) if ts else None,
            'by_event': dict(sorted(counts.items(),
                                    key=lambda kv: str(kv[0]))),
            'files': files,
        }
        if args.percentiles:
            # Latency distributions over every reconstructed request:
            # the stamped observations (ttft/queue_wait/gap), not ts
            # arithmetic — same numbers obs/slo.py's report carries.
            ttfts, waits, gaps = [], [], []
            for tl in reconstruct(records).values():
                if tl.ttft is not None:
                    ttfts.append(tl.ttft)
                if tl.queue_wait is not None:
                    waits.append(tl.queue_wait)
                gaps.extend(tl.token_gaps)
            rep['latency_percentiles'] = {
                name: obs_slo._percentile_block(vals)
                for name, vals in (('ttft', ttfts),
                                   ('queue_wait', waits),
                                   ('gap', gaps))}
        reports.append(rep)
    merged = None
    if multi and reports:
        # Per-replica breakdown of the MERGED source set: the counts
        # table keyed by replica label, so a disaggregated run's
        # router/prefill/replica event mix is visible without opening
        # each log (before this, merging collapsed the labels away).
        events = sorted({ev for rep in reports
                         for ev in rep['by_event']})
        merged = {
            'log': '<merged>',
            'events': sum(rep['events'] for rep in reports),
            'by_replica': {
                rep['replica']: {'events': rep['events'],
                                 'by_event': rep['by_event']}
                for rep in reports},
            'event_names': events,
        }
        reports.append(merged)
    if args.json:
        # Always a list — one element per readable log (plus one
        # trailing '<merged>' per-replica breakdown object when
        # several / labeled sources were passed) — so consumers get a
        # stable shape regardless of how many paths were passed.
        print(json.dumps(reports, indent=2, default=str))
        return rc
    for rep in reports:
        if rep.get('log') == '<merged>':
            print(f'merged ({len(rep["by_replica"])} replicas, '
                  f'{rep["events"]} events) — per-replica breakdown:')
            width = max(len(ev) for ev in rep['event_names']) + 2
            names = list(rep['by_replica'])
            print('  ' + ' ' * width
                  + ' '.join(f'{n:>10}' for n in names))
            for ev in rep['event_names']:
                row = ' '.join(
                    f'{rep["by_replica"][n]["by_event"].get(ev, 0):>10}'
                    for n in names)
                print(f'  {ev:<{width}}{row}')
            continue
        rate = (f'{rep["events_per_second"]:.1f}/s'
                if rep['events_per_second'] else 'n/a')
        print(f'{rep["log"]}: {rep["events"]} events over '
              f'{rep["wall_span_seconds"]:.2f}s ({rate}) in '
              f'{len(rep["files"])} file(s)')
        for ev, n in rep['by_event'].items():
            print(f'  {ev:24} {n}')
        for fi in rep['files']:
            print(f'  file {fi["path"]}: {fi["lines"]} lines, '
                  f'{fi["bytes"]} bytes')
        for name, blk in rep.get('latency_percentiles', {}).items():
            def _ms(v):
                return 'n/a' if v is None else f'{v * 1e3:.1f}ms'
            print(f'  {name:11} p50={_ms(blk["p50"])} '
                  f'p95={_ms(blk["p95"])} p99={_ms(blk["p99"])} '
                  f'over {blk["count"]}')
    return rc


def _load_spec(args):
    spec = obs_slo.SloSpec(ttft=args.ttft, per_token=args.per_token,
                           e2e=args.e2e)
    if getattr(args, 'spec', None):
        with open(args.spec, encoding='utf-8') as f:
            d = json.load(f)
        # Accept a bare SloSpec dict OR a whole SLO_BASELINE.json
        # (whose contract lives under 'spec') — so the refresh loop is
        # `slo report LOG --spec SLO_BASELINE.json --baseline-out
        # SLO_BASELINE.json` with no spec duplication.
        if isinstance(d.get('spec'), dict):
            d = d['spec']
        spec = obs_slo.SloSpec.from_dict(d)
    return spec


def _cmd_slo_report(args):
    report = obs_slo.goodput(_parse_log_args(args.logs),
                             _load_spec(args))
    if args.baseline_out:
        with open(args.baseline_out, 'w', encoding='utf-8') as f:
            json.dump(obs_slo.make_baseline(report), f, indent=2)
            f.write('\n')
        print(f'baseline written to {args.baseline_out}')
    if args.json:
        print(json.dumps(report.to_dict(), indent=2, default=str))
    else:
        print(obs_slo.render_report(report))
    return 0


def _cmd_slo_check(args):
    baseline = obs_slo.load_baseline(args.against)
    # The spec under test is the BASELINE's: a check must measure the
    # same contract the baseline recorded, or the comparison is moot.
    spec = obs_slo.SloSpec.from_dict(baseline.get('spec', {}))
    report = obs_slo.goodput(_parse_log_args(args.logs), spec)
    violations = obs_slo.check_baseline(report, baseline)
    if args.json:
        print(json.dumps({'violations': violations,
                          'report': report.to_dict(brief=True)},
                         indent=2, default=str))
    else:
        for v in violations:
            print(f'SLO VIOLATION: {v}')
        print(obs_slo.render_report(report))
        print(f'slo check vs {args.against}: '
              f'{"FAIL" if violations else "OK"} '
              f'({len(violations)} violation(s))')
    return 1 if violations else 0


def _cmd_doctor(args):
    from distributed_dot_product_tpu.obs import doctor as obs_doctor
    from distributed_dot_product_tpu.obs import flight as obs_flight
    labeled = []
    for label, path in _parse_labeled(args.bundle)[0]:
        try:
            labeled.append((label, obs_flight.load_bundle(path)))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f'{path}: unreadable bundle: {e}', file=sys.stderr)
            return 1
    incident = obs_doctor.diagnose_bundles(labeled)
    if args.json:
        print(json.dumps(incident.to_dict(), indent=2, default=str))
    else:
        print(obs_doctor.render_incident(incident))
    return 0


def _cmd_critpath(args):
    from distributed_dot_product_tpu.obs import critpath as obs_critpath
    source = _parse_log_args(args.logs)
    try:
        chains = obs_critpath.attribute(source)
        dispatch = obs_critpath.dispatch_floor(source)
    except (ValueError, OSError) as e:
        print(f'critpath: unreadable source: {e}', file=sys.stderr)
        return 1
    prof = obs_critpath.profile(chains, dispatch=dispatch)
    if args.json:
        print(obs_critpath.to_json(prof))
    else:
        print(obs_critpath.render_report(prof))
    # The CI contract: every COMPLETED request's phases partition its
    # e2e within tolerance (partial chains — torn logs — are reported,
    # never asserted against).
    return 1 if prof['partition_failures'] else 0


def _cmd_trace_export(args):
    from distributed_dot_product_tpu.obs import trace as obs_trace
    source = _parse_log_args(args.logs)
    try:
        trace = obs_trace.write_trace(source, args.out)
    except (ValueError, OSError) as e:
        print(f'trace export: {e}', file=sys.stderr)
        return 1
    errors = obs_trace.validate_trace(trace)
    for err in errors:
        print(f'trace export: INVALID: {err}', file=sys.stderr)
    n = len(trace['traceEvents'])
    print(f'{args.out}: {n} trace events '
          f'({"OK" if not errors else "INVALID"})')
    return 1 if errors else 0


def _cmd_timeline(args):
    tl = timeline(args.request_id, args.log)
    payload = {
        'request_id': tl.request_id, 'status': tl.status,
        'reason': tl.reason, 'tenant': tl.tenant,
        'complete': tl.complete,
        'errors': tl.errors, 'phases': tl.phases(),
        'admits': tl.admits, 'quarantines': tl.quarantines,
        'preempts': tl.preempts, 'tokens': tl.tokens,
    }
    if args.json:
        # Machine-readable: full event records, compact encoding.
        payload['events'] = tl.events
        print(json.dumps(payload, separators=(',', ':'), default=str))
    else:
        payload['events'] = [(r['seq'], r['event']) for r in tl.events]
        print(json.dumps(payload, indent=2, default=str))
    return 0 if tl.complete else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m distributed_dot_product_tpu.obs',
        description=__doc__)
    sub = parser.add_subparsers(dest='cmd', required=True)

    v = sub.add_parser('validate', help='schema-check JSONL event logs')
    v.add_argument('logs', nargs='+')
    v.add_argument('--require', default='',
                   type=lambda s: [e for e in s.split(',') if e],
                   help='comma-separated events that must appear')
    v.add_argument('--timelines', action='store_true',
                   help='also require every request lifecycle complete')
    v.set_defaults(fn=_cmd_validate)

    s = sub.add_parser('stats', help='operational summary of a log '
                                     '(counts, rate, rotation files)')
    s.add_argument('logs', nargs='+')
    s.add_argument('--percentiles', action='store_true',
                   help='also reconstruct requests and print p50/p95/'
                        'p99 of ttft, queue wait and inter-token gap')
    s.add_argument('--json', action='store_true',
                   help='one machine-readable JSON object instead of '
                        'the human table')
    s.set_defaults(fn=_cmd_stats)

    slo = sub.add_parser(
        'slo', help='goodput-under-SLO accounting over the event log')
    slo_sub = slo.add_subparsers(dest='slo_cmd', required=True)
    r = slo_sub.add_parser(
        'report', help='classify every request against an SloSpec')
    r.add_argument('logs', nargs='+',
                   help='log path(s); several merge as replicas '
                        '(optionally labeled replica=path)')
    r.add_argument('--ttft', type=float, default=None,
                   help='TTFT deadline, seconds')
    r.add_argument('--per-token', type=float, default=None,
                   help='max inter-token gap, seconds')
    r.add_argument('--e2e', type=float, default=None,
                   help='end-to-end deadline, seconds')
    r.add_argument('--spec', default=None,
                   help='JSON SloSpec file (overrides the flags; may '
                        'carry per-tenant overrides)')
    r.add_argument('--baseline-out', default=None,
                   help='also write an SLO_BASELINE.json payload here '
                        '(the refresh path scripts/ci.sh documents)')
    r.add_argument('--json', action='store_true')
    r.set_defaults(fn=_cmd_slo_report)
    c = slo_sub.add_parser(
        'check', help='gate a log against a committed SLO baseline '
                      '(exit 1 on violations, each naming metric and '
                      'tenant)')
    c.add_argument('logs', nargs='+')
    c.add_argument('--against', required=True,
                   help='committed SLO_BASELINE.json (its embedded '
                        'spec is the contract checked)')
    c.add_argument('--json', action='store_true')
    c.set_defaults(fn=_cmd_slo_check)

    d = sub.add_parser(
        'doctor', help='diagnose flight-recorder post-mortem bundle(s) '
                       '(classify the incident, name the replica and '
                       'affected tenants/requests) from the bundles '
                       'alone')
    d.add_argument('bundle', nargs='+',
                   help='bundle director(ies) (MANIFEST.json + ring '
                        'JSONL + snapshots); several merge as '
                        'per-replica bundles, optionally labeled '
                        'replica=path — the verdict then names the '
                        'replica')
    d.add_argument('--json', action='store_true',
                   help='machine-readable incident object')
    d.set_defaults(fn=_cmd_doctor)

    cp = sub.add_parser(
        'critpath',
        help='critical-path latency attribution: per-request phase '
             'chains (queue/handoff/prefill/decode/stall/commit) that '
             'partition e2e exactly, aggregated per tenant/replica, '
             'plus the dispatch-floor split (exit 1 when any '
             'completed request fails the partition check)')
    cp.add_argument('logs', nargs='+',
                    help='log path(s); several merge as replicas '
                         '(optionally labeled replica=path)')
    cp.add_argument('--json', action='store_true',
                    help='machine-readable profile object')
    cp.set_defaults(fn=_cmd_critpath)

    tr = sub.add_parser(
        'trace', help='Chrome-trace / Perfetto export of the event log')
    tr_sub = tr.add_subparsers(dest='trace_cmd', required=True)
    te = tr_sub.add_parser(
        'export',
        help='emit Chrome-trace JSON: one track per replica/slot, '
             'phase slices per request, instant markers for faults/'
             'preempts/anomalies/handoffs (load in ui.perfetto.dev)')
    te.add_argument('logs', nargs='+',
                    help='log path(s); several merge as replicas '
                         '(optionally labeled replica=path)')
    te.add_argument('-o', '--out', required=True,
                    help='output trace JSON path')
    te.set_defaults(fn=_cmd_trace_export)

    t = sub.add_parser('timeline', help='print one request lifecycle')
    t.add_argument('log')
    t.add_argument('request_id')
    t.add_argument('--json', action='store_true',
                   help='compact machine-readable output with full '
                        'event records')
    t.set_defaults(fn=_cmd_timeline)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == '__main__':
    sys.exit(main())
