# -*- coding: utf-8 -*-
"""
Offline event-log tooling::

    python -m distributed_dot_product_tpu.obs validate LOG [LOG...]
        [--require event[,event...]] [--timelines]
    python -m distributed_dot_product_tpu.obs timeline LOG REQUEST_ID

``validate`` schema-checks every record of each log's rotated set
against :data:`~distributed_dot_product_tpu.obs.events.EVENT_SCHEMA`
(exit 1 on any violation). ``--require`` additionally demands that the
named events appear at least once — how scripts/smoke_serve.sh asserts
the injected fault cocktail actually landed in the log. ``--timelines``
reconstructs every request and fails on incomplete lifecycles.

``timeline`` prints one request's reconstructed lifecycle.

Runs on plain files — no devices touched, safe in any CI stage.
"""

import argparse
import collections
import json
import sys

from distributed_dot_product_tpu.obs.events import validate_file
from distributed_dot_product_tpu.obs.timeline import reconstruct, timeline


def _cmd_validate(args):
    rc = 0
    for path in args.logs:
        records, errors = validate_file(path)
        counts = collections.Counter(r.get('event') for r in records)
        for err in errors:
            print(f'{path}: SCHEMA: {err}')
            rc = 1
        missing = [ev for ev in args.require if not counts.get(ev)]
        for ev in missing:
            print(f'{path}: REQUIRED event never recorded: {ev}')
            rc = 1
        if args.timelines:
            for rid, tl in sorted(reconstruct(records).items()):
                for err in tl.errors:
                    print(f'{path}: TIMELINE {rid}: {err}')
                    rc = 1
        summary = ' '.join(f'{ev}={n}' for ev, n in sorted(counts.items()))
        print(f'{path}: {len(records)} events '
              f'({"OK" if rc == 0 else "INVALID"}) {summary}')
    return rc


def _cmd_timeline(args):
    tl = timeline(args.request_id, args.log)
    print(json.dumps({
        'request_id': tl.request_id, 'status': tl.status,
        'reason': tl.reason, 'complete': tl.complete,
        'errors': tl.errors, 'phases': tl.phases(),
        'admits': tl.admits, 'quarantines': tl.quarantines,
        'tokens': tl.tokens,
        'events': [(r['seq'], r['event']) for r in tl.events],
    }, indent=2, default=str))
    return 0 if tl.complete else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m distributed_dot_product_tpu.obs',
        description=__doc__)
    sub = parser.add_subparsers(dest='cmd', required=True)

    v = sub.add_parser('validate', help='schema-check JSONL event logs')
    v.add_argument('logs', nargs='+')
    v.add_argument('--require', default='',
                   type=lambda s: [e for e in s.split(',') if e],
                   help='comma-separated events that must appear')
    v.add_argument('--timelines', action='store_true',
                   help='also require every request lifecycle complete')
    v.set_defaults(fn=_cmd_validate)

    t = sub.add_parser('timeline', help='print one request lifecycle')
    t.add_argument('log')
    t.add_argument('request_id')
    t.set_defaults(fn=_cmd_timeline)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == '__main__':
    sys.exit(main())
