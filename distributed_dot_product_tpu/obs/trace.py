# -*- coding: utf-8 -*-
"""
Chrome-trace / Perfetto export of the JSONL event log — the repo's
first VISUAL timeline of a serving run or incident.

:func:`export_trace` folds one or many (labeled) event logs into the
Chrome Trace Event Format (the JSON flavor ``chrome://tracing`` and
https://ui.perfetto.dev load directly):

- one **process track per replica** (the merge label), one **thread
  track per slot** — a disaggregated run renders as router / prefill /
  replica lanes side by side;
- one **complete slice ("X") per critical-path phase segment** of every
  request (queue / handoff / prefill / decode / stall / commit, from
  :mod:`distributed_dot_product_tpu.obs.critpath` — the slices are the
  partition, so the lane visually accounts for the request's whole e2e);
- **instant markers ("i")** for the discrete incidents an operator
  scrubs for: fault injections, preemptions, quarantines, anomaly
  detections, KV handoffs, page-corruption verdicts, replica losses,
  recoveries, post-mortem dumps.

Timestamps are the log's ``ts`` rebased to the earliest record and
scaled to microseconds (the format's unit); on a virtual-clock run the
trace is in virtual time — exactly the timeline the phase partition is
proved against. :func:`validate_trace` is the CI gate: required keys on
every event, non-negative durations, per-track monotone ``ts``.

CLI: ``python -m distributed_dot_product_tpu.obs trace export LOG
[replica=LOG ...] -o trace.json``.
"""

import json
from typing import Dict, List

from distributed_dot_product_tpu.obs.critpath import (
    _attribute_one, _REQ_PREFIXES,
)
from distributed_dot_product_tpu.obs.events import (
    merge_events, read_events,
)
from distributed_dot_product_tpu.obs.timeline import _is_multi_source

__all__ = ['export_trace', 'write_trace', 'validate_trace',
           'INSTANT_EVENTS']

# Discrete incidents worth a marker on the timeline (event name →
# rendered marker name). Everything else is either a phase slice
# (request lifecycle) or bookkeeping the visual view would drown in.
INSTANT_EVENTS = {
    'fault.inject': 'fault',
    'serve.preempt': 'preempt',
    'serve.quarantine': 'quarantine',
    'serve.evict': 'evict',
    'anomaly.detected': 'anomaly',
    'prefill.handoff': 'handoff',
    'kv.corrupt': 'kv_corrupt',
    'replica.lost': 'replica_lost',
    'replica.rejoin': 'replica_rejoin',
    'prefill.lost': 'prefill_lost',
    'request.recovered': 'recovered',
    'postmortem.dump': 'postmortem',
    'profile.capture': 'profile',
}

# Marker fields worth carrying into args (small, readable — not the
# whole record: Perfetto renders args as a flat table).
_MARKER_FIELDS = ('request_id', 'reason', 'requeued', 'slot', 'kind',
                  'metric', 'detector', 'value', 'target', 'pages',
                  'site', 'trigger', 'status')


def _records(source):
    return (merge_events(source) if _is_multi_source(source)
            else read_events(source))


def export_trace(source) -> dict:
    """Build the Chrome-trace object (``{'traceEvents': [...]}``) from
    ``source`` — a log path, decoded records, or a list of paths /
    ``(replica, path)`` pairs merged with replica labels."""
    records = _records(source)
    if not records:
        return {'traceEvents': [], 'displayTimeUnit': 'ms'}
    t0 = min(r.get('ts', 0.0) for r in records)

    def us(ts):
        return max(0.0, (ts - t0) * 1e6)

    # pid per replica label, in first-seen order; pid 1.. (0 renders
    # oddly in some viewers). Unlabeled single-log exports get one
    # 'log' process.
    pids: Dict[str, int] = {}

    def pid_of(label):
        label = label or 'log'
        if label not in pids:
            pids[label] = len(pids) + 1
        return pids[label]

    events: List[dict] = []
    # Request phase slices: group request-scoped records, attribute,
    # and render each partition segment as one complete slice on the
    # (terminal replica, admit slot) track.
    per_request: Dict[str, List[dict]] = {}
    for rec in records:
        rid = rec.get('request_id')
        if rid is not None \
                and rec.get('event', '').startswith(_REQ_PREFIXES):
            per_request.setdefault(rid, []).append(rec)
    for rid, recs in per_request.items():
        chain = _attribute_one(rid, recs)
        label = chain.replicas[-1] if chain.replicas else None
        slot = next((r['slot'] for r in recs
                     if r.get('event') == 'serve.admit'
                     and r.get('slot') is not None), 0)
        pid = pid_of(label)
        for phase, start, end in chain.segments:
            events.append({
                'name': phase, 'cat': 'phase', 'ph': 'X',
                'ts': us(start), 'dur': max(0.0, (end - start) * 1e6),
                'pid': pid, 'tid': int(slot),
                'args': {'request_id': rid,
                         'tenant': chain.tenant or 'default'}})
    # Instant markers for the discrete incidents.
    for rec in records:
        name = INSTANT_EVENTS.get(rec.get('event'))
        if name is None:
            continue
        slot = rec.get('slot')
        args = {k: rec[k] for k in _MARKER_FIELDS
                if rec.get(k) is not None}
        args['event'] = rec['event']
        events.append({
            'name': name, 'cat': 'incident', 'ph': 'i',
            'ts': us(rec.get('ts', t0)),
            'pid': pid_of(rec.get('replica')),
            'tid': int(slot) if slot is not None else 0,
            's': 't' if slot is not None else 'p',
            'args': args})
    # Per-track monotone ts is part of the exported contract (CI
    # validates it) — sort by (ts, pid, tid), stably.
    events.sort(key=lambda e: (e['ts'], e['pid'], e['tid']))
    # Track naming metadata (ph='M') leads the stream.
    meta = []
    for label, pid in sorted(pids.items(), key=lambda kv: kv[1]):
        meta.append({'name': 'process_name', 'ph': 'M', 'ts': 0.0,
                     'pid': pid, 'tid': 0,
                     'args': {'name': label}})
    return {'traceEvents': meta + events, 'displayTimeUnit': 'ms'}


def write_trace(source, path) -> dict:
    """Export and write ``path``; returns the trace object."""
    trace = export_trace(source)
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(trace, f, separators=(',', ':'))
    return trace


def validate_trace(trace) -> List[str]:
    """Schema-check a Chrome-trace object (or JSON string): required
    keys on every event, non-negative ``dur`` on complete slices,
    non-decreasing ``ts`` per (pid, tid) track. Returns error strings
    (empty = valid) — the ``obs trace export`` CI gate re-loads the
    emitted file through this."""
    errors = []
    if isinstance(trace, (str, bytes)):
        try:
            trace = json.loads(trace)
        except json.JSONDecodeError as e:
            return [f'not JSON: {e}']
    if not isinstance(trace, dict) or 'traceEvents' not in trace:
        return ["missing top-level 'traceEvents'"]
    evs = trace['traceEvents']
    if not isinstance(evs, list):
        return ["'traceEvents' is not a list"]
    last_ts: Dict[tuple, float] = {}
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            errors.append(f'event {i}: not an object')
            continue
        for key in ('name', 'ph', 'ts', 'pid', 'tid'):
            if key not in ev:
                errors.append(f'event {i}: missing {key!r}')
        ph, ts = ev.get('ph'), ev.get('ts')
        if not isinstance(ts, (int, float)):
            errors.append(f'event {i}: non-numeric ts {ts!r}')
            continue
        if ph == 'X':
            dur = ev.get('dur')
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f'event {i}: X without dur>=0 '
                              f'(dur={dur!r})')
        if ph == 'M':
            continue       # metadata is unordered by convention
        track = (ev.get('pid'), ev.get('tid'))
        if ts < last_ts.get(track, float('-inf')):
            errors.append(
                f'event {i}: ts {ts} regresses on track {track}')
        last_ts[track] = ts
    return errors
