# -*- coding: utf-8 -*-
"""
Post-mortem bundle diagnosis — ``obs doctor BUNDLE``.

Given a flight-recorder bundle (obs/flight.py), classify the incident
FROM THE BUNDLE ALONE — no live process, no source log — and name who
it hurt. The classifier scores seven incident classes against the
evidence in the ring's event window, the metric snapshot, the thread
stacks and the MANIFEST trigger:

- ``kv_corruption``  — KV pages failed checksum verification:
  ``kv.corrupt`` verdicts, injected ``page_corrupt`` chaos, the
  ``kv_corrupt`` dump trigger, corruption-tagged recovery arcs and
  typed ``kv_corrupt`` terminals. The verdict names the DIRTY member
  (from the kv.corrupt declaration) and the victim streams healed off
  its poisoned pages.
- ``replica_loss``   — a decode replica died mid-stream: a
  ``replica.lost`` declaration, probe-miss streaks, injected replica
  crashes, ``request.recovered`` arcs and typed ``replica_lost``
  terminals. The verdict names the LOST replica (from the declaration
  — the dead member cannot speak for itself).
- ``stuck_step``     — the decode loop stopped beating: watchdog
  liveness-stall transitions, a ``stall`` dump trigger, an injected
  ``stuck_step`` fault, a scheduler thread blocked in a sleep/step.
- ``nan_storm``      — numerics went bad: quarantine events piling up,
  ``failed_nan`` terminals, injected NaN faults, a ``nan_storm`` dump.
- ``cache_exhaustion`` — the paged KV pool ran dry: typed
  ``cache_exhausted`` sheds, preemption events, ``pages_free`` at 0.
- ``deadline_storm`` — latency ate the deadlines: ``deadline_exceeded``
  rejects and ``deadline_expired`` retirements dominating.
- ``overload``       — more traffic than capacity: ``queue_full``
  sheds, NOT_READY(queue full) readiness excursions, degradation.

Every class reports its evidence lines; the primary classification is
the highest score (ties resolve in the order above — a stall is a
sharper finding than the overload it causes). Affected parties come
from the same window via :mod:`~distributed_dot_product_tpu.obs.slo`:
per-tenant goodput over the ring's events, plus the concrete request
ids the incident touched (quarantined / preempted / shed / failed).
"""

import dataclasses
from typing import Dict, List, Optional

from distributed_dot_product_tpu.obs import slo as obs_slo
from distributed_dot_product_tpu.obs.timeline import reconstruct

__all__ = ['Incident', 'diagnose', 'diagnose_bundles',
           'render_incident']

# Classification order = tie-break priority (sharper findings first —
# a dead replica explains the deadline/overload storms downstream of
# it, never the other way around; a corruption verdict explains the
# expulsions and recoveries downstream of IT, so it outranks the
# loss class its healing arc borrows).
CLASSES = ('kv_corruption', 'replica_loss', 'stuck_step', 'nan_storm',
           'cache_exhaustion', 'deadline_storm', 'overload')

_MAX_LISTED = 16    # request ids printed per affected category


@dataclasses.dataclass
class Incident:
    """One diagnosis. ``classes`` maps every incident class to
    ``{'score': float, 'evidence': [str, ...]}``; ``primary`` is the
    winning class (None only for an empty window)."""
    primary: Optional[str]
    classes: Dict[str, dict]
    trigger: Optional[str]
    reason: str
    window: dict
    tenants: Dict[str, dict]
    affected: Dict[str, List[str]]
    anomalies: List[dict]
    notes: List[str]
    # Multi-bundle diagnosis (one bundle per serving replica): the
    # replica whose bundle carries the primary class's strongest
    # evidence — None on a single-bundle diagnosis. A `replica_loss`
    # primary OVERRIDES this with the LOST replica's name (from the
    # replica.lost declaration): the verdict points at the dead
    # member, not at the router whose bundle narrates the loss.
    replica: Optional[str] = None

    def to_dict(self):
        return dataclasses.asdict(self)


def _count(events, name, **match):
    out = 0
    for rec in events:
        if rec.get('event') != name:
            continue
        if all(rec.get(k) == v for k, v in match.items()):
            out += 1
    return out


def _stack_evidence(stacks):
    """Frames that look like a blocked serving loop: a sleep inside
    the fault injector's stuck-step hook, or a thread wedged in the
    engine's compiled dispatch."""
    hits = []
    for thread, frames in (stacks or {}).items():
        text = '\n'.join(frames)
        if 'on_decode_step' in text and 'sleep' in text:
            hits.append(f'thread {thread} blocked in an injected '
                        f'stuck step (fault sleep on the loop stack)')
        elif 'decode_step' in text and 'sleep' in text:
            hits.append(f'thread {thread} sleeping inside a decode '
                        f'step')
    return hits


def diagnose(bundle) -> Incident:
    """Classify ``bundle`` (a dict from :func:`~distributed_dot_product
    _tpu.obs.flight.load_bundle`, or a path handed straight to it)."""
    if not isinstance(bundle, dict):
        from distributed_dot_product_tpu.obs import flight
        bundle = flight.load_bundle(bundle)
    manifest = bundle.get('manifest', {})
    events = bundle.get('events', [])
    trigger = manifest.get('trigger')
    reason = manifest.get('reason', '')
    notes = []
    ring = manifest.get('ring', {})
    if ring.get('dropped'):
        notes.append(f'ring evicted {ring["dropped"]} records — the '
                     f'window is truncated; early lifecycle events '
                     f'may be missing')

    scores = {c: {'score': 0.0, 'evidence': []} for c in CLASSES}

    def vote(cls, points, evidence):
        scores[cls]['score'] += points
        scores[cls]['evidence'].append(evidence)

    sched_section = (bundle.get('sections') or {}).get('scheduler') or {}

    # -- KV-corruption evidence -----------------------------------------
    corrupt = [r for r in events if r.get('event') == 'kv.corrupt']
    dirty = [str(r.get('target')) for r in corrupt
             if r.get('target') is not None]
    if corrupt:
        pages = sorted({int(p) for r in corrupt
                        for p in (r.get('pages') or [])})
        # Sequence-sharded replicas attach the owning kv shard(s) to
        # the verdict — fold them in so the diagnosis localizes the
        # flip within the mesh, not just within the pool.
        shards = sorted({int(s) for r in corrupt
                         for s in (r.get('shards') or [])})
        where = f' on kv shard(s) {shards}' if shards else ''
        vote('kv_corruption', 6.0 * len(corrupt),
             f'kv.corrupt verdict(s) on {", ".join(sorted(set(dirty)))}'
             f' — page(s) {pages}{where} quarantined')
    inj_corrupt = _count(events, 'fault.inject', kind='page_corrupt')
    if inj_corrupt:
        vote('kv_corruption', 4.0 * inj_corrupt,
             f'injected fault: page_corrupt x{inj_corrupt}')
    if trigger == 'kv_corrupt':
        vote('kv_corruption', 4.0,
             'bundle dumped by the kv_corrupt trigger')
    corrupt_rec = sum(1 for r in events
                      if r.get('event') == 'request.recovered'
                      and r.get('reason') == 'kv_corrupt')
    if corrupt_rec:
        vote('kv_corruption', min(1.0 * corrupt_rec, 8.0),
             f'{corrupt_rec} victim stream(s) healed off poisoned '
             f'pages through the recovery ledger')
    corrupt_rej = sum(1 for r in events
                      if r.get('event') == 'serve.reject'
                      and r.get('reason') == 'kv_corrupt')
    if corrupt_rej:
        vote('kv_corruption', 2.0 * corrupt_rej,
             f'{corrupt_rej} typed kv_corrupt terminal(s)')

    # -- replica-loss evidence ------------------------------------------
    lost = [str(r.get('target')) for r in events
            if r.get('event') == 'replica.lost'
            and r.get('target') is not None]
    if lost:
        vote('replica_loss', 6.0 * len(lost),
             f'replica.lost declared for {", ".join(lost)}')
    inj_crash = (_count(events, 'fault.inject', kind='replica_crash')
                 + _count(events, 'fault.inject', kind='handoff_crash')
                 + _count(events, 'fault.inject',
                          kind='probe_blackhole'))
    if inj_crash:
        vote('replica_loss', 4.0 * inj_crash,
             f'injected fault: replica-scoped chaos x{inj_crash}')
    if trigger == 'replica_lost':
        vote('replica_loss', 4.0,
             'bundle dumped by the replica_lost trigger')
    # Corruption-tagged recoveries vote for kv_corruption above, not
    # here: the ledger arc is shared, the root cause is not.
    recovered = sum(1 for r in events
                    if r.get('event') == 'request.recovered'
                    and r.get('reason') != 'kv_corrupt')
    if recovered:
        vote('replica_loss', min(1.0 * recovered, 8.0),
             f'{recovered} stream(s) resolved through the recovery '
             f'ledger')
    lost_rej = sum(1 for r in events
                   if r.get('event') == 'serve.reject'
                   and r.get('reason') == 'replica_lost')
    if lost_rej:
        vote('replica_loss', 2.0 * lost_rej,
             f'{lost_rej} typed replica_lost terminal(s)')
    probe_missed = _count(events, 'replica.probe', state='missed')
    if probe_missed:
        vote('replica_loss', min(0.5 * probe_missed, 2.0),
             f'{probe_missed} liveness probe miss(es)')

    # -- stall evidence -------------------------------------------------
    stalls = _count(events, 'health.liveness', state='stalled')
    if stalls:
        vote('stuck_step', 6.0 * stalls,
             f'watchdog liveness went STALLED {stalls}x')
    inj_stuck = _count(events, 'fault.inject', kind='stuck_step')
    if inj_stuck:
        vote('stuck_step', 4.0 * inj_stuck,
             f'injected fault: stuck_step x{inj_stuck}')
    if trigger == 'stall':
        vote('stuck_step', 4.0, 'bundle dumped by the stall trigger')
    if sched_section.get('liveness') == 'stalled':
        age = sched_section.get('last_beat_age_s')
        vote('stuck_step', 3.0,
             'scheduler introspection shows liveness STALLED at dump '
             'time' + (f' (last beat {age:.2f}s ago)'
                       if isinstance(age, (int, float)) else ''))
    for hit in _stack_evidence(bundle.get('stacks')):
        vote('stuck_step', 2.0, hit)

    # -- NaN evidence ---------------------------------------------------
    quarantines = _count(events, 'serve.quarantine')
    if quarantines:
        vote('nan_storm', 2.0 * quarantines,
             f'{quarantines} slot quarantine(s)')
    failed = _count(events, 'serve.retire', status='failed_nan')
    if failed:
        vote('nan_storm', 3.0 * failed,
             f'{failed} request(s) failed_nan (requeues exhausted)')
    inj_nan = (_count(events, 'fault.inject', kind='nan_slot')
               + _count(events, 'fault.inject', kind='nan_batch'))
    if inj_nan:
        vote('nan_storm', 2.0 * inj_nan,
             f'injected fault: nan x{inj_nan}')
    if trigger == 'nan_storm':
        vote('nan_storm', 4.0, 'bundle dumped by the NaN-storm trigger')

    # -- cache-exhaustion evidence --------------------------------------
    # Drain preempts (serve.preempt with drain=true) are elastic
    # membership changes, not a dry pool — they must not vote here.
    preempts = sum(1 for r in events
                   if r.get('event') == 'serve.preempt'
                   and not r.get('drain'))
    if preempts:
        vote('cache_exhaustion', 2.0 * preempts,
             f'{preempts} page-pool preemption(s)')
    cache_rej = sum(1 for r in events
                    if r.get('event') in ('serve.reject', 'serve.retire')
                    and r.get('reason') == 'cache_exhausted')
    if cache_rej:
        vote('cache_exhaustion', 3.0 * cache_rej,
             f'{cache_rej} typed cache_exhausted shed(s)')
    for sample in bundle.get('metric_samples', []):
        gauges = (sample.get('metrics') or {}).get('gauges', {})
        free = gauges.get('serve.cache.pages_free')
        total_used = gauges.get('serve.cache.pages_used', 0)
        if free == 0 and total_used:
            vote('cache_exhaustion', 2.0,
                 'a metric sample shows pages_free == 0')
            break

    # -- deadline evidence ----------------------------------------------
    dl = (sum(1 for r in events if r.get('event') == 'serve.reject'
              and r.get('reason') == 'deadline_exceeded')
          + _count(events, 'serve.retire', status='deadline_expired'))
    if dl:
        vote('deadline_storm', min(1.0 * dl, 10.0),
             f'{dl} deadline miss(es) (typed rejects + expirations)')

    # -- overload evidence ----------------------------------------------
    qfull = sum(1 for r in events if r.get('event') == 'serve.reject'
                and r.get('reason') == 'queue_full')
    if qfull:
        vote('overload', min(1.0 * qfull, 8.0),
             f'{qfull} queue_full shed(s)')
    not_ready = sum(1 for r in events
                    if r.get('event') == 'health.readiness'
                    and r.get('state') == 'not_ready'
                    and 'queue' in str(r.get('reason', '')))
    if not_ready:
        vote('overload', min(1.0 * not_ready, 4.0),
             f'readiness went NOT_READY (queue full) {not_ready}x')
    degraded = sum(1 for r in events
                   if r.get('event') == 'health.readiness'
                   and r.get('state') == 'degraded')
    if degraded:
        vote('overload', min(0.5 * degraded, 2.0),
             f'readiness DEGRADED under pressure {degraded}x')

    # -- control-plane arcs (serve/control.py) --------------------------
    # The controller's own record of the incident: tightening and
    # scale-ups are overload evidence (the loop SAW more traffic than
    # capacity and acted); page-driven tightening points at the pool.
    adjusts = [r for r in events if r.get('event') == 'control.adjust']
    tightened = [r for r in adjusts
                 if str(r.get('reason', '')).startswith(
                     ('breach', 'pressure'))]
    if tightened:
        vote('overload', min(0.5 * len(tightened), 2.0),
             f'controller tightened admission {len(tightened)}x')
    # 'breach:pages_free' and 'pressure:page_pool:<v>' both point at
    # the paged KV pool as the tightening driver.
    pages_driven = [r for r in tightened
                    if 'page' in str(r.get('reason', ''))]
    if pages_driven:
        vote('cache_exhaustion', min(0.5 * len(pages_driven), 2.0),
             f'controller tightened on page-pool signals '
             f'{len(pages_driven)}x')
    ups = _count(events, 'control.scale', direction='up')
    if ups:
        vote('overload', min(1.0 * ups, 4.0),
             f'controller scaled decode replicas up {ups}x')
    drains = _count(events, 'control.drain')
    scale_downs = _count(events, 'control.scale', direction='down')
    if adjusts or ups or drains or scale_downs:
        notes.append(f'control plane acted in this window: '
                     f'{len(adjusts)} knob adjust(s), {ups} scale-up(s), '
                     f'{scale_downs} scale-down(s), {drains} drain(s)')

    # -- anomaly verdicts ride along as supporting context --------------
    anomalies = [r for r in events if r.get('event') == 'anomaly.detected']
    for rec in anomalies:
        watch = str(rec.get('watch', rec.get('metric', '')))
        if 'ttft' in watch or 'token' in watch:
            vote('stuck_step', 0.5,
                 f'anomaly detector tripped on {watch}')
        if 'queue' in watch or 'reject' in watch:
            vote('overload', 0.5,
                 f'anomaly detector tripped on {watch}')
        if 'pages' in watch:
            vote('cache_exhaustion', 0.5,
                 f'anomaly detector tripped on {watch}')

    # -- critpath section: where the window's time actually went --------
    # The flight recorder's stock provider (obs/flight.py) embeds the
    # ring's critical-path summary; the dominant phase is evidence in
    # its own right (queue-dominant windows are overload, stall-
    # dominant ones point at the pool's preempt/requeue churn) and the
    # verdict names it either way.
    crit = (bundle.get('sections') or {}).get('critpath') or {}
    crit_phases = crit.get('phases') or {}
    if crit_phases:
        dominant = max(crit_phases, key=crit_phases.get)
        total_s = sum(crit_phases.values()) or 1.0
        share = 100.0 * crit_phases[dominant] / total_s
        notes.append(
            f'critpath: dominant phase of the incident window is '
            f'{dominant!r} ({share:.0f}% of the attributed time over '
            f'{crit.get("requests", 0)} request(s))')
        if dominant == 'queue':
            vote('overload', 1.0,
                 f'critpath: queue is the dominant phase '
                 f'({share:.0f}% of attributed time)')
        elif dominant == 'stall':
            vote('cache_exhaustion', 1.0,
                 f'critpath: requeue stalls dominate '
                 f'({share:.0f}% of attributed time)')
        disp = (crit.get('dispatch') or {}).get('total') or {}
        if disp.get('overhead_per_token') is not None:
            notes.append(
                f'critpath: host dispatch overhead '
                f'{disp["overhead_per_token"] * 1e3:.3f} ms/token '
                f'over {disp.get("ticks", 0)} decode tick(s)')

    ranked = sorted(CLASSES,
                    key=lambda c: (-scores[c]['score'],
                                   CLASSES.index(c)))
    primary = ranked[0] if scores[ranked[0]]['score'] > 0 else None

    # -- who it hurt: per-tenant goodput + concrete request ids --------
    timelines = reconstruct(events)
    spec = obs_slo.SloSpec()        # deadline-free: classes met /
    report = obs_slo.goodput(events, spec)  # rejected / incomplete
    tenants = {t: {'requests': tb['requests'],
                   'met': tb['counts']['met'],
                   'rejected': tb['counts']['rejected'],
                   'incomplete': tb['counts']['incomplete']}
               for t, tb in sorted(report.per_tenant.items())}
    affected = {'quarantined': [], 'preempted': [], 'recovered': [],
                'rejected': [], 'failed': [], 'incomplete': [],
                'in_flight': []}
    # The slot table at dump time: who was ON the device when the
    # incident hit (a mid-run bundle's events alone can't tell which
    # incompletes actually held slots).
    for slot in sched_section.get('slots', []):
        rid = slot.get('request_id')
        if rid and rid not in affected['in_flight']:
            affected['in_flight'].append(rid)
    for rid, tl in sorted(timelines.items()):
        if tl.quarantines:
            affected['quarantined'].append(rid)
        if tl.preempts:
            affected['preempted'].append(rid)
        if tl.recoveries:
            affected['recovered'].append(rid)
        if tl.status == 'rejected':
            affected['rejected'].append(rid)
        elif tl.status in ('failed_nan', 'evicted', 'deadline_expired'):
            affected['failed'].append(rid)
        elif tl.status is None:
            affected['incomplete'].append(rid)

    ts = [r['ts'] for r in events
          if isinstance(r.get('ts'), (int, float))]
    window = {'events': len(events),
              'first_ts': min(ts) if ts else None,
              'last_ts': max(ts) if ts else None,
              'ring_dropped': ring.get('dropped', 0)}
    if not events:
        notes.append('the bundle carries no events — was an event log '
                     'active when the recorder ran?')
    # A replica_loss verdict names the DEAD member from the
    # declaration (the latest, if several fell); a kv_corruption
    # verdict names the DIRTY one the same way.
    where = None
    if primary == 'replica_loss' and lost:
        where = lost[-1]
    elif primary == 'kv_corruption' and dirty:
        where = dirty[-1]
    return Incident(primary=primary, classes=scores, trigger=trigger,
                    reason=reason, window=window, tenants=tenants,
                    affected=affected, anomalies=anomalies, notes=notes,
                    replica=where)


def diagnose_bundles(labeled) -> Incident:
    """Diagnose a SET of per-replica bundles — a disaggregated
    topology dumps one black box per decode pool, and the incident
    verdict must say WHICH replica it happened on. ``labeled`` is an
    iterable of ``(replica, bundle_or_path)`` pairs; one pair
    degenerates to :func:`diagnose` (no labels in the output, the
    single-process contract unchanged).

    Merge semantics: per-class scores SUM across bundles (evidence
    lines are prefixed ``[replica]``), the primary class is the
    argmax of the merged scores, and :attr:`Incident.replica` names
    the bundle contributing the most primary-class score — the
    replica the verdict points at. Affected request ids are prefixed
    ``replica:`` so an id names where its lifecycle ran; per-tenant
    counts sum (a tenant's requests span replicas)."""
    labeled = list(labeled)
    if not labeled:
        raise ValueError('diagnose_bundles needs at least one bundle')
    if len(labeled) == 1:
        return diagnose(labeled[0][1])
    incidents = [(str(label), diagnose(bundle))
                 for label, bundle in labeled]
    scores = {c: {'score': 0.0, 'evidence': []} for c in CLASSES}
    tenants: Dict[str, dict] = {}
    affected = {}
    anomalies, notes = [], []
    first_ts, last_ts = [], []
    n_events = dropped = 0
    for label, inc in incidents:
        for cls in CLASSES:
            info = inc.classes[cls]
            scores[cls]['score'] += info['score']
            scores[cls]['evidence'] += [f'[{label}] {ev}'
                                        for ev in info['evidence']]
        for tenant, tb in inc.tenants.items():
            agg = tenants.setdefault(tenant, {k: 0 for k in tb})
            for k, v in tb.items():
                agg[k] = agg.get(k, 0) + v
        for cat, ids in inc.affected.items():
            affected.setdefault(cat, []).extend(
                f'{label}:{rid}' for rid in ids)
        # Anomaly records carry their replica too (every other merged
        # field names its source — an unattributed anomaly would read
        # as the wrong replica's).
        anomalies += [{**rec, 'replica': label}
                      for rec in inc.anomalies]
        notes += [f'[{label}] {n}' for n in inc.notes]
        w = inc.window
        n_events += w['events']
        dropped += w.get('ring_dropped', 0)
        if w['first_ts'] is not None:
            first_ts.append(w['first_ts'])
            last_ts.append(w['last_ts'])
    ranked = sorted(CLASSES, key=lambda c: (-scores[c]['score'],
                                            CLASSES.index(c)))
    primary = ranked[0] if scores[ranked[0]]['score'] > 0 else None
    where = trigger = None
    reason = ''
    if primary is not None:
        where, inc = max(
            incidents, key=lambda li: li[1].classes[primary]['score'])
        trigger, reason = inc.trigger, inc.reason
        if primary in ('replica_loss', 'kv_corruption') \
                and inc.replica is not None:
            # The strongest evidence lives in the ROUTER's bundle (the
            # corpse cannot narrate its own death, and the corruption
            # verdict is the router's) — but the verdict must name the
            # replica it happened ON, not the narrator.
            where = inc.replica
    window = {'events': n_events,
              'first_ts': min(first_ts) if first_ts else None,
              'last_ts': max(last_ts) if last_ts else None,
              'ring_dropped': dropped}
    return Incident(primary=primary, classes=scores, trigger=trigger,
                    reason=reason, window=window,
                    tenants=dict(sorted(tenants.items())),
                    affected=affected, anomalies=anomalies,
                    notes=notes, replica=where)


def _fmt_ids(ids):
    shown = ' '.join(ids[:_MAX_LISTED])
    more = len(ids) - _MAX_LISTED
    return shown + (f' (+{more} more)' if more > 0 else '')


def render_incident(incident: Incident) -> str:
    """The human incident report ``obs doctor`` prints."""
    parts = []
    primary = incident.primary or 'inconclusive'
    score = (incident.classes.get(incident.primary, {}).get('score', 0)
             if incident.primary else 0)
    parts.append(f'INCIDENT: {primary} (score {score:.1f}'
                 + (f', replica {incident.replica}'
                    if incident.replica else '')
                 + (f', dump trigger: {incident.trigger}'
                    if incident.trigger else '') + ')')
    if incident.reason:
        parts.append(f'  reason: {incident.reason}')
    w = incident.window
    parts.append(f'  window: {w["events"]} events'
                 + (f' over {w["last_ts"] - w["first_ts"]:.2f}s'
                    if w['first_ts'] is not None else '')
                 + (f', ring dropped {w["ring_dropped"]}'
                    if w['ring_dropped'] else ''))
    parts.append('classification:')
    for cls in sorted(incident.classes,
                      key=lambda c: -incident.classes[c]['score']):
        info = incident.classes[cls]
        if not info['score']:
            continue
        parts.append(f'  {cls:18} {info["score"]:6.1f}')
        for ev in info['evidence']:
            parts.append(f'      - {ev}')
    if not any(i['score'] for i in incident.classes.values()):
        parts.append('  (no incident evidence in the window)')
    if incident.anomalies:
        parts.append(f'anomaly verdicts: {len(incident.anomalies)}')
        for rec in incident.anomalies[:8]:
            where = (f'[{rec["replica"]}] ' if rec.get('replica')
                     else '')
            parts.append(f'  - {where}'
                         f'{rec.get("watch", rec.get("metric"))}: '
                         f'{rec.get("detector")} value='
                         f'{rec.get("value")}')
    parts.append('affected tenants:')
    if incident.tenants:
        for tenant, tb in incident.tenants.items():
            parts.append(f'  {tenant:12} {tb["requests"]:4d} requests: '
                         f'{tb["met"]} completed in-SLO-window, '
                         f'{tb["rejected"]} rejected, '
                         f'{tb["incomplete"]} incomplete/failed')
    else:
        parts.append('  (no request lifecycle in the window)')
    for cat, ids in incident.affected.items():
        if ids:
            parts.append(f'affected requests ({cat}): {_fmt_ids(ids)}')
    for note in incident.notes:
        parts.append(f'note: {note}')
    return '\n'.join(parts)
