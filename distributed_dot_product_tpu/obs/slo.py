# -*- coding: utf-8 -*-
"""
Goodput-under-SLO accounting over the JSONL event log — the operator
number a serving stack is actually judged by.

The scheduler stamps its latency observations INTO the events it emits
(``queue_wait`` on admit, ``ttft``/``gap`` on decode, ``total_seconds``
on retire — all on its own injectable clock), so a request's entire SLO
verdict is derivable OFFLINE from the log alone. This module does that
derivation:

- :class:`SloSpec`: the contract — TTFT deadline, per-token (inter-
  token gap) deadline, optional end-to-end deadline, per-tenant
  overrides.
- :func:`goodput`: reconstruct every request's timeline (multi-replica
  log sets merge through ``events.merge_events``) and classify each
  submitted request into EXACTLY ONE of ``met`` / ``missed_ttft`` /
  ``missed_token`` / ``missed_e2e`` / ``rejected`` / ``incomplete`` —
  the classes partition the submitted set, so
  ``sum(counts) == requests`` is a standing invariant, per tenant and
  in aggregate. Goodput % = met / submitted.
- :func:`check_baseline`: the CI gate — compare a report against a
  committed ``SLO_BASELINE.json`` with tolerances, emitting
  ``slo.violation`` events into the active log, exactly mirroring the
  ``perf check`` gate (obs/perf.py).

CLI (``python -m distributed_dot_product_tpu.obs slo ...``)::

    obs slo report LOG [LOG...] --ttft 0.25 --per-token 0.05 [--json]
    obs slo report LOG --spec spec.json --baseline-out SLO_BASELINE.json
    obs slo check LOG [LOG...] --against SLO_BASELINE.json

Classification semantics: ``rejected`` = typed shed (at submit or in
queue); ``incomplete`` = the stream did not complete — either a
non-completed terminal (evicted / deadline_expired / failed_nan /
abandoned) or no terminal in the log at all (truncated log, live run);
the ``missed_*`` classes apply to COMPLETED streams only, checked in
TTFT → per-token → e2e order so each request lands in one class.
"""

import dataclasses
import json
from typing import Dict, List, Optional

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.obs.timeline import reconstruct

__all__ = ['SLO_BASELINE_SCHEMA', 'CLASSES', 'SloSpec', 'SloReport',
           'classify', 'goodput', 'check_baseline', 'render_report']

SLO_BASELINE_SCHEMA = 1

# The complete partition, in classification order.
CLASSES = ('met', 'missed_ttft', 'missed_token', 'missed_e2e',
           'rejected', 'incomplete')


@dataclasses.dataclass
class SloSpec:
    """The service-level contract. All deadlines in seconds; ``None``
    disables that check. ``tenants`` maps tenant name → override dict
    with any of the ``ttft``/``per_token``/``e2e`` keys (unset keys
    inherit the global value)."""
    ttft: Optional[float] = None
    per_token: Optional[float] = None
    e2e: Optional[float] = None
    tenants: Dict[str, dict] = dataclasses.field(default_factory=dict)

    def resolve(self, tenant):
        """Effective ``(ttft, per_token, e2e)`` for ``tenant``."""
        o = self.tenants.get(tenant, {})
        return (o.get('ttft', self.ttft),
                o.get('per_token', self.per_token),
                o.get('e2e', self.e2e))

    def to_dict(self):
        return {'ttft': self.ttft, 'per_token': self.per_token,
                'e2e': self.e2e, 'tenants': self.tenants}

    @classmethod
    def from_dict(cls, d):
        return cls(ttft=d.get('ttft'), per_token=d.get('per_token'),
                   e2e=d.get('e2e'), tenants=dict(d.get('tenants', {})))


def classify(tl, spec: SloSpec) -> str:
    """One timeline → one class (see module docstring for semantics)."""
    if tl.status == 'rejected':
        return 'rejected'
    if not tl.complete or tl.status != 'completed':
        return 'incomplete'
    ttft_d, tok_d, e2e_d = spec.resolve(tl.tenant or 'default')
    if ttft_d is not None and (tl.ttft is None or tl.ttft > ttft_d):
        return 'missed_ttft'
    if tok_d is not None and tl.token_gaps \
            and max(tl.token_gaps) > tok_d:
        return 'missed_token'
    if e2e_d is not None and (tl.total_seconds is None
                              or tl.total_seconds > e2e_d):
        return 'missed_e2e'
    return 'met'


def _pct(values, p):
    """Nearest-rank percentile (same rule as utils.tracing.Histogram),
    None on empty."""
    if not values:
        return None
    vals = sorted(values)
    idx = min(len(vals) - 1,
              max(0, int(round((p / 100.0) * (len(vals) - 1)))))
    return vals[idx]


def _percentile_block(values):
    return {'count': len(values), 'p50': _pct(values, 50),
            'p95': _pct(values, 95), 'p99': _pct(values, 99),
            'max': max(values) if values else None}


@dataclasses.dataclass
class SloReport:
    """The goodput verdict for one log (set). ``counts`` partitions
    the submitted requests over :data:`CLASSES`; ``per_tenant`` holds
    the same shape per tenant and sums back to the aggregate."""
    spec: dict
    requests: int
    counts: Dict[str, int]
    goodput_pct: float
    per_tenant: Dict[str, dict]
    percentiles: Dict[str, dict]
    statuses: Dict[str, int]
    by_request: Dict[str, str]

    def to_dict(self, *, brief=False):
        out = {
            'spec': self.spec, 'requests': self.requests,
            'counts': dict(self.counts),
            'goodput_pct': self.goodput_pct,
            'per_tenant': {t: dict(v)
                           for t, v in sorted(self.per_tenant.items())},
            'percentiles': self.percentiles,
            'statuses': dict(sorted(self.statuses.items())),
        }
        if not brief:
            out['by_request'] = dict(sorted(self.by_request.items()))
        return out


def goodput(source, spec: SloSpec) -> SloReport:
    """Compute the goodput report for ``source`` — a log path, an
    EventLog, decoded records, or a LIST of per-replica paths /
    ``(replica, path)`` pairs (merged; one request's lifecycle may span
    a prefill pool's log and a decode pool's)."""
    timelines = reconstruct(source)
    counts = {c: 0 for c in CLASSES}
    per_tenant: Dict[str, dict] = {}
    statuses: Dict[str, int] = {}
    by_request: Dict[str, str] = {}
    ttfts, waits, gaps = [], [], []
    for rid, tl in sorted(timelines.items()):
        cls = classify(tl, spec)
        by_request[rid] = cls
        counts[cls] += 1
        tenant = tl.tenant or 'default'
        tb = per_tenant.setdefault(
            tenant, {'requests': 0, 'goodput_pct': 0.0,
                     'counts': {c: 0 for c in CLASSES}})
        tb['requests'] += 1
        tb['counts'][cls] += 1
        status = tl.status or 'in_flight'
        statuses[status] = statuses.get(status, 0) + 1
        if tl.ttft is not None:
            ttfts.append(tl.ttft)
        if tl.queue_wait is not None:
            waits.append(tl.queue_wait)
        gaps.extend(tl.token_gaps)
    total = sum(counts.values())
    for tb in per_tenant.values():
        tb['goodput_pct'] = (100.0 * tb['counts']['met']
                             / tb['requests'] if tb['requests'] else 0.0)
    return SloReport(
        spec=spec.to_dict(), requests=total, counts=counts,
        goodput_pct=(100.0 * counts['met'] / total if total else 0.0),
        per_tenant=per_tenant,
        percentiles={'ttft': _percentile_block(ttfts),
                     'queue_wait': _percentile_block(waits),
                     'gap': _percentile_block(gaps)},
        statuses=statuses, by_request=by_request)


# -- the regression gate ------------------------------------------------

DEFAULT_TOLERANCES = {
    # Generous CPU tolerances (mirroring the PERF_BASELINE convention):
    # the virtual clock makes a clean rerun EXACTLY reproducible, so
    # these absorb intentional small config drift, not noise.
    'goodput_abs': 10.0,          # percentage points, aggregate
    'tenant_goodput_abs': 15.0,   # percentage points, per tenant
}


def make_baseline(report: SloReport, *, tolerances=None, note=None):
    """The committed-baseline payload for ``report`` (what
    ``slo report --baseline-out`` writes)."""
    return {
        'schema': SLO_BASELINE_SCHEMA,
        '_refresh': note or (
            'Refresh IN THE SAME DIFF as an intentional serving/load '
            'change: `python benchmark.py --mode serve-load '
            '--event-log /tmp/slo.jsonl` (the flag defaults ARE the '
            'CI smoke config) then `python -m '
            'distributed_dot_product_tpu.obs slo report /tmp/slo.jsonl '
            '--spec SLO_BASELINE.json --baseline-out '
            'SLO_BASELINE.json`'),
        'spec': report.spec,
        'requests': report.requests,
        'goodput_pct': report.goodput_pct,
        'per_tenant': {t: v['goodput_pct']
                       for t, v in sorted(report.per_tenant.items())},
        'tolerances': dict(tolerances or DEFAULT_TOLERANCES),
    }


def check_baseline(report: SloReport, baseline: dict, *,
                   emit_events=True) -> List[str]:
    """Gate ``report`` against a committed baseline; returns violation
    strings (empty = pass). Every violation names the metric (and the
    tenant, when per-tenant) and also lands in the active event log as
    an ``slo.violation`` — same discipline as ``perf check``."""
    violations = []

    def _flag(metric, msg, tenant=None, cur=None, base=None):
        where = f'tenant {tenant}: ' if tenant else ''
        violations.append(f'{where}{metric}: {msg}')
        if emit_events and obs_events.get_active() is not None:
            obs_events.emit('slo.violation', metric=metric,
                            tenant=tenant, current=cur, baseline=base,
                            detail=msg)

    if baseline.get('schema') != SLO_BASELINE_SCHEMA:
        return [f'schema: baseline has schema='
                f'{baseline.get("schema")!r} (expected '
                f'{SLO_BASELINE_SCHEMA}) — refresh it']
    tol = {**DEFAULT_TOLERANCES, **baseline.get('tolerances', {})}
    base_req = baseline.get('requests')
    if base_req is not None and report.requests != base_req:
        _flag('requests',
              f'{report.requests} classified vs baseline {base_req} — '
              f'the smoke config drifted from the one the baseline '
              f'was recorded with (refresh both together)',
              cur=report.requests, base=base_req)
    limit = baseline['goodput_pct'] - tol['goodput_abs']
    if report.goodput_pct < limit:
        _flag('goodput_pct',
              f'{report.goodput_pct:.1f}% vs baseline '
              f'{baseline["goodput_pct"]:.1f}% (floor {limit:.1f}% at '
              f'-{tol["goodput_abs"]} pts)',
              cur=report.goodput_pct, base=baseline['goodput_pct'])
    for tenant, base_gp in sorted(baseline.get('per_tenant',
                                               {}).items()):
        tb = report.per_tenant.get(tenant)
        if tb is None:
            _flag('coverage', 'tenant present in the baseline but '
                  'absent from the log (trace config drifted? refresh '
                  'the baseline if intentional)', tenant=tenant)
            continue
        limit = base_gp - tol['tenant_goodput_abs']
        if tb['goodput_pct'] < limit:
            _flag('goodput_pct',
                  f'{tb["goodput_pct"]:.1f}% vs baseline '
                  f'{base_gp:.1f}% (floor {limit:.1f}% at '
                  f'-{tol["tenant_goodput_abs"]} pts)',
                  tenant=tenant, cur=tb['goodput_pct'], base=base_gp)
    for tenant in sorted(report.per_tenant):
        if tenant not in baseline.get('per_tenant', {}):
            _flag('coverage', 'tenant not in the baseline — refresh '
                  'SLO_BASELINE.json in the same change that added '
                  'the tenant', tenant=tenant)
    return violations


# -- rendering ----------------------------------------------------------

def _fmt_s(v):
    return 'n/a' if v is None else f'{v * 1e3:.1f}ms'


def render_report(report: SloReport) -> str:
    """Human goodput table: aggregate verdict, per-tenant breakdown,
    latency percentiles."""
    spec = report.spec
    parts = [
        f'SLO: ttft<{spec.get("ttft")}s per_token<'
        f'{spec.get("per_token")}s e2e<{spec.get("e2e")}s '
        f'({len(spec.get("tenants", {}))} tenant overrides)',
        f'goodput: {report.goodput_pct:.1f}% '
        f'({report.counts["met"]}/{report.requests} met)',
        '  ' + ' '.join(f'{c}={report.counts[c]}' for c in CLASSES),
    ]
    for tenant, tb in sorted(report.per_tenant.items()):
        parts.append(
            f'  tenant {tenant:10} {tb["goodput_pct"]:5.1f}% of '
            f'{tb["requests"]:4d}  ' + ' '.join(
                f'{c}={tb["counts"][c]}' for c in CLASSES
                if tb['counts'][c]))
    for name, blk in report.percentiles.items():
        parts.append(
            f'  {name:11} p50={_fmt_s(blk["p50"])} '
            f'p95={_fmt_s(blk["p95"])} p99={_fmt_s(blk["p99"])} '
            f'max={_fmt_s(blk["max"])} over {blk["count"]}')
    parts.append('  statuses: ' + ' '.join(
        f'{k}={v}' for k, v in sorted(report.statuses.items())))
    return '\n'.join(parts)


def load_baseline(path):
    with open(path, encoding='utf-8') as f:
        return json.load(f)
