# -*- coding: utf-8 -*-
"""
Online anomaly detection over the metric streams the serving loop
already emits — the generalization of the perf observatory's one
hard-coded TTFT-p99 profile trigger into a pluggable watchdog.

Three detector families, each a tiny online algorithm over ONE scalar
stream (no history buffers beyond O(1) state):

- :class:`StaticThreshold` — breach when the value crosses a fixed
  ``above``/``below`` bound (page-pool exhaustion, queue-full).
- :class:`EwmaZScore` — exponentially-weighted mean/variance; breach
  when the standardized residual exceeds ``z`` sigmas after a warmup
  (latency regressions, throughput collapses — no tuning per service).
- :class:`RateOfChange` — breach when one update moves more than
  ``max_delta`` (absolute) or ``max_ratio`` × the previous value
  (cliff detection on gauges that should move smoothly).

A :class:`Watch` binds a detector to a registry stream (gauge value,
histogram percentile, counter rate, or a custom ``fn``) with a
per-watch real-time cooldown and an ``actions`` tuple naming what a
breach chains: ``'profile'`` begins one bounded
:class:`~distributed_dot_product_tpu.obs.devmon.ProfileCapture` (the
regression gets profiled WHILE it happens), ``'dump'`` writes a flight
post-mortem bundle (obs/flight.py). Every breach emits a
closed-vocabulary ``anomaly.detected`` event into the event log, so
``obs doctor`` sees the detector's verdict next to the lifecycle it
judged.

:class:`AnomalyWatchdog` evaluates its watch list from the scheduler's
tick (throttled to ``min_interval`` REAL seconds — between evaluations
a tick costs one clock read), or from any caller's own cadence.
:func:`default_watches` is the stock catalog: TTFT p99, tokens/s,
queue depth, ``serve.cache.pages_free``, reject rate.
"""

import dataclasses
import math
import time
from typing import Callable, Optional, Sequence, Tuple

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.obs import flight as obs_flight
from distributed_dot_product_tpu.utils import tracing

__all__ = ['Detector', 'StaticThreshold', 'EwmaZScore', 'RateOfChange',
           'Watch', 'AnomalyWatchdog', 'default_watches']


class Detector:
    """One online detector over one scalar stream. :meth:`update`
    consumes the next observation and returns None (in spec) or a
    JSON-able dict describing the breach (stamped onto the
    ``anomaly.detected`` event)."""

    def update(self, value) -> Optional[dict]:
        raise NotImplementedError

    def reset(self):
        """Forget learned state (a quarantine/requeue storm ends; the
        operator wants fresh baselines, not poisoned ones)."""


class StaticThreshold(Detector):
    """Breach when ``value > above`` or ``value < below``."""

    def __init__(self, *, above=None, below=None):
        if above is None and below is None:
            raise ValueError('StaticThreshold needs above= or below=')
        self.above = above
        self.below = below

    def update(self, value):
        if self.above is not None and value > self.above:
            return {'kind': 'above', 'threshold': self.above}
        if self.below is not None and value < self.below:
            return {'kind': 'below', 'threshold': self.below}
        return None


class EwmaZScore(Detector):
    """Exponentially-weighted mean/variance z-score.

    The first ``min_samples`` observations only TRAIN the baseline
    (every stream starts cold — flagging the first request's TTFT
    against an empty history would alert on every startup). After
    warmup, an observation more than ``z`` sigmas from the EWMA mean
    breaches; breaching observations still update the baseline (with
    weight ``alpha``), so a sustained level shift re-baselines instead
    of alerting forever. Two sigma floors keep a near-constant stream
    honest — ``min_sigma`` absolute and ``rel_floor`` as a fraction of
    the mean — so variance ~0 must not turn the first harmless jitter
    into an astronomical z."""

    def __init__(self, *, z=4.0, alpha=0.2, min_samples=16,
                 min_sigma=1e-9, rel_floor=0.05):
        self.z = float(z)
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self.min_sigma = float(min_sigma)
        self.rel_floor = float(rel_floor)
        self.reset()

    def reset(self):
        self._n = 0
        self._mean = 0.0
        self._var = 0.0

    def update(self, value):
        v = float(value)
        verdict = None
        if self._n >= self.min_samples:
            sigma = max(math.sqrt(self._var), self.min_sigma,
                        abs(self._mean) * self.rel_floor)
            score = (v - self._mean) / sigma
            if abs(score) > self.z:
                verdict = {'kind': 'zscore', 'z': score,
                           'mean': self._mean, 'sigma': sigma,
                           'threshold': self.z}
        # Welford-flavored EWMA update (West 1979): one pass, O(1).
        a = self.alpha if self._n else 1.0
        delta = v - self._mean
        self._mean += a * delta
        self._var = (1.0 - a) * (self._var + a * delta * delta)
        self._n += 1
        return verdict


class RateOfChange(Detector):
    """Breach when one observation moves more than ``max_delta``
    (absolute) or ``max_ratio`` times the previous magnitude from the
    last one — cliffs on streams that should move smoothly
    (pages_free collapsing within one tick)."""

    def __init__(self, *, max_delta=None, max_ratio=None):
        if max_delta is None and max_ratio is None:
            raise ValueError('RateOfChange needs max_delta= or '
                             'max_ratio=')
        self.max_delta = max_delta
        self.max_ratio = max_ratio
        self.reset()

    def reset(self):
        self._prev = None

    def update(self, value):
        v = float(value)
        prev, self._prev = self._prev, v
        if prev is None:
            return None
        delta = v - prev
        if self.max_delta is not None and abs(delta) > self.max_delta:
            return {'kind': 'delta', 'delta': delta, 'previous': prev,
                    'threshold': self.max_delta}
        if self.max_ratio is not None and abs(prev) > 0 \
                and abs(delta) > self.max_ratio * abs(prev):
            return {'kind': 'ratio', 'delta': delta, 'previous': prev,
                    'threshold': self.max_ratio}
        return None


@dataclasses.dataclass
class Watch:
    """One watched stream. ``signal`` selects how ``metric`` is read
    from the registry: ``'gauge'``/``'counter'`` read the value,
    ``'p50'``/``'p99'`` a histogram's reservoir percentile, ``'fn'``
    calls ``fn(registry)``. ``rate=True`` differentiates the read
    value against real time (counters → per-second rates). A stream
    with no series yet (or a NaN read) is skipped — absence of traffic
    is not an anomaly. ``actions``: any of ``'profile'``/``'dump'``,
    fired on breach when the watchdog holds a profiler / a flight
    recorder is installed. ``cooldown`` is the per-watch re-alert
    floor (REAL seconds)."""
    name: str
    metric: str
    detector: Detector
    signal: str = 'gauge'
    fn: Optional[Callable] = None
    rate: bool = False
    cooldown: float = 30.0
    actions: Tuple[str, ...] = ()
    # runtime state (not config)
    _last_breach: Optional[float] = dataclasses.field(
        default=None, repr=False, compare=False)
    _rate_anchor: Optional[tuple] = dataclasses.field(
        default=None, repr=False, compare=False)
    _last_fed: Optional[float] = dataclasses.field(
        default=None, repr=False, compare=False)

    def read(self, registry, now):
        """Current observation, or None (no series / empty / first
        rate sample)."""
        if self.signal == 'fn':
            value = self.fn(registry)
        elif self.signal in ('gauge', 'counter'):
            m = registry.peek(self.signal, self.metric)
            value = None if m is None else m.value
        elif self.signal in ('p50', 'p99'):
            h = registry.peek('histogram', self.metric)
            value = None if h is None else h.percentile(
                50 if self.signal == 'p50' else 99)
        else:
            raise ValueError(f'unknown signal {self.signal!r}')
        if value is None or (isinstance(value, float)
                             and math.isnan(value)):
            return None
        if not self.rate:
            return float(value)
        anchor, self._rate_anchor = self._rate_anchor, (now, value)
        if anchor is None or now <= anchor[0]:
            return None
        return (value - anchor[1]) / (now - anchor[0])


class AnomalyWatchdog:
    """Evaluate a watch list against ``registry`` (see module
    docstring). ``profiler`` (optional
    :class:`~distributed_dot_product_tpu.obs.devmon.ProfileCapture`)
    serves the ``'profile'`` action; the ``'dump'`` action resolves
    the process flight recorder at breach time. ``event_log``: the
    explicit sink, else the active log (the events idiom)."""

    def __init__(self, registry=None, watches: Sequence[Watch] = (),
                 *, profiler=None, event_log=None, min_interval=0.25,
                 profile_seconds=2.0, clock=None):
        self.registry = registry or tracing.get_registry()
        self.watches = list(watches)
        self.profiler = profiler
        self.event_log = event_log
        self.min_interval = float(min_interval)
        self.profile_seconds = float(profile_seconds)
        # ``clock`` times the tick throttle, the per-watch breach
        # cooldowns and the rate differentiation. Default REAL time
        # (the live-serving contract this module documents); the
        # closed-loop controller (serve/control.py) injects the
        # scheduler's virtual clock so a seeded load run's breach
        # sequence — and therefore its control history — replays
        # bit-identically.
        self.clock = clock or time.monotonic
        self._last_tick = None
        self.breaches = []      # [(watch name, verdict dict)]
        self._c_breach = self.registry.counter('anomaly.breaches')

    def tick(self, force=False):
        """Evaluate every watch once, throttled to ``min_interval``
        seconds on the watchdog's clock (REAL by default) unless
        ``force``. Returns the breaches fired this evaluation as
        ``[(watch, verdict), ...]``."""
        now = self.clock()
        if not force and self._last_tick is not None \
                and now - self._last_tick < self.min_interval:
            return []
        self._last_tick = now
        fired = []
        for watch in self.watches:
            try:
                value = watch.read(self.registry, now)
            except Exception as e:
                tracing.log_exception('anomaly.read', e,
                                      registry=self.registry)
                continue
            if value is None:
                continue
            # A non-rate reading identical to the last one fed carries
            # NO new information (a histogram p99 is constant between
            # admissions; the tick cadence outruns the stream): feeding
            # it anyway would collapse an EWMA detector's variance
            # toward zero and turn the next real observation's tiny
            # jitter into an astronomical z — a false breach on a
            # healthy service. Rates are fresh per interval by
            # construction and always feed.
            if not watch.rate and value == watch._last_fed:
                continue
            watch._last_fed = value
            verdict = watch.detector.update(value)
            if verdict is None:
                continue
            if watch._last_breach is not None \
                    and now - watch._last_breach < watch.cooldown:
                continue
            watch._last_breach = now
            self._breach(watch, value, verdict)
            fired.append((watch, verdict))
        return fired

    def _breach(self, watch: Watch, value, verdict):
        self._c_breach.inc()
        self.registry.counter('anomaly.breaches.' + watch.name).inc()
        self.breaches.append((watch.name, dict(verdict, value=value)))
        obs_events.emit('anomaly.detected', _log=self.event_log,
                        metric=watch.metric,
                        detector=type(watch.detector).__name__,
                        value=value, watch=watch.name, **verdict)
        if 'profile' in watch.actions and self.profiler is not None:
            try:
                self.profiler.start(
                    self.profile_seconds,
                    trigger=f'anomaly.{watch.name}',
                    event_log=self.event_log, value=value)
            except Exception as e:
                # CaptureInFlight included: contention, never a crash.
                tracing.log_exception('anomaly.profile', e,
                                      registry=self.registry)
        if 'dump' in watch.actions:
            try:
                obs_flight.recorder().maybe_dump(
                    trigger='anomaly',
                    reason=f'{watch.name}: {verdict}')
            except Exception as e:
                tracing.log_exception('anomaly.dump', e,
                                      registry=self.registry)


def _reject_total(registry):
    """Sum of the typed per-reason reject counters (lazy import — obs
    must not pull the serve package at module load)."""
    from distributed_dot_product_tpu.serve.admission import RejectReason
    total = 0
    for reason in RejectReason:
        c = registry.peek('counter', f'serve.rejected.{reason.value}')
        if c is not None:
            total += c.value
    return float(total)


def default_watches(*, queue_limit=None, paged=False,
                    ttft_z=4.0, cooldown=30.0) -> list:
    """The stock serving catalog (every stream already emitted by the
    scheduler/admission layers — arming the watchdog adds no new
    instrumentation):

    - ``ttft_p99``: EWMA z-score on the ``serve.ttft_seconds``
      reservoir p99 (chains a profile capture + a flight dump — the
      generalization of the old one-off scheduler trigger).
    - ``tokens_per_s``: EWMA z-score on the
      ``serve.tokens_generated`` rate (throughput collapse).
    - ``queue_depth``: static threshold at 90% of ``queue_limit``
      when given, else EWMA (overload).
    - ``pages_free`` (paged engines): static threshold below 1 —
      pool exhaustion (chains a flight dump).
    - ``reject_rate``: EWMA z-score on the summed typed-reject rate.
    - ``kv_corrupt``: static threshold on the router's corruption
      counter — ANY checksum-failed page chains a flight dump (the
      post-mortem bundle is how the doctor attributes the verdict).
    - ``dispatch_overhead_p99``: EWMA z-score on the
      ``serve.dispatch_overhead_seconds`` reservoir p99 — a
      dispatch-floor regression (host loop suddenly eating more of
      each decode tick) auto-captures a flight bundle carrying the
      critpath summary that names where the time went.
    """
    watches = [
        Watch(name='ttft_p99', metric='serve.ttft_seconds',
              signal='p99', detector=EwmaZScore(z=ttft_z),
              cooldown=cooldown, actions=('profile', 'dump')),
        Watch(name='tokens_per_s', metric='serve.tokens_generated',
              signal='counter', rate=True,
              detector=EwmaZScore(z=ttft_z), cooldown=cooldown),
        Watch(name='queue_depth', metric='serve.queue_depth',
              signal='gauge',
              detector=(StaticThreshold(above=0.9 * queue_limit)
                        if queue_limit else EwmaZScore(z=ttft_z)),
              cooldown=cooldown),
        Watch(name='reject_rate', metric='serve.rejected',
              signal='fn', fn=_reject_total, rate=True,
              detector=EwmaZScore(z=ttft_z), cooldown=cooldown),
        Watch(name='kv_corrupt', metric='router.kv_corrupt',
              signal='counter', detector=StaticThreshold(above=0),
              cooldown=cooldown, actions=('dump',)),
        Watch(name='dispatch_overhead_p99',
              metric='serve.dispatch_overhead_seconds', signal='p99',
              detector=EwmaZScore(z=ttft_z), cooldown=cooldown,
              actions=('dump',)),
    ]
    if paged:
        watches.append(
            Watch(name='pages_free', metric='serve.cache.pages_free',
                  signal='gauge', detector=StaticThreshold(below=1),
                  cooldown=cooldown, actions=('dump',)))
    return watches
