# -*- coding: utf-8 -*-
"""
Incident flight recorder — a bounded in-memory black box that captures
the service's state AT the moment of failure, and the post-mortem
bundle a human (or ``obs doctor``) can diagnose from alone.

The obs stack can already explain a run *after the fact* (event log +
timelines, perf observatory, goodput accounting); this module owns the
incident-response half:

- :class:`FlightRecorder`: a hard-bounded ring (records AND bytes) that
  tees every record the active :class:`~distributed_dot_product_tpu
  .obs.events.EventLog` emits (already-encoded lines — no second
  serialization) plus periodic metric-registry samples and
  ``device_stats_snapshot()`` polls. Always-on cheap when enabled;
  **zero-alloc when disabled** — the events tee is one global
  None-check (the spans contract), and :func:`recorder` returns one
  shared null object so call sites never branch.
- :meth:`FlightRecorder.dump_bundle`: writes a schema-versioned bundle
  directory — MANIFEST + the ring's event window as VALID event-log
  JSONL (``obs validate`` / ``reconstruct`` / ``goodput`` work on it
  unchanged) + the full metrics snapshot + device stats + all-thread
  stack dumps (``sys._current_frames``) + any registered introspection
  sections (the scheduler contributes its slot table / queue depth /
  page-pool stats via :func:`add_provider`).

Triggers (each emits a ``postmortem.dump`` event): the serving
watchdog's stall callback, an unhandled scheduler-loop exception, a
NaN-quarantine storm, an anomaly breach (obs/anomaly.py), SIGTERM
(:meth:`FlightRecorder.install_sigterm`), ``GET /dump`` on the
:class:`~distributed_dot_product_tpu.obs.exporter.MetricsServer`, and
manual calls. Auto triggers go through :meth:`FlightRecorder
.maybe_dump`, which rate-limits per trigger so a stall that repeats
does not dump a storm of bundles.

Usage::

    from distributed_dot_product_tpu.obs import flight

    with flight.recording(base_dir='/tmp/flight') as rec:
        ...                              # serve under traffic
        rec.dump_bundle(trigger='manual')

    # or process-wide via the env knob a shell driver sets:
    rec = flight.open_from_env()         # $DDP_TPU_FLIGHT_DIR
"""

import collections
import contextlib
import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Dict, Optional

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.utils import tracing

__all__ = ['BUNDLE_SCHEMA', 'ENV_VAR', 'FlightRecorder', 'recorder',
           'get_recorder', 'install', 'recording', 'open_from_env',
           'add_provider', 'remove_provider', 'load_bundle']

BUNDLE_SCHEMA = 1
ENV_VAR = 'DDP_TPU_FLIGHT_DIR'

# Bundle file names (MANIFEST lists them; load_bundle reads them).
_EVENTS_FILE = 'events.jsonl'
_METRICS_FILE = 'metrics.json'
_SAMPLES_FILE = 'metric_samples.jsonl'
_DEVICES_FILE = 'device_samples.jsonl'
_STACKS_FILE = 'stacks.json'


def _thread_stacks():
    """``{thread_name: [frame lines...]}`` for every live thread —
    what a hung scheduler looks like from the inside (the watchdog
    thread dumping this while the loop thread sleeps inside a wedged
    step is exactly the post-mortem a stall needs)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = names.get(ident, f'thread-{ident}')
        out[label] = [line.rstrip('\n')
                      for line in traceback.format_stack(frame)]
    return out


class FlightRecorder:
    """Bounded black-box ring + bundle dumper (see module docstring).

    ``max_records`` / ``max_bytes`` hard-bound the ring — whichever
    fills first evicts from the oldest end, and the eviction count is
    recorded in the MANIFEST (a truncated window is an audit fact, not
    a silent gap). ``sample_interval`` throttles the periodic metric /
    device samples (REAL seconds — :meth:`sample` is safe to call every
    scheduler tick); ``dump_cooldown`` rate-limits :meth:`maybe_dump`
    per trigger. ``registry`` is the metrics registry sampled into the
    ring and snapshotted into bundles (default: the process registry).
    """

    def __init__(self, base_dir, *, max_records=2048,
                 max_bytes=2 * 2 ** 20, sample_interval=1.0,
                 dump_cooldown=30.0,
                 registry: Optional[tracing.MetricsRegistry] = None,
                 devices=None, clock=time.time):
        self.base_dir = os.fspath(base_dir)
        self.max_records = int(max_records)
        self.max_bytes = int(max_bytes)
        self.sample_interval = float(sample_interval)
        self.dump_cooldown = float(dump_cooldown)
        self.registry = registry or tracing.get_registry()
        self.clock = clock
        self._devices = devices
        self._lock = threading.Lock()
        self._ring = collections.deque()     # guarded-by: self._lock
        self._bytes = 0                      # guarded-by: self._lock
        self._dropped = 0                    # guarded-by: self._lock
        self._teed = 0                       # guarded-by: self._lock
        self._last_sample = None             # real-time throttle anchor
        self._last_dump: Dict[str, float] = {}
        self._n_dumps = 0                    # guarded-by: self._lock
        self.dumps = []                      # [{'path','trigger',...}]
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._prev_sigterm = None
        self._c_dumps = self.registry.counter('flight.dumps')
        self._g_records = self.registry.gauge('flight.ring_records')
        self._g_bytes = self.registry.gauge('flight.ring_bytes')

    # -- the ring -------------------------------------------------------
    def _add(self, kind, line, teed=False):
        with self._lock:
            if teed:
                self._teed += 1
            self._ring.append((kind, line))
            self._bytes += len(line)
            while self._ring and (len(self._ring) > self.max_records
                                  or self._bytes > self.max_bytes):
                _, old = self._ring.popleft()
                self._bytes -= len(old)
                self._dropped += 1

    def _tee_event(self, rec, line):
        """The events-module hook: every record any EventLog emits
        lands here as its already-encoded line (installed via
        :func:`install`; one global None-check when not). The tee
        count rides ``_add``'s lock — this runs under the SOURCE
        log's lock while the sampling thread holds ours."""
        self._add('event', line, teed=True)

    def sample(self, force=False):
        """One metric-registry sample + device-stats poll into the
        ring, throttled to ``sample_interval`` REAL seconds unless
        ``force`` — the scheduler calls this every tick; steady-state
        cost between samples is one clock read and a compare."""
        now = time.monotonic()
        if not force and self._last_sample is not None \
                and now - self._last_sample < self.sample_interval:
            return False
        self._last_sample = now
        ts = self.clock()
        snap = self.registry.snapshot()
        self._add('metrics', json.dumps(
            {'ts': ts, 'metrics': snap},
            separators=(',', ':'), default=str))
        try:
            from distributed_dot_product_tpu.obs.devmon import (
                device_stats_snapshot,
            )
            devs = device_stats_snapshot(self._devices)
        except Exception as e:      # a dead backend must not kill obs
            tracing.log_exception('flight.device_sample', e,
                                  registry=self.registry)
            devs = None
        self._add('devices', json.dumps(
            {'ts': ts, 'devices': devs},
            separators=(',', ':'), default=str))
        # Gauge values read under the ring lock: the scheduler tick and
        # the background sampling thread both land here, and a torn
        # read would export a records/bytes pair from two moments.
        with self._lock:
            records, ring_bytes = len(self._ring), self._bytes
        self._g_records.set(records)
        self._g_bytes.set(ring_bytes)
        return True

    def stats(self):
        with self._lock:
            return {'records': len(self._ring), 'bytes': self._bytes,
                    'dropped': self._dropped, 'teed': self._teed,
                    'max_records': self.max_records,
                    'max_bytes': self.max_bytes,
                    'dumps': self._n_dumps}

    # -- background sampling thread (optional; the scheduler's per-tick
    # sample() calls make it unnecessary under a serving loop) ---------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name='obs-flight', daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.sample()
            except Exception as e:
                tracing.log_exception('flight.sample', e,
                                      registry=self.registry)
            self._stop.wait(self.sample_interval)

    def stop(self):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None

    # -- dumping --------------------------------------------------------
    def maybe_dump(self, *, trigger, reason='', sections=None):
        """Rate-limited :meth:`dump_bundle` for AUTO triggers: at most
        one bundle per ``dump_cooldown`` REAL seconds per trigger kind
        (a stall that repeats while an operator reacts must not fill
        the disk with near-identical bundles). Returns the bundle path,
        or None when suppressed."""
        now = time.monotonic()
        last = self._last_dump.get(trigger)
        if last is not None and now - last < self.dump_cooldown:
            return None
        path = self.dump_bundle(trigger=trigger, reason=reason,
                                sections=sections)
        # Cooldown anchors on SUCCESS only: a dump that failed (disk
        # full, base_dir transiently unwritable) must not suppress the
        # retry the still-firing trigger will request.
        self._last_dump[trigger] = time.monotonic()
        return path

    def dump_bundle(self, out_dir=None, *, trigger='manual', reason='',
                    sections=None, event_log=None):
        """Write one post-mortem bundle directory and return its path.

        Layout (all files listed in MANIFEST.json):

        - ``events.jsonl`` — the ring's event window, byte-identical to
          the lines the source log wrote (``obs validate`` /
          ``reconstruct`` / ``goodput`` run on it unchanged).
        - ``metric_samples.jsonl`` / ``device_samples.jsonl`` — the
          ring's periodic samples (one final forced sample is taken
          here, so a bundle always carries the state AT dump time).
        - ``metrics.json`` — the full registry snapshot at dump time.
        - ``stacks.json`` — every live thread's stack.
        - ``<name>.json`` per introspection section: ``sections``
          passed by the caller (the scheduler's triggers hand their
          slot table in directly) merged over the module-level
          :func:`add_provider` registry (the ``/dump`` endpoint's
          path); explicit sections win on name collision.

        Emits a ``postmortem.dump`` event into ``event_log`` (or the
        active log) AFTER the files land — the bundle never contains
        its own dump record, the next one does.
        """
        self.sample(force=True)
        with self._lock:
            entries = list(self._ring)
            ring_stats = {'records': len(self._ring),
                          'bytes': self._bytes,
                          'dropped': self._dropped,
                          'max_records': self.max_records,
                          'max_bytes': self.max_bytes}
            self._n_dumps += 1
            n = self._n_dumps
        if out_dir is None:
            out_dir = os.path.join(self.base_dir,
                                   f'bundle-{n:04d}-{trigger}')
        out_dir = os.fspath(out_dir)
        os.makedirs(out_dir, exist_ok=True)

        by_kind = {'event': [], 'metrics': [], 'devices': []}
        for kind, line in entries:
            by_kind.setdefault(kind, []).append(line)
        for fname, kind in ((_EVENTS_FILE, 'event'),
                            (_SAMPLES_FILE, 'metrics'),
                            (_DEVICES_FILE, 'devices')):
            with open(os.path.join(out_dir, fname), 'w',
                      encoding='utf-8') as f:
                for line in by_kind[kind]:
                    f.write(line + '\n')
        with open(os.path.join(out_dir, _METRICS_FILE), 'w',
                  encoding='utf-8') as f:
            json.dump(self.registry.snapshot(), f, indent=2,
                      default=str)
        with open(os.path.join(out_dir, _STACKS_FILE), 'w',
                  encoding='utf-8') as f:
            json.dump(_thread_stacks(), f, indent=2)

        merged = {}
        for name, fn in list(_PROVIDERS.items()):
            try:
                merged[name] = fn()
            except Exception as e:  # a broken provider can't block a dump
                tracing.log_exception('flight.provider', e,
                                      registry=self.registry)
        merged.update(sections or {})
        section_files = {}
        for name, payload in merged.items():
            fname = f'{name}.json'
            section_files[name] = fname
            with open(os.path.join(out_dir, fname), 'w',
                      encoding='utf-8') as f:
                json.dump(payload, f, indent=2, default=str)

        # ONE version probe shared with /metrics' build_info gauge —
        # a bundle MANIFEST and a scrape of the same process can never
        # disagree (lazy import: exporter pulls http.server, which the
        # recorder's hot path must not pay at module load).
        from distributed_dot_product_tpu.obs.exporter import (
            build_info_labels,
        )
        info = build_info_labels()
        manifest = {
            'schema': BUNDLE_SCHEMA,
            'bundle': 'ddp-flight-postmortem',
            'created_ts': self.clock(),
            'trigger': trigger,
            'reason': reason,
            'event_schema_version': obs_events.SCHEMA_VERSION,
            'jax_version': info['jax_version'],
            'python_version': info['python_version'],
            'ring': ring_stats,
            'files': {'events': _EVENTS_FILE,
                      'metrics': _METRICS_FILE,
                      'metric_samples': _SAMPLES_FILE,
                      'device_samples': _DEVICES_FILE,
                      'stacks': _STACKS_FILE,
                      'sections': section_files},
        }
        with open(os.path.join(out_dir, 'MANIFEST.json'), 'w',
                  encoding='utf-8') as f:
            json.dump(manifest, f, indent=2)
        self._c_dumps.inc()
        info = {'path': out_dir, 'trigger': trigger, 'reason': reason,
                'ts': manifest['created_ts']}
        self.dumps.append(info)
        obs_events.emit('postmortem.dump', _log=event_log,
                        trigger=trigger, path=out_dir, reason=reason)
        return out_dir

    # -- SIGTERM trigger ------------------------------------------------
    def install_sigterm(self, *, dump_timeout=5.0):
        """Chain a SIGTERM handler that dumps one bundle (trigger
        ``'sigterm'``) and then invokes whatever handler was installed
        before (the training driver's final-save handler keeps
        working). Main-thread only (signal module contract); opt-in —
        a library must not steal signals by default.

        The dump runs on a WORKER thread with a bounded join, never
        inline in the handler: the signal can interrupt the main
        thread while it holds the event-log / ring / registry locks
        (all non-reentrant — ``EventLog.emit`` calls the tee under its
        lock), and an inline dump re-acquiring them would deadlock the
        handler and make the process ignore SIGTERM entirely. With the
        worker, a blocked dump merely times out after ``dump_timeout``
        seconds, finishes in the background once the interrupted frame
        releases its lock, and the chained handler always runs."""
        def _dump():
            try:
                self.maybe_dump(trigger='sigterm', reason='SIGTERM')
            except Exception as e:
                tracing.log_exception('flight.sigterm_dump', e,
                                      registry=self.registry)

        def _handler(signum, frame):
            worker = threading.Thread(target=_dump,
                                      name='obs-flight-sigterm',
                                      daemon=True)
            worker.start()
            worker.join(dump_timeout)
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)
        return self

    def uninstall_sigterm(self):
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None


class _NullRecorder:
    """The disabled path: one shared, stateless no-op recorder —
    :func:`recorder` returns it when nothing is installed, so hot call
    sites (the scheduler's per-tick ``sample()``) never allocate or
    branch (the spans ``_NullSpan`` contract)."""

    __slots__ = ()

    def sample(self, force=False):
        return False

    def maybe_dump(self, **kwargs):
        return None

    def dump_bundle(self, *args, **kwargs):
        return None

    def stats(self):
        return {'records': 0, 'bytes': 0, 'dropped': 0, 'teed': 0,
                'max_records': 0, 'max_bytes': 0, 'dumps': 0}


_NULL = _NullRecorder()
_RECORDER: Optional[FlightRecorder] = None

# Introspection providers: name -> zero-arg callable returning a
# JSON-able section for every bundle (the scheduler registers its slot
# table / queue / page-pool introspection here so even an HTTP /dump
# with no scheduler in hand captures it). Module-level, not per
# recorder: a provider registered before the recorder is installed
# still contributes.
_PROVIDERS: Dict[str, object] = {}


def add_provider(name, fn):
    """Register ``fn()`` to be embedded as ``<name>.json`` in every
    bundle. Returns ``fn`` (decorator-friendly)."""
    _PROVIDERS[name] = fn
    return fn


def remove_provider(name, fn=None):
    """Remove a provider; with ``fn`` given, only when it is still the
    registered one (a closed scheduler must not unregister its
    replacement's section)."""
    if fn is None or _PROVIDERS.get(name) is fn:
        _PROVIDERS.pop(name, None)


def _critpath_section():
    """Stock provider: the critpath summary of the requests in the
    ring's event window — every post-mortem bundle answers "where was
    the time going when this happened" without the operator replaying
    the full log (``obs doctor`` names the dominant phase from this
    section). Reads the installed recorder's ring under its lock; an
    empty/absent ring yields an empty summary, never an error."""
    rec = _RECORDER
    if rec is None:
        return {'requests': 0, 'complete': 0, 'partial': 0,
                'partition_failures': [], 'phases': {}}
    with rec._lock:
        lines = [line for kind, line in rec._ring if kind == 'event']
    records = []
    for line in lines:
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            continue            # torn tee line: skip, never block a dump
    from distributed_dot_product_tpu.obs import critpath as obs_critpath
    return obs_critpath.summarize_records(records)


add_provider('critpath', _critpath_section)


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def recorder():
    """The installed :class:`FlightRecorder`, or the shared null
    recorder — call sites use the result unconditionally."""
    return _RECORDER if _RECORDER is not None else _NULL


def install(rec: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    """Install ``rec`` as the process-wide recorder (None uninstalls);
    wires the events-module tee. Returns the previous recorder."""
    global _RECORDER
    prev = _RECORDER
    _RECORDER = rec
    obs_events._TEE = rec._tee_event if rec is not None else None
    return prev


@contextlib.contextmanager
def recording(base_dir=None, **kwargs):
    """Scoped enablement (the normal way to wire a run)::

        with flight.recording(base_dir='/tmp/flight') as rec:
            ...
            rec.dump_bundle(trigger='manual')
    """
    import tempfile
    if base_dir is None:
        base_dir = tempfile.mkdtemp(prefix='ddp_flight_')
    rec = FlightRecorder(base_dir, **kwargs)
    prev = install(rec)
    try:
        yield rec
    finally:
        install(prev)
        rec.stop()


def open_from_env(environ=None, **kwargs) -> Optional[FlightRecorder]:
    """A :class:`FlightRecorder` rooted at ``$DDP_TPU_FLIGHT_DIR`` (or
    None when the knob is unset) — how shell drivers
    (scripts/smoke_serve.sh) arm the black box without touching
    python. NOT auto-installed; callers decide the scope."""
    env = os.environ if environ is None else environ
    path = env.get(ENV_VAR)
    return FlightRecorder(path, **kwargs) if path else None


# -- read side ------------------------------------------------------------

def load_bundle(path):
    """Read a bundle directory back into one dict: ``manifest``,
    decoded ``events`` (seq-sorted, via ``events.read_events`` — a
    crash-torn tail line is tolerated), ``metrics``, ``metric_samples``
    / ``device_samples`` (decoded lines), ``stacks``, and ``sections``.
    Raises ``FileNotFoundError``/``ValueError`` on a directory that is
    not a bundle — ``obs doctor`` maps that to exit 1."""
    path = os.fspath(path)
    mpath = os.path.join(path, 'MANIFEST.json')
    if not os.path.exists(mpath):
        raise FileNotFoundError(f'{path}: no MANIFEST.json — not a '
                                f'flight bundle')
    with open(mpath, encoding='utf-8') as f:
        manifest = json.load(f)
    if manifest.get('schema') != BUNDLE_SCHEMA:
        raise ValueError(f'{path}: bundle schema '
                         f'{manifest.get("schema")!r} (supported: '
                         f'{BUNDLE_SCHEMA})')
    files = manifest.get('files', {})

    def _read_json(key, default):
        fname = files.get(key)
        fpath = fname and os.path.join(path, fname)
        if not fpath or not os.path.exists(fpath):
            return default
        with open(fpath, encoding='utf-8') as f:
            return json.load(f)

    def _read_jsonl(key):
        fname = files.get(key)
        fpath = fname and os.path.join(path, fname)
        if not fpath or not os.path.exists(fpath):
            return []
        out = []
        with open(fpath, encoding='utf-8') as f:
            lines = f.read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                if i != len(lines) - 1:     # torn tail tolerated
                    raise
        return out

    events_path = os.path.join(path, files.get('events', _EVENTS_FILE))
    events = (obs_events.read_events(events_path)
              if os.path.exists(events_path) else [])
    sections = {name: _read_json_name(path, fname)
                for name, fname in files.get('sections', {}).items()}
    return {
        'path': path,
        'manifest': manifest,
        'events': events,
        'events_path': events_path,
        'metrics': _read_json('metrics', {}),
        'metric_samples': _read_jsonl('metric_samples'),
        'device_samples': _read_jsonl('device_samples'),
        'stacks': _read_json('stacks', {}),
        'sections': sections,
    }


def _read_json_name(bundle_path, fname):
    fpath = os.path.join(bundle_path, fname)
    if not os.path.exists(fpath):
        return None
    with open(fpath, encoding='utf-8') as f:
        return json.load(f)
