# -*- coding: utf-8 -*-
"""
Request-timeline reconstruction over the JSONL event log.

The serving scheduler stamps its latency observations INTO the events
it emits (``queue_wait`` on admit, ``ttft``/``gap`` on decode,
``total_seconds`` on retire — measured on the scheduler's own clock, so
reconstruction is immune to wall-clock skew between the scheduler and
the log). This module turns the flat event stream back into per-request
lifecycles and checks them against the serving contract:

    admit → (prefill* | decode* | quarantine)* → retire(status)
  | reject(reason)                       # shed at submit or in queue
  | retire(abandoned)                    # cancelled while still queued
  | recovered → re-admit | reject        # replica died mid-stream

A :class:`Timeline` whose ``complete`` is False carries the specific
violations in ``errors`` — the smoke audit (examples/serve_lm.py
``--event-log``) and the tier-1 fault-cocktail test fail on any of
them, which is what makes "every request reconstructable from the log
alone" a standing contract rather than a hope.
"""

import dataclasses
import os
from typing import Dict, List, Optional

from distributed_dot_product_tpu.obs.events import (
    EventLog, merge_events, read_events,
)

__all__ = ['Timeline', 'timeline', 'reconstruct']

# Events that end a request's lifecycle.
_TERMINAL = {'serve.retire', 'serve.reject'}
# Events legal only while the request holds a slot.
_RUNNING_ONLY = {'serve.prefill', 'serve.decode', 'serve.evict',
                 'serve.quarantine', 'serve.preempt',
                 'spec.propose', 'spec.verify'}


@dataclasses.dataclass
class Timeline:
    """One request's reconstructed lifecycle. Latency fields are None
    when the log carries no observation for them (e.g. a rejected
    request has no TTFT)."""
    request_id: str
    events: List[dict]
    status: Optional[str] = None       # terminal status, None if absent
    reason: Optional[str] = None
    # Tenant label (schema v2 admit/reject/retire events carry it) —
    # what per-tenant goodput accounting (obs/slo.py) groups by.
    tenant: Optional[str] = None
    # Replica labels this request's events came from (multi-source
    # merge_events reconstruction): a disaggregated request's timeline
    # legitimately spans a prefill pool and a decode pool.
    replicas: List[str] = dataclasses.field(default_factory=list)
    complete: bool = False
    errors: List[str] = dataclasses.field(default_factory=list)
    queue_wait: Optional[float] = None
    ttft: Optional[float] = None
    token_gaps: List[float] = dataclasses.field(default_factory=list)
    total_seconds: Optional[float] = None
    admits: int = 0
    quarantines: int = 0
    preempts: int = 0
    tokens: int = 0
    # Speculative-decoding arcs (spec.propose / spec.verify): how many
    # verify steps served this request, how many tokens its proposers
    # guessed and how many of those greedy verification accepted — the
    # amortization record (committed tokens per verify step =
    # accepted/spec_steps + 1), reconstructed from the log alone.
    spec_steps: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0
    # Disaggregated-serving arcs: router placements (router.route, in
    # the router's log) and prefill→decode KV handoffs
    # (prefill.handoff, in the prefill pool's) — a routed request's
    # lifecycle legitimately spans up to three logs.
    routes: int = 0
    handoffs: int = 0
    # Degradation-ladder engagements (serve.degrade — the rung used to
    # fire silently): admissions of this request with a capped budget.
    degrades: int = 0
    # Replica-loss recovery arcs (request.recovered, in the router's
    # log): times this request's stream was resolved off a dead replica
    # — re-dispatched to a survivor or terminally rejected replica_lost.
    recoveries: int = 0
    # The subset of those displacements caused by a KV page corruption
    # verdict (request.recovered with reason=kv_corrupt): the stream
    # was expelled off poisoned pages and healed on a clean replica
    # (or terminally rejected kv_corrupt past the recovery budget).
    corruptions: int = 0

    def phases(self):
        """Compact ``{phase: seconds}`` view for printing."""
        out = {}
        if self.queue_wait is not None:
            out['queue_wait'] = self.queue_wait
        if self.ttft is not None:
            out['ttft'] = self.ttft
        if self.token_gaps:
            out['decode'] = sum(self.token_gaps)
        if self.total_seconds is not None:
            out['total'] = self.total_seconds
        return out


def _reset_delivered_latency(tl: Timeline):
    """A requeue (quarantine or preemption) DISCARDS the attempt's
    stream — the retry regenerates it from scratch. The timeline's
    latency verdict describes the DELIVERED stream, so the aborted
    attempt's TTFT and gaps are dropped here; the next stamped TTFT
    (still measured from the ORIGINAL submit on the scheduler's clock
    — _commit_token anchors at submitted_at) wins. ``tokens`` stays
    cumulative: work done is work done, delivered or not."""
    tl.ttft = None
    tl.token_gaps = []


def _validate(tl: Timeline):
    """Run the lifecycle automaton over ``tl.events`` (already
    seq-sorted), populating status/errors/derived fields."""
    state = 'submitted'     # submitted -> running -> (queued ->) done
    for rec in tl.events:
        ev = rec['event']
        if tl.tenant is None and rec.get('tenant') is not None:
            tl.tenant = rec['tenant']
        replica = rec.get('replica')
        if replica is not None and replica not in tl.replicas:
            tl.replicas.append(replica)
        if ev == 'router.route':
            # Placement rides its OWN log: at equal timestamps the
            # merge may order it before or after the replica-side
            # lifecycle (a one-tick request can even retire at the
            # route's ts), so it is state-exempt — counted, never a
            # transition and never an after-terminal violation.
            tl.routes += 1
            continue
        if ev == 'prefill.handoff':
            tl.handoffs += 1
            continue
        if ev == 'serve.degrade':
            # The degrade rung fires at SUBMIT, before the admit (or
            # queue-full reject) verdict, and a drained-and-requeued
            # request may degrade again on resubmission after its
            # terminal would have been legal — state-exempt, counted.
            tl.degrades += 1
            continue
        if ev == 'serve.preempt' and rec.get('expel'):
            # Corruption-containment expulsion rides the DIRTY
            # replica's log; at equal timestamps the merge may order
            # it after the router's request.recovered already returned
            # the request to 'queued' (or after the no-survivor
            # terminal reject) — state-exempt, counted, slot freed if
            # still held.
            tl.preempts += 1
            if state == 'running':
                state = 'queued'
                _reset_delivered_latency(tl)
            continue
        if state == 'done':
            tl.errors.append(f'event {ev} after terminal state')
            continue
        if ev == 'serve.admit':
            if state == 'running':
                tl.errors.append('admit while already running')
            state = 'running'
            tl.admits += 1
            if tl.queue_wait is None:
                tl.queue_wait = rec.get('queue_wait')
        elif ev in _RUNNING_ONLY:
            if state != 'running':
                tl.errors.append(f'{ev} without a slot (state={state})')
            if ev == 'serve.decode':
                tl.tokens += 1
                if rec.get('ttft') is not None and tl.ttft is None:
                    tl.ttft = rec['ttft']
                if rec.get('gap') is not None:
                    tl.token_gaps.append(rec['gap'])
            elif ev == 'spec.verify':
                tl.spec_steps += 1
                tl.spec_proposed += rec.get('proposed', 0)
                tl.spec_accepted += rec.get('accepted', 0)
            elif ev == 'serve.quarantine':
                tl.quarantines += 1
                # Quarantine frees the slot: a requeued request must be
                # re-admitted; an exhausted one goes straight to retire.
                state = 'queued' if rec.get('requeued') else 'running'
                if rec.get('requeued'):
                    _reset_delivered_latency(tl)
            elif ev == 'serve.preempt':
                # Page-pool preemption: same slot-freeing arc as a
                # quarantine (requeued → re-admit; exhausted retries →
                # the terminal evict/retire follows while 'running').
                tl.preempts += 1
                state = 'queued' if rec.get('requeued') else 'running'
                if rec.get('requeued'):
                    _reset_delivered_latency(tl)
        elif ev == 'request.recovered':
            # The replica holding this stream died. The slot died with
            # it, so the request returns to 'queued' whatever the
            # requeued flag says: requeued=True is followed by a
            # survivor's admit, requeued=False by a terminal
            # serve.reject reason=replica_lost — both legal from
            # 'queued'. This is how a recovery arc CLOSES across the
            # dead replica's torn log: the victim's record ends
            # mid-stream with no terminal, and the router log alone
            # supplies the transition out of it. Delivered latency of
            # the aborted attempt is discarded like any requeue; the
            # next TTFT is still anchored at the ORIGINAL submit.
            tl.recoveries += 1
            if rec.get('reason') == 'kv_corrupt':
                # Displaced by a corruption verdict, not a dead
                # replica — same automaton arc, separate tally.
                tl.corruptions += 1
            state = 'queued'
            _reset_delivered_latency(tl)
        elif ev == 'serve.retire':
            tl.status = rec.get('status')
            tl.reason = rec.get('reason')
            tl.total_seconds = rec.get('total_seconds')
            if state == 'submitted' and tl.status != 'abandoned':
                tl.errors.append(
                    f'retire({tl.status}) without an admit')
            state = 'done'
        elif ev == 'serve.reject':
            tl.status = 'rejected'
            tl.reason = rec.get('reason')
            if tl.reason is None:
                tl.errors.append('reject without a reason')
            if state == 'running':
                tl.errors.append('reject while holding a slot')
            state = 'done'
        else:
            tl.errors.append(f'non-serve event {ev} in request timeline')
    if state != 'done':
        tl.errors.append(f'no terminal event (ended in state {state})')
    if tl.status == 'evicted' and not any(
            r['event'] == 'serve.evict' for r in tl.events):
        tl.errors.append('retire(evicted) without a serve.evict event')
    tl.complete = not tl.errors
    return tl


def _is_multi_source(source):
    """A list/tuple of log paths (or ``(replica, path)`` pairs) — as
    opposed to a list of already-decoded records, which read_events
    handles directly."""
    if not isinstance(source, (list, tuple)) or not source:
        return False
    first = source[0]
    if isinstance(first, (str, os.PathLike, EventLog)):
        return True
    return (isinstance(first, (tuple, list)) and len(first) == 2
            and isinstance(first[1], (str, os.PathLike)))


def reconstruct(source) -> Dict[str, Timeline]:
    """Rebuild EVERY request's timeline from ``source`` (an EventLog, a
    log path — rotated set included — or decoded records). A LIST of
    paths (or ``(replica, path)`` pairs) reconstructs across the merged
    multi-replica stream (:func:`~distributed_dot_product_tpu.obs
    .events.merge_events`): one request's timeline may then span a
    prefill pool's log and a decode pool's. Returns
    ``{request_id: Timeline}``."""
    records = (merge_events(source) if _is_multi_source(source)
               else read_events(source))
    per_request: Dict[str, List[dict]] = {}
    for rec in records:
        rid = rec.get('request_id')
        ev = rec.get('event', '')
        if rid is not None and ev.startswith(('serve.', 'spec.',
                                              'router.', 'prefill.',
                                              'request.')):
            per_request.setdefault(rid, []).append(rec)
    return {rid: _validate(Timeline(request_id=rid, events=evs))
            for rid, evs in per_request.items()}


def timeline(request_id, source) -> Timeline:
    """One request's reconstructed :class:`Timeline`. A request that
    never reached the log yields an (incomplete) empty timeline rather
    than a KeyError — absence is itself an audit finding."""
    tl = reconstruct(source).get(request_id)
    if tl is None:
        tl = Timeline(request_id=request_id, events=[],
                      errors=['no events recorded'])
    return tl
