# -*- coding: utf-8 -*-
"""
Prometheus-text exporter for the in-process metrics registry, plus an
optional stdlib HTTP endpoint serving ``/metrics`` and ``/healthz``.

No external metrics dependency exists in the image, so this renders the
`Prometheus exposition format (0.0.4)` by hand from
``MetricsRegistry`` state:

- counters  → ``<ns>_<name>_total`` (``# TYPE counter``)
- gauges    → ``<ns>_<name>`` (``# TYPE gauge``)
- histograms → a summary family: ``{quantile="0.5"|"0.99"}`` lines from
  the aged reservoir (CURRENT behavior — what an alert wants) plus the
  Prometheus-mandated cumulative ``_count``/``_sum`` from the lifetime
  totals (``Histogram.summary()``'s ``total_count``/``total_sum``);
  histograms with bucket bounds ALSO render a real cumulative
  histogram family ``<fam>_hist`` with ``_bucket{le="..."}`` lines —
  lifetime counters an external Prometheus can sum across replicas
  (ROADMAP item 2's per-replica merge needs exactly that).

Dotted registry names are sanitized (``serve.queue_depth`` →
``ddp_serve_queue_depth``); labeled metrics (``registry.counter(name,
labels={...})``) render with escaped label values per the exposition
rules (backslash, double-quote, newline).

The server is **off by default** — construct and :meth:`~MetricsServer.
start` it explicitly::

    srv = MetricsServer(registry, health=monitor, port=9100).start()
    ...  # curl localhost:9100/metrics ; curl localhost:9100/healthz
    srv.stop()

``/healthz`` returns the :class:`~distributed_dot_product_tpu.serve.
health.HealthMonitor` snapshot, status 200 while readiness is
``ready``/``degraded`` (degraded still serves) and 503 otherwise — the
shape a load-balancer probe consumes.

With a ``profiler`` (:class:`~distributed_dot_product_tpu.obs.devmon.
ProfileCapture`), ``/profile?seconds=N`` begins one bounded
``jax.profiler`` trace capture — 200 with the trace directory, 409
while one is already in flight (never two traces), 400 on a bad
duration, 404 when the server carries no profiler.

``GET /dump[?reason=...]`` writes one flight-recorder post-mortem
bundle (obs/flight.py) — the server's explicit ``flight=`` recorder or
the process-installed one; 404 without either. Every render also
carries the constant ``<ns>_build_info{schema_version,jax_version,
python_version}`` gauge so merged multi-replica scrapes can detect
version skew.
"""

import http.server
import json
import math
import re
import threading
import urllib.parse
from typing import Optional

from distributed_dot_product_tpu.utils import tracing

__all__ = ['render_prometheus', 'escape_label_value', 'MetricsServer',
           'build_info_labels']

_NAME_SANITIZE = re.compile(r'[^a-zA-Z0-9_:]')


def _metric_name(namespace, name):
    base = _NAME_SANITIZE.sub('_', name)
    return f'{namespace}_{base}' if namespace else base


def escape_label_value(value):
    """Escape a label value per the exposition format: backslash,
    double-quote and newline."""
    return (str(value).replace('\\', '\\\\').replace('"', '\\"')
            .replace('\n', '\\n'))


def _labels_str(labels, extra=()):
    items = list(labels.items()) + list(extra)
    if not items:
        return ''
    body = ','.join(f'{k}="{escape_label_value(v)}"' for k, v in items)
    return '{' + body + '}'


def _fmt(value):
    v = float(value)
    if math.isnan(v):
        return 'NaN'
    if math.isinf(v):
        return '+Inf' if v > 0 else '-Inf'
    return repr(v) if not v.is_integer() else str(int(v))


def build_info_labels():
    """The constant build-info label set (computed once per process):
    event-schema version, jax version, python version. A Prometheus
    merging several replicas' scrapes (ROADMAP item 2) joins on these
    to detect version skew across the fleet; flight-recorder bundle
    MANIFESTs embed the same values (ONE probe — the scrape and the
    bundle can never disagree about the process that wrote them)."""
    global _BUILD_INFO
    if _BUILD_INFO is None:
        import platform
        from distributed_dot_product_tpu.obs import events as _events
        try:
            import jax
            jax_version = jax.__version__
        except (ImportError, AttributeError):
            # The exporter must render without jax too.
            jax_version = 'unavailable'
        _BUILD_INFO = {
            'schema_version': str(_events.SCHEMA_VERSION),
            'jax_version': jax_version,
            'python_version': platform.python_version(),
        }
    return _BUILD_INFO


_BUILD_INFO = None


def render_prometheus(registry: Optional['tracing.MetricsRegistry'] = None,
                      *, namespace='ddp') -> str:
    """Render ``registry`` (default: the process registry) as Prometheus
    exposition text. Reads are snapshot-consistent per metric (each
    counter/gauge read is atomic, each histogram summary is computed
    under its own lock), so concurrent writers never produce torn
    values — only values at least as fresh as the render's start.

    Always includes the constant ``<ns>_build_info`` gauge (value 1,
    labels ``schema_version``/``jax_version``/``python_version``) —
    the standard build-info idiom, so a multi-replica merge can detect
    version skew from the scrape alone."""
    registry = registry or tracing.get_registry()
    info_fam = _metric_name(namespace, 'build_info')
    lines = [
        f'# HELP {info_fam} constant build/version info '
        f'(schema_version, jax_version, python_version)',
        f'# TYPE {info_fam} gauge',
        f'{info_fam}{_labels_str(build_info_labels())} 1',
    ]
    # Cumulative-bucket histogram families are buffered and emitted
    # after the main body: interleaving `<fam>` summary lines and
    # `<fam>_hist` bucket lines per label set would split each family
    # into non-contiguous groups, which strict exposition parsers
    # (OpenMetrics, promtool) reject. iter_metrics() yields label sets
    # of one family adjacently, so each buffer stays grouped.
    hist_lines = []
    typed = {info_fam}

    def _head(kind, fam, comment, out=None):
        if fam not in typed:
            typed.add(fam)
            out = lines if out is None else out
            out.append(f'# HELP {fam} {comment}')
            out.append(f'# TYPE {fam} {kind}')

    for kind, name, labels, value in registry.iter_metrics():
        if kind == 'counter':
            fam = _metric_name(namespace, name) + '_total'
            _head('counter', fam, f'counter {name}')
            lines.append(f'{fam}{_labels_str(labels)} {_fmt(value)}')
        elif kind == 'gauge':
            fam = _metric_name(namespace, name)
            _head('gauge', fam, f'gauge {name}')
            lines.append(f'{fam}{_labels_str(labels)} {_fmt(value)}')
        else:   # histogram summary: value is Histogram.summary()
            fam = _metric_name(namespace, name)
            _head('summary', fam, f'histogram {name} '
                                  f'(quantiles over the aged reservoir)')
            for q, key in (('0.5', 'p50'), ('0.99', 'p99')):
                lines.append(
                    f'{fam}{_labels_str(labels, [("quantile", q)])} '
                    f'{_fmt(value[key])}')
            lines.append(f'{fam}_count{_labels_str(labels)} '
                         f'{_fmt(value["total_count"])}')
            lines.append(f'{fam}_sum{_labels_str(labels)} '
                         f'{_fmt(value["total_sum"])}')
            buckets = value.get('buckets')
            if buckets:
                # Real cumulative histogram series under a SEPARATE
                # family (`<fam>` is already TYPE summary; mixing
                # children kinds under one family is invalid
                # exposition). These are lifetime counters, so an
                # external Prometheus can sum them across replicas —
                # the aggregation the reservoir quantiles can't give.
                famh = fam + '_hist'
                _head('histogram', famh,
                      f'histogram {name} (cumulative lifetime buckets)',
                      out=hist_lines)
                for le, n in buckets:
                    hist_lines.append(
                        f'{famh}_bucket'
                        f'{_labels_str(labels, [("le", _fmt(le))])} '
                        f'{_fmt(n)}')
                hist_lines.append(
                    f'{famh}_bucket'
                    f'{_labels_str(labels, [("le", "+Inf")])} '
                    f'{_fmt(value["total_count"])}')
                hist_lines.append(f'{famh}_count{_labels_str(labels)} '
                                  f'{_fmt(value["total_count"])}')
                hist_lines.append(f'{famh}_sum{_labels_str(labels)} '
                                  f'{_fmt(value["total_sum"])}')
    lines += hist_lines
    return '\n'.join(lines) + '\n'


_HEALTHY = ('ready', 'degraded')


class _ObsHTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    # Exporter endpoints hold references, not state:
    registry = None
    health = None
    profiler = None
    flight = None
    namespace = 'ddp'


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = 'ddp-obs/1'

    def _send(self, code, body, content_type):
        data = body.encode('utf-8')
        self.send_response(code)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):     # noqa: N802 (stdlib API name)
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        if path == '/metrics':
            body = render_prometheus(self.server.registry,
                                     namespace=self.server.namespace)
            self._send(200, body,
                       'text/plain; version=0.0.4; charset=utf-8')
        elif path == '/healthz':
            health = self.server.health
            if health is None:
                self._send(200, json.dumps({'status': 'ok',
                                            'health': None}) + '\n',
                           'application/json')
                return
            snap = health.snapshot()
            ok = (snap['readiness'] in _HEALTHY
                  and snap['liveness'] == 'alive')
            self._send(200 if ok else 503,
                       json.dumps(snap, default=str) + '\n',
                       'application/json')
        elif path == '/profile':
            self._do_profile()
        elif path == '/dump':
            self._do_dump()
        else:
            self._send(404, 'not found\n', 'text/plain')

    def _do_dump(self):
        """``GET /dump[?reason=...]``: write one flight-recorder
        post-mortem bundle (obs/flight.py) on demand — the operator's
        "grab the black box NOW" button. Uses the server's explicit
        recorder, else the process-installed one; 404 when neither
        exists (the recorder is opt-in like the profiler). The dump is
        direct (not cooldown-limited): an explicit human request
        always gets a bundle."""
        from distributed_dot_product_tpu.obs import flight as _flight
        rec = self.server.flight or _flight.get_recorder()
        if rec is None:
            self._send(404, json.dumps(
                {'error': 'no flight recorder installed in this '
                          'process'}) + '\n', 'application/json')
            return
        query = urllib.parse.parse_qs(
            urllib.parse.urlsplit(self.path).query)
        reason = query.get('reason', [''])[0]
        try:
            path = rec.dump_bundle(trigger='http', reason=reason)
        except Exception as e:
            # Answer 500 instead of dropping the connection, but keep
            # the failure observable (silent-except contract).
            tracing.log_exception('exporter.dump_endpoint', e,
                                  registry=self.server.registry)
            self._send(500, json.dumps(
                {'error': f'{type(e).__name__}: {e}'}) + '\n',
                'application/json')
            return
        self._send(200, json.dumps({'status': 'dumped', 'path': path})
                   + '\n', 'application/json')

    def _do_profile(self):
        """``GET /profile?seconds=N``: begin one bounded profiler
        capture (obs/devmon.py ProfileCapture). 409 while a capture is
        in flight — never two traces; 404 when the server was built
        without a profiler (the guarded-off default)."""
        from distributed_dot_product_tpu.obs.devmon import CaptureInFlight
        profiler = self.server.profiler
        if profiler is None:
            self._send(404, json.dumps(
                {'error': 'no profiler configured on this server'})
                + '\n', 'application/json')
            return
        query = urllib.parse.parse_qs(
            urllib.parse.urlsplit(self.path).query)
        try:
            seconds = float(query['seconds'][0]) if 'seconds' in query \
                else None
            if seconds is not None and not seconds > 0:
                raise ValueError(seconds)
        except (ValueError, TypeError):
            self._send(400, json.dumps(
                {'error': 'seconds must be a positive number'}) + '\n',
                'application/json')
            return
        try:
            info = profiler.start(seconds, trigger='http')
        except CaptureInFlight as e:
            self._send(409, json.dumps({'error': str(e)}) + '\n',
                       'application/json')
            return
        self._send(200, json.dumps({'status': 'capturing', **info})
                   + '\n', 'application/json')

    def log_message(self, fmt, *args):
        # Probes hit /healthz every few seconds — stay silent.
        pass


class MetricsServer:
    """Background ``/metrics`` + ``/healthz`` endpoint. OFF by default:
    nothing binds a port until :meth:`start`. ``port=0`` picks an
    ephemeral port (read it back from ``.port`` — how tests avoid
    collisions)."""

    def __init__(self, registry=None, *, health=None, profiler=None,
                 flight=None, host='127.0.0.1', port=0,
                 namespace='ddp'):
        self.registry = registry or tracing.get_registry()
        self.health = health
        # Optional obs.devmon.ProfileCapture: enables the guarded
        # /profile?seconds=N endpoint (404 without one).
        self.profiler = profiler
        # Optional obs.flight.FlightRecorder for GET /dump (falls back
        # to the process-installed recorder; 404 without either).
        self.flight = flight
        self.host = host
        self.port = port
        self.namespace = namespace
        self._server: Optional[_ObsHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._server is not None:
            return self
        srv = _ObsHTTPServer((self.host, self.port), _Handler)
        srv.registry = self.registry
        srv.health = self.health
        srv.profiler = self.profiler
        srv.flight = self.flight
        srv.namespace = self.namespace
        self.port = srv.server_address[1]
        self._server = srv
        self._thread = threading.Thread(target=srv.serve_forever,
                                        name='obs-metrics-server',
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def url(self):
        return f'http://{self.host}:{self.port}'

    def stop(self):
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
