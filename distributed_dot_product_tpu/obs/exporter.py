# -*- coding: utf-8 -*-
"""
Prometheus-text exporter for the in-process metrics registry, plus an
optional stdlib HTTP endpoint serving ``/metrics`` and ``/healthz``.

No external metrics dependency exists in the image, so this renders the
`Prometheus exposition format (0.0.4)` by hand from
``MetricsRegistry`` state:

- counters  → ``<ns>_<name>_total`` (``# TYPE counter``)
- gauges    → ``<ns>_<name>`` (``# TYPE gauge``)
- histograms → a summary family: ``{quantile="0.5"|"0.99"}`` lines from
  the aged reservoir (CURRENT behavior — what an alert wants) plus the
  Prometheus-mandated cumulative ``_count``/``_sum`` from the lifetime
  totals (``Histogram.summary()``'s ``total_count``/``total_sum``).

Dotted registry names are sanitized (``serve.queue_depth`` →
``ddp_serve_queue_depth``); labeled metrics (``registry.counter(name,
labels={...})``) render with escaped label values per the exposition
rules (backslash, double-quote, newline).

The server is **off by default** — construct and :meth:`~MetricsServer.
start` it explicitly::

    srv = MetricsServer(registry, health=monitor, port=9100).start()
    ...  # curl localhost:9100/metrics ; curl localhost:9100/healthz
    srv.stop()

``/healthz`` returns the :class:`~distributed_dot_product_tpu.serve.
health.HealthMonitor` snapshot, status 200 while readiness is
``ready``/``degraded`` (degraded still serves) and 503 otherwise — the
shape a load-balancer probe consumes.
"""

import http.server
import json
import math
import re
import threading
from typing import Optional

from distributed_dot_product_tpu.utils import tracing

__all__ = ['render_prometheus', 'escape_label_value', 'MetricsServer']

_NAME_SANITIZE = re.compile(r'[^a-zA-Z0-9_:]')


def _metric_name(namespace, name):
    base = _NAME_SANITIZE.sub('_', name)
    return f'{namespace}_{base}' if namespace else base


def escape_label_value(value):
    """Escape a label value per the exposition format: backslash,
    double-quote and newline."""
    return (str(value).replace('\\', '\\\\').replace('"', '\\"')
            .replace('\n', '\\n'))


def _labels_str(labels, extra=()):
    items = list(labels.items()) + list(extra)
    if not items:
        return ''
    body = ','.join(f'{k}="{escape_label_value(v)}"' for k, v in items)
    return '{' + body + '}'


def _fmt(value):
    v = float(value)
    if math.isnan(v):
        return 'NaN'
    if math.isinf(v):
        return '+Inf' if v > 0 else '-Inf'
    return repr(v) if not v.is_integer() else str(int(v))


def render_prometheus(registry: Optional['tracing.MetricsRegistry'] = None,
                      *, namespace='ddp') -> str:
    """Render ``registry`` (default: the process registry) as Prometheus
    exposition text. Reads are snapshot-consistent per metric (each
    counter/gauge read is atomic, each histogram summary is computed
    under its own lock), so concurrent writers never produce torn
    values — only values at least as fresh as the render's start."""
    registry = registry or tracing.get_registry()
    lines = []
    typed = set()

    def _head(kind, fam, comment):
        if fam not in typed:
            typed.add(fam)
            lines.append(f'# HELP {fam} {comment}')
            lines.append(f'# TYPE {fam} {kind}')

    for kind, name, labels, value in registry.iter_metrics():
        if kind == 'counter':
            fam = _metric_name(namespace, name) + '_total'
            _head('counter', fam, f'counter {name}')
            lines.append(f'{fam}{_labels_str(labels)} {_fmt(value)}')
        elif kind == 'gauge':
            fam = _metric_name(namespace, name)
            _head('gauge', fam, f'gauge {name}')
            lines.append(f'{fam}{_labels_str(labels)} {_fmt(value)}')
        else:   # histogram summary: value is Histogram.summary()
            fam = _metric_name(namespace, name)
            _head('summary', fam, f'histogram {name} '
                                  f'(quantiles over the aged reservoir)')
            for q, key in (('0.5', 'p50'), ('0.99', 'p99')):
                lines.append(
                    f'{fam}{_labels_str(labels, [("quantile", q)])} '
                    f'{_fmt(value[key])}')
            lines.append(f'{fam}_count{_labels_str(labels)} '
                         f'{_fmt(value["total_count"])}')
            lines.append(f'{fam}_sum{_labels_str(labels)} '
                         f'{_fmt(value["total_sum"])}')
    return '\n'.join(lines) + '\n' if lines else ''


_HEALTHY = ('ready', 'degraded')


class _ObsHTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    # Exporter endpoints hold references, not state:
    registry = None
    health = None
    namespace = 'ddp'


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = 'ddp-obs/1'

    def _send(self, code, body, content_type):
        data = body.encode('utf-8')
        self.send_response(code)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):     # noqa: N802 (stdlib API name)
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        if path == '/metrics':
            body = render_prometheus(self.server.registry,
                                     namespace=self.server.namespace)
            self._send(200, body,
                       'text/plain; version=0.0.4; charset=utf-8')
        elif path == '/healthz':
            health = self.server.health
            if health is None:
                self._send(200, json.dumps({'status': 'ok',
                                            'health': None}) + '\n',
                           'application/json')
                return
            snap = health.snapshot()
            ok = (snap['readiness'] in _HEALTHY
                  and snap['liveness'] == 'alive')
            self._send(200 if ok else 503,
                       json.dumps(snap, default=str) + '\n',
                       'application/json')
        else:
            self._send(404, 'not found\n', 'text/plain')

    def log_message(self, fmt, *args):
        # Probes hit /healthz every few seconds — stay silent.
        pass


class MetricsServer:
    """Background ``/metrics`` + ``/healthz`` endpoint. OFF by default:
    nothing binds a port until :meth:`start`. ``port=0`` picks an
    ephemeral port (read it back from ``.port`` — how tests avoid
    collisions)."""

    def __init__(self, registry=None, *, health=None,
                 host='127.0.0.1', port=0, namespace='ddp'):
        self.registry = registry or tracing.get_registry()
        self.health = health
        self.host = host
        self.port = port
        self.namespace = namespace
        self._server: Optional[_ObsHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._server is not None:
            return self
        srv = _ObsHTTPServer((self.host, self.port), _Handler)
        srv.registry = self.registry
        srv.health = self.health
        srv.namespace = self.namespace
        self.port = srv.server_address[1]
        self._server = srv
        self._thread = threading.Thread(target=srv.serve_forever,
                                        name='obs-metrics-server',
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def url(self):
        return f'http://{self.host}:{self.port}'

    def stop(self):
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
