# -*- coding: utf-8 -*-
"""
Critical-path latency attribution over the JSONL event log.

obs/timeline.py answers "did every request live a legal lifecycle";
this module answers "where did its time GO". From the merged
multi-replica stream alone it reconstructs each request's causal phase
chain —

    submit ──queue──▶ admit ──prefill──▶ first token ──decode──▶ …
           … ──stall──▶ re-admit … ──commit──▶ retire
    (with `handoff` segments where the prefill pool built and
    transferred the KV, and `queue` collapsing to the whole chain for
    a shed request)

— as adjacent timestamp segments that PARTITION the request's e2e
latency exactly. The submit anchor is derived from the terminal record
(`ts − total_seconds`, both stamped on the scheduler's own clock), so
on a virtual-clock run the partition is exact to float rounding: the
check `sum(phases) == e2e` within 1e-6 is a standing CI gate
(scripts/smoke_router.sh), not a hope.

Two aggregations ride on the chains:

- :func:`profile` — per-tenant / per-replica phase totals plus the
  tail cohort ("where does p99 e2e go": the mean phase split of the
  requests at or above the p99 e2e), the view ROADMAP item 5 needs
  before attacking any one phase.
- :func:`dispatch_floor` — the host-loop share of each decode tick,
  folded from `serve.dispatch` records (tick wall seconds vs device-
  program seconds, REAL time): the measured ~0.212 ms/step floor as a
  per-replica, per-token number next to the virtual-time phases it
  does NOT contaminate.

CLI: ``python -m distributed_dot_product_tpu.obs critpath LOG
[replica=LOG ...] [--json]`` — exits non-zero when any completed
request's phases fail to partition its e2e.
"""

import dataclasses
import json
from typing import Dict, List, Optional

from distributed_dot_product_tpu.obs.events import (
    merge_events, read_events,
)
from distributed_dot_product_tpu.obs.timeline import _is_multi_source

__all__ = ['PhaseChain', 'attribute', 'profile', 'dispatch_floor',
           'summarize_records', 'render_report', 'PARTITION_TOL',
           'PHASES']

# The closed phase vocabulary, in causal order. Every e2e second of
# every request lands in exactly one of these.
PHASES = ('queue', 'handoff', 'prefill', 'decode', 'stall', 'commit')

# |sum(phases) − e2e| gate. Virtual-clock runs are exact to float
# rounding; this absorbs the rounding, nothing else.
PARTITION_TOL = 1e-6

# Request-scoped events the attribution walks (the same prefixes the
# timeline automaton collects — serve.dispatch carries no request_id
# and is aggregated separately by dispatch_floor).
_REQ_PREFIXES = ('serve.', 'spec.', 'router.', 'prefill.', 'request.')


@dataclasses.dataclass
class PhaseChain:
    """One request's phase-attributed lifecycle."""
    request_id: str
    tenant: Optional[str] = None
    status: Optional[str] = None       # terminal status, None = torn
    reason: Optional[str] = None
    replicas: List[str] = dataclasses.field(default_factory=list)
    # Adjacent (phase, start_ts, end_ts) segments covering
    # [submit_ts, terminal_ts]; zero-width segments are dropped.
    segments: List[tuple] = dataclasses.field(default_factory=list)
    # {phase: seconds} — the partition. Phases with zero time absent.
    phases: Dict[str, float] = dataclasses.field(default_factory=dict)
    e2e: Optional[float] = None        # stamped total_seconds
    submit_ts: Optional[float] = None
    # handoff build/transfer split (REAL seconds, summed over the
    # request's prefill.handoff records) — rides alongside the
    # virtual-time phases, never inside them.
    handoff_build: float = 0.0
    handoff_transfer: float = 0.0
    tokens: int = 0
    stalls: int = 0                    # requeue arcs (preempt/
    #                                    quarantine/recovery)
    partial: bool = False              # no terminal / no e2e anchor:
    #                                    attributed best-effort, never
    #                                    asserted against
    errors: List[str] = dataclasses.field(default_factory=list)

    @property
    def partition_error(self) -> Optional[float]:
        """|sum(phases) − e2e|, None while unanchored."""
        if self.e2e is None:
            return None
        return abs(sum(self.phases.values()) - self.e2e)

    @property
    def ok(self) -> bool:
        """Partition holds and the chain closed cleanly."""
        if self.partial:
            return not self.errors
        err = self.partition_error
        return not self.errors and err is not None \
            and err <= PARTITION_TOL


def _submit_anchor(recs):
    """The submit-time anchor, in preference order: terminal ts −
    total_seconds (exact — both stamps share the scheduler clock),
    else first admit ts − queue_wait, else the first record's ts
    (partial chain, zero-width first segment). Returns
    ``(submit_ts, e2e, partial)``."""
    terminal_ts = total = None
    for rec in recs:
        if rec.get('event') in ('serve.retire', 'serve.reject') \
                and rec.get('total_seconds') is not None:
            terminal_ts, total = rec['ts'], rec['total_seconds']
    if terminal_ts is not None:
        return terminal_ts - total, total, False
    for rec in recs:
        if rec.get('event') == 'serve.admit' \
                and rec.get('queue_wait') is not None:
            return rec['ts'] - rec['queue_wait'], None, True
    return (recs[0].get('ts', 0.0), None, True) if recs \
        else (0.0, None, True)


def _attribute_one(rid, recs) -> PhaseChain:
    """Walk one request's merged records, cutting a phase segment at
    every record boundary. State machine mirrors the timeline
    automaton; the phase of a segment is a function of the state the
    request was IN while the segment elapsed (plus the handoff
    override — the pool's build+transfer is its own causal link)."""
    chain = PhaseChain(request_id=rid)
    submit_ts, e2e, partial = _submit_anchor(recs)
    chain.submit_ts, chain.e2e, chain.partial = submit_ts, e2e, partial
    state = 'queued'        # queued | prefill | decode | stalled | done
    prev_ts = submit_ts
    phases = {}

    def cut(phase, ts):
        nonlocal prev_ts
        dur = ts - prev_ts
        if dur < -PARTITION_TOL:
            chain.errors.append(
                f'non-monotone ts at {phase}: {ts} < {prev_ts}')
            dur = 0.0
        dur = max(0.0, dur)
        if dur > 0.0:
            phases[phase] = phases.get(phase, 0.0) + dur
            chain.segments.append((phase, prev_ts, ts))
        prev_ts = max(prev_ts, ts)

    for rec in recs:
        ev = rec.get('event', '')
        ts = rec.get('ts', prev_ts)
        if chain.tenant is None and rec.get('tenant') is not None:
            chain.tenant = rec['tenant']
        replica = rec.get('replica')
        if replica is not None and replica not in chain.replicas:
            chain.replicas.append(replica)
        if state == 'done':
            # After-terminal records are the timeline automaton's
            # violation to flag; attribution just stops the clock.
            continue
        if ev == 'prefill.handoff':
            cut('handoff', ts)
            chain.handoff_build += rec.get('build_seconds') or 0.0
            chain.handoff_transfer += rec.get('transfer_seconds') or 0.0
            continue
        if ev in ('router.route', 'serve.degrade', 'spec.propose',
                  'spec.verify', 'serve.prefill', 'serve.evict'):
            # Same-state markers: the segment they end stays in the
            # current phase (route/degrade elapse in the queue,
            # prefill chunks in the prefill phase, spec bookkeeping in
            # decode, the evict instant in whatever preceded its
            # terminal).
            cut(_STATE_PHASE[state], ts)
            continue
        if ev == 'serve.admit':
            cut(_STATE_PHASE[state], ts)
            state = 'prefill'
        elif ev == 'serve.decode':
            cut('prefill' if state == 'prefill' else 'decode', ts)
            state = 'decode'
            chain.tokens += 1
        elif ev in ('serve.quarantine', 'serve.preempt'):
            cut(_STATE_PHASE[state], ts)
            if rec.get('requeued'):
                state = 'stalled'
                chain.stalls += 1
        elif ev == 'request.recovered':
            cut(_STATE_PHASE[state], ts)
            state = 'stalled'
            chain.stalls += 1
        elif ev in ('serve.retire', 'serve.reject'):
            cut('commit' if state == 'decode'
                else _STATE_PHASE[state], ts)
            chain.status = ('rejected' if ev == 'serve.reject'
                            else rec.get('status'))
            chain.reason = rec.get('reason')
            state = 'done'
        else:
            cut(_STATE_PHASE[state], ts)
    if state != 'done':
        chain.partial = True
    chain.phases = phases
    return chain


# Phase a second belongs to while the request sits in each automaton
# state (the queued→'queue' vs →'stall' split is first-attempt-aware
# at the call sites above).
_STATE_PHASE = {'queued': 'queue', 'prefill': 'prefill',
                'decode': 'decode', 'stalled': 'stall',
                'done': 'commit'}


def attribute(source) -> Dict[str, PhaseChain]:
    """Phase-attribute EVERY request in ``source`` (a log path, an
    EventLog, decoded records, or a list of paths / ``(replica,
    path)`` pairs merged via
    :func:`~distributed_dot_product_tpu.obs.events.merge_events`).
    Returns ``{request_id: PhaseChain}``."""
    records = (merge_events(source) if _is_multi_source(source)
               else read_events(source))
    per_request: Dict[str, List[dict]] = {}
    for rec in records:
        rid = rec.get('request_id')
        if rid is not None \
                and rec.get('event', '').startswith(_REQ_PREFIXES):
            per_request.setdefault(rid, []).append(rec)
    return {rid: _attribute_one(rid, recs)
            for rid, recs in per_request.items()}


def _percentile(values, q):
    if not values:
        return None
    vals = sorted(values)
    idx = min(len(vals) - 1, int(round(q / 100.0 * (len(vals) - 1))))
    return vals[idx]


def _phase_totals(chains):
    out = {p: 0.0 for p in PHASES}
    for c in chains:
        for p, v in c.phases.items():
            out[p] = out.get(p, 0.0) + v
    return {p: v for p, v in out.items() if v > 0.0}


def profile(chains, dispatch=None) -> dict:
    """Aggregate critical-path profile over ``chains`` (an
    :func:`attribute` result or its values). Returns a plain dict
    (JSON-ready):

    - ``requests`` / ``complete`` / ``partial`` / ``partition_failures``
    - ``phases``: total seconds per phase, all requests
    - ``tail``: the p99-e2e cohort's phase split — "where does p99 go"
    - ``ttft_tail``: same cohort cut on p99 TTFT-side phases
      (queue+handoff+prefill)
    - ``by_tenant`` / ``by_replica``: per-group phase totals + e2e p50/p99
    - ``handoff``: build/transfer REAL-seconds split summed
    - ``dispatch``: :func:`dispatch_floor` result, when records given
    """
    if isinstance(chains, dict):
        chains = list(chains.values())
    anchored = [c for c in chains if not c.partial]
    failures = [c for c in anchored
                if (c.partition_error or 0.0) > PARTITION_TOL
                or c.errors]
    out = {
        'requests': len(chains),
        'complete': len(anchored),
        'partial': sum(c.partial for c in chains),
        'partition_failures': [c.request_id for c in failures],
        'phases': _phase_totals(chains),
        'handoff': {
            'build_seconds': sum(c.handoff_build for c in chains),
            'transfer_seconds': sum(c.handoff_transfer
                                    for c in chains),
        },
    }
    e2es = [c.e2e for c in anchored if c.e2e is not None]
    out['e2e'] = {'p50': _percentile(e2es, 50),
                  'p99': _percentile(e2es, 99),
                  'count': len(e2es)}
    p99 = _percentile(e2es, 99)
    if p99 is not None:
        cohort = [c for c in anchored
                  if c.e2e is not None and c.e2e >= p99]
        out['tail'] = {'cohort': len(cohort),
                       'phases': _phase_totals(cohort)}
    ttfts = [sum(c.phases.get(p, 0.0)
                 for p in ('queue', 'handoff', 'prefill'))
             for c in anchored if c.tokens]
    t99 = _percentile(ttfts, 99)
    if t99 is not None:
        cohort = [c for c in anchored if c.tokens and
                  sum(c.phases.get(p, 0.0)
                      for p in ('queue', 'handoff', 'prefill')) >= t99]
        out['ttft_tail'] = {'cohort': len(cohort),
                            'phases': _phase_totals(cohort)}
    for key, group in (('by_tenant', lambda c: c.tenant or 'default'),
                       ('by_replica',
                        lambda c: c.replicas[-1] if c.replicas
                        else 'unlabeled')):
        buckets: Dict[str, list] = {}
        for c in chains:
            buckets.setdefault(group(c), []).append(c)
        out[key] = {
            name: {
                'requests': len(cs),
                'phases': _phase_totals(cs),
                'e2e_p99': _percentile(
                    [c.e2e for c in cs if c.e2e is not None], 99),
            } for name, cs in sorted(buckets.items())}
    if dispatch is not None:
        out['dispatch'] = dispatch
    return out


def dispatch_floor(source) -> dict:
    """Fold ``serve.dispatch`` records (per decode tick: REAL tick
    wall seconds vs device-program seconds) into the host-loop floor
    per replica: tick count, total/mean overhead, overhead share of
    tick time, and overhead per committed token — the number ROADMAP
    item 5's multi-tick decode has to beat."""
    records = (merge_events(source) if _is_multi_source(source)
               else read_events(source))
    per_replica: Dict[str, dict] = {}
    for rec in records:
        if rec.get('event') != 'serve.dispatch':
            continue
        name = rec.get('replica', 'unlabeled')
        agg = per_replica.setdefault(
            name, {'ticks': 0, 'tick_seconds': 0.0,
                   'device_seconds': 0.0, 'overhead_seconds': 0.0,
                   'tokens': 0})
        agg['ticks'] += 1
        tick = rec.get('tick_seconds') or 0.0
        dev = rec.get('device_seconds') or 0.0
        agg['tick_seconds'] += tick
        agg['device_seconds'] += dev
        agg['overhead_seconds'] += rec.get('overhead',
                                           max(0.0, tick - dev))
        agg['tokens'] += rec.get('tokens') or 0
    for agg in per_replica.values():
        agg['overhead_share'] = (
            agg['overhead_seconds'] / agg['tick_seconds']
            if agg['tick_seconds'] > 0 else None)
        agg['overhead_per_token'] = (
            agg['overhead_seconds'] / agg['tokens']
            if agg['tokens'] > 0 else None)
    total = {'ticks': sum(a['ticks'] for a in per_replica.values()),
             'overhead_seconds': sum(a['overhead_seconds']
                                     for a in per_replica.values()),
             'tokens': sum(a['tokens']
                           for a in per_replica.values())}
    total['overhead_per_token'] = (
        total['overhead_seconds'] / total['tokens']
        if total['tokens'] > 0 else None)
    return {'per_replica': per_replica, 'total': total}


def summarize_records(records) -> dict:
    """One-shot critpath summary over already-decoded records — the
    flight-recorder provider's entry point (the post-mortem ring IS a
    record list; no filesystem round trip at dump time). The ring may
    interleave several logs' tee streams (router + replicas in one
    process share one recorder), so records order by ``(ts, seq)``
    here — NOT per-source seq, which the ring does not preserve."""
    recs = sorted(records,
                  key=lambda r: (r.get('ts', 0), r.get('seq', 0)))
    per_request: Dict[str, List[dict]] = {}
    for rec in recs:
        rid = rec.get('request_id')
        if rid is not None \
                and rec.get('event', '').startswith(_REQ_PREFIXES):
            per_request.setdefault(rid, []).append(rec)
    chains = {rid: _attribute_one(rid, rs)
              for rid, rs in per_request.items()}
    return profile(chains, dispatch=dispatch_floor(recs))


def _fmt_s(v):
    return '-' if v is None else f'{v * 1000:.3f}ms'


def render_report(prof: dict) -> str:
    """The human-facing ``obs critpath`` text report."""
    lines = []
    lines.append(
        f"requests={prof['requests']} complete={prof['complete']} "
        f"partial={prof['partial']} "
        f"partition_failures={len(prof['partition_failures'])}")
    e2e = prof.get('e2e') or {}
    lines.append(f"e2e: p50={_fmt_s(e2e.get('p50'))} "
                 f"p99={_fmt_s(e2e.get('p99'))} "
                 f"n={e2e.get('count', 0)}")
    total = sum(prof.get('phases', {}).values()) or 1.0
    lines.append('phase totals (all requests):')
    for p in PHASES:
        v = prof.get('phases', {}).get(p)
        if v:
            lines.append(f'  {p:<8} {v:12.6f}s  '
                         f'{100.0 * v / total:5.1f}%')
    for key, title in (('tail', 'p99-e2e cohort'),
                       ('ttft_tail', 'p99-TTFT cohort')):
        sec = prof.get(key)
        if sec:
            split = sec.get('phases', {})
            tot = sum(split.values()) or 1.0
            parts = ' '.join(
                f'{p}={100.0 * split[p] / tot:.1f}%'
                for p in PHASES if p in split)
            lines.append(f"{title} (n={sec['cohort']}): {parts}")
    ho = prof.get('handoff') or {}
    if ho.get('build_seconds') or ho.get('transfer_seconds'):
        lines.append(
            f"handoff split (real): "
            f"build={ho['build_seconds']:.6f}s "
            f"transfer={ho['transfer_seconds']:.6f}s")
    for key in ('by_tenant', 'by_replica'):
        groups = prof.get(key) or {}
        if len(groups) > 1 or key == 'by_replica':
            lines.append(f'{key[3:]} breakdown:')
            for name, g in groups.items():
                split = g.get('phases', {})
                tot = sum(split.values()) or 1.0
                parts = ' '.join(
                    f'{p}={100.0 * split[p] / tot:.1f}%'
                    for p in PHASES if p in split)
                lines.append(
                    f"  {name:<12} n={g['requests']:<4} "
                    f"e2e_p99={_fmt_s(g.get('e2e_p99'))} {parts}")
    disp = prof.get('dispatch') or {}
    if disp.get('total', {}).get('ticks'):
        lines.append('dispatch floor (REAL seconds, host-loop share '
                     'of decode ticks):')
        for name, agg in sorted(disp['per_replica'].items()):
            share = agg.get('overhead_share')
            ptok = agg.get('overhead_per_token')
            lines.append(
                f"  {name:<12} ticks={agg['ticks']:<6} "
                f"overhead={agg['overhead_seconds']:.6f}s "
                f"share={share * 100:.1f}% "
                f"per_token={_fmt_s(ptok)}"
                if share is not None else
                f"  {name:<12} ticks={agg['ticks']}")
        tot = disp['total']
        lines.append(
            f"  total        ticks={tot['ticks']:<6} "
            f"overhead={tot['overhead_seconds']:.6f}s "
            f"per_token={_fmt_s(tot.get('overhead_per_token'))}")
    if prof.get('partition_failures'):
        lines.append('PARTITION FAILURES: '
                     + ', '.join(prof['partition_failures']))
    return '\n'.join(lines)


def to_json(prof: dict) -> str:
    return json.dumps(prof, indent=2, sort_keys=True, default=str)
