# -*- coding: utf-8 -*-
"""
Compiled-program performance accounting: the compiler/device half of the
observability layer.

The host-side spans/events (PR 5) record what a run *did*; this module
records what its compiled programs *cost* — without touching hardware.
For every entrypoint registered in ``analysis/registry.py`` it lowers
and compiles hermetically (the same 8-virtual-device CPU mesh graphlint
traces on) and extracts:

- XLA ``cost_analysis()``: FLOPs and bytes accessed — the compiler's own
  accounting of the program, independent of any timer floor.
- ``memory_analysis()``: argument/output/temp/alias bytes, the exact
  buffer-assignment footprint RESULTS.md's ``mem GiB`` column reports
  for the timed programs.
- Compile wall time and HLO structure counts (collectives by kind,
  fusion count) — a fusion that splits or a collective that multiplies
  is a perf regression even when the numerics stay right.
- Retrace totals (``analysis/retrace.py``) incurred while building the
  snapshot — a registry build that suddenly traces a step twice is the
  round-5 retrace-storm class resurfacing.

From FLOPs and bytes it derives **arithmetic intensity** and classifies
each entry compute- vs bandwidth-bound against configurable hardware
peaks (defaults: the 197 TF/s bf16 ceiling and the 474 GB/s measured
decode bandwidth from RESULTS.md), giving each program a roofline model
time — the "how fast could this possibly run" column next to every
measured number.

CLI (``scripts/ci.sh`` stage [5/5] drives it)::

    python -m distributed_dot_product_tpu.obs.perf snapshot -o PERF_BASELINE.json
    python -m distributed_dot_product_tpu.obs.perf check --against PERF_BASELINE.json
    python -m distributed_dot_product_tpu.obs.perf report

``snapshot`` writes a schema-versioned JSON baseline; ``check`` exits 1
on per-entry tolerance violations (flops / bytes / peak memory /
compile seconds / retrace counts), naming the offending entry and
metric — and emits ``perf.regression`` events when an event log is
active; ``report`` renders the roofline table. Refresh the committed
baseline after an intentional program change with the ``snapshot``
command above.

``benchmark.py`` uses :func:`program_model` to stamp the same
model-vs-measured columns onto every benchmark row.
"""

import dataclasses
import json
import re
import time
from typing import Optional

__all__ = ['PERF_SCHEMA_VERSION', 'HardwarePeaks', 'DEFAULT_PEAKS',
           'Tolerances', 'program_model', 'analyze_spec', 'snapshot',
           'check_snapshots', 'render_report', 'main']

PERF_SCHEMA_VERSION = 1

# Fields compared with a symmetric relative tolerance by `check`.
# argument_bytes is in the set because it is fully determined by the
# registered example shapes/dtypes — a widened cache dtype shows up
# here as an exact 2x, even when fusion jitter muddies bytes_accessed.
_REL_FIELDS = ('flops', 'bytes_accessed', 'argument_bytes',
               'peak_bytes')


@dataclasses.dataclass(frozen=True)
class HardwarePeaks:
    """Roofline ceilings. Defaults are this repo's measured record
    (RESULTS.md): the 197 TF/s bf16 device ceiling the readback-fenced
    timer is calibrated against, and the 474 GB/s decode-path HBM
    bandwidth actually achieved at kv2/131K."""
    flops_per_s: float = 197e12
    bytes_per_s: float = 474e9

    @property
    def ridge_flops_per_byte(self) -> float:
        """Arithmetic intensity at which the roofline knee sits: above
        it a program can saturate the MXU, below it HBM is the wall."""
        return self.flops_per_s / self.bytes_per_s

    def as_dict(self):
        return {'flops_per_s': self.flops_per_s,
                'bytes_per_s': self.bytes_per_s,
                'ridge_flops_per_byte': self.ridge_flops_per_byte}


DEFAULT_PEAKS = HardwarePeaks()


@dataclasses.dataclass(frozen=True)
class Tolerances:
    """Per-entry gate widths for :func:`check_snapshots`. ``rel`` bounds
    flops / bytes / peak-memory drift symmetrically (CPU-mesh lowering
    is deterministic for a fixed jax version, but the gate must survive
    fusion-order jitter across point releases); compile time passes
    while ``current <= baseline * compile_factor + compile_slack_s``
    (machines differ — only an order-of-magnitude blowup is a finding);
    retrace totals allow ``retrace_slack`` extra traces (default 0: one
    extra trace of a cached step IS the regression). ``abs_floor``
    exempts absolute drifts below it (units of the compared field):
    the smallest registered entries are a few KiB total, where a
    single re-fused buffer moves the relative number by half without
    meaning anything at real scale."""
    rel: float = 0.25
    compile_factor: float = 10.0
    compile_slack_s: float = 5.0
    retrace_slack: int = 0
    abs_floor: float = 64 * 1024.0


# -- program-level extraction -------------------------------------------

_HLO_COLLECTIVES = ('all-gather', 'all-reduce', 'collective-permute',
                    'all-to-all', 'reduce-scatter',
                    'collective-broadcast')


def _hlo_counts(hlo_text):
    """Collective call sites by kind (async ``-start`` forms folded into
    their base op) and fusion count from compiled HLO text."""
    coll = {}
    for op in _HLO_COLLECTIVES:
        n = len(re.findall(rf'\b{re.escape(op)}(?:-start)?\(', hlo_text))
        if n:
            coll[op] = n
    fusions = len(re.findall(r'\bfusion\(', hlo_text))
    return coll, fusions


def _first_cost(compiled):
    """``cost_analysis()`` as one flat dict (jax 0.4.x returns a
    one-element list; newer versions a dict)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def program_model(compiled, *, measured_seconds=None, peaks=None):
    """Cost/roofline model of one compiled XLA program, as a plain JSON-
    serializable dict — the per-row payload ``benchmark.py`` stamps next
    to its measured numbers. Returns None when the backend exposes no
    cost or memory analysis (some tunneled PJRT plugins).

    With ``measured_seconds``, also derives the model-vs-measured
    columns: achieved GFLOP/s and GB/s over the *compiler-counted*
    flops/bytes (as opposed to the analytic FLOP formulas the benchmark
    rows already carry) and the measured/model time ratio (1.0 = the
    program runs at its roofline)."""
    peaks = peaks or DEFAULT_PEAKS
    try:
        cost = _first_cost(compiled)
        # memory_analysis() returns None (no raise) on backends without
        # it (tunneled PJRT plugins) — the attribute reads must stay
        # inside this try so that case hits the None fallback too.
        ma = compiled.memory_analysis()
        mem = {
            'argument_bytes': ma.argument_size_in_bytes,
            'output_bytes': ma.output_size_in_bytes,
            'temp_bytes': ma.temp_size_in_bytes,
            'alias_bytes': ma.alias_size_in_bytes,
        }
    except Exception:  # graphlint: allow[silent-except] optional backend API
        return None
    flops = float(cost.get('flops', 0.0) or 0.0)
    nbytes = float(cost.get('bytes accessed', 0.0) or 0.0)
    if flops <= 0.0 and nbytes <= 0.0:
        return None
    ai = (flops / nbytes) if nbytes else float('inf')
    roofline = ('compute-bound' if ai >= peaks.ridge_flops_per_byte
                else 'bandwidth-bound')
    model_s = max(flops / peaks.flops_per_s, nbytes / peaks.bytes_per_s)
    out = {
        'flops': flops,
        'bytes_accessed': nbytes,
        'arithmetic_intensity': ai,
        'roofline': roofline,
        'model_seconds': model_s,
        **mem,
        'peak_bytes': (mem['argument_bytes'] + mem['output_bytes']
                       + mem['temp_bytes'] - mem['alias_bytes']),
    }
    if measured_seconds and measured_seconds > 0:
        out['measured_seconds'] = measured_seconds
        out['measured_gflops_per_s'] = flops / measured_seconds / 1e9
        out['measured_gb_per_s'] = nbytes / measured_seconds / 1e9
        out['fraction_of_roofline'] = model_s / measured_seconds
    return out


# -- entrypoint-level analysis ------------------------------------------

def _lower_spec(spec):
    """Lower a TraceSpec the way its declaration asks (mirrors the
    donation rule in analysis/jaxpr_rules.py, so the program analyzed
    here is the program the linter certifies)."""
    import jax
    if spec.prejitted:
        return spec.fn.lower(*spec.args)
    return jax.jit(
        spec.fn,
        donate_argnums=spec.donate_argnums or (),
        static_argnums=spec.static_argnums or (),
    ).lower(*spec.args)


def analyze_spec(spec, *, peaks=None):
    """Compile one registered entrypoint and return its cost record.
    Never raises for a broken entry: the record then carries an
    ``error`` field (check treats that as a violation, mirroring the
    jaxpr linter's trace-error isolation)."""
    peaks = peaks or DEFAULT_PEAKS
    t0 = time.perf_counter()
    try:
        compiled = _lower_spec(spec).compile()
    except Exception as e:  # graphlint: allow[silent-except]
        msg = str(e).splitlines()[0] if str(e) else repr(e)
        return {'error': f'lower/compile failed: {msg}'}  # reported, not lost
    compile_s = time.perf_counter() - t0
    rec = program_model(compiled, peaks=peaks)
    if rec is None:
        return {'error': 'backend exposes no cost/memory analysis'}
    try:
        coll, fusions = _hlo_counts(compiled.as_text())
    except Exception:  # graphlint: allow[silent-except] optional backend API
        coll, fusions = {}, 0
    rec.update(compile_seconds=compile_s, collectives=coll,
               n_collectives=sum(coll.values()), n_fusions=fusions)
    return rec


def _build_entry(name, build):
    """Builder → spec with the registry-name override the linter also
    applies; builder failures become error records."""
    spec = build()
    if spec.name != name:
        spec = spec.replace(name=name)
    return spec


def snapshot(entrypoints=None, *, peaks=None):
    """Compile every registered entrypoint and return the schema-
    versioned snapshot dict ``check``/``report`` consume. Retrace totals
    are recorded as the *delta* incurred while building this snapshot,
    so the number is deterministic regardless of what the process traced
    before."""
    import jax

    from distributed_dot_product_tpu.analysis import retrace
    from distributed_dot_product_tpu.analysis.registry import (
        default_entrypoints,
    )
    peaks = peaks or DEFAULT_PEAKS
    if entrypoints is None:
        entrypoints = default_entrypoints()

    # retrace.totals() spans live AND retired counters, so the
    # before/after diff is immune to GC timing and to whatever the
    # process traced (and discarded) before this snapshot began.
    before = retrace.totals()
    entries = {}
    for name, build in entrypoints.items():
        try:
            spec = _build_entry(name, build)
        except Exception as e:  # graphlint: allow[silent-except]
            msg = str(e).splitlines()[0] if str(e) else repr(e)
            entries[name] = {'error': f'builder failed: {msg}'}  # reported
            continue
        entries[name] = analyze_spec(spec, peaks=peaks)
    after = retrace.totals()
    retrace_totals = {
        name: after[name] - before.get(name, 0)
        for name in sorted(after)
    }
    return {
        'schema': PERF_SCHEMA_VERSION,
        'created_unix': time.time(),
        'jax_version': jax.__version__,
        'platform': jax.devices()[0].platform,
        'n_devices': len(jax.devices()),
        'peaks': peaks.as_dict(),
        'entries': entries,
        'retrace_totals': retrace_totals,
    }


# -- the regression gate ------------------------------------------------

def check_snapshots(current, baseline, *, tol: Optional[Tolerances] = None,
                    emit_events=True):
    """Compare a current snapshot against a baseline; returns a list of
    human-readable violation strings (empty = gate passes). Every
    violation also lands in the active observability event log as a
    ``perf.regression`` event (when one is active), so a CI run's
    findings share the durable stream with everything else."""
    tol = tol or Tolerances()
    violations = []

    def _flag(entry, metric, msg, cur=None, base=None):
        violations.append(f'{entry}: {metric}: {msg}')
        if emit_events:
            from distributed_dot_product_tpu.obs import events
            if events.get_active() is not None:
                events.emit('perf.regression', entry=entry, metric=metric,
                            current=cur, baseline=base, detail=msg)

    for snap, label in ((current, 'current'), (baseline, 'baseline')):
        if snap.get('schema') != PERF_SCHEMA_VERSION:
            return [f'<snapshot>: schema: {label} snapshot has schema='
                    f'{snap.get("schema")!r} (expected '
                    f'{PERF_SCHEMA_VERSION}) — refresh it with '
                    f'`perf snapshot`']

    base_entries = baseline.get('entries', {})
    cur_entries = current.get('entries', {})
    for name, base in base_entries.items():
        cur = cur_entries.get(name)
        if cur is None:
            _flag(name, 'coverage', 'entry present in the baseline but '
                  'missing from the current snapshot (deregistered? '
                  'refresh the baseline if intentional)')
            continue
        if 'error' in cur:
            _flag(name, 'error', cur['error'])
            continue
        if 'error' in base:
            # The baseline itself recorded a failure; a now-working
            # entry is an improvement — require a refresh, not a pass,
            # so the baseline never rots silently.
            _flag(name, 'error', f'baseline recorded an error '
                  f'({base["error"]}) — refresh the baseline')
            continue
        for field in _REL_FIELDS:
            b, c = float(base[field]), float(cur[field])
            limit = max(tol.rel * abs(b), tol.abs_floor)
            if abs(c - b) > limit:
                _flag(name, field,
                      f'{c:,.0f} vs baseline {b:,.0f} '
                      f'(|Δ|={abs(c - b):,.0f} > ±{limit:,.0f} at '
                      f'rel tol {tol.rel})', cur=c, base=b)
        b, c = float(base['compile_seconds']), float(cur['compile_seconds'])
        limit = b * tol.compile_factor + tol.compile_slack_s
        if c > limit:
            _flag(name, 'compile_seconds',
                  f'{c:.2f}s vs baseline {b:.2f}s (limit {limit:.2f}s '
                  f'= x{tol.compile_factor} + {tol.compile_slack_s}s)',
                  cur=c, base=b)
    for name in cur_entries:
        if name not in base_entries:
            _flag(name, 'coverage', 'entry not in the baseline — refresh '
                  'PERF_BASELINE.json (`perf snapshot -o '
                  'PERF_BASELINE.json`) in the same change that '
                  'registered it')

    base_rt = baseline.get('retrace_totals', {})
    cur_rt = current.get('retrace_totals', {})
    for name, b in base_rt.items():
        c = cur_rt.get(name, 0)
        if c > b + tol.retrace_slack:
            _flag(name, 'retrace_total',
                  f'{c} traces during snapshot vs baseline {b} '
                  f'(+{tol.retrace_slack} allowed) — a cached step is '
                  f'being rebuilt (the round-5 retrace-storm class)',
                  cur=c, base=b)
    for name, c in cur_rt.items():
        # Current-only watcher names gate against an implicit baseline
        # of 0 — a storm under a NEW counter name must not slip past
        # the gate it was built for (the entry gate already demands a
        # baseline refresh for new registrations; same discipline).
        if name not in base_rt and c > tol.retrace_slack:
            _flag(name, 'retrace_total',
                  f'{c} traces during snapshot under a name not in '
                  f'the baseline — refresh PERF_BASELINE.json in the '
                  f'same change that added the watcher',
                  cur=c, base=0)
    return violations


# -- reporting ----------------------------------------------------------

def _si(value, unit=''):
    for scale, suffix in ((1e12, 'T'), (1e9, 'G'), (1e6, 'M'),
                          (1e3, 'K')):
        if abs(value) >= scale:
            return f'{value / scale:.2f} {suffix}{unit}'
    return f'{value:.0f} {unit}'.rstrip()


def render_report(snap):
    """Roofline table over a snapshot: one line per entry — compiler-
    counted FLOPs/bytes, arithmetic intensity, the bound classification
    and the roofline model time at the snapshot's peaks."""
    peaks = snap.get('peaks', DEFAULT_PEAKS.as_dict())
    head = (f'perf snapshot: {len(snap.get("entries", {}))} entrypoints '
            f'on {snap.get("platform")}[{snap.get("n_devices")}] '
            f'jax {snap.get("jax_version")}\n'
            f'roofline peaks: '
            f'{peaks["flops_per_s"] / 1e12:.0f} TF/s, '
            f'{peaks["bytes_per_s"] / 1e9:.0f} GB/s '
            f'(ridge {peaks["ridge_flops_per_byte"]:.0f} FLOP/byte)')
    rows = [f'{"entrypoint":34} {"flops":>10} {"bytes":>10} '
            f'{"FLOP/B":>7} {"bound":>10} {"model µs":>9} '
            f'{"peak KiB":>9} {"coll":>4} {"fus":>4} {"compile":>8}']
    for name, e in sorted(snap.get('entries', {}).items()):
        if 'error' in e:
            rows.append(f'{name:34} ERROR: {e["error"]}')
            continue
        bound = e['roofline'].replace('-bound', '')
        rows.append(
            f'{name:34} {_si(e["flops"]):>10} '
            f'{_si(e["bytes_accessed"], "B"):>10} '
            f'{e["arithmetic_intensity"]:7.2f} {bound:>10} '
            f'{e["model_seconds"] * 1e6:9.2f} '
            f'{e["peak_bytes"] / 1024:9.1f} '
            f'{e["n_collectives"]:4d} {e["n_fusions"]:4d} '
            f'{e["compile_seconds"]:7.2f}s')
    rt = snap.get('retrace_totals', {})
    tail = ('retrace totals during snapshot: '
            + (' '.join(f'{k}={v}' for k, v in sorted(rt.items()))
               if rt else '(none watched)'))
    return '\n'.join([head, ''] + rows + ['', tail])


# -- CLI ----------------------------------------------------------------

def _fresh_snapshot(args):
    peaks = HardwarePeaks(flops_per_s=args.peak_tflops * 1e12,
                          bytes_per_s=args.peak_gbps * 1e9)
    entrypoints = None
    if args.registry:
        from distributed_dot_product_tpu.analysis.registry import (
            resolve_registry_arg,
        )
        try:
            entrypoints = resolve_registry_arg(args.registry)
        except ValueError as e:
            raise SystemExit(str(e))
    return snapshot(entrypoints, peaks=peaks)


def _cmd_snapshot(args):
    snap = _fresh_snapshot(args)
    text = json.dumps(snap, indent=2, sort_keys=True, default=str)
    if args.out in (None, '-'):
        print(text)
    else:
        with open(args.out, 'w') as f:
            f.write(text + '\n')
        n_err = sum('error' in e for e in snap['entries'].values())
        print(f'perf snapshot: {len(snap["entries"])} entrypoints '
              f'({n_err} errored) -> {args.out}')
    return 0


def _cmd_check(args):
    with open(args.against) as f:
        baseline = json.load(f)
    if args.current:
        with open(args.current) as f:
            current = json.load(f)
    else:
        current = _fresh_snapshot(args)
    tol = Tolerances(rel=args.rel_tol,
                     compile_factor=args.compile_factor,
                     compile_slack_s=args.compile_slack,
                     retrace_slack=args.retrace_slack,
                     abs_floor=args.abs_floor)
    violations = check_snapshots(current, baseline, tol=tol)
    for v in violations:
        print(f'PERF REGRESSION: {v}')
    n = len(current.get('entries', {}))
    print(f'perf check: {n} entrypoints vs {args.against}: '
          + ('OK' if not violations
             else f'{len(violations)} violation'
                  f'{"s" if len(violations) != 1 else ""}'))
    return 1 if violations else 0


def _cmd_report(args):
    if args.snapshot_file:
        with open(args.snapshot_file) as f:
            snap = json.load(f)
    else:
        snap = _fresh_snapshot(args)
    print(render_report(snap))
    return 0


def main(argv=None):
    import argparse
    import os

    parser = argparse.ArgumentParser(
        prog='python -m distributed_dot_product_tpu.obs.perf',
        description='compiled-program cost/roofline accounting and the '
                    'perf-regression gate')
    parser.add_argument('--registry', metavar='MODULE:ATTR',
                        help='analyze this {name: builder} mapping '
                             'instead of the central registry (the '
                             'seeded-regression tests drive the gate '
                             'through fixtures this way)')
    parser.add_argument('--peak-tflops', type=float,
                        default=DEFAULT_PEAKS.flops_per_s / 1e12,
                        help='roofline compute ceiling in TF/s '
                             '(default: RESULTS.md bf16 ceiling)')
    parser.add_argument('--peak-gbps', type=float,
                        default=DEFAULT_PEAKS.bytes_per_s / 1e9,
                        help='roofline bandwidth ceiling in GB/s '
                             '(default: RESULTS.md measured decode '
                             'bandwidth)')
    sub = parser.add_subparsers(dest='cmd', required=True)

    s = sub.add_parser('snapshot', help='compile every entrypoint and '
                                        'write the cost snapshot')
    s.add_argument('-o', '--out', default=None,
                   help='output JSON path (default: stdout)')
    s.set_defaults(fn=_cmd_snapshot)

    c = sub.add_parser('check', help='gate a snapshot against a baseline '
                                     '(exit 1 on violations)')
    c.add_argument('--against', required=True,
                   help='baseline snapshot JSON (the committed '
                        'PERF_BASELINE.json in CI)')
    c.add_argument('--current', default=None,
                   help='pre-computed current snapshot JSON (default: '
                        'compile a fresh one)')
    c.add_argument('--rel-tol', type=float, default=Tolerances.rel,
                   help='relative tolerance on flops/bytes/peak-memory')
    c.add_argument('--compile-factor', type=float,
                   default=Tolerances.compile_factor)
    c.add_argument('--compile-slack', type=float,
                   default=Tolerances.compile_slack_s)
    c.add_argument('--retrace-slack', type=int,
                   default=Tolerances.retrace_slack)
    c.add_argument('--abs-floor', type=float,
                   default=Tolerances.abs_floor,
                   help='ignore absolute drifts below this (field '
                        'units) — keeps KiB-scale entries from '
                        'tripping on fusion jitter')
    c.set_defaults(fn=_cmd_check)

    r = sub.add_parser('report', help='render the roofline table')
    r.add_argument('snapshot_file', nargs='?', default=None,
                   help='render this snapshot JSON (default: compile a '
                        'fresh one)')
    r.set_defaults(fn=_cmd_report)

    args = parser.parse_args(argv)

    needs_devices = not (
        (args.cmd == 'check' and args.current)
        or (args.cmd == 'report' and args.snapshot_file))
    if needs_devices:
        # Hermetic platform, forced BEFORE jax commits to a backend —
        # same everywhere (TPU host, CI runner, laptop), so snapshots
        # and baselines are comparable by construction.
        os.environ.setdefault('JAX_PLATFORMS', 'cpu')
        from distributed_dot_product_tpu._compat import ensure_cpu_devices
        ensure_cpu_devices(8)

    return args.fn(args)


if __name__ == '__main__':
    import sys
    sys.exit(main())
