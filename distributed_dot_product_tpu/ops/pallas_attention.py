# -*- coding: utf-8 -*-
"""
Fused flash-attention Pallas TPU kernels (the hot-op fusion layer).

The reference computes attention as four separate eager ops — scores matmul,
mask fill, softmax, context matmul (reference module.py:60-69) — each
reading/writing the full ``(*, T/N, T)`` score tensor through device memory.
XLA fuses the elementwise pieces; these kernels fuse the *whole* chain in
VMEM with an online softmax, so score blocks never touch HBM: traffic drops
from O(T²) to O(T·d) and live score memory from O(Tq·Tk) to
O(BLOCK_Q·BLOCK_K) — in BOTH directions. The backward is the standard
flash recompute strategy as two Pallas kernels (a dq pass and a dk/dv
pass): score blocks are re-derived from q/k and the saved row logsumexp,
so training memory is O(T·d) too, not O(T²).

No reference analog (SURVEY §7 step 6 names this as the post-parity
performance pass). Layout, per the TPU Pallas playbook:

- forward grid = (batch·heads, Tq/BLOCK_Q, Tk/BLOCK_K) with the K sweep
  innermost — TPU grids run sequentially, so the running
  ``(max, denom, numerator)`` accumulators live in VMEM scratch across K
  steps; only one ``(BLOCK, d)`` tile of K/V is resident at a time (Pallas
  double-buffers the HBM→VMEM streams), so sequence length is bounded by
  HBM, not VMEM;
- backward dq grid sweeps K innermost with a dq accumulator; the dk/dv
  grid transposes the sweep (Q innermost) with dk/dv accumulators — each
  pass recomputes ``p = exp(s − lse)`` from the residuals ``(q, k, lse)``
  and contracts with the standard flash-backward algebra
  ``ds = p · (dp − Δ)``, ``Δ = rowsum(dO ⊙ O)``;
- all matmuls hit the MXU with fp32 accumulation
  (``preferred_element_type``) whatever the input dtype; block shapes are
  lane(128)/sublane aligned;
- causal programs whose whole K block lies in the masked future skip the
  matmuls entirely (``pl.when``) — ~2× for causal attention, forward and
  backward; sliding-window programs additionally skip blocks wholly past
  the window (compute linear in T);
- masked logits are ``-inf`` (safe: every shift is clamped finite, see
  ``_apply_masks``), so fully-masked rows return 0 with zero gradients
  in-kernel, matching
  :mod:`distributed_dot_product_tpu.models.ring_attention` semantics (the
  reference NaNs on fully-masked rows, SURVEY §4).

On non-TPU backends (the 8-virtual-device CPU test mesh) the kernels run in
Pallas interpreter mode, so the identical code paths are covered by the
regular test suite.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
# pltpu is importable (pure Python) even off-TPU; the interpreter emulates
# VMEM scratch on CPU.
from jax.experimental.pallas import tpu as pltpu

__all__ = ['flash_attention']

_NEG_BIG = -0.7 * 3.4e38  # large-finite fp32; keeps exp()/VJP NaN-free


def _block_sizes(tq, tk, dtype, d_total=128, has_mask=False):
    """Measured on v5e (T=16K, d=64, bf16): 1024×1024 blocks hit
    ~76 TFLOP/s vs ~38 at 512×512; 2048×2048 exceeds VMEM. Halve the Q
    block when the head dims are large — or when a mask is present
    (Mosaic widens bool blocks to s32 in VMEM, so a (1024, 1024) mask
    block alone is 4 MB of the ~16 MB scoped budget)."""
    sub = 16 if dtype == jnp.bfloat16 else 8
    cap_q = 1024 if d_total <= 256 and not has_mask else 512
    bq = min(cap_q, max(sub, -(-tq // sub) * sub))
    bk = min(1024, max(128 if tk >= 128 else sub,
                       -(-tk // sub) * sub))
    return bq, bk


def _bwd_block_sizes(tq, tk, dtype, d_total=128, has_mask=False):
    """The backward keeps more tiles live per program (q, k, v, dO, plus
    the p/dp/ds score blocks and the dk/dv accumulators). Measured on v5e
    (T=16K, d=64, bf16): 1024×1024 runs the fwd+bwd chain 17% faster than
    512×512 and still fits VMEM; halve when the head dims are large or a
    (s32-widened) mask block joins the working set."""
    sub = 16 if dtype == jnp.bfloat16 else 8
    cap_q = 1024 if d_total <= 256 and not has_mask else 256
    cap_k = 1024 if d_total <= 256 and not has_mask else 512
    bq = min(cap_q, max(sub, -(-tq // sub) * sub))
    bk = min(cap_k, max(128 if tk >= 128 else sub,
                        -(-tk // sub) * sub))
    return bq, bk


def _pad_dim(x, axis, mult):
    size = x.shape[axis]
    target = -(-size // mult) * mult
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad)


def _apply_masks(s, qi, ki, bq, bk, causal, kv_len, mask_ref, off_ref,
                 seg=None, pos=None, mask_live=None, window=None,
                 alibi=None):
    """Shared logit masking: user mask block, segment ids, causal future,
    Tk padding.

    The mask arrives as int8 (1 = masked): Mosaic widens bool kernel
    operands to s32 — a full-size O(4·Tq·Tk) HBM copy — but takes int8
    blocks natively. ``off_ref`` ((1, 2) int32) holds the GLOBAL indices
    of query row 0 AND key column 0 — sequence-sharded callers pass their
    shard offsets so the causal triangle is over global positions with no
    materialized mask (ring folds report the rotating block's column
    offset too, which also keys the dropout hash to true global
    coordinates). ``seg``/``pos`` carry (1, B, 1)/(1, 1, B) int32
    per-position vector blocks (plus their SMEM skip tables, unused here):
    ``seg`` masks pairs in different segments (the packed-sequence mask
    form, O(T) not O(T²) HBM traffic); ``pos`` masks pairs where the query
    GLOBAL position precedes the key's — causal over arbitrary row
    layouts (zigzag/striped sharding).

    Masked logits are ``-inf``, NOT the large-finite ``_NEG_BIG``: every
    kernel shifts ``s`` by a value clamped ≥ ``_NEG_BIG`` (the running-max
    scratch is INITIALIZED to ``_NEG_BIG``, the bounded kernel's shift and
    the backward's lse are finite by construction), so ``exp2(s − shift)``
    is exactly 0 for masked entries and never NaN. That makes fully-masked
    rows yield 0 output / 0 gradients *inside* the kernel — which is also
    what makes whole-block skipping exact: a skipped block contributes
    nothing, the same as folding its all-zero weights.
    """
    if mask_ref is not None:
        masked = mask_ref[0] != 0
        if mask_live is not None:
            # Scalar-prefetch redirection aliases non-mixed tiles onto
            # block (0, 0): their resident mask content is arbitrary and
            # must not be applied (``mask_live`` = this tile is mixed).
            masked = jnp.logical_and(masked, mask_live)
        s = jnp.where(masked, -jnp.inf, s)
    if alibi is not None:
        # ALiBi: additive relative-position bias slope·(col − row) over
        # GLOBAL positions (the wrapper pre-folds log2e so the bias is in
        # the kernel's log2 logit units). Distances come from the pos
        # vectors when given (arbitrary layouts), else from the
        # contiguous off_ref arithmetic — the wrapper guarantees one of
        # the two (same requirement as ``window``).
        if pos is not None:
            dist = (pos[1][0] - pos[0][0]).astype(jnp.float32)
        else:
            rows = (off_ref[0, 0] + qi * bq
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
            cols = (off_ref[0, 1] + ki * bk
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1))
            dist = (cols - rows).astype(jnp.float32)
        s = s + alibi * dist
    if seg is not None:
        s = jnp.where(seg[0][0] != seg[1][0], -jnp.inf, s)
    if pos is not None:
        s = jnp.where(pos[0][0] < pos[1][0], -jnp.inf, s)
        if window is not None:
            # Sliding window over explicit positions: a pair whose key
            # lies ≥ window positions in the query's past is masked.
            s = jnp.where(pos[0][0] - pos[1][0] >= window, -jnp.inf, s)
    if causal:
        rows = (off_ref[0, 0] + qi * bq
                + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
        cols = (off_ref[0, 1] + ki * bk
                + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1))
        s = jnp.where(rows < cols, -jnp.inf, s)
        if window is not None:
            s = jnp.where(rows - cols >= window, -jnp.inf, s)
    if kv_len % bk:
        cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(cols >= kv_len, -jnp.inf, s)
    return s


def _causal_run(causal, off_ref, qi, ki, bq, bk, window=None):
    """Block-skip predicate: does this (Q block, K block) pair contain any
    un-masked causal entry? With a traced row offset this is a dynamic
    scalar — ``pl.when`` still skips the matmuls at run time. ``window``
    additionally skips blocks wholly ≥ window positions in the past (the
    oldest pair is newest-query − oldest-key = block row 0 vs the K
    block's LAST column): compute becomes O(Tq·window), not O(Tq·Tk).
    Row/column global offsets both come from ``off_ref`` (see
    ``_apply_masks``)."""
    if not causal:
        return True
    rel = off_ref[0, 0] - off_ref[0, 1]
    run = rel + (qi + 1) * bq - 1 >= ki * bk
    if window is not None:
        run = jnp.logical_and(
            run, rel + qi * bq - (ki * bk + bk - 1) < window)
    return run


def _row_has_valid(mask, causal, tq, tk, row_offset=0, window=None):
    """(..., Tq, 1) bool: does row i have ANY attendable key, counting the
    causal (and sliding-window) restriction too? Rows without one output 0
    with zero gradients (in every softmax path — the kernels' semantics
    must not depend on WHICH mask made the row empty). ``row_offset`` is
    the global index of row 0 (sequence-sharded callers pass their shard
    offset)."""
    valid = ~mask
    if causal:
        rows = row_offset + jnp.arange(tq)
        cols = jnp.arange(tk)
        allowed = rows[:, None] >= cols[None, :]
        if window is not None:
            allowed = jnp.logical_and(
                allowed, rows[:, None] - cols[None, :] < window)
        valid = jnp.logical_and(valid, allowed)
    return jnp.any(valid, axis=-1, keepdims=True)


def _bcast_lead(kind, shape_lead, batch, ndim_trailing):
    """Validate that an auxiliary input's leading dims broadcast against the
    q/k/v batch dims; returns them left-padded with 1s to ``len(batch)``."""
    if len(shape_lead) > len(batch):
        # More leading dims than q/k/v: the output batch shape comes solely
        # from q/k/v, so NumPy-style broadcasting cannot apply — reject
        # instead of silently indexing only [0].
        raise ValueError(
            f'{kind} has {len(shape_lead)} leading dims but q/k/v have '
            f'{len(batch)}; {kind} may not add batch dims')
    lead = (1,) * (len(batch) - len(shape_lead)) + tuple(shape_lead)
    for db, dm in zip(batch, lead):
        if dm not in (1, db):
            raise ValueError(
                f'{kind} leading dims {tuple(shape_lead)} do not broadcast '
                f'against q/k/v leading dims {tuple(batch)}')
    return lead


def _batch_index_fn(batch, lead):
    """Flat-batch-index map (folded into a BlockSpec) from the q/k/v flat
    batch index to the flat index of an aux input whose size-1 lead axes
    are broadcast (stride 0)."""
    strides = []
    stride = 1
    for db, dm in zip(reversed(batch), reversed(lead)):
        strides.append(0 if dm == 1 else stride)
        stride *= dm

    strides.reverse()

    def index(b):
        out = 0
        rem = b
        for db, st in zip(reversed(batch), reversed(strides)):
            out = out + (rem % db) * st
            rem = rem // db
        return out

    return index


def _mask_setup(mask, batch, tq, tk, tq_p, tk_p):
    """Validate mask broadcasting and flatten it WITHOUT materializing the
    broadcast: returns the padded flat mask, a flat-batch-index map that
    skips size-1 mask axes, and the mask's (broadcast-padded) lead dims.

    Padding rows/cols are set True (masked) so padded K columns never
    contribute and padded Q rows recompute as fully-masked (their
    cotangents are zero-padded anyway).
    """
    if mask.shape[-2:] != (tq, tk):
        raise ValueError(
            f'mask trailing dims {mask.shape[-2:]} must equal '
            f'(Tq, Tk) = {(tq, tk)}')
    mlead = _bcast_lead('mask', mask.shape[:-2], batch, 2)
    nm = int(math.prod(mlead)) if mlead else 1
    # int8, not bool: see _apply_masks. Padding rows/cols are masked (1).
    maskf = jnp.pad(mask.reshape(nm, tq, tk).astype(jnp.int8),
                    ((0, 0), (0, tq_p - tq), (0, tk_p - tk)),
                    constant_values=1)
    return maskf, _batch_index_fn(batch, mlead), mlead


def _vec_setup(kind, pair, batch, tq, tk, tq_p, tk_p, pad_q, pad_k):
    """Prepare a per-position int vector pair for the kernels (segment ids
    or global positions): ``(vec_q, vec_kv)`` with trailing shapes
    ``(Tq,)`` / ``(Tk,)`` (leading dims broadcastable against q/k/v like a
    mask's). Returns the padded flat column/row vectors ``(nq, Tq_p, 1)``
    / ``(nk, 1, Tk_p)``, their batch-index maps, and their lead dims.
    ``pad_q``/``pad_k`` are the padding sentinels (chosen per use so
    padded positions always end up masked)."""
    vec_q, vec_k = pair
    if vec_q.shape[-1] != tq or vec_k.shape[-1] != tk:
        raise ValueError(
            f'{kind} trailing dims ({vec_q.shape[-1]}, '
            f'{vec_k.shape[-1]}) must equal (Tq, Tk) = {(tq, tk)}')
    qlead = _bcast_lead(f'{kind}[0]', vec_q.shape[:-1], batch, 1)
    klead = _bcast_lead(f'{kind}[1]', vec_k.shape[:-1], batch, 1)
    nq = int(math.prod(qlead)) if qlead else 1
    nk = int(math.prod(klead)) if klead else 1
    vqf = jnp.pad(vec_q.astype(jnp.int32).reshape(nq, tq, 1),
                  ((0, 0), (0, tq_p - tq), (0, 0)), constant_values=pad_q)
    vkf = jnp.pad(vec_k.astype(jnp.int32).reshape(nk, 1, tk),
                  ((0, 0), (0, 0), (0, tk_p - tk)), constant_values=pad_k)
    return (vqf, _batch_index_fn(batch, qlead), qlead,
            vkf, _batch_index_fn(batch, klead), klead)


def _seg_setup(segment_ids, batch, tq, tk, tq_p, tk_p):
    """Segment-id pair: ids must be non-negative — Q padding uses sentinel
    −1 and K padding −2, so padded positions never match anything (and
    padded K columns stay masked even without the ``kv_len % bk``
    guard)."""
    return _vec_setup('segment_ids', segment_ids, batch, tq, tk, tq_p,
                      tk_p, -1, -2)


def _pos_setup(positions, batch, tq, tk, tq_p, tk_p):
    """Explicit-global-position pair for causal masking over ARBITRARY row
    layouts (zigzag/striped sequence sharding): entry (i, j) is masked
    when ``pos_q[i] < pos_kv[j]``. Positions must be non-negative; Q pads
    with −1 (< every real position ⇒ padded rows fully masked) and K pads
    with a huge sentinel (> every real position ⇒ padded columns
    masked)."""
    return _vec_setup('positions', positions, batch, tq, tk, tq_p, tk_p,
                      -1, 2 ** 30)




_LOG2E = math.log2(math.e)
_LN2 = math.log(2.0)
# softmax_mode='bounded' safety threshold: with worst-case
# bound − true_rowmax ≤ 100 log2 units, the max softmax weight is
# ≥ 2^-100 — above TPU's flush-to-zero line (2^-126) with ≥26 log2 units
# left for the tail, i.e. only weights < 2^-26 relative are lost.
_BOUNDED_SAFE_GAP = 100.0


# Dense block-skip summaries above this size stay un-streamed (the skip is
# dropped, not the mask): SMEM is ~a MiB per core and the summary competes
# with nothing else we place there.
_RUNSUM_SMEM_CAP = 512 * 1024

# Test hook: force the scalar-prefetch mask redirect under the (slow)
# Mosaic interpreter so the CPU suite can cover the TPU-only path on tiny
# shapes.
_REDIRECT_ON_INTERPRET = False

# Test hook: likewise for the banded window grid (scalar-prefetch index
# maps need the Mosaic interpreter off-TPU; the full-grid window path with
# in-kernel skipping is the off-TPU default and is numerically identical).
_BAND_ON_INTERPRET = False

# Test hook: likewise for the trapezoid causal grid.
_TRAP_ON_INTERPRET = False

# Trapezoid pair-table budget: 2 int32 tables of npairs entries ride SMEM
# via scalar prefetch; past this many pairs fall back to the full grid
# with in-kernel skipping (same 512 KiB SMEM thinking as _RUNSUM_SMEM_CAP).
_TRAP_MAX_PAIRS = 64 * 1024


def _trap_tables(rel, nqb, nkb, bq, bk):
    """Flattened causal-trapezoid pair tables (STATIC offsets only).

    Plain causal attention runs a full (nqb, nkb) grid where nearly half
    the programs are skipped by ``pl.when`` — but a skipped program still
    pays its block DMA and grid sequencing (RESULTS.md measured that
    overhead at 19× on the window path, which is why windows got a banded
    grid). The trapezoid grid removes it for causal: the K axis
    flattens into ONE grid axis of exactly the valid (Q block, K block)
    pairs, ordered Q-major with K ascending, and scalar-prefetched SMEM
    tables map each program to its actual block indices. Out-of-triangle
    blocks then cost nothing at all — no DMA, no sequencing.

    Returns ``(qtab, ktab, ext)``: per-pair Q/K block indices and the
    per-Q-block K extent (the kernels derive accumulator init/finalize
    from ``ki == 0`` / ``ki == ext[qi] − 1``). ``rel`` is the static
    row−column global offset. Rows whose extent would be 0 (entirely in
    the future — negative ``rel``) keep one fully-masked pair so their
    output block is still written (as 0).
    """
    import numpy as np
    qi = np.arange(nqb)
    ext = np.clip((rel + (qi + 1) * bq + bk - 1) // bk, 1, nkb)
    qtab = np.repeat(qi, ext)
    ktab = np.concatenate([np.arange(e) for e in ext])
    return (jnp.asarray(qtab, jnp.int32), jnp.asarray(ktab, jnp.int32),
            jnp.asarray(ext, jnp.int32))


def _trap_tables_t(rel, nqb, nkb, bq, bk):
    """Transposed trapezoid tables for the dk/dv pass (K-major, Q
    ascending from each K block's first causally-visible Q block).
    Returns ``(qtab, ktab, qlo)`` — init fires at ``qi == qlo[kj]``,
    finalize at ``qi == nqb − 1`` (the bottom row block sees every K
    block). K blocks beyond every row keep one fully-masked pair so
    their dk/dv blocks are still written (as 0)."""
    import numpy as np
    kj = np.arange(nkb)
    qlo = np.clip((kj * bk - rel + bq) // bq - 1, 0, nqb - 1)
    counts = nqb - qlo
    ktab = np.repeat(kj, counts)
    qtab = np.concatenate([np.arange(lo, nqb) for lo in qlo])
    return (jnp.asarray(qtab, jnp.int32), jnp.asarray(ktab, jnp.int32),
            jnp.asarray(qlo, jnp.int32))


def _trap_chunk_bounds(rel, tq, tk, bq, bk):
    """Q-row chunk boundaries such that each chunk's causal pair table
    fits ``_TRAP_MAX_PAIRS``: beyond-cap sequences (T≈512K at block 1024)
    split into a few row chunks, each of which the trapezoid grid then
    covers — the kernels never see the full grid. Greedy accumulation of
    per-Q-block extents; returns [(row0, row1), ...] (block-aligned,
    one entry = no chunking needed)."""
    import numpy as np
    nqb = -(-tq // bq)
    nkb = -(-tk // bk)
    ext = np.clip((rel + (np.arange(nqb) + 1) * bq + bk - 1) // bk,
                  1, nkb)
    return _greedy_bounds(ext, bq, tq)


def _greedy_bounds(counts, blk, total):
    bounds = []
    start = 0
    acc = 0
    for i, e in enumerate(counts):
        if acc + e > _TRAP_MAX_PAIRS and i > start:
            bounds.append((start * blk, min(i * blk, total)))
            start, acc = i, 0
        acc += int(e)
    bounds.append((start * blk, total))
    return bounds


def _trap_chunk_bounds_t(rel, tq, tk, bq, bk):
    """K-block chunk boundaries for the dk/dv pass (each K chunk's
    transposed pair table fits the cap); chunks emit DISJOINT dk/dv
    slices, so beyond-cap backward chunking needs no partial sums."""
    import numpy as np
    nqb = -(-tq // bq)
    nkb = -(-tk // bk)
    qlo = np.clip((np.arange(nkb) * bk - rel + bq) // bq - 1, 0, nqb - 1)
    return _greedy_bounds(nqb - qlo, bk, tk)


def _trap_eligible(causal, window, mask, positions, causal_offset,
                   kv_offset, mode, interpret):
    """The trapezoid grid applies to plain causal attention with STATIC
    offsets: a traced offset (sequence-sharded SPMD — every shard runs
    one program, but their triangles differ) would make the pair count
    dynamic, which a grid size cannot be. Windows have their own banded
    grid; dense masks keep the full grid (their skip tables are indexed
    by absolute blocks); 'bounded' keeps the full grid (its win case is
    the forward-only sweep, see RESULTS.md)."""
    import numpy as np
    static = (isinstance(causal_offset, (int, np.integer))
              and isinstance(kv_offset, (int, np.integer)))
    return (causal and window is None and mask is None and positions is None
            and static and mode == 'exact'
            and ((not interpret) or _TRAP_ON_INTERPRET))


def _wrap_specs_pairs(specs, transposed=False):
    """Re-aim 3-axis index maps at the pair grid: program p's block
    indices come from the prefetched tables (``rs[0]``/``rs[1]`` = the
    Q/K tables). SMEM whole-array specs (block_shape None) pass through.
    ``transposed``: inner maps have the (b, kj, qi) signature of the
    dk/dv grid."""
    def wrap(spec):
        if spec.block_shape is None:
            return spec
        f = spec.index_map
        if transposed:
            g = lambda b, p, *rs, f=f: f(b, rs[1][p], rs[0][p], *rs)  # noqa: E731,E501
        else:
            g = lambda b, p, *rs, f=f: f(b, rs[0][p], rs[1][p], *rs)  # noqa: E731,E501
        return pl.BlockSpec(spec.block_shape, g)
    return [wrap(s) for s in specs]


def _mask_streams_per_tile(nb, tq, tk, dtype, d_total, allow_redirect,
                           bwd=False):
    """Will the dense mask stream for (almost) every tile? Only when the
    block-skip summary cannot ride SMEM (or the redirect is off) — block
    sizing must then keep the halved blocks that fit the streamed mask in
    VMEM. With the redirect live, the resident mask block is a single
    aliased tile and full-size blocks win (measured on v5e, T=16K d=96
    bf16 fwd+bwd: 44.7 ms at 256×512 vs 31.2 ms at 1024×1024)."""
    if not allow_redirect:
        return True
    f = _bwd_block_sizes if bwd else _block_sizes
    bq, bk = f(tq, tk, dtype, d_total=d_total, has_mask=False)
    return nb * (-(-tq // bq)) * (-(-tk // bk)) * 4 > _RUNSUM_SMEM_CAP


def _band_size(b_outer, b_inner, window, n_inner):
    """Number of inner-axis blocks a sliding-window band can touch per
    outer block: the band spans ``b_outer + window − 1`` positions, so at
    most ``ceil((b_outer + window − 2)/b_inner) + 1`` blocks."""
    return min(n_inner, (b_outer + window - 2) // b_inner + 2)


def _band_lo(raw, n_inner, band):
    """Clamp a band's first inner block so ``[lo, lo + band)`` stays in
    range; edge blocks pulled into the band are masked/skipped in-kernel
    (the run predicate uses the ACTUAL block index)."""
    return jnp.clip(raw, 0, n_inner - band)


def _split_aux(rest, has_mask, has_seg, has_pos, has_alibi=False):
    """Pop the optional (mask, segments, positions, alibi) ref groups off
    the input tail shared by every kernel signature (the block-skip
    summary rides the scalar-prefetch slot instead, always ref 0).
    Segments and positions each contribute (vec_q, vec_k, qmm, kmm) refs;
    alibi is one (nb,) SMEM slope table."""
    mask_ref = seg = pos = alibi_ref = None
    if has_mask:
        mask_ref, *rest = rest
    if has_seg:
        vq, vk, qmm, kmm, *rest = rest
        seg = (vq, vk, qmm, kmm)
    if has_pos:
        vq, vk, qmm, kmm, *rest = rest
        pos = (vq, vk, qmm, kmm)
    if has_alibi:
        alibi_ref, *rest = rest
    return mask_ref, seg, pos, alibi_ref, rest


def _run_pred(causal, off_ref, qi, ki, bq, bk, b, seg, pos, runsum_ref,
              window=None):
    """Combined block-skip predicate from scalar SMEM tables (vector
    reductions to scalars trip Mosaic relayouts, and (1, 1, ·) VMEM blocks
    are rejected outright — SMEM with program-id indexing is the TPU way):

    - causal: the K block lies strictly in every query row's future;
    - segments (per-block [min, max] id intervals): disjoint intervals
      cannot contain an equal pair — true for ANY id layout, tight for
      the sorted ids of packed sequences;
    - positions (per-block [min, max] global positions): a block whose
      every query position precedes its every key position is fully in
      the causal future — the zigzag/striped analog of the causal skip;
    - dense mask (``runsum``, precomputed any-unmasked-entry per block
      pair): skips the matmuls of fully-masked tiles (their mask block DMA
      is already paid — compute only).

    Exactness: masked logits are -inf ⇒ weights exactly 0 (see
    ``_apply_masks``), so skipping a fully-masked block is identical to
    folding it.
    """
    run = _causal_run(causal, off_ref, qi, ki, bq, bk, window)

    def _and(a, x):
        return x if a is True else jnp.logical_and(a, x)

    if seg is not None:
        _, _, qmm, kmm = seg
        run = _and(run, jnp.logical_and(qmm[b, qi, 0] <= kmm[b, ki, 1],
                                        kmm[b, ki, 0] <= qmm[b, qi, 1]))
    if pos is not None:
        _, _, qmm, kmm = pos
        run = _and(run, qmm[b, qi, 1] >= kmm[b, ki, 0])
        if window is not None:
            # Whole block ≥ window in the past when even its NEWEST key
            # precedes its OLDEST query by window or more.
            run = _and(run, qmm[b, qi, 0] - kmm[b, ki, 1] < window)
    if runsum_ref is not None:
        run = _and(run, runsum_ref[b, qi, ki] != 0)
    return run


def _dropout_keep(seed_ref, b, qi, ki, bq, bk, rate, off_ref, pos=None):
    """Per-block keep mask for attention-weight dropout, as a PURE
    function of (seed, flat batch, GLOBAL element coordinates) — a
    counter-based murmur3-finalizer hash, not a stateful PRNG. Element
    coordinates make the mask independent of the block decomposition, so
    the dq and dk/dv passes (whose block sizes legitimately differ from
    the forward's at large head dims / streamed masks) regenerate the
    forward's EXACT mask from any grid, banded or not — and the same
    code runs under the plain interpreter (no TPU PRNG primitives).
    Coordinates are GLOBAL on both axes: rows/columns come from the
    explicit ``pos`` vectors when given (zigzag/striped layouts), else
    from ``off_ref``'s (row, column) offsets — so sequence-parallel
    shards AND ring folds sharing one replicated seed hash different
    global elements instead of repeating one block's pattern, and a ring
    fold draws the identical mask a single-device kernel would for the
    same elements. Returns a (bq, bk) bool and the 1/(1−rate) scale."""
    u = jnp.uint32
    if pos is not None:
        rows = jnp.broadcast_to(pos[0][0], (bq, bk)).astype(u)
        cols = jnp.broadcast_to(pos[1][0], (bq, bk)).astype(u)
    else:
        rows = (off_ref[0, 0] + qi * bq
                + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
                ).astype(u)
        cols = (off_ref[0, 1] + ki * bk
                + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
                ).astype(u)
    x = (rows * u(2654435761)
         ^ cols * u(2246822519)
         ^ (seed_ref[0, 0].astype(u)
            + jnp.asarray(b, jnp.int32).astype(u) * u(668265263)))
    # murmur3 fmix32: full avalanche, so adjacent coordinates decorrelate.
    x = x ^ (x >> u(16))
    x = x * u(2246822507)
    x = x ^ (x >> u(13))
    x = x * u(3266489909)
    x = x ^ (x >> u(16))
    threshold = u(min(int(rate * 2.0 ** 32), 2 ** 32 - 1))
    return x >= threshold, 1.0 / (1.0 - rate)


def _score_block(q_ref, k_ref, quant):
    """(BQ, BK) score block in log2 logit units. Standard path: q arrived
    pre-folded by scale·log2e (the exp2 trick), one bf16 MXU dot.
    Quantized path (``quant = (sqf_ref, skr_ref)``; q/k refs hold int8):
    an int8×int8→int32 MXU dot — measured ~1.65× the bf16 rate on v5e
    (245 vs 148 TOP/s) — then a row-vector and a column-vector multiply
    apply the per-row dequantization scales (``sqf`` carries the
    scale·log2e fold, ``skr`` is the raw k-row scale)."""
    if quant is None:
        return jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    sqf_ref, skr_ref = quant
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32).astype(jnp.float32)
    return s * sqf_ref[0] * skr_ref[0]


def _make_fwd_kernel(causal, bq, bk, kv_len, has_mask, has_seg, has_pos,
                     has_alibi, has_mask_skip, save_lse, window=None,
                     band_fn=None, quantized=False, dropout=None,
                     trap=False):
    def kernel(*refs):
        if trap:
            tq_ref, tk_ref, ext_ref, *refs = refs
        elif band_fn is not None:
            bandoff_ref, *refs = refs
        if has_mask_skip:
            runsum_ref, *refs = refs
        else:
            runsum_ref = None
        off_ref, *refs = refs
        if dropout is not None:
            seed_ref, *refs = refs
        q_ref, k_ref, v_ref, *rest = refs
        quant = None
        if quantized:
            sqf_ref, skr_ref, *rest = rest
            quant = (sqf_ref, skr_ref)
        mask_ref, seg, pos, alibi_ref, rest = _split_aux(
            rest, has_mask, has_seg, has_pos, has_alibi)
        if save_lse:
            o_ref, lse_ref, m_s, l_s, acc_s = rest
        else:
            (o_ref, m_s, l_s, acc_s), lse_ref = rest, None
        if trap:
            # Trapezoid pair grid: program_id(1) walks the flattened
            # valid (Q block, K block) pairs Q-major; each Q block's run
            # starts at K block 0 and ends at its causal extent.
            p = pl.program_id(1)
            qi = tq_ref[p]
            ki = tk_ref[p]
            first_k = ki == 0
            last_k_cond = ki == ext_ref[qi] - 1
        else:
            qi = pl.program_id(1)
            kj = pl.program_id(2)
            # Banded window grid: the K sweep covers only this Q block's
            # band; ki is the ACTUAL K block index (all masking/skip
            # arithmetic uses it), kj the program position (init/finalize
            # conditions).
            ki = kj if band_fn is None else band_fn(qi, bandoff_ref[0]) + kj
            first_k = kj == 0
            last_k_cond = kj == pl.num_programs(2) - 1

        @pl.when(first_k)
        def _():
            m_s[:] = jnp.full_like(m_s, _NEG_BIG)
            l_s[:] = jnp.zeros_like(l_s)
            acc_s[:] = jnp.zeros_like(acc_s)

        # Block skip: K block strictly in the causal future of every query
        # row, fully past the sliding window, or provably fully masked →
        # contributes nothing.
        pid_b = pl.program_id(0)  # hoisted: program_id inside a
        # pl.when body is not substituted by the plain interpreter
        slope = None if alibi_ref is None else alibi_ref[pid_b]
        run = _run_pred(causal, off_ref, qi, ki, bq, bk,
                        pl.program_id(0), seg, pos, runsum_ref, window)

        @pl.when(run)
        def _():
            # Keep matmul operands in their native dtype (bf16 in, fp32
            # accumulate) — upcasting to fp32 before the dot halves MXU
            # throughput. The softmax scale and exp's internal log2(e)
            # multiply are BOTH pre-folded into q by the wrapper (the
            # "exp2 trick"), so the only per-score-element VPU work here
            # is max / subtract / exp2 / sum / downcast — at small head
            # dim the kernel is VPU-bound and each removed op is ~15%.
            v = v_ref[0]                                    # (BK, dv)
            s = _score_block(q_ref, k_ref, quant)  # (BQ, BK), log2 units
            mask_live = (None if runsum_ref is None else
                         runsum_ref[pl.program_id(0), qi, ki] == 1)
            s = _apply_masks(s, qi, ki, bq, bk, causal, kv_len,
                             mask_ref, off_ref, seg, pos, mask_live,
                             window, slope)

            m_prev = m_s[:]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp2(s - m_new)
            corr = jnp.exp2(m_prev - m_new)
            m_s[:] = m_new
            # Dropout acts on the NORMALIZED weights, so the denominator
            # accumulates the undropped p while the numerator folds the
            # kept entries (inverted-dropout scaled) — algebraically
            # identical to dropout(softmax(s))·v.
            l_s[:] = l_s[:] * corr + p.sum(axis=-1, keepdims=True)
            p_num = p
            if dropout is not None:
                keep, inv = _dropout_keep(seed_ref, pid_b, qi, ki,
                                          bq, bk, dropout, off_ref, pos)
                p_num = jnp.where(keep, p, 0.0) * inv
            acc_s[:] = acc_s[:] * corr + jax.lax.dot_general(
                p_num.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(last_k_cond)
        def _():
            l = l_s[:]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            out = acc_s[:] / safe_l
            # l == 0 ⇔ the row has no attendable key (every logit -inf,
            # every weight exactly 0) — out is then 0 with zero grads,
            # in-kernel (the reference NaNs here, SURVEY §4).
            o_ref[0] = out.astype(o_ref.dtype)
            if save_lse:
                # Convert from log2 back to natural-log units for the
                # backward: lse = ln2·(m₂ + log2 l) = m + ln l.
                lse_ref[0] = _LN2 * (m_s[:] + jnp.log2(safe_l))

    return kernel


def _aux_setup(mask, segment_ids, positions, batch, tq, tk, tq_p, tk_p,
               bq, bk, allow_redirect=True, k_of=None, q_of_t=None,
               alibi=None):
    """Specs (both grid orders) + args + presence flags for the optional
    (mask, segments, block-skip table) kernel inputs, shared by the
    forward and both backward passes — args are computed ONCE (the int8
    mask copy and the skip tables are O(T²)-read reductions; the dq and
    dk/dv passes must not each pay them again). ``specs_t`` carries index
    maps for the dk/dv grid ``(b, kj, qi)`` (Q innermost).

    The skip tables (segment per-block [min, max], dense any-unmasked
    summary) are whole-array SMEM inputs pre-broadcast to the flat batch —
    kernels index them by raw program ids, no per-input batch maps.

    ``k_of`` / ``q_of_t``: banded-window grid translations — map the
    (batch, outer, inner, prefetch-refs) grid coordinates to the ACTUAL
    K block (normal grids) / Q block (transposed grid). None = identity
    (the grid axis IS the block index). Banded grids carry no dense mask
    (asserted), so only the per-position vec specs need them."""
    kof = k_of or (lambda b, i, j, rs: j)
    qot = q_of_t or (lambda b, j, i, rs: i)
    assert mask is None or (k_of is None and q_of_t is None), \
        'banded window grids do not support dense masks'
    nqb, nkb = tq_p // bq, tk_p // bk
    nb = int(math.prod(batch)) if batch else 1
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    specs, specs_t, args = [], [], []
    runsum = None
    maskf = mask_idx = mlead = None
    if mask is not None:
        maskf, mask_idx, mlead = _mask_setup(mask, batch, tq, tk,
                                             tq_p, tk_p)
        # 3-state per-tile summary: 0 = every entry masked (tile skipped),
        # 1 = mixed (mask block streamed + applied), 2 = no entry masked
        # (tile computed, mask block neither streamed nor applied).
        # Dropped when it would crowd SMEM (mask then streams for every
        # tile, the round-2 behavior) — and off-TPU (``allow_redirect``):
        # the redirect needs a scalar-prefetch grid, which only the slow
        # Mosaic interpreter emulates, and the DMA it saves isn't real on
        # the test mesh anyway.
        if allow_redirect and nb * nqb * nkb * 4 <= _RUNSUM_SMEM_CAP:
            tile = maskf.reshape(maskf.shape[0], nqb, bq, nkb, bk)
            state = jnp.where(tile.min(axis=(2, 4)) == 1, 0,
                              jnp.where(tile.max(axis=(2, 4)) == 0, 2, 1))
            runsum = jnp.broadcast_to(
                state.reshape(*mlead, nqb, nkb),
                (*batch, nqb, nkb)).reshape(nb, nqb, nkb).astype(jnp.int32)

        if runsum is None:
            mask_map = lambda b, i, j, *rs: (mask_idx(b), i, j)  # noqa: E731
        else:  # scalar-prefetch mode: maps receive the summary ref
            # Scalar-prefetch redirection: non-mixed tiles (skipped, or
            # computed mask-free) alias block (0, 0, 0), so consecutive
            # programs re-use the resident copy and their O(bq·bk) mask
            # DMA disappears.
            def mask_map(b, i, j, *rs):
                mixed = rs[0][b, i, j] == 1
                return (jnp.where(mixed, mask_idx(b), 0),
                        jnp.where(mixed, i, 0), jnp.where(mixed, j, 0))
        specs.append(pl.BlockSpec((1, bq, bk), mask_map))
        specs_t.append(pl.BlockSpec(
            (1, bq, bk), lambda b, j, i, *rs: mask_map(b, i, j, *rs)))
        args.append(maskf)
    for pair, setup in ((segment_ids, _seg_setup), (positions, _pos_setup)):
        if pair is None:
            continue
        vqf, vq_idx, qlead, vkf, vk_idx, klead = setup(
            pair, batch, tq, tk, tq_p, tk_p)
        specs.append(pl.BlockSpec(
            (1, bq, 1), lambda b, i, j, *rs, f=vq_idx: (f(b), i, 0)))
        specs.append(pl.BlockSpec(
            (1, 1, bk),
            lambda b, i, j, *rs, f=vk_idx: (f(b), 0, kof(b, i, j, rs))))
        specs_t.append(pl.BlockSpec(
            (1, bq, 1),
            lambda b, j, i, *rs, f=vq_idx: (f(b), qot(b, j, i, rs), 0)))
        specs_t.append(pl.BlockSpec(
            (1, 1, bk), lambda b, j, i, *rs, f=vk_idx: (f(b), 0, j)))
        args.extend([vqf, vkf])
        # Per-block [min, max] intervals, (nb, n_blocks, 2) in SMEM —
        # these drive the cross-segment / causal-future block skips.
        sq = vqf[..., 0].reshape(vqf.shape[0], nqb, bq)
        sk = vkf[:, 0].reshape(vkf.shape[0], nkb, bk)
        qmm = jnp.stack([sq.min(-1), sq.max(-1)], -1)
        kmm = jnp.stack([sk.min(-1), sk.max(-1)], -1)
        qmm = jnp.broadcast_to(qmm.reshape(*qlead, nqb, 2),
                               (*batch, nqb, 2)).reshape(nb, nqb, 2)
        kmm = jnp.broadcast_to(kmm.reshape(*klead, nkb, 2),
                               (*batch, nkb, 2)).reshape(nb, nkb, 2)
        specs.extend([smem_spec, smem_spec])
        specs_t.extend([smem_spec, smem_spec])
        args.extend([qmm, kmm])
    if alibi is not None:
        # Per-head ALiBi slopes: one f32 scalar per flat batch entry,
        # whole-array SMEM (kernels index by program id 0). Lead dims
        # broadcast like a mask's (e.g. (H,) against (B, H)).
        alead = _bcast_lead('alibi_slopes', alibi.shape, batch, 0)
        aflat = jnp.broadcast_to(
            jnp.asarray(alibi, jnp.float32).reshape(alead),
            tuple(batch)).reshape(nb)
        specs.append(smem_spec)
        specs_t.append(smem_spec)
        args.append(aflat)
    # prefetch == a live summary: the call becomes a scalar-prefetch grid
    # and kernels pop the summary as ref 0.
    flags = (mask is not None, segment_ids is not None,
             positions is not None, alibi is not None, runsum is not None)
    return specs, specs_t, args, flags, runsum


def _pallas_call(kernel, grid, in_specs, out_specs, scratch, out_shape,
                 interpret, prefetch):
    """Build + invoke: a scalar-prefetch grid when any prefetch operands
    are live (the dense-mask block-skip summary and/or the window band
    offset), a plain grid otherwise. Prefetch refs reach both the index
    maps (as trailing ``*rs`` args — the same lambdas serve both modes)
    and the kernel (as leading refs). ``interpret=True`` under prefetch
    upgrades to the Mosaic TPU interpreter — the default HLO interpreter
    cannot evaluate scalar-prefetch grids ("MLIR translation rule for
    primitive 'program_id' not found for platform cpu")."""
    prefetch = [p for p in prefetch if p is not None]
    interp = interpret
    if interpret is True and prefetch:
        # The HLO interpreter cannot evaluate scalar-prefetch grids —
        # upgrade to the Mosaic TPU interpreter. The params class moved
        # across jax versions (InterpretParams / TPUInterpretParams);
        # old jax has neither, and its HLO interpreter is left to try
        # (callers on those versions fall back to non-prefetch paths).
        for name in ('InterpretParams', 'TPUInterpretParams'):
            cls = getattr(pltpu, name, None)
            if cls is not None:
                interp = cls()
                break
    if prefetch:
        call = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=len(prefetch), grid=grid,
                in_specs=in_specs, out_specs=out_specs,
                scratch_shapes=scratch),
            out_shape=out_shape, interpret=interp)
        return lambda *a: call(*prefetch, *a)
    return pl.pallas_call(kernel, grid=grid, in_specs=in_specs,
                          out_specs=out_specs, scratch_shapes=scratch,
                          out_shape=out_shape, interpret=interp)


def _quantize_rows(x, nb_x, t, d):
    """Per-row symmetric int8 quantization: ``x ≈ x_i8 · s_row`` with
    ``s_row = max|row|/127`` (eps-clamped so all-zero rows stay finite).
    The rounding error is ≤ s_row/2 per element — ~0.4% of the row's max,
    the class of error bf16 inputs already carry."""
    x32 = x.astype(jnp.float32).reshape(nb_x, t, d)
    sx = jnp.maximum(jnp.max(jnp.abs(x32), axis=-1, keepdims=True) / 127.0,
                     1e-20)
    xi = jnp.round(x32 / sx).astype(jnp.int8)
    return xi, sx


def _kv_group(q, k):
    """Grouped-query (GQA/MQA) factor: q may carry more heads than k/v —
    lead dims must match except the head axis (-3), which must divide.
    Returns how many consecutive flat q-batch indices share one kv block
    (1 = standard multi-head). The flat mapping is ``b_kv = b // group``
    because the head axis is the innermost lead dim."""
    if tuple(k.shape[:-2]) == tuple(q.shape[:-2]):
        return 1
    if (q.ndim < 3 or k.ndim != q.ndim
            or k.shape[:-3] != q.shape[:-3]
            or q.shape[-3] % k.shape[-3]):
        raise ValueError(
            f'k/v lead dims {k.shape[:-2]} must equal q lead dims '
            f'{q.shape[:-2]} or differ only on the head axis (-3) with '
            f'q heads divisible by kv heads (GQA)')
    return q.shape[-3] // k.shape[-3]


def _flash_fwd_impl(q, k, v, mask, causal_offset, scale, causal, interpret,
                    mode='exact', save_lse=False, segment_ids=None,
                    positions=None, window=None, alibi=None, qk_quant=None,
                    dropout_rate=0.0, dropout_seed=None, kv_offset=0):
    *batch, tq, d = q.shape
    tk = k.shape[-2]
    d_v = v.shape[-1]
    # Canonicalize the softmax mode BEFORE any grid/chunk eligibility
    # check: dropout rides the exact kernel only, quantization's running
    # max is already correct on the dequantized scores, and the
    # Cauchy-Schwarz bound does not cover the additive ALiBi term (≤ 0
    # only for non-negative slopes, and slopes may be traced) — in each
    # case 'bounded' is an optimization hint that resolves to the exact
    # kernel, which must then still be eligible for the trapezoid pair
    # grid (both the beyond-cap chunking below and the in-cap selection).
    if mode == 'bounded' and (dropout_rate or qk_quant == 'int8'
                              or alibi is not None):
        mode = 'exact'
    if _trap_eligible(causal, window, mask, positions, causal_offset,
                      kv_offset, mode, interpret):
        # Beyond-cap pair tables: split the Q rows into chunks that each
        # fit, and run each chunk through this same impl with a shifted
        # row offset — every chunk then takes the trapezoid grid. Row
        # chunking is exact: outputs are per-row, per-row int8 scales are
        # per-row, the dropout hash keys on global coordinates (which the
        # shifted offset preserves), and seg_q slices with its rows.
        bq0, bk0 = _block_sizes(tq, tk, q.dtype, d_total=d + d_v)
        bounds = _trap_chunk_bounds(
            int(causal_offset) - int(kv_offset), tq, tk, bq0, bk0)
        if len(bounds) > 1:
            outs, lses = [], []
            for r0, r1 in bounds:
                seg = segment_ids
                if seg is not None:
                    seg = (seg[0][..., r0:r1], seg[1])
                res = _flash_fwd_impl(
                    q[..., r0:r1, :], k, v, None, causal_offset + r0,
                    scale, causal, interpret, mode, save_lse=save_lse,
                    segment_ids=seg, alibi=alibi, qk_quant=qk_quant,
                    dropout_rate=dropout_rate, dropout_seed=dropout_seed,
                    kv_offset=kv_offset)
                if save_lse:
                    outs.append(res[0])
                    lses.append(res[1])
                else:
                    outs.append(res)
            out = jnp.concatenate(outs, axis=-2)
            if save_lse:
                return out, jnp.concatenate(lses, axis=-1)
            return out
    nb = int(math.prod(batch)) if batch else 1
    kv_group = _kv_group(q, k)
    nbk = nb // kv_group
    # (1, 2) int32 input: the global indices of query row 0 and key
    # column 0 (possibly traced, e.g. lax.axis_index under shard_map /
    # the ring fold's rotating owner). Always fed — a dead scalar read
    # costs nothing and keeps the kernel signatures uniform.
    off = jnp.stack([jnp.asarray(causal_offset, jnp.int32),
                     jnp.asarray(kv_offset, jnp.int32)]).reshape(1, 2)
    off_spec = pl.BlockSpec((1, 2), lambda b, i, j, *rs: (0, 0))

    allow_redirect = (not interpret) or _REDIRECT_ON_INTERPRET
    streams_mask = mask is not None and _mask_streams_per_tile(
        nb, tq, tk, q.dtype, d + d_v, allow_redirect)
    bq, bk = _block_sizes(tq, tk, q.dtype, d_total=d + d_v,
                          has_mask=streams_mask)
    # exp2 trick: fold scale·log2(e) into q so the kernel's score block
    # needs no per-element multiply (exp2 replaces exp, whose hardware
    # lowering is exp2(x·log2e) anyway). One extra rounding of q, same
    # class of error as the bf16 inputs themselves.
    quantized = qk_quant == 'int8'
    sqf = skr = None
    if quantized:
        # int8 QK^T: the fwd score matmul runs on the int8 MXU path
        # (~1.65x bf16, measured on v5e); the scale*log2e fold rides the
        # q-row scale vector instead of q itself.
        qi8, sq = _quantize_rows(q, nb, tq, d)
        ki8, sk = _quantize_rows(k, nbk, tk, d)
        qf = _pad_dim(qi8, 1, bq)
        kf = _pad_dim(ki8, 1, bk)
        sqf = _pad_dim(sq * (scale * _LOG2E), 1, bq)       # (nb, Tq_p, 1)
        skr = _pad_dim(jnp.swapaxes(sk, 1, 2), 2, bk)      # (nbk, 1, Tk_p)
    else:
        q2 = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
        qf = _pad_dim(q2.reshape(nb, tq, d), 1, bq)
        kf = _pad_dim(k.reshape(nbk, tk, d), 1, bk)
    vf = _pad_dim(v.reshape(nbk, tk, d_v), 1, bk)
    tq_p, tk_p = qf.shape[1], kf.shape[1]
    nqb, nkb = tq_p // bq, tk_p // bk

    # Banded window grid: with a contiguous causal window, each Q block
    # only ever folds the ~window/bk K blocks of its band — shrink the K
    # grid axis to the band and select the actual K block in the index
    # maps from the (scalar-prefetched) global row offset. Out-of-band
    # blocks then cost NOTHING (no grid step, no DMA): compute and HBM
    # traffic are O(Tq·window). Dense masks keep the full grid (their
    # runsum tables are indexed by absolute blocks and T²-masks don't
    # arise in the long-context configs that use windows); explicit
    # positions keep it too (a shard's rows are not one contiguous band).
    banded = (window is not None and causal and mask is None
              and positions is None
              and ((not interpret) or _BAND_ON_INTERPRET))
    band_fn = bandoff = kof = None
    trap = trap_pre = None
    if banded:
        band = _band_size(bq, bk, window, nkb)

        def band_fn(i, off_s):
            return _band_lo((off_s + i * bq - (window - 1)) // bk,
                            nkb, band)

        def kof(b, i, j, rs):
            # Single source of truth for the band's K-block translation —
            # the q/k/v BlockSpec maps and the aux (segment) maps both
            # derive from it (rs[0] is the prefetched row−column offset).
            return band_fn(i, rs[0][0]) + j
        bandoff = (off[0, 0] - off[0, 1]).reshape(1)
        grid = (nb, nqb, band)
    else:
        grid = (nb, nqb, nkb)
        if _trap_eligible(causal, window, mask, positions, causal_offset,
                          kv_offset, mode, interpret):
            qtab, ktab, ext = _trap_tables(
                int(causal_offset) - int(kv_offset), nqb, nkb, bq, bk)
            if qtab.shape[0] <= _TRAP_MAX_PAIRS:
                trap = True
                trap_pre = [qtab, ktab, ext]
                grid = (nb, int(qtab.shape[0]))
    k_map = lambda b, i, j, *rs: (  # noqa: E731
        b // kv_group, j if kof is None else kof(b, i, j, rs), 0)

    specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j, *rs: (b, i, 0)),
        pl.BlockSpec((1, bk, d), k_map),
        pl.BlockSpec((1, bk, d_v), k_map),
    ]
    args = [qf, kf, vf]
    if quantized:
        specs += [
            pl.BlockSpec((1, bq, 1), lambda b, i, j, *rs: (b, i, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, i, j, *rs: (
                b // kv_group, 0,
                j if kof is None else kof(b, i, j, rs))),
        ]
        args += [sqf, skr]
    dropout = float(dropout_rate) if dropout_rate else None
    seed_specs, seed_args = [], []
    if dropout is not None:
        seed_specs = [pl.BlockSpec((1, 1), lambda b, i, j, *rs: (0, 0))]
        seed_args = [jnp.asarray(dropout_seed, jnp.int32).reshape(1, 1)]
    aux_specs, _, aux_args, flags, runsum = _aux_setup(
        mask, segment_ids, positions, batch, tq, tk, tq_p, tk_p, bq, bk,
        allow_redirect=allow_redirect, k_of=kof,
        alibi=(None if alibi is None else alibi * _LOG2E))

    out_specs = pl.BlockSpec((1, bq, d_v), lambda b, i, j, *rs: (b, i, 0))
    out_shape = jax.ShapeDtypeStruct((nb, tq_p, d_v), v.dtype)
    if save_lse:
        out_specs = [out_specs,
                     pl.BlockSpec((1, bq, 1),
                                  lambda b, i, j, *rs: (b, i, 0))]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((nb, tq_p, 1), jnp.float32)]

    def run_exact(*_):
        kernel = _make_fwd_kernel(causal, bq, bk, tk, *flags, save_lse,
                                  window, band_fn, quantized, dropout,
                                  trap=bool(trap))
        in_specs = [off_spec] + seed_specs + specs + aux_specs
        o_specs = out_specs
        if trap:
            in_specs = _wrap_specs_pairs(in_specs)
            o_specs = (_wrap_specs_pairs(o_specs) if save_lse
                       else _wrap_specs_pairs([o_specs])[0])
        return _pallas_call(
            kernel, grid, in_specs, o_specs, _scratch(bq, d_v), out_shape,
            interpret, trap_pre if trap else [bandoff, runsum],
        )(off, *seed_args, *args, *aux_args)

    if mode == 'bounded':
        # Per-row upper bound on the (log2-unit) scores via Cauchy-Schwarz:
        # |s2_ij| ≤ ‖q2_i‖·‖k_j‖ ≤ ‖q2_i‖·max_j‖k_j‖. The +1 covers fp32
        # accumulation rounding in the kernel's dot.
        q32 = q2.reshape(nb, tq, d).astype(jnp.float32)
        k32 = k.reshape(nbk, tk, d).astype(jnp.float32)
        qn = jnp.sqrt(jnp.sum(q32 * q32, axis=-1, keepdims=True))
        kn = jnp.sqrt(jnp.max(jnp.sum(k32 * k32, axis=-1), axis=-1))
        if kv_group > 1:   # per-kv-head max norm → its q-head group
            kn = jnp.repeat(kn, kv_group)
        mvec = qn * kn[:, None, None] + 1.0                 # (nb, Tq, 1)
        mvecf = _pad_dim(mvec, 1, bq)
        mvec_spec = pl.BlockSpec((1, bq, 1), lambda b, i, j, *rs: (b, i, 0))

        def run_bounded(*_):
            kernel = _make_fwd_kernel_bounded(
                causal, bq, bk, tk, *flags, save_lse, window, band_fn)
            return _pallas_call(
                kernel, grid, [off_spec] + specs + [mvec_spec] + aux_specs,
                out_specs, _scratch(bq, d_v)[1:],  # no m buffer
                out_shape, interpret, [bandoff, runsum],
            )(off, *args, mvecf, *aux_args)

        # Safety net: the bound shift is only exact while
        # bound − true_rowmax stays inside fp32's exponent range; since
        # true_rowmax ≥ −‖q2_i‖·max‖k‖, the worst-case gap is 2·bound.
        # When any row could exceed the safe gap, run the exact kernel
        # instead (lax.cond: both are compiled, one executes) — 'bounded'
        # is then an optimization hint, never a correctness trade.
        worst_gap = 2.0 * jnp.max(mvec)
        res = jax.lax.cond(worst_gap <= _BOUNDED_SAFE_GAP,
                           run_bounded, run_exact)
    else:
        res = run_exact()
    out, lse = res if save_lse else (res, None)
    out = out[:, :tq].reshape(*batch, tq, d_v)
    # No post-hoc empty-row zeroing: -inf masking makes the kernels emit
    # exactly 0 for rows with no attendable key (see _apply_masks), so the
    # O(Tq·Tk) any-valid reduction the wrapper used to run is pure cost.
    if save_lse:
        return out, lse[:, :tq, 0].reshape(*batch, tq)
    return out


def _scratch(bq, d_v):
    return [pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d_v), jnp.float32)]


def _make_fwd_kernel_bounded(causal, bq, bk, kv_len, has_mask, has_seg,
                             has_pos, has_alibi, has_mask_skip, save_lse,
                             window=None, band_fn=None):
    """Forward kernel for ``softmax_mode='bounded'``: the per-row shift is
    a precomputed upper bound on the row max (Cauchy-Schwarz,
    ``‖q_i‖·max_j‖k_j‖``, fed as an input), so the kernel drops the
    running-max lane reduction, both correction multiplies and the m
    scratch — the ablated cost is ~15% of kernel time at d=64 (the max
    reduce is the single most expensive VPU op in the exact kernel).

    Softmax is shift-invariant, so the result matches the exact kernel
    whenever ``bound − true_rowmax`` stays within fp32's exponent range
    (the wrapper guarantees this by falling back to the exact kernel when
    the worst-case gap ``2·max(bound)`` exceeds ``_BOUNDED_SAFE_GAP``).
    """
    def kernel(*refs):
        if band_fn is not None:
            bandoff_ref, *refs = refs
        if has_mask_skip:
            runsum_ref, *refs = refs
        else:
            runsum_ref = None
        off_ref, q_ref, k_ref, v_ref, m_ref, *rest = refs
        mask_ref, seg, pos, alibi_ref, rest = _split_aux(
            rest, has_mask, has_seg, has_pos, has_alibi)
        if save_lse:
            o_ref, lse_ref, l_s, acc_s = rest
        else:
            (o_ref, l_s, acc_s), lse_ref = rest, None
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        ki = kj if band_fn is None else band_fn(qi, bandoff_ref[0]) + kj
        last_k = pl.num_programs(2) - 1

        @pl.when(kj == 0)
        def _():
            l_s[:] = jnp.zeros_like(l_s)
            acc_s[:] = jnp.zeros_like(acc_s)

        pid_b = pl.program_id(0)  # hoisted: program_id inside a
        # pl.when body is not substituted by the plain interpreter
        slope = None if alibi_ref is None else alibi_ref[pid_b]
        run = _run_pred(causal, off_ref, qi, ki, bq, bk,
                        pl.program_id(0), seg, pos, runsum_ref, window)

        @pl.when(run)
        def _():
            q = q_ref[0]                                    # (BQ, d)
            k = k_ref[0]                                    # (BK, d)
            v = v_ref[0]                                    # (BK, dv)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (BQ, BK), log2 units
            mask_live = (None if runsum_ref is None else
                         runsum_ref[pl.program_id(0), qi, ki] == 1)
            s = _apply_masks(s, qi, ki, bq, bk, causal, kv_len,
                             mask_ref, off_ref, seg, pos, mask_live,
                             window, slope)
            p = jnp.exp2(s - m_ref[0])                      # bound shift
            l_s[:] += p.sum(axis=-1, keepdims=True)
            acc_s[:] += jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(kj == last_k)
        def _():
            l = l_s[:]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            # l == 0: fully-masked rows (all weights underflowed to 0) —
            # acc is 0 too, so the output is the required 0.
            o_ref[0] = (acc_s[:] / safe_l).astype(o_ref.dtype)
            if save_lse:
                lse_ref[0] = _LN2 * (m_ref[0] + jnp.log2(safe_l))

    return kernel


def _make_dq_kernel(scale, causal, bq, bk, kv_len, has_mask, has_seg,
                    has_pos, has_alibi, has_mask_skip, window=None,
                    band_fn=None, quantized=False, dropout=None,
                    trap=False):
    def kernel(*refs):
        if trap:
            tq_ref, tk_ref, ext_ref, *refs = refs
        elif band_fn is not None:
            bandoff_ref, *refs = refs
        if has_mask_skip:
            runsum_ref, *refs = refs
        else:
            runsum_ref = None
        off_ref, *refs = refs
        if dropout is not None:
            seed_ref, *refs = refs
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
         *rest) = refs
        quant = None
        if quantized:
            sqf_ref, skr_ref, sqc_ref, skc_ref, *rest = rest
            quant = (sqf_ref, skr_ref)
        mask_ref, seg, pos, alibi_ref, rest = _split_aux(
            rest, has_mask, has_seg, has_pos, has_alibi)
        dq_ref, dq_acc = rest
        if trap:
            p = pl.program_id(1)
            qi = tq_ref[p]
            ki = tk_ref[p]
            first_k = ki == 0
            last_k_cond = ki == ext_ref[qi] - 1
        else:
            qi = pl.program_id(1)
            kj = pl.program_id(2)
            ki = kj if band_fn is None else band_fn(qi, bandoff_ref[0]) + kj
            first_k = kj == 0
            last_k_cond = kj == pl.num_programs(2) - 1

        @pl.when(first_k)
        def _():
            dq_acc[:] = jnp.zeros_like(dq_acc)

        pid_b = pl.program_id(0)  # hoisted: program_id inside a
        # pl.when body is not substituted by the plain interpreter
        slope = None if alibi_ref is None else alibi_ref[pid_b]
        run = _run_pred(causal, off_ref, qi, ki, bq, bk,
                        pl.program_id(0), seg, pos, runsum_ref, window)

        @pl.when(run)
        def _():
            # q_ref holds q·(scale·log2e) and lse_ref holds lse·log2e (both
            # pre-folded by the wrapper, mirroring the forward's exp2
            # trick) so no per-score-element multiply is needed here:
            # p = exp(s−lse) = exp2(s₂ − lse₂). Quantized: the score
            # recompute reuses the int8 dot (consistent with the saved
            # lse); the ds·k contraction dequantizes k in-block.
            v = v_ref[0]                                    # (BK, dv)
            g = g_ref[0]                                    # (BQ, dv)
            s = _score_block(q_ref, k_ref, quant)  # (BQ, BK), log2 units
            mask_live = (None if runsum_ref is None else
                         runsum_ref[pl.program_id(0), qi, ki] == 1)
            s = _apply_masks(s, qi, ki, bq, bk, causal, kv_len,
                             mask_ref, off_ref, seg, pos, mask_live,
                             window, slope)
            p = jnp.exp2(s - lse_ref[0])                    # (BQ, BK)
            dp = jax.lax.dot_general(
                g, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # (BQ, BK)
            if dropout is not None:
                # Same element-coordinate mask as the forward; Δ already
                # equals rowsum(m̃·a ⊙ dp) by the rowsum(dO⊙O) identity.
                keep, inv = _dropout_keep(seed_ref, pid_b, qi, ki,
                                          bq, bk, dropout, off_ref, pos)
                dp = jnp.where(keep, dp, 0.0) * inv
            if quantized:
                k_op = (k_ref[0].astype(jnp.float32)
                        * skc_ref[0]).astype(v.dtype)
            else:
                k_op = k_ref[0]
            ds = (p * (dp - delta_ref[0])).astype(k_op.dtype)
            dq_acc[:] += scale * jax.lax.dot_general(
                ds, k_op, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # (BQ, d)

        @pl.when(last_k_cond)
        def _():
            dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)

    return kernel


def _make_dkv_kernel(scale, causal, bq, bk, kv_len, has_mask, has_seg,
                     has_pos, has_alibi, has_mask_skip, window=None,
                     band_fn=None, quantized=False, dropout=None,
                     trap=False, nqb=None):
    def kernel(*refs):
        if trap:
            tq_ref, tk_ref, qlo_ref, *refs = refs
        elif band_fn is not None:
            bandoff_ref, *refs = refs
        if has_mask_skip:
            runsum_ref, *refs = refs
        else:
            runsum_ref = None
        off_ref, *refs = refs
        if dropout is not None:
            seed_ref, *refs = refs
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
         *rest) = refs
        quant = None
        if quantized:
            sqf_ref, skr_ref, sqc_ref, skc_ref, *rest = rest
            quant = (sqf_ref, skr_ref)
        mask_ref, seg, pos, alibi_ref, rest = _split_aux(
            rest, has_mask, has_seg, has_pos, has_alibi)
        dk_ref, dv_ref, dk_acc, dv_acc = rest
        if trap:
            # Transposed trapezoid: K-major pair walk; each K block's Q
            # run starts at its first causally-visible Q block and always
            # ends at the bottom row block.
            p = pl.program_id(1)
            qi = tq_ref[p]
            kj = tk_ref[p]
            first_q = qi == qlo_ref[kj]
            last_q_cond = qi == nqb - 1
        else:
            kj = pl.program_id(1)
            qr = pl.program_id(2)
            # Banded: qr sweeps only the Q blocks whose window band
            # touches this K block; qi is the ACTUAL Q block index.
            qi = qr if band_fn is None else band_fn(kj, bandoff_ref[0]) + qr
            first_q = qr == 0
            last_q_cond = qr == pl.num_programs(2) - 1

        @pl.when(first_q)
        def _():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        pid_b = pl.program_id(0)  # hoisted: program_id inside a
        # pl.when body is not substituted by the plain interpreter
        slope = None if alibi_ref is None else alibi_ref[pid_b]
        run = _run_pred(causal, off_ref, qi, kj, bq, bk,
                        pl.program_id(0), seg, pos, runsum_ref, window)

        @pl.when(run)
        def _():
            # q_ref / lse_ref are pre-folded by ·(scale·log2e) / ·log2e as
            # in the dq kernel. dk wants scale·dsᵀ·q with the ORIGINAL q;
            # the dot below uses the folded q, so divide the accumulator
            # update by log2e once per (BK, d) block. Quantized: q is
            # dequantized in-block with its RAW row scales, so the update
            # multiplies by the plain softmax scale instead.
            v = v_ref[0]                                    # (BK, dv)
            g = g_ref[0]                                    # (BQ, dv)
            s = _score_block(q_ref, k_ref, quant)  # (BQ, BK), log2 units
            mask_live = (None if runsum_ref is None else
                         runsum_ref[pl.program_id(0), qi, kj] == 1)
            s = _apply_masks(s, qi, kj, bq, bk, causal, kv_len,
                             mask_ref, off_ref, seg, pos, mask_live,
                             window, slope)
            p = jnp.exp2(s - lse_ref[0])                    # (BQ, BK)
            p_num = p
            if dropout is not None:
                keep, inv = _dropout_keep(seed_ref, pid_b, qi, kj,
                                          bq, bk, dropout, off_ref, pos)
                p_num = jnp.where(keep, p, 0.0) * inv
            dv_acc[:] += jax.lax.dot_general(
                p_num.astype(g.dtype), g, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # (BK, dv)
            dp = jax.lax.dot_general(
                g, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # (BQ, BK)
            if dropout is not None:
                dp = jnp.where(keep, dp, 0.0) * inv
            if quantized:
                q_op = (q_ref[0].astype(jnp.float32)
                        * sqc_ref[0]).astype(v.dtype)
                dk_scale = scale
            else:
                q_op = q_ref[0]
                dk_scale = 1.0 / _LOG2E
            ds = (p * (dp - delta_ref[0])).astype(q_op.dtype)
            dk_acc[:] += dk_scale * jax.lax.dot_general(
                ds, q_op, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # (BK, d)

        @pl.when(last_q_cond)
        def _():
            dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)

    return kernel


def _flash_bwd_impl(q, k, v, mask, causal_offset, out, lse, g, scale,
                    causal, interpret, grad_dtype=None, segment_ids=None,
                    positions=None, window=None, alibi=None, qk_quant=None,
                    dropout_rate=0.0, dropout_seed=None, kv_offset=0,
                    only='both'):
    """Blockwise flash backward: dq pass + dk/dv pass, O(block²) score
    memory. Algebra: with ``p = exp(s − lse)`` (the softmax weights),
    ``dv = pᵀ·dO``, ``ds = p ⊙ (dO·vᵀ − Δ)`` where ``Δ = rowsum(dO ⊙ O)``,
    ``dq = scale·ds·k``, ``dk = scale·dsᵀ·q``.

    Empty-row cotangents need no explicit zeroing: with -inf masking the
    recomputed weights of such rows are exactly 0 (``lse`` clamps to
    ``_NEG_BIG``), so every gradient term dies in-kernel. ``grad_dtype``
    overrides the output gradient dtype (the ring path accumulates
    per-block grads across W steps and wants fp32 partials rather than W
    roundings to bf16).
    """
    *batch, tq, d = q.shape
    tk = k.shape[-2]
    d_v = v.shape[-1]
    if only == 'both' and _trap_eligible(causal, window, mask, positions,
                                         causal_offset, kv_offset,
                                         'exact', interpret):
        rel = int(causal_offset) - int(kv_offset)
        bq0, bk0 = _bwd_block_sizes(tq, tk, q.dtype, d_total=d + d_v)
        q_bounds = _trap_chunk_bounds(rel, tq, tk, bq0, bk0)
        k_bounds = _trap_chunk_bounds_t(rel, tq, tk, bq0, bk0)
        if max(len(q_bounds), len(k_bounds)) > 1:
            # Beyond-cap chunking: every chunk's output is a DISJOINT
            # slice (dq rows from Q chunks, dk/dv rows from K chunks),
            # so nothing is partial-summed and peak memory matches the
            # unchunked program (an earlier Q-only variant summed fp32
            # dk/dv partials per chunk and OOMed a 16 GiB chip at
            # T=512K). Each per-chunk call runs only its own pass.
            dqs = []
            for r0, r1 in q_bounds:
                seg = segment_ids
                if seg is not None:
                    seg = (seg[0][..., r0:r1], seg[1])
                dq_c, _, _ = _flash_bwd_impl(
                    q[..., r0:r1, :], k, v, None, causal_offset + r0,
                    out[..., r0:r1, :], lse[..., r0:r1],
                    g[..., r0:r1, :], scale, causal, interpret,
                    grad_dtype=grad_dtype, segment_ids=seg, alibi=alibi,
                    qk_quant=qk_quant, dropout_rate=dropout_rate,
                    dropout_seed=dropout_seed, kv_offset=kv_offset,
                    only='dq')
                dqs.append(dq_c)
            dks, dvs = [], []
            for c0, c1 in k_bounds:
                seg = segment_ids
                if seg is not None:
                    seg = (seg[0], seg[1][..., c0:c1])
                _, dk_c, dv_c = _flash_bwd_impl(
                    q, k[..., c0:c1, :], v[..., c0:c1, :], None,
                    causal_offset, out, lse, g, scale, causal, interpret,
                    grad_dtype=grad_dtype, segment_ids=seg, alibi=alibi,
                    qk_quant=qk_quant, dropout_rate=dropout_rate,
                    dropout_seed=dropout_seed, kv_offset=kv_offset + c0,
                    only='dkv')
                dks.append(dk_c)
                dvs.append(dv_c)
            return (jnp.concatenate(dqs, axis=-2),
                    jnp.concatenate(dks, axis=-2),
                    jnp.concatenate(dvs, axis=-2))
    nb = int(math.prod(batch)) if batch else 1
    kv_group = _kv_group(q, k)
    nbk = nb // kv_group

    off = jnp.stack([jnp.asarray(causal_offset, jnp.int32),
                     jnp.asarray(kv_offset, jnp.int32)]).reshape(1, 2)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # (*batch, Tq, 1)

    allow_redirect = (not interpret) or _REDIRECT_ON_INTERPRET
    streams_mask = mask is not None and _mask_streams_per_tile(
        nb, tq, tk, q.dtype, d + d_v, allow_redirect, bwd=True)
    bq, bk = _bwd_block_sizes(tq, tk, q.dtype, d_total=d + d_v,
                              has_mask=streams_mask)
    # Same exp2 pre-folding as the forward: q carries scale·log2e, lse is
    # converted to log2 units, so the kernels' (BQ, BK) score blocks need
    # no per-element multiply.
    quantized = qk_quant == 'int8'
    if quantized:
        # Recompute the SAME quantization as the forward (deterministic),
        # so the rebuilt p matches the saved lse exactly; gradients are
        # straight-through in the rounding (the standard treatment).
        qi8, sq = _quantize_rows(q, nb, tq, d)
        ki8, sk = _quantize_rows(k, nbk, tk, d)
        qf = _pad_dim(qi8, 1, bq)
        kf = _pad_dim(ki8, 1, bk)
        sqf = _pad_dim(sq * (scale * _LOG2E), 1, bq)
        skr = _pad_dim(jnp.swapaxes(sk, 1, 2), 2, bk)
        sqc = _pad_dim(sq, 1, bq)                # raw: in-kernel dequant
        skc = _pad_dim(sk, 1, bk)
    else:
        q2 = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
        qf = _pad_dim(q2.reshape(nb, tq, d), 1, bq)
        kf = _pad_dim(k.reshape(nbk, tk, d), 1, bk)
    vf = _pad_dim(v.reshape(nbk, tk, d_v), 1, bk)
    gf = _pad_dim(g.reshape(nb, tq, d_v), 1, bq)            # zero-padded
    # Clamp: a fully-masked row's lse is ln2·_NEG_BIG, whose ·log2e
    # conversion overflows fp32 to -inf — and the kernels' recompute
    # exp2(s − lse₂) with s = -inf (masked) would then be NaN. Clamped to
    # the (finite) _NEG_BIG shift, masked entries recompute p = 0 exactly.
    lsef = _pad_dim(jnp.maximum(lse * _LOG2E, _NEG_BIG)
                    .reshape(nb, tq, 1), 1, bq)
    deltaf = _pad_dim(delta.reshape(nb, tq, 1), 1, bq)
    tq_p, tk_p = qf.shape[1], kf.shape[1]

    args = [qf, kf, vf, gf, lsef, deltaf]
    if quantized:
        args += [sqf, skr, sqc, skc]
    nqb, nkb = tq_p // bq, tk_p // bk

    # Banded window grids (see _flash_fwd_impl): the dq pass sweeps only
    # each Q block's K band; the dk/dv pass sweeps only each K block's Q
    # band (the transposed band, width ~window/bq).
    banded = (window is not None and causal and mask is None
              and positions is None
              and ((not interpret) or _BAND_ON_INTERPRET))
    kband_fn = qband_fn = bandoff = kof = qot = None
    trap = trap_pre = trap_pre_t = None
    if not banded and _trap_eligible(causal, window, mask, positions,
                                     causal_offset, kv_offset, 'exact',
                                     interpret):
        rel = int(causal_offset) - int(kv_offset)
        tabs = _trap_tables(rel, nqb, nkb, bq, bk)
        tabs_t = _trap_tables_t(rel, nqb, nkb, bq, bk)
        if max(tabs[0].shape[0], tabs_t[0].shape[0]) <= _TRAP_MAX_PAIRS:
            trap = True
            trap_pre = list(tabs)
            trap_pre_t = list(tabs_t)
    if banded:
        kband = _band_size(bq, bk, window, nkb)
        qband = _band_size(bk, bq, window, nqb)

        def kband_fn(i, off_s):
            return _band_lo((off_s + i * bq - (window - 1)) // bk,
                            nkb, kband)

        def qband_fn(j, off_s):
            # First Q block with a causal view of K block j:
            # ceil((j·bk − off − bq + 1)/bq) = floor((j·bk − off)/bq).
            return _band_lo((j * bk - off_s) // bq, nqb, qband)

        # Single source of truth for each grid's band translation — the
        # main BlockSpec maps and the aux (segment) maps derive from
        # these (rs[0] is the prefetched global row offset).
        def kof(b, i, j, rs):
            return kband_fn(i, rs[0][0]) + j

        def qot(b, j, i, rs):
            return qband_fn(j, rs[0][0]) + i
        bandoff = (off[0, 0] - off[0, 1]).reshape(1)
    k_map = lambda b, i, j, *rs: (  # noqa: E731
        b // kv_group, j if kof is None else kof(b, i, j, rs), 0)
    # dk/dv are computed as PER-Q-HEAD partials (the K/V INPUT blocks are
    # group-shared via b // kv_group, the outputs are not) and group-summed
    # after the call — the sequential grid cannot carry one accumulator
    # across the group's separated kj sweeps.
    kv_map_t = lambda b, j, i, *rs: (b // kv_group, j, 0)  # noqa: E731
    q_map_t = lambda b, j, i, *rs: (  # noqa: E731
        b, i if qot is None else qot(b, j, i, rs), 0)

    aux_specs, aux_specs_t, aux_args, flags, runsum = _aux_setup(
        mask, segment_ids, positions, batch, tq, tk, tq_p, tk_p, bq, bk,
        allow_redirect=allow_redirect, k_of=kof, q_of_t=qot,
        alibi=(None if alibi is None else alibi * _LOG2E))

    off_spec = pl.BlockSpec((1, 2), lambda b, i, j, *rs: (0, 0))
    dropout = float(dropout_rate) if dropout_rate else None
    seed_specs, seed_args = [], []
    if dropout is not None:
        seed_specs = [pl.BlockSpec((1, 1), lambda b, i, j, *rs: (0, 0))]
        seed_args = [jnp.asarray(dropout_seed, jnp.int32).reshape(1, 1)]

    quant_specs = quant_specs_t = []
    if quantized:
        def _kj(b, i, j, rs):
            return j if kof is None else kof(b, i, j, rs)
        quant_specs = [
            pl.BlockSpec((1, bq, 1), lambda b, i, j, *rs: (b, i, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, i, j, *rs: (
                b // kv_group, 0, _kj(b, i, j, rs))),
            pl.BlockSpec((1, bq, 1), lambda b, i, j, *rs: (b, i, 0)),
            pl.BlockSpec((1, bk, 1), lambda b, i, j, *rs: (
                b // kv_group, _kj(b, i, j, rs), 0)),
        ]
        quant_specs_t = [
            pl.BlockSpec((1, bq, 1), q_map_t),
            pl.BlockSpec((1, 1, bk), lambda b, j, i, *rs: (
                b // kv_group, 0, j)),
            pl.BlockSpec((1, bq, 1), q_map_t),
            pl.BlockSpec((1, bk, 1), lambda b, j, i, *rs: (
                b // kv_group, j, 0)),
        ]

    # --- dq pass: grid (batch, Q block, K band), K innermost ---
    dq = dk = dv = None
    if only in ('both', 'dq'):
        dq_in_specs = [
            off_spec,
            *seed_specs,
            pl.BlockSpec((1, bq, d), lambda b, i, j, *rs: (b, i, 0)),
            pl.BlockSpec((1, bk, d), k_map),
            pl.BlockSpec((1, bk, d_v), k_map),
            pl.BlockSpec((1, bq, d_v), lambda b, i, j, *rs: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j, *rs: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j, *rs: (b, i, 0)),
        ] + quant_specs + aux_specs
        dq_out_spec = pl.BlockSpec((1, bq, d),
                                   lambda b, i, j, *rs: (b, i, 0))
        if trap:
            dq_grid = (nb, int(trap_pre[0].shape[0]))
            dq_in_specs = _wrap_specs_pairs(dq_in_specs)
            dq_out_spec = _wrap_specs_pairs([dq_out_spec])[0]
        else:
            dq_grid = (nb, nqb, kband if banded else nkb)
        dq = _pallas_call(
            _make_dq_kernel(scale, causal, bq, bk, tk, *flags,
                            window=window, band_fn=kband_fn,
                            quantized=quantized, dropout=dropout,
                            trap=bool(trap)),
            dq_grid, dq_in_specs, dq_out_spec,
            [pltpu.VMEM((bq, d), jnp.float32)],
            jax.ShapeDtypeStruct((nb, tq_p, d), grad_dtype or q.dtype),
            interpret, trap_pre if trap else [bandoff, runsum],
        )(off, *seed_args, *args, *aux_args)
        dq = dq[:, :tq].reshape(q.shape)

    # --- dk/dv pass: grid (batch, K block, Q band), Q innermost ---
    if only in ('both', 'dkv'):
        dkv_in_specs = [
            off_spec,
            *seed_specs,
            pl.BlockSpec((1, bq, d), q_map_t),
            pl.BlockSpec((1, bk, d), kv_map_t),
            pl.BlockSpec((1, bk, d_v), kv_map_t),
            pl.BlockSpec((1, bq, d_v), q_map_t),
            pl.BlockSpec((1, bq, 1), q_map_t),
            pl.BlockSpec((1, bq, 1), q_map_t),
        ] + quant_specs_t + aux_specs_t
        dkv_out_specs = [
            pl.BlockSpec((1, bk, d), lambda b, j, i, *rs: (b, j, 0)),
            pl.BlockSpec((1, bk, d_v), lambda b, j, i, *rs: (b, j, 0)),
        ]
        if trap:
            dkv_grid = (nb, int(trap_pre_t[0].shape[0]))
            dkv_in_specs = _wrap_specs_pairs(dkv_in_specs, transposed=True)
            dkv_out_specs = _wrap_specs_pairs(dkv_out_specs,
                                              transposed=True)
        else:
            dkv_grid = (nb, nkb, qband if banded else nqb)
        dk, dv = _pallas_call(
            _make_dkv_kernel(scale, causal, bq, bk, tk, *flags,
                             window=window, band_fn=qband_fn,
                             quantized=quantized, dropout=dropout,
                             trap=bool(trap), nqb=nqb),
            dkv_grid, dkv_in_specs, dkv_out_specs,
            [pltpu.VMEM((bk, d), jnp.float32),
             pltpu.VMEM((bk, d_v), jnp.float32)],
            [
                jax.ShapeDtypeStruct((nb, tk_p, d), grad_dtype or k.dtype),
                jax.ShapeDtypeStruct((nb, tk_p, d_v),
                                     grad_dtype or v.dtype),
            ],
            interpret, trap_pre_t if trap else [bandoff, runsum],
        )(off, *seed_args, *args, *aux_args)

        dk = dk[:, :tk]
        dv = dv[:, :tk]
        if kv_group > 1:
            # Group members are consecutive flat q-batch indices (head
            # axis is the innermost lead dim): sum each group's partials
            # in fp32.
            dk = dk.reshape(nbk, kv_group, tk, d).astype(jnp.float32
                                                         ).sum(1)
            dv = dv.reshape(nbk, kv_group, tk, d_v).astype(jnp.float32
                                                           ).sum(1)
            dk = dk.astype(grad_dtype or k.dtype)
            dv = dv.astype(grad_dtype or v.dtype)
        dk = dk.reshape(k.shape)
        dv = dv.reshape(v.shape)
    return dq, dk, dv


def _reference_math(q, k, v, mask, scale, causal):
    """Identical math in jnp — the test oracle."""
    tq, tk = q.shape[-2], k.shape[-2]
    s = jnp.einsum('...td,...od->...to', q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    if mask is not None:
        s = jnp.where(mask, _NEG_BIG, s)
    if causal:
        future = jnp.arange(tq)[:, None] < jnp.arange(tk)[None, :]
        s = jnp.where(future, _NEG_BIG, s)
    attn = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('...to,...od->...td', attn, v.astype(jnp.float32))
    if mask is not None:
        out = jnp.where(_row_has_valid(mask, causal, tq, tk), out, 0.0)
    return out.astype(v.dtype)


def _seg_pair(seg_q, seg_k):
    return None if seg_q is None else (seg_q, seg_k)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(12, 13, 14, 15, 16, 17, 18))
def _flash(q, k, v, mask, causal_offset, kv_offset, seg_q, seg_k, pos_q,
           pos_k, alibi, dropout_seed, scale, causal, interpret, mode,
           window, qk_quant, dropout_rate):
    return _flash_fwd_impl(q, k, v, mask, causal_offset, scale, causal,
                           interpret, mode,
                           segment_ids=_seg_pair(seg_q, seg_k),
                           positions=_seg_pair(pos_q, pos_k),
                           window=window, alibi=alibi, qk_quant=qk_quant,
                           dropout_rate=dropout_rate,
                           dropout_seed=dropout_seed, kv_offset=kv_offset)


def _flash_fwd(q, k, v, mask, causal_offset, kv_offset, seg_q, seg_k,
               pos_q, pos_k, alibi, dropout_seed, scale, causal, interpret,
               mode, window, qk_quant, dropout_rate):
    out, lse = _flash_fwd_impl(q, k, v, mask, causal_offset, scale, causal,
                               interpret, mode, save_lse=True,
                               segment_ids=_seg_pair(seg_q, seg_k),
                               positions=_seg_pair(pos_q, pos_k),
                               window=window, alibi=alibi,
                               qk_quant=qk_quant,
                               dropout_rate=dropout_rate,
                               dropout_seed=dropout_seed,
                               kv_offset=kv_offset)
    return out, (q, k, v, mask, causal_offset, kv_offset, seg_q, seg_k,
                 pos_q, pos_k, alibi, dropout_seed, out, lse)


def _flash_bwd(scale, causal, interpret, mode, window, qk_quant,
               dropout_rate, res, g):
    # The backward is mode-independent: lse = log Σ exp(s) is invariant to
    # the forward's shift choice, and the bwd kernels recompute p from it.
    (q, k, v, mask, causal_offset, kv_offset, seg_q, seg_k, pos_q, pos_k,
     alibi, dropout_seed, out, lse) = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, mask, causal_offset, out, lse, g,
                                 scale, causal, interpret,
                                 segment_ids=_seg_pair(seg_q, seg_k),
                                 positions=_seg_pair(pos_q, pos_k),
                                 window=window, alibi=alibi,
                                 qk_quant=qk_quant,
                                 dropout_rate=dropout_rate,
                                 dropout_seed=dropout_seed,
                                 kv_offset=kv_offset)
    return (dq, dk, dv, None, None, None, None, None, None, None, None,
            None)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, mask=None, *, causal=False, causal_offset=0,
                    kv_offset=0, scale=None, interpret=None,
                    softmax_mode='exact', segment_ids=None, positions=None,
                    window=None, alibi_slopes=None, qk_quant=None,
                    dropout_rate=0.0, dropout_seed=None):
    """Fused attention ``softmax(q·kᵀ·scale [+mask])·v`` as TPU kernels.

    ``q (..., Tq, d)``, ``k (..., Tk, d)``, ``v (..., Tk, d_v)``; optional
    boolean ``mask (..., Tq, Tk)`` broadcastable over the leading dims
    (True = masked out, the reference's convention, reference README.md:67).

    Grouped-query attention (GQA/MQA): k/v may carry FEWER heads than q —
    lead dims equal except the head axis (-3), q heads divisible by kv
    heads (``Hkv = 1`` is multi-query). Each group of ``Hq/Hkv``
    consecutive q heads attends the same K/V head; K/V HBM residency is
    O(Hkv·T·d). Backward returns kv-head-shaped dk/dv (per-q-head
    partials group-summed in fp32). No reference analog.
    ``segment_ids``: the compact packed-sequence mask form — a
    ``(seg_q, seg_kv)`` pair of non-negative int arrays with trailing
    shapes ``(Tq,)`` / ``(Tk,)`` (leading dims broadcastable like the
    mask's), or a single ``(..., T)`` array used for both sides when
    ``Tq == Tk``. Positions in different segments don't attend — the same
    semantics as the dense ``mask[i, j] = seg_q[i] != seg_kv[j]`` with
    O(T) instead of O(Tq·Tk) HBM traffic, and (Q block, K block) pairs
    with provably disjoint id ranges are skipped outright. Composes with
    ``mask`` and ``causal`` (union of maskings); rows left with no
    attendable key output 0 with zero gradients.

    ``positions``: causal masking over EXPLICIT global positions — a
    ``(pos_q, pos_kv)`` pair (or single array, same rules as
    ``segment_ids``) of non-negative ints; pair (i, j) is masked when
    ``pos_q[i] < pos_kv[j]``. This is ``causal=True`` generalized to
    arbitrary row layouts (zigzag/striped sequence sharding, where a
    shard's rows are not one contiguous run and a scalar
    ``causal_offset`` cannot describe them); blocks whose positions are
    provably all-future are skipped like the contiguous causal skip.
    Mutually exclusive with ``causal``; composes with ``mask`` and
    ``segment_ids``.

    ``dropout_rate``/``dropout_seed``: attention-weight dropout
    (inverted scaling, applied to the normalized weights) with the mask
    generated IN-KERNEL as a pure hash of (seed, batch, global element
    coordinates) — no O(Tq·Tk) mask tensor, no RNG state, and because
    the mask depends only on element coordinates it is identical across
    block decompositions (the backward's blocks legitimately differ),
    grid orders AND backends: a given seed reproduces the same mask on
    CPU and TPU. The seed is explicit (int or traced int32 scalar;
    derive it from your ``jax.random`` key).

    ``qk_quant='int8'``: per-row symmetric int8 quantization of q and k —
    the score matmul runs on the MXU's int8 path (2× the bf16 rate raw;
    measured end-to-end it wins only at LARGE head dim, e.g. ~+11% at
    d=256, because the per-block dequant multiplies cost VPU time and at
    small d the kernel is VPU-bound anyway). This is a deliberate,
    self-consistent approximation: outputs differ from the exact kernel
    by int8 rounding noise (~1% of row scale), and the VJP is exactly the
    straight-through gradient of the quantized forward (verified against
    a dense STE oracle). Composes with every mask form, GQA and windows;
    ``softmax_mode='bounded'`` falls back to exact.

    ``alibi_slopes``: ALiBi — per-head additive bias
    ``slope·(pos_k − pos_q)`` on the logits (lead dims broadcastable
    against q/k/v's, e.g. ``(H,)``; the classic geometric slopes are the
    user's choice). Needs ``causal=True`` or ``positions`` so the kernel
    knows global positions; computed in-kernel from the same position
    arithmetic as the causal triangle, so it costs no O(T²) input.
    Treated as a constant in the VJP (no slope gradients — standard
    ALiBi trains them frozen). With ``softmax_mode='bounded'`` the exact
    kernel runs instead (the norm bound does not cover the bias term).

    ``window``: sliding-window (local) attention — a static positive int;
    query at global position ``p`` attends only keys in
    ``(p − window, p]``. Requires causal semantics (``causal=True`` or
    ``positions``), composing as the intersection; K blocks wholly past
    the window are skipped via the same SMEM tables as the causal skip,
    so compute AND HBM traffic drop to O(Tq·window) — long-context cost
    becomes linear in T. No reference analog (its module materializes
    every (T/N, T) score row, reference module.py:66-67).

    Differentiable end-to-end with blockwise Pallas kernels in both
    directions — peak memory is O(T·d) for forward AND backward (the
    backward recomputes score blocks from the saved row logsumexp).
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU so the
    CPU test mesh runs the same code.

    ``causal_offset``: the GLOBAL index of query row 0 (int or traced
    scalar, e.g. ``lax.axis_index(...) * (T // N)`` under ``shard_map``) —
    lets sequence-sharded callers run causal attention of local query rows
    against gathered keys with no materialized O(Tq·Tk) triangle; the
    causal comparison and the block-skip predicate use
    ``causal_offset + row`` as the global row position. ``kv_offset`` is
    the same for key column 0 — callers whose k/v slab is itself a slice
    of a longer global sequence (the ring path's rotating blocks) pass it
    so causal masking AND the dropout hash see true global columns.

    ``softmax_mode``:

    - ``'exact'`` (default): numerically-stable online softmax with a
      running row max — safe for any input magnitudes.
    - ``'bounded'``: replaces the running max with the per-row
      Cauchy-Schwarz bound ``scale·‖q_i‖·max_j‖k_j‖``, removing the most
      expensive VPU op of the kernel (~15% faster at small head dim).
      Softmax is shift-invariant, so this changes results only through
      fp32 underflow of weights far below the bound; a built-in guard
      runs the exact kernel instead whenever the worst-case gap
      (``2·scale·log2e·max‖q‖·max‖k‖``, e.g. huge-norm yet near-orthogonal
      q/k) could reach fp32's exponent limits — 'bounded' is an
      optimization hint, never a correctness trade. Typical normalized
      activations stay far under the threshold and take the fast path.
    """
    if softmax_mode not in ('exact', 'bounded'):
        raise ValueError(f"softmax_mode must be 'exact' or 'bounded', "
                         f'got {softmax_mode!r}')
    if v.shape[:-2] != k.shape[:-2] or v.shape[-2] != k.shape[-2]:
        raise ValueError(
            f'k and v must agree on lead dims and Tk; got k {k.shape}, '
            f'v {v.shape}')
    _kv_group(q, k)  # validate GQA lead-dim contract up front
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'

    def _pair(value, name):
        if value is None:
            return None, None
        if isinstance(value, (tuple, list)):
            return value
        if q.shape[-2] != k.shape[-2]:
            raise ValueError(
                f'a single {name} array needs Tq == Tk; pass a '
                f'(q-side, kv-side) pair for cross-length attention')
        return value, value

    seg_q, seg_k = _pair(segment_ids, 'segment_ids')
    pos_q, pos_k = _pair(positions, 'positions')
    if positions is not None and causal:
        raise ValueError(
            'positions IS causal masking (over explicit global positions) '
            '— pass one or the other, not both')
    if window is not None:
        if not isinstance(window, int) or window < 1:
            raise ValueError(f'window must be a positive int, got {window!r}')
        if not causal and positions is None:
            raise ValueError(
                'window is a lookback cap and needs causal semantics: pass '
                'causal=True (contiguous rows) or positions (explicit '
                'layouts)')
    if alibi_slopes is not None:
        alibi_slopes = jnp.asarray(alibi_slopes, jnp.float32)
        if not causal and positions is None:
            raise ValueError(
                'alibi_slopes bias by relative GLOBAL position: pass '
                'causal=True (contiguous rows) or positions (explicit '
                'layouts) so the kernel knows the positions')
    if qk_quant not in (None, 'int8'):
        raise ValueError(f"qk_quant must be None or 'int8', "
                         f'got {qk_quant!r}')
    dropout_rate = float(dropout_rate)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f'dropout_rate must be in [0, 1), '
                         f'got {dropout_rate}')
    if dropout_rate and dropout_seed is None:
        raise ValueError(
            'dropout needs an explicit dropout_seed (int or traced int32 '
            'scalar) — the kernel holds no hidden RNG state; derive it '
            'from your jax.random key, e.g. '
            'jax.random.randint(key, (), 0, 2**31 - 1)')
    return _flash(q, k, v, mask, causal_offset, kv_offset, seg_q, seg_k,
                  pos_q, pos_k, alibi_slopes, dropout_seed, float(scale),
                  bool(causal), bool(interpret), softmax_mode, window,
                  qk_quant, dropout_rate)


def graphlint_entrypoints():
    """Static-analysis registration hook (analysis/registry.py): the
    fused flash kernels at bf16 — THE paths whose fp32-accumulation
    contract the f32-accum rule encodes (every in-kernel dot_general
    must carry preferred_element_type=f32, int8 scoring i32). The
    linter descends into the pallas_call jaxprs, so a regression inside
    a kernel body is caught even though the kernel is one opaque
    primitive to XLA."""
    from functools import partial

    def _sds(*shape, dtype='bfloat16'):
        import jax
        import jax.numpy as jnp
        return jax.ShapeDtypeStruct(shape, jnp.dtype(dtype))

    def fwd_bf16():
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        q = _sds(1, 2, 16, 8)
        return TraceSpec(name='ops.flash_fwd_bf16',
                         fn=partial(flash_attention, causal=True),
                         args=(q, q, q))

    def bwd_bf16():
        import jax
        import jax.numpy as jnp
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )

        def loss(q, k, v):
            out = flash_attention(q, k, v, causal=True)
            return jnp.sum(out.astype(jnp.float32))

        q = _sds(1, 2, 16, 8)
        return TraceSpec(name='ops.flash_bwd_bf16',
                         fn=jax.grad(loss, argnums=(0, 1, 2)),
                         args=(q, q, q))

    def fwd_int8():
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        q = _sds(1, 2, 16, 8)
        return TraceSpec(name='ops.flash_fwd_int8',
                         fn=partial(flash_attention, causal=True,
                                    qk_quant='int8'),
                         args=(q, q, q))

    return {
        'ops.flash_fwd_bf16': fwd_bf16,
        'ops.flash_bwd_bf16': bwd_bf16,
        'ops.flash_fwd_int8': fwd_int8,
    }
