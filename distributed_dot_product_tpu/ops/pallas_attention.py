# -*- coding: utf-8 -*-
"""
Fused flash-attention Pallas TPU kernels (the hot-op fusion layer).

The reference computes attention as four separate eager ops — scores matmul,
mask fill, softmax, context matmul (reference module.py:60-69) — each
reading/writing the full ``(*, T/N, T)`` score tensor through device memory.
XLA fuses the elementwise pieces; these kernels fuse the *whole* chain in
VMEM with an online softmax, so score blocks never touch HBM: traffic drops
from O(T²) to O(T·d) and live score memory from O(Tq·Tk) to
O(BLOCK_Q·BLOCK_K) — in BOTH directions. The backward is the standard
flash recompute strategy as two Pallas kernels (a dq pass and a dk/dv
pass): score blocks are re-derived from q/k and the saved row logsumexp,
so training memory is O(T·d) too, not O(T²).

No reference analog (SURVEY §7 step 6 names this as the post-parity
performance pass). Layout, per the TPU Pallas playbook:

- forward grid = (batch·heads, Tq/BLOCK_Q, Tk/BLOCK_K) with the K sweep
  innermost — TPU grids run sequentially, so the running
  ``(max, denom, numerator)`` accumulators live in VMEM scratch across K
  steps; only one ``(BLOCK, d)`` tile of K/V is resident at a time (Pallas
  double-buffers the HBM→VMEM streams), so sequence length is bounded by
  HBM, not VMEM;
- backward dq grid sweeps K innermost with a dq accumulator; the dk/dv
  grid transposes the sweep (Q innermost) with dk/dv accumulators — each
  pass recomputes ``p = exp(s − lse)`` from the residuals ``(q, k, lse)``
  and contracts with the standard flash-backward algebra
  ``ds = p · (dp − Δ)``, ``Δ = rowsum(dO ⊙ O)``;
- all matmuls hit the MXU with fp32 accumulation
  (``preferred_element_type``) whatever the input dtype; block shapes are
  lane(128)/sublane aligned;
- causal programs whose whole K block lies in the masked future skip the
  matmuls entirely (``pl.when``) — ~2× for causal attention, forward and
  backward;
- masked logits use a large-finite negative (not ``-inf``) and fully-masked
  rows return 0 with zero gradients, matching
  :mod:`distributed_dot_product_tpu.models.ring_attention` semantics (the
  reference NaNs on fully-masked rows, SURVEY §4).

On non-TPU backends (the 8-virtual-device CPU test mesh) the kernels run in
Pallas interpreter mode, so the identical code paths are covered by the
regular test suite.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
# pltpu is importable (pure Python) even off-TPU; the interpreter emulates
# VMEM scratch on CPU.
from jax.experimental.pallas import tpu as pltpu

__all__ = ['flash_attention']

_NEG_BIG = -0.7 * 3.4e38  # large-finite fp32; keeps exp()/VJP NaN-free


def _block_sizes(tq, tk, dtype, d_total=128, has_mask=False):
    """Measured on v5e (T=16K, d=64, bf16): 1024×1024 blocks hit
    ~76 TFLOP/s vs ~38 at 512×512; 2048×2048 exceeds VMEM. Halve the Q
    block when the head dims are large — or when a mask is present
    (Mosaic widens bool blocks to s32 in VMEM, so a (1024, 1024) mask
    block alone is 4 MB of the ~16 MB scoped budget)."""
    sub = 16 if dtype == jnp.bfloat16 else 8
    cap_q = 1024 if d_total <= 256 and not has_mask else 512
    bq = min(cap_q, max(sub, -(-tq // sub) * sub))
    bk = min(1024, max(128 if tk >= 128 else sub,
                       -(-tk // sub) * sub))
    return bq, bk


def _bwd_block_sizes(tq, tk, dtype, d_total=128, has_mask=False):
    """The backward keeps more tiles live per program (q, k, v, dO, plus
    the p/dp/ds score blocks and the dk/dv accumulators). Measured on v5e
    (T=16K, d=64, bf16): 1024×1024 runs the fwd+bwd chain 17% faster than
    512×512 and still fits VMEM; halve when the head dims are large or a
    (s32-widened) mask block joins the working set."""
    sub = 16 if dtype == jnp.bfloat16 else 8
    cap_q = 1024 if d_total <= 256 and not has_mask else 256
    cap_k = 1024 if d_total <= 256 and not has_mask else 512
    bq = min(cap_q, max(sub, -(-tq // sub) * sub))
    bk = min(cap_k, max(128 if tk >= 128 else sub,
                        -(-tk // sub) * sub))
    return bq, bk


def _pad_dim(x, axis, mult):
    size = x.shape[axis]
    target = -(-size // mult) * mult
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad)


def _apply_masks(s, qi, ki, bq, bk, causal, kv_len, mask_ref, off_ref):
    """Shared logit masking: user mask block, causal future, Tk padding.

    The mask arrives as int8 (1 = masked): Mosaic widens bool kernel
    operands to s32 — a full-size O(4·Tq·Tk) HBM copy — but takes int8
    blocks natively. ``off_ref`` (scalar, (1, 1) int32) holds the GLOBAL
    index of query row 0 — sequence-sharded callers pass their shard's
    offset so the causal triangle is over global positions with no
    materialized mask.
    """
    if mask_ref is not None:
        s = jnp.where(mask_ref[0] != 0, _NEG_BIG, s)
    if causal:
        rows = (off_ref[0, 0] + qi * bq
                + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
        cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(rows < cols, _NEG_BIG, s)
    if kv_len % bk:
        cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(cols >= kv_len, _NEG_BIG, s)
    return s


def _causal_run(causal, off_ref, qi, ki, bq, bk):
    """Block-skip predicate: does this (Q block, K block) pair contain any
    un-masked causal entry? With a traced row offset this is a dynamic
    scalar — ``pl.when`` still skips the matmuls at run time."""
    if not causal:
        return True
    return off_ref[0, 0] + (qi + 1) * bq - 1 >= ki * bk


def _row_has_valid(mask, causal, tq, tk, row_offset=0):
    """(..., Tq, 1) bool: does row i have ANY attendable key, counting the
    causal restriction too? Rows without one output 0 with zero gradients
    (in every softmax path — the kernels' semantics must not depend on
    WHICH mask made the row empty). ``row_offset`` is the global index of
    row 0 (sequence-sharded callers pass their shard offset)."""
    valid = ~mask
    if causal:
        rows = row_offset + jnp.arange(tq)
        allowed = rows[:, None] >= jnp.arange(tk)[None, :]
        valid = jnp.logical_and(valid, allowed)
    return jnp.any(valid, axis=-1, keepdims=True)


def _mask_setup(mask, batch, tq, tk, tq_p, tk_p):
    """Validate mask broadcasting and flatten it WITHOUT materializing the
    broadcast: returns the padded flat mask and a flat-batch-index map
    (folded into the BlockSpec) that skips size-1 mask axes.

    Padding rows/cols are set True (masked) so padded K columns never
    contribute and padded Q rows recompute as fully-masked (their
    cotangents are zero-padded anyway).
    """
    if mask.ndim - 2 > len(batch):
        # More leading dims than q/k/v: the output batch shape comes solely
        # from q/k/v, so NumPy-style broadcasting cannot apply — reject
        # instead of silently indexing only mask[0].
        raise ValueError(
            f'mask has {mask.ndim - 2} leading dims but q/k/v have '
            f'{len(batch)}; a mask may not add batch dims')
    mlead = (1,) * (len(batch) - (mask.ndim - 2)) + mask.shape[:-2]
    if mask.shape[-2:] != (tq, tk):
        raise ValueError(
            f'mask trailing dims {mask.shape[-2:]} must equal '
            f'(Tq, Tk) = {(tq, tk)}')
    for db, dm in zip(batch, mlead):
        if dm not in (1, db):
            raise ValueError(
                f'mask leading dims {mask.shape[:-2]} do not broadcast '
                f'against q/k/v leading dims {tuple(batch)}')
    nm = int(math.prod(mlead)) if mlead else 1
    # int8, not bool: see _apply_masks. Padding rows/cols are masked (1).
    maskf = jnp.pad(mask.reshape(nm, tq, tk).astype(jnp.int8),
                    ((0, 0), (0, tq_p - tq), (0, tk_p - tk)),
                    constant_values=1)

    # Row-major strides of the mask's leading dims inside the batch.
    midx_strides = []
    stride = 1
    for db, dm in zip(reversed(batch), reversed(mlead)):
        midx_strides.append(0 if dm == 1 else stride)
        stride *= dm
    midx_strides.reverse()

    def mask_batch_index(b):
        out = 0
        rem = b
        for db, st in zip(reversed(batch), reversed(midx_strides)):
            out = out + (rem % db) * st
            rem = rem // db
        return out

    return maskf, mask_batch_index


_LOG2E = math.log2(math.e)
_LN2 = math.log(2.0)
# softmax_mode='bounded' safety threshold: with worst-case
# bound − true_rowmax ≤ 100 log2 units, the max softmax weight is
# ≥ 2^-100 — above TPU's flush-to-zero line (2^-126) with ≥26 log2 units
# left for the tail, i.e. only weights < 2^-26 relative are lost.
_BOUNDED_SAFE_GAP = 100.0


def _make_fwd_kernel(causal, bq, bk, kv_len, has_mask, save_lse):
    def kernel(*refs):
        if has_mask:
            off_ref, q_ref, k_ref, v_ref, mask_ref, *rest = refs
        else:
            off_ref, q_ref, k_ref, v_ref, *rest = refs
            mask_ref = None
        if save_lse:
            o_ref, lse_ref, m_s, l_s, acc_s = rest
        else:
            (o_ref, m_s, l_s, acc_s), lse_ref = rest, None
        qi = pl.program_id(1)
        ki = pl.program_id(2)
        last_k = pl.num_programs(2) - 1

        @pl.when(ki == 0)
        def _():
            m_s[:] = jnp.full_like(m_s, _NEG_BIG)
            l_s[:] = jnp.zeros_like(l_s)
            acc_s[:] = jnp.zeros_like(acc_s)

        # Causal block skip: the whole K block is strictly in the future of
        # every query row of this program → contributes nothing.
        run = _causal_run(causal, off_ref, qi, ki, bq, bk)

        @pl.when(run)
        def _():
            # Keep matmul operands in their native dtype (bf16 in, fp32
            # accumulate) — upcasting to fp32 before the dot halves MXU
            # throughput. The softmax scale and exp's internal log2(e)
            # multiply are BOTH pre-folded into q by the wrapper (the
            # "exp2 trick"), so the only per-score-element VPU work here
            # is max / subtract / exp2 / sum / downcast — at small head
            # dim the kernel is VPU-bound and each removed op is ~15%.
            q = q_ref[0]                                    # (BQ, d)
            k = k_ref[0]                                    # (BK, d)
            v = v_ref[0]                                    # (BK, dv)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (BQ, BK), log2 units
            s = _apply_masks(s, qi, ki, bq, bk, causal, kv_len,
                             mask_ref, off_ref)

            m_prev = m_s[:]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp2(s - m_new)
            corr = jnp.exp2(m_prev - m_new)
            m_s[:] = m_new
            l_s[:] = l_s[:] * corr + p.sum(axis=-1, keepdims=True)
            acc_s[:] = acc_s[:] * corr + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(ki == last_k)
        def _():
            l = l_s[:]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            out = acc_s[:] / safe_l
            # l == 0 happens only for causal rows before any valid column of
            # a fully-skipped prefix (impossible: block (qi,0) always runs)
            # or for fully-masked rows, which must return 0 (parity with
            # ring_attention; the reference NaNs here, SURVEY §4). With
            # large-finite mask bias, fully-masked rows have l >= eps but
            # garbage weights — zero them via the mask below in the wrapper.
            o_ref[0] = out.astype(o_ref.dtype)
            if save_lse:
                # Convert from log2 back to natural-log units for the
                # backward: lse = ln2·(m₂ + log2 l) = m + ln l.
                lse_ref[0] = _LN2 * (m_s[:] + jnp.log2(safe_l))

    return kernel


def _flash_fwd_impl(q, k, v, mask, causal_offset, scale, causal, interpret,
                    mode='exact', save_lse=False):
    *batch, tq, d = q.shape
    tk = k.shape[-2]
    d_v = v.shape[-1]
    nb = int(math.prod(batch)) if batch else 1
    # Scalar (1, 1) int32 input: the global index of query row 0 (possibly
    # traced, e.g. lax.axis_index under shard_map). Always fed — a dead
    # scalar read costs nothing and keeps the kernel signatures uniform.
    off = jnp.asarray(causal_offset, jnp.int32).reshape(1, 1)
    off_spec = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0))

    bq, bk = _block_sizes(tq, tk, q.dtype, d_total=d + d_v,
                          has_mask=mask is not None)
    # exp2 trick: fold scale·log2(e) into q so the kernel's score block
    # needs no per-element multiply (exp2 replaces exp, whose hardware
    # lowering is exp2(x·log2e) anyway). One extra rounding of q, same
    # class of error as the bf16 inputs themselves.
    q2 = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    qf = _pad_dim(q2.reshape(nb, tq, d), 1, bq)
    kf = _pad_dim(k.reshape(nb, tk, d), 1, bk)
    vf = _pad_dim(v.reshape(nb, tk, d_v), 1, bk)
    tq_p, tk_p = qf.shape[1], kf.shape[1]
    grid = (nb, tq_p // bq, tk_p // bk)

    specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, d_v), lambda b, i, j: (b, j, 0)),
    ]
    args = [qf, kf, vf]
    mask_specs, mask_args = [], []
    if mask is not None:
        maskf, mask_batch_index = _mask_setup(mask, batch, tq, tk,
                                              tq_p, tk_p)
        mask_specs.append(pl.BlockSpec(
            (1, bq, bk), lambda b, i, j: (mask_batch_index(b), i, j)))
        mask_args.append(maskf)

    out_specs = pl.BlockSpec((1, bq, d_v), lambda b, i, j: (b, i, 0))
    out_shape = jax.ShapeDtypeStruct((nb, tq_p, d_v), v.dtype)
    if save_lse:
        out_specs = [out_specs,
                     pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((nb, tq_p, 1), jnp.float32)]

    def run_exact(*_):
        kernel = _make_fwd_kernel(causal, bq, bk, tk, mask is not None,
                                  save_lse)
        return pl.pallas_call(
            kernel, grid=grid, in_specs=[off_spec] + specs + mask_specs,
            out_specs=out_specs, out_shape=out_shape,
            scratch_shapes=_scratch(bq, d_v), interpret=interpret,
        )(off, *args, *mask_args)

    if mode == 'bounded':
        # Per-row upper bound on the (log2-unit) scores via Cauchy-Schwarz:
        # |s2_ij| ≤ ‖q2_i‖·‖k_j‖ ≤ ‖q2_i‖·max_j‖k_j‖. The +1 covers fp32
        # accumulation rounding in the kernel's dot.
        q32 = q2.reshape(nb, tq, d).astype(jnp.float32)
        k32 = k.reshape(nb, tk, d).astype(jnp.float32)
        qn = jnp.sqrt(jnp.sum(q32 * q32, axis=-1, keepdims=True))
        kn = jnp.sqrt(jnp.max(jnp.sum(k32 * k32, axis=-1), axis=-1))
        mvec = qn * kn[:, None, None] + 1.0                 # (nb, Tq, 1)
        mvecf = _pad_dim(mvec, 1, bq)
        mvec_spec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))

        def run_bounded(*_):
            kernel = _make_fwd_kernel_bounded(
                causal, bq, bk, tk, mask is not None, save_lse)
            return pl.pallas_call(
                kernel, grid=grid,
                in_specs=[off_spec] + specs + [mvec_spec] + mask_specs,
                out_specs=out_specs, out_shape=out_shape,
                scratch_shapes=_scratch(bq, d_v)[1:],  # no m buffer
                interpret=interpret,
            )(off, *args, mvecf, *mask_args)

        # Safety net: the bound shift is only exact while
        # bound − true_rowmax stays inside fp32's exponent range; since
        # true_rowmax ≥ −‖q2_i‖·max‖k‖, the worst-case gap is 2·bound.
        # When any row could exceed the safe gap, run the exact kernel
        # instead (lax.cond: both are compiled, one executes) — 'bounded'
        # is then an optimization hint, never a correctness trade.
        worst_gap = 2.0 * jnp.max(mvec)
        res = jax.lax.cond(worst_gap <= _BOUNDED_SAFE_GAP,
                           run_bounded, run_exact)
    else:
        res = run_exact()
    out, lse = res if save_lse else (res, None)
    out = out[:, :tq].reshape(*batch, tq, d_v)
    if mask is not None:
        any_valid = _row_has_valid(mask, causal, tq, tk,
                                   row_offset=off[0, 0])
        out = jnp.where(any_valid, out, jnp.zeros((), out.dtype))
    if save_lse:
        return out, lse[:, :tq, 0].reshape(*batch, tq)
    return out


def _scratch(bq, d_v):
    return [pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d_v), jnp.float32)]


def _make_fwd_kernel_bounded(causal, bq, bk, kv_len, has_mask, save_lse):
    """Forward kernel for ``softmax_mode='bounded'``: the per-row shift is
    a precomputed upper bound on the row max (Cauchy-Schwarz,
    ``‖q_i‖·max_j‖k_j‖``, fed as an input), so the kernel drops the
    running-max lane reduction, both correction multiplies and the m
    scratch — the ablated cost is ~15% of kernel time at d=64 (the max
    reduce is the single most expensive VPU op in the exact kernel).

    Softmax is shift-invariant, so the result matches the exact kernel
    whenever ``bound − true_rowmax`` stays within fp32's exponent range
    (the wrapper guarantees this by falling back to the exact kernel when
    the worst-case gap ``2·max(bound)`` exceeds ``_BOUNDED_SAFE_GAP``).
    """
    def kernel(*refs):
        if has_mask:
            off_ref, q_ref, k_ref, v_ref, m_ref, mask_ref, *rest = refs
        else:
            off_ref, q_ref, k_ref, v_ref, m_ref, *rest = refs
            mask_ref = None
        if save_lse:
            o_ref, lse_ref, l_s, acc_s = rest
        else:
            (o_ref, l_s, acc_s), lse_ref = rest, None
        qi = pl.program_id(1)
        ki = pl.program_id(2)
        last_k = pl.num_programs(2) - 1

        @pl.when(ki == 0)
        def _():
            l_s[:] = jnp.zeros_like(l_s)
            acc_s[:] = jnp.zeros_like(acc_s)

        run = _causal_run(causal, off_ref, qi, ki, bq, bk)

        @pl.when(run)
        def _():
            q = q_ref[0]                                    # (BQ, d)
            k = k_ref[0]                                    # (BK, d)
            v = v_ref[0]                                    # (BK, dv)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (BQ, BK), log2 units
            s = _apply_masks(s, qi, ki, bq, bk, causal, kv_len,
                             mask_ref, off_ref)
            p = jnp.exp2(s - m_ref[0])                      # bound shift
            l_s[:] += p.sum(axis=-1, keepdims=True)
            acc_s[:] += jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(ki == last_k)
        def _():
            l = l_s[:]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            # l == 0: fully-masked rows (all weights underflowed to 0) —
            # acc is 0 too, so the output is the required 0.
            o_ref[0] = (acc_s[:] / safe_l).astype(o_ref.dtype)
            if save_lse:
                lse_ref[0] = _LN2 * (m_ref[0] + jnp.log2(safe_l))

    return kernel


def _make_dq_kernel(scale, causal, bq, bk, kv_len, has_mask):
    def kernel(*refs):
        if has_mask:
            (off_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
             mask_ref, dq_ref, dq_acc) = refs
        else:
            (off_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
             dq_ref, dq_acc) = refs
            mask_ref = None
        qi = pl.program_id(1)
        ki = pl.program_id(2)
        last_k = pl.num_programs(2) - 1

        @pl.when(ki == 0)
        def _():
            dq_acc[:] = jnp.zeros_like(dq_acc)

        run = _causal_run(causal, off_ref, qi, ki, bq, bk)

        @pl.when(run)
        def _():
            # q_ref holds q·(scale·log2e) and lse_ref holds lse·log2e (both
            # pre-folded by the wrapper, mirroring the forward's exp2
            # trick) so no per-score-element multiply is needed here:
            # p = exp(s−lse) = exp2(s₂ − lse₂).
            q = q_ref[0]                                    # (BQ, d)·c
            k = k_ref[0]                                    # (BK, d)
            v = v_ref[0]                                    # (BK, dv)
            g = g_ref[0]                                    # (BQ, dv)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (BQ, BK), log2 units
            s = _apply_masks(s, qi, ki, bq, bk, causal, kv_len,
                             mask_ref, off_ref)
            p = jnp.exp2(s - lse_ref[0])                    # (BQ, BK)
            dp = jax.lax.dot_general(
                g, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # (BQ, BK)
            ds = (p * (dp - delta_ref[0])).astype(k.dtype)
            dq_acc[:] += scale * jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # (BQ, d)

        @pl.when(ki == last_k)
        def _():
            dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)

    return kernel


def _make_dkv_kernel(scale, causal, bq, bk, kv_len, has_mask):
    def kernel(*refs):
        if has_mask:
            (off_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
             mask_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
        else:
            (off_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
             dk_ref, dv_ref, dk_acc, dv_acc) = refs
            mask_ref = None
        kj = pl.program_id(1)
        qi = pl.program_id(2)
        last_q = pl.num_programs(2) - 1

        @pl.when(qi == 0)
        def _():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        run = _causal_run(causal, off_ref, qi, kj, bq, bk)

        @pl.when(run)
        def _():
            # q_ref / lse_ref are pre-folded by ·(scale·log2e) / ·log2e as
            # in the dq kernel. dk wants scale·dsᵀ·q with the ORIGINAL q;
            # the dot below uses the folded q, so divide the accumulator
            # update by log2e once per (BK, d) block.
            q = q_ref[0]                                    # (BQ, d)·c
            k = k_ref[0]                                    # (BK, d)
            v = v_ref[0]                                    # (BK, dv)
            g = g_ref[0]                                    # (BQ, dv)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (BQ, BK), log2 units
            s = _apply_masks(s, qi, kj, bq, bk, causal, kv_len,
                             mask_ref, off_ref)
            p = jnp.exp2(s - lse_ref[0])                    # (BQ, BK)
            dv_acc[:] += jax.lax.dot_general(
                p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # (BK, dv)
            dp = jax.lax.dot_general(
                g, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # (BQ, BK)
            ds = (p * (dp - delta_ref[0])).astype(q.dtype)
            dk_acc[:] += (1.0 / _LOG2E) * jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # (BK, d)

        @pl.when(qi == last_q)
        def _():
            dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)

    return kernel


def _flash_bwd_impl(q, k, v, mask, causal_offset, out, lse, g, scale,
                    causal, interpret, zero_invalid_rows=True,
                    grad_dtype=None):
    """Blockwise flash backward: dq pass + dk/dv pass, O(block²) score
    memory. Algebra: with ``p = exp(s − lse)`` (the softmax weights),
    ``dv = pᵀ·dO``, ``ds = p ⊙ (dO·vᵀ − Δ)`` where ``Δ = rowsum(dO ⊙ O)``,
    ``dq = scale·ds·k``, ``dk = scale·dsᵀ·q``.

    ``zero_invalid_rows=False`` skips the empty-row cotangent zeroing —
    for callers (the ring path) whose ``mask`` is only one COLUMN BLOCK of
    the full mask: a row empty in this block but attendable elsewhere has
    near-zero weights here already, and zeroing its ``g`` by the block-local
    test would wrongly kill its contribution. Such callers pre-zero ``g``
    against the GLOBAL mask themselves. ``grad_dtype`` overrides the output
    gradient dtype (the ring path accumulates per-block grads across W
    steps and wants fp32 partials rather than W roundings to bf16).
    """
    *batch, tq, d = q.shape
    tk = k.shape[-2]
    d_v = v.shape[-1]
    nb = int(math.prod(batch)) if batch else 1

    off = jnp.asarray(causal_offset, jnp.int32).reshape(1, 1)
    if mask is not None and zero_invalid_rows:
        # Forward zeroed rows with no attendable key (counting causal), so
        # their cotangent must not flow back through the (garbage-weight)
        # softmax recompute.
        any_valid = _row_has_valid(mask, causal, tq, tk,
                                   row_offset=off[0, 0])
        g = jnp.where(any_valid, g, jnp.zeros((), g.dtype))
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # (*batch, Tq, 1)

    bq, bk = _bwd_block_sizes(tq, tk, q.dtype, d_total=d + d_v,
                              has_mask=mask is not None)
    # Same exp2 pre-folding as the forward: q carries scale·log2e, lse is
    # converted to log2 units, so the kernels' (BQ, BK) score blocks need
    # no per-element multiply.
    q2 = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    qf = _pad_dim(q2.reshape(nb, tq, d), 1, bq)
    kf = _pad_dim(k.reshape(nb, tk, d), 1, bk)
    vf = _pad_dim(v.reshape(nb, tk, d_v), 1, bk)
    gf = _pad_dim(g.reshape(nb, tq, d_v), 1, bq)            # zero-padded
    lsef = _pad_dim((lse * _LOG2E).reshape(nb, tq, 1), 1, bq)
    deltaf = _pad_dim(delta.reshape(nb, tq, 1), 1, bq)
    tq_p, tk_p = qf.shape[1], kf.shape[1]

    args = [qf, kf, vf, gf, lsef, deltaf]
    has_mask = mask is not None
    if has_mask:
        maskf, mask_batch_index = _mask_setup(mask, batch, tq, tk,
                                              tq_p, tk_p)
        args.append(maskf)

    off_spec = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0))

    # --- dq pass: grid (batch, Q block, K block), K innermost ---
    dq_in_specs = [
        off_spec,
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, d_v), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bq, d_v), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
    ]
    if has_mask:
        dq_in_specs.append(pl.BlockSpec(
            (1, bq, bk), lambda b, i, j: (mask_batch_index(b), i, j)))
    dq = pl.pallas_call(
        _make_dq_kernel(scale, causal, bq, bk, tk, has_mask),
        grid=(nb, tq_p // bq, tk_p // bk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, tq_p, d), grad_dtype or q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(off, *args)

    # --- dk/dv pass: grid (batch, K block, Q block), Q innermost ---
    dkv_in_specs = [
        off_spec,
        pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, bk, d_v), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, bq, d_v), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
    ]
    if has_mask:
        dkv_in_specs.append(pl.BlockSpec(
            (1, bq, bk), lambda b, j, i: (mask_batch_index(b), i, j)))
    dk, dv = pl.pallas_call(
        _make_dkv_kernel(scale, causal, bq, bk, tk, has_mask),
        grid=(nb, tk_p // bk, tq_p // bq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d_v), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb, tk_p, d), grad_dtype or k.dtype),
            jax.ShapeDtypeStruct((nb, tk_p, d_v), grad_dtype or v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d_v), jnp.float32)],
        interpret=interpret,
    )(off, *args)

    dq = dq[:, :tq].reshape(q.shape)
    dk = dk[:, :tk].reshape(k.shape)
    dv = dv[:, :tk].reshape(v.shape)
    return dq, dk, dv


def _reference_math(q, k, v, mask, scale, causal):
    """Identical math in jnp — the test oracle."""
    tq, tk = q.shape[-2], k.shape[-2]
    s = jnp.einsum('...td,...od->...to', q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    if mask is not None:
        s = jnp.where(mask, _NEG_BIG, s)
    if causal:
        future = jnp.arange(tq)[:, None] < jnp.arange(tk)[None, :]
        s = jnp.where(future, _NEG_BIG, s)
    attn = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('...to,...od->...td', attn, v.astype(jnp.float32))
    if mask is not None:
        out = jnp.where(_row_has_valid(mask, causal, tq, tk), out, 0.0)
    return out.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash(q, k, v, mask, causal_offset, scale, causal, interpret, mode):
    return _flash_fwd_impl(q, k, v, mask, causal_offset, scale, causal,
                           interpret, mode)


def _flash_fwd(q, k, v, mask, causal_offset, scale, causal, interpret,
               mode):
    out, lse = _flash_fwd_impl(q, k, v, mask, causal_offset, scale, causal,
                               interpret, mode, save_lse=True)
    return out, (q, k, v, mask, causal_offset, out, lse)


def _flash_bwd(scale, causal, interpret, mode, res, g):
    # The backward is mode-independent: lse = log Σ exp(s) is invariant to
    # the forward's shift choice, and the bwd kernels recompute p from it.
    q, k, v, mask, causal_offset, out, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, mask, causal_offset, out, lse, g,
                                 scale, causal, interpret)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, mask=None, *, causal=False, causal_offset=0,
                    scale=None, interpret=None, softmax_mode='exact'):
    """Fused attention ``softmax(q·kᵀ·scale [+mask])·v`` as TPU kernels.

    ``q (..., Tq, d)``, ``k (..., Tk, d)``, ``v (..., Tk, d_v)``; optional
    boolean ``mask (..., Tq, Tk)`` broadcastable over the leading dims
    (True = masked out, the reference's convention, reference README.md:67).
    Differentiable end-to-end with blockwise Pallas kernels in both
    directions — peak memory is O(T·d) for forward AND backward (the
    backward recomputes score blocks from the saved row logsumexp).
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU so the
    CPU test mesh runs the same code.

    ``causal_offset``: the GLOBAL index of query row 0 (int or traced
    scalar, e.g. ``lax.axis_index(...) * (T // N)`` under ``shard_map``) —
    lets sequence-sharded callers run causal attention of local query rows
    against gathered keys with no materialized O(Tq·Tk) triangle; the
    causal comparison and the block-skip predicate use
    ``causal_offset + row`` as the global row position.

    ``softmax_mode``:

    - ``'exact'`` (default): numerically-stable online softmax with a
      running row max — safe for any input magnitudes.
    - ``'bounded'``: replaces the running max with the per-row
      Cauchy-Schwarz bound ``scale·‖q_i‖·max_j‖k_j‖``, removing the most
      expensive VPU op of the kernel (~15% faster at small head dim).
      Softmax is shift-invariant, so this changes results only through
      fp32 underflow of weights far below the bound; a built-in guard
      runs the exact kernel instead whenever the worst-case gap
      (``2·scale·log2e·max‖q‖·max‖k‖``, e.g. huge-norm yet near-orthogonal
      q/k) could reach fp32's exponent limits — 'bounded' is an
      optimization hint, never a correctness trade. Typical normalized
      activations stay far under the threshold and take the fast path.
    """
    if softmax_mode not in ('exact', 'bounded'):
        raise ValueError(f"softmax_mode must be 'exact' or 'bounded', "
                         f'got {softmax_mode!r}')
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    return _flash(q, k, v, mask, causal_offset, float(scale), bool(causal),
                  bool(interpret), softmax_mode)
