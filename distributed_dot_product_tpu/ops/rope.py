# -*- coding: utf-8 -*-
"""
Rotary position embeddings (RoPE), sequence-shard-aware.

RoPE rotates each (even, odd-half) feature pair of q/k by an angle
proportional to the token's GLOBAL position, so attention logits depend
only on relative distance. No reference analog (the reference has no
positional encoding at all); provided because it is the standard
long-context companion to the attention stack here — and under sequence
parallelism the rotation MUST use global positions, which is exactly the
plumbing this framework already has (shard offsets, zigzag position
vectors).

Convention: NeoX/LLaMA "half" layout — the feature dim splits into two
halves ``(x1, x2)`` rotated as ``(x1·cos − x2·sin, x1·sin + x2·cos)``,
with frequencies ``base^(−2i/d)`` over the first half. Pure jnp: the
O(T·d) elementwise work is HBM-trivial next to attention and XLA fuses it
into the surrounding projections; it needs no Pallas kernel.
"""

import jax.numpy as jnp
from jax import lax

from distributed_dot_product_tpu.utils.comm import SEQ_AXIS

__all__ = ['rope', 'rope_seq_parallel']


def rope(x, positions=None, *, base=10000.0, offset=0, dtype=jnp.float32):
    """Apply rotary embedding to ``x (..., T, d)`` (``d`` even).

    ``positions``: per-token GLOBAL positions ``(..., T)`` (leading dims
    broadcastable against x's); default ``offset + arange(T)`` —
    sequence-sharded callers pass their shard's global offset (a traced
    scalar like ``lax.axis_index(axis) * (T // N)`` works), or explicit
    ``positions`` for non-contiguous layouts (zigzag — the same vectors
    fed to ``flash_attention(positions=...)``).

    The rotation is computed in ``dtype`` (default f32 — bf16 angles lose
    relative-position precision beyond ~10K tokens) and cast back to
    ``x.dtype``.
    """
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f'rope needs an even feature dim, got {d}')
    t = x.shape[-2]
    if positions is None:
        positions = offset + jnp.arange(t)
    positions = jnp.asarray(positions, dtype)
    inv_freq = base ** (-jnp.arange(0, d, 2, dtype=dtype) / d)   # (d/2,)
    angles = positions[..., None] * inv_freq                     # (..., T, d/2)
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    x1 = x[..., : d // 2].astype(dtype)
    x2 = x[..., d // 2:].astype(dtype)
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def rope_seq_parallel(x, *, axis_name=SEQ_AXIS, positions=None,
                      base=10000.0, dtype=jnp.float32):
    """``rope`` for a ``(..., T/N, d)`` shard inside ``shard_map``: global
    positions default to ``axis_index·T/N + arange`` (contiguous
    sharding); pass the shard's ``positions`` vector for zigzag/striped
    layouts."""
    if positions is None:
        tn = x.shape[-2]
        positions = lax.axis_index(axis_name) * tn + jnp.arange(tn)
    return rope(x, positions, base=base, dtype=dtype)
