# -*- coding: utf-8 -*-
from distributed_dot_product_tpu.ops.functions import (  # noqa: F401
    distributed_matmul_nt, distributed_matmul_tn, distributed_matmul_all,
)
from distributed_dot_product_tpu.ops.ops import (  # noqa: F401
    matmul_nt, matmul_all, matmul_tn,
    RightTransposeMultiplication, FullMultiplication,
    LeftTransposeMultiplication,
)
