# -*- coding: utf-8 -*-
"""
Distributed sequence-matmul kernels (functional layer, no custom gradients).

TPU-native rebuild of the reference L2 layer (reference
multiplication/functions.py): three distributed matrix products over a
sequence axis ``T`` sharded ``T/N`` per device —

- ``distributed_matmul_nt``:  ``A·Bᵀ``  (reference functions.py:44-99)
- ``distributed_matmul_tn``:  ``Aᵀ·B``  (reference functions.py:102-148)
- ``distributed_matmul_all``: ``A·B``   (reference functions.py:160-212)

All three are plain functions meant to run **inside a shard_map body** over
a 1-D mesh axis (default ``'seq'``): every array argument is the *local
shard* ``(*, T/N, ·)``, exactly the reference's per-process view. Use the
``*_global`` wrappers (or your own ``shard_map``) to apply them to global
arrays on a mesh.

Communication mapping (reference → here):

- chunked ``hvd.allgather`` loops (reference functions.py:89-97, 202-210)
  → a ``lax.scan`` whose body all-gathers one ``offset``-sized slab and
  feeds one large MXU matmul. ``offset`` keeps its meaning: gathered-operand
  memory is O(W·offset·d) instead of O(T·d) (reference functions.py:64-68);
  smaller offset = less live memory, more (smaller) collectives.
- the reference's per-block ``hvd.allreduce_async(Sum)`` + keep-own-block in
  ``tn`` (reference functions.py:140-147) is exactly a reduce-scatter
  → one ``lax.psum_scatter``.
- the MPI barrier opening every kernel (reference functions.py:77) has no
  analog: one compiled XLA program cannot misorder its collectives.
- ``impl='ring'`` gives a ``lax.ppermute`` systolic-ring variant of nt/all
  (neighbour exchange over the ICI torus instead of all-gather) — a pattern
  the reference doesn't have; it keeps peak gathered memory at one shard
  regardless of ``offset`` and overlaps compute with ICI transfers.

Shape contracts (identical to the reference; W = mesh-axis size):

===========  =======================  =======================  ==================
kernel       left                     right                    out
===========  =======================  =======================  ==================
nt           ``(*, T/N, D)``          ``(*, T/N, D)``          ``(*, T/N, T)``
tn           ``(*, T/N, T)``          ``(*, T/N, D)``          ``(*, T/N, D)``
all          ``(*, T/N, T)``          ``(*, T/N, D)``          ``(*, T/N, D)``
===========  =======================  =======================  ==================

Global column order of ``nt`` matches the reference's interleave (reference
functions.py:98): global column ``w·(T/N) + j`` is local row ``j`` of shard
``w`` — i.e. plain global order.

The reference also defines a dead ``distributed_matmul_block`` with a typo
(reference functions.py:151-157, SURVEY §2.1); deliberately not carried
forward.
"""

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_dot_product_tpu.utils.comm import SEQ_AXIS
from distributed_dot_product_tpu.utils.tracing import measure

__all__ = [
    'distributed_matmul_nt', 'distributed_matmul_tn',
    'distributed_matmul_all',
    'distributed_matmul_nt_global', 'distributed_matmul_tn_global',
    'distributed_matmul_all_global',
]


def _axis_size(axis_name):
    # Static Python int inside shard_map (mesh axis sizes are compile-time).
    return lax.psum(1, axis_name)


def _check_offset(offset):
    if offset is not None and int(offset) < 1:
        raise ValueError(
            f'offset must be a positive chunk size or None (full gather), '
            f'got {offset}')


def _pad_to_multiple(x, multiple, axis):
    """Zero-pad ``x`` along ``axis`` up to the next multiple. Returns
    (padded, padded_size). Lifts the reference's hard requirement that
    ``offset`` divide ``T/N`` (reference functions.py:66) — the pad columns
    are sliced off after the chunk loop."""
    size = x.shape[axis]
    target = -(-size // multiple) * multiple
    if target == size:
        return x, size
    pad = [(0, 0)] * x.ndim
    pad[axis % x.ndim] = (0, target - size)
    return jnp.pad(x, pad), target


@measure
def distributed_matmul_nt(left, right, offset=32, axis_name=SEQ_AXIS,
                          impl='allgather', precision=None):
    """``A·Bᵀ`` over sequence-sharded operands (reference functions.py:44-99).

    ``left``/``right``: local shards ``(*, T/N, D)``; returns ``(*, T/N, T)``
    — each shard holds its row-block of the global ``(T, T)`` product, with
    columns in global order.

    ``offset``: rows of ``right`` gathered per step (memory/time knob,
    reference functions.py:64-68). ``None`` gathers everything in one step.
    ``impl``: ``'allgather'`` (chunked, honors ``offset``) or ``'ring'``
    (ppermute neighbour ring; ``offset`` ignored — peak gathered memory is
    always exactly one shard).
    """
    if impl == 'ring':
        return _matmul_nt_ring(left, right, axis_name, precision)
    _check_offset(offset)
    W = _axis_size(axis_name)
    Tn = right.shape[-2]
    offset = Tn if offset is None else min(int(offset), Tn)

    if offset >= Tn:
        # Single step: tiled all-gather puts rows in global order already.
        gathered = lax.all_gather(right, axis_name, axis=right.ndim - 2,
                                  tiled=True)  # (*, T, D)
        return jnp.matmul(left, jnp.swapaxes(gathered, -1, -2),
                          precision=precision)

    r, Tp = _pad_to_multiple(right, offset, axis=-2)
    nchunks = Tp // offset

    def body(c, _):
        chunk = lax.dynamic_slice_in_dim(r, c * offset, offset, axis=-2)
        g = lax.all_gather(chunk, axis_name)        # (W, *, offset, D)
        # (*, T/N, W, offset): one fused MXU contraction per step.
        part = jnp.einsum('...td,w...od->...two', left, g,
                          precision=precision)
        return c + 1, part

    _, ys = lax.scan(body, 0, None, length=nchunks)
    # ys: (nchunks, *, T/N, W, offset) -> (*, T/N, W, nchunks, offset)
    out = jnp.moveaxis(ys, 0, -2)
    out = out.reshape(*out.shape[:-3], W, Tp)
    if Tp != Tn:
        out = out[..., :Tn]  # drop pad columns inside each shard's block
    # (*, T/N, W, T/N) -> (*, T/N, T): global column = w*(T/N) + j, the same
    # interleave as the reference's unsqueeze/transpose/reshape
    # (reference functions.py:98).
    return out.reshape(*left.shape[:-1], W * Tn)


def _matmul_nt_ring(left, right, axis_name, precision):
    """Systolic-ring ``A·Bᵀ``: rotate ``right`` shards around the mesh ring
    with ``lax.ppermute``; at step ``s`` the resident buffer is shard
    ``(rank+s) mod W``, producing that owner's column block. ICI-friendly:
    W-1 neighbour exchanges, no radix-W all-gather; gathered memory = one
    shard."""
    W = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    Tn = right.shape[-2]
    out_shape = (*left.shape[:-1], W * Tn)
    perm = [(i, (i - 1) % W) for i in range(W)]

    def compute(s, buf, out):
        owner = (idx + s) % W
        block = jnp.einsum('...td,...od->...to', left, buf,
                           precision=precision)  # (*, T/N, T/N)
        return lax.dynamic_update_slice_in_dim(
            out, block.astype(out.dtype), owner * Tn, axis=-1)

    def body(s, carry):
        buf, out = carry
        out = compute(s, buf, out)
        return lax.ppermute(buf, axis_name, perm), out

    dtype = jnp.result_type(left.dtype, right.dtype)
    # W-1 rotated steps; the last resident block needs no trailing permute.
    buf, out = lax.fori_loop(
        0, W - 1, body, (right, jnp.zeros(out_shape, dtype)))
    return compute(W - 1, buf, out)


@measure
def distributed_matmul_tn(left, right, axis_name=SEQ_AXIS, precision=None):
    """``Aᵀ·B`` over sequence-sharded operands (reference
    functions.py:102-148).

    ``left``: ``(*, T/N, C)`` with ``C = W·(C/W)``; ``right``:
    ``(*, T/N, D)``. Returns ``(*, C/W, D)`` — shard ``w`` keeps rows
    ``[w·C/W, (w+1)·C/W)`` of the global ``AᵀB``.

    The reference expressed this as W named async allreduces where each rank
    keeps only its own block (reference functions.py:140-147) — that is
    reduce-scatter by construction, so here it is a single
    ``lax.psum_scatter`` riding ICI. No ``offset`` knob, same as the
    reference (functions.py:103).
    """
    W = _axis_size(axis_name)
    C = left.shape[-1]
    if C % W:
        raise ValueError(
            f'distributed_matmul_tn: left last dim {C} must be divisible by '
            f'the mesh axis size {W}')
    blocks = left.reshape(*left.shape[:-1], W, C // W)  # (*, T/N, W, C/W)
    # Local partial of every output block: (W, *, C/W, D).
    contrib = jnp.einsum('...twc,...td->w...cd', blocks, right,
                         precision=precision)
    return lax.psum_scatter(contrib, axis_name, scatter_dimension=0,
                            tiled=False)


@measure
def distributed_matmul_all(left, right, offset=32, axis_name=SEQ_AXIS,
                           impl='allgather', precision=None):
    """``A·B`` over sequence-sharded operands (reference
    functions.py:160-212).

    ``left``: ``(*, T/N, T)`` (e.g. attention rows), ``right``:
    ``(*, T/N, D)`` (e.g. values). Returns ``(*, T/N, D)``.

    ``offset``: feature *columns* of ``right`` gathered per step — the same
    D-chunking as the reference (functions.py:202-210); gathered memory is
    O(T·offset). ``impl='ring'`` rotates whole ``right`` shards instead
    (gathered memory O(T/N·D), W-1 neighbour hops).
    """
    if impl == 'ring':
        return _matmul_all_ring(left, right, axis_name, precision)
    _check_offset(offset)
    W = _axis_size(axis_name)
    Tn, D = right.shape[-2], right.shape[-1]
    offset = D if offset is None else min(int(offset), D)
    concat_axis = right.ndim - 2

    if offset >= D:
        gathered = lax.all_gather(right, axis_name, axis=concat_axis,
                                  tiled=True)  # (*, T, D)
        return jnp.matmul(left, gathered, precision=precision)

    r, Dp = _pad_to_multiple(right, offset, axis=-1)
    nchunks = Dp // offset

    def body(c, _):
        chunk = lax.dynamic_slice_in_dim(r, c * offset, offset, axis=-1)
        g = lax.all_gather(chunk, axis_name, axis=concat_axis,
                           tiled=True)  # (*, T, offset) in global row order
        part = jnp.matmul(left, g, precision=precision)  # (*, T/N, offset)
        return c + 1, part

    _, ys = lax.scan(body, 0, None, length=nchunks)
    # (nchunks, *, T/N, offset) -> (*, T/N, nchunks*offset)
    out = jnp.moveaxis(ys, 0, -2)
    out = out.reshape(*out.shape[:-2], Dp)
    return out[..., :D] if Dp != D else out


def _matmul_all_ring(left, right, axis_name, precision):
    """Ring ``A·B``: rotate ``right`` shards; at step ``s`` multiply the
    resident shard (owner ``(rank+s) mod W``) against the matching column
    block of ``left`` and accumulate."""
    W = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    Tn = right.shape[-2]
    perm = [(i, (i - 1) % W) for i in range(W)]
    acc_dtype = jnp.result_type(left.dtype, right.dtype)

    def compute(s, buf, acc):
        owner = (idx + s) % W
        block = lax.dynamic_slice_in_dim(left, owner * Tn, Tn, axis=-1)
        return acc + jnp.matmul(block, buf, precision=precision)

    def body(s, carry):
        buf, acc = carry
        acc = compute(s, buf, acc)
        return lax.ppermute(buf, axis_name, perm), acc

    out_shape = (*left.shape[:-1], right.shape[-1])
    # W-1 rotated steps; the last resident block needs no trailing permute.
    buf, acc = lax.fori_loop(
        0, W - 1, body, (right, jnp.zeros(out_shape, acc_dtype)))
    return compute(W - 1, buf, acc)


# ---------------------------------------------------------------------------
# Global-array wrappers: apply the shard-local kernels to global arrays on a
# mesh. The reference has no analog (its processes only ever see shards);
# these are the convenient entry points for single-program users.
# ---------------------------------------------------------------------------

def _seq_specs(ndims, mesh_axis):
    return tuple(
        P(*([None] * (nd - 2) + [mesh_axis, None])) for nd in ndims)


def _shard_mapped(fn, mesh, ndims_in, ndim_out, mesh_axis=SEQ_AXIS):
    in_specs = _seq_specs(ndims_in, mesh_axis)
    (out_spec,) = _seq_specs([ndim_out], mesh_axis)
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_spec, check_vma=False)


def distributed_matmul_nt_global(left, right, offset=32, mesh=None,
                                 mesh_axis=SEQ_AXIS, **kw):
    """``A·Bᵀ`` on *global* arrays ``(*, T, D)`` sharded over ``mesh``."""
    fn = partial(distributed_matmul_nt, offset=offset, axis_name=mesh_axis,
                 **kw)
    return _shard_mapped(fn, mesh, (left.ndim, right.ndim), left.ndim,
                         mesh_axis)(left, right)


def distributed_matmul_tn_global(left, right, mesh=None,
                                 mesh_axis=SEQ_AXIS, **kw):
    """``Aᵀ·B`` on *global* arrays sharded over ``mesh``."""
    fn = partial(distributed_matmul_tn, axis_name=mesh_axis, **kw)
    return _shard_mapped(fn, mesh, (left.ndim, right.ndim), left.ndim,
                         mesh_axis)(left, right)


def distributed_matmul_all_global(left, right, offset=32, mesh=None,
                                  mesh_axis=SEQ_AXIS, **kw):
    """``A·B`` on *global* arrays sharded over ``mesh``."""
    fn = partial(distributed_matmul_all, offset=offset, axis_name=mesh_axis,
                 **kw)
    return _shard_mapped(fn, mesh, (left.ndim, right.ndim), left.ndim,
                         mesh_axis)(left, right)


def graphlint_entrypoints():
    """Static-analysis registration hook (analysis/registry.py): the
    distributed matmuls — forward AND the custom-vjp backward, whose
    kernels are defined in terms of the other two ops — under a real
    2-device mesh, so the collective-axis rule sees the all_gather /
    ppermute / psum_scatter traffic of both comm impls."""

    def _grad_spec(impl):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        from distributed_dot_product_tpu.ops.ops import (
            matmul_all, matmul_nt,
        )
        from distributed_dot_product_tpu.parallel.mesh import seq_mesh
        mesh = seq_mesh(2)

        def body(a, b):
            scores = matmul_nt(a, b, 2, impl=impl)     # (B, T/N, T)
            return matmul_all(scores, b, 2, impl=impl)

        sharded = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(None, SEQ_AXIS, None), P(None, SEQ_AXIS, None)),
            out_specs=P(None, SEQ_AXIS, None), check_vma=False)

        def loss(a, b):
            return jnp.sum(sharded(a, b).astype(jnp.float32))

        a = jax.ShapeDtypeStruct((1, 8, 4), jnp.float32)
        return TraceSpec(name=f'ops.matmul_grad_{impl}',
                         fn=jax.grad(loss, argnums=(0, 1)),
                         args=(a, a), mesh_axes=(SEQ_AXIS,))

    from functools import partial as _partial
    return {
        'ops.matmul_grad_allgather': _partial(_grad_spec, 'allgather'),
        'ops.matmul_grad_ring': _partial(_grad_spec, 'ring'),
    }
