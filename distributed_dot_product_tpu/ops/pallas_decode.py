# -*- coding: utf-8 -*-
"""
Fused KV-cache decode kernel (the serving hot path): one token per
slot per step, or — VERIFY-k — up to k new rows per slot in one
program, the fused verify step of draft-verify speculative decoding
(Leviathan et al.; each of the k query rows keeps its own online-
softmax state and masks the intra-step causal triangle among the k
appended rows).

``models/decode.py``'s XLA formulation runs a decode step as two ops —
``append_kv_slots`` (a masked gather over the whole ``t_max`` axis) and
``decode_attention`` (a masked einsum softmax over the full buffer) —
which is correct and backend-portable but leaves the chained serving
loop ~2× above its own measured physics floor: with the cache riding a
``lax.scan`` carry between the two ops, XLA materializes full
cache-shaped copies per step (RESULTS.md "KV-cache decode": 10.34
ms/step at B=8/131K vs 4.25 + 0.9 ms of attention + append in
isolation), and the int8 K mirror *loses* to bf16 (0.32 vs 0.21
ms/step) because XLA's s8 dot lowering at 4-row operands never cashes
the halved bytes in.

This kernel is the fix both RESULTS entries name: ONE Pallas program
per decode step that

- **appends in place**: the K/V buffers (and the int8 mirror, when the
  cache carries one) are passed as aliased outputs
  (``input_output_aliases``), and only the single block containing the
  append row is ever written — the cache never travels through a scan
  carry or a donated-copy, and unwritten blocks keep their bits by the
  aliasing contract;
- **splits K over the time axis**: the grid sweeps ``t_max`` in
  ``block_k`` chunks with running ``(max, denom, acc)`` accumulators in
  VMEM scratch (the flash-decoding work partition; on TPU the grid is
  sequential per core, so the split is what lets Pallas double-buffer
  the HBM→VMEM cache stream while the MXU works);
- **masks per slot**: the per-slot valid lengths arrive as a
  scalar-prefetch vector that both the kernel (causal/window masking,
  the new row's score substitution) and the BlockSpec index maps read —
  blocks past a slot's fill are never even DMA'd (the index map clamps
  to the last useful block, and Pallas skips re-fetching a resident
  block), so a half-empty serving batch streams half the bytes;
- **dequantizes int8 in kernel**: the quantized path streams the 1-byte
  ``k_q`` mirror plus its per-row scales and scores s8×s8→s32 on the
  MXU with the dequantization applied to the s32 block — the halved K
  bytes finally reach the memory system as halved traffic instead of
  dying in XLA's s8 lowering.

Numerics: the same exp2-trick online softmax as
:mod:`~distributed_dot_product_tpu.ops.pallas_attention` (scale·log2e
pre-folded into q, masked logits −inf against a ``_NEG_BIG``-clamped
running max, empty rows → exact 0). Outputs are the UN-normalized
``(num, max, denom)`` triple so sequence-sharded callers can merge
shards by the flash-decoding pmax/psum rule; local callers divide once
outside (G rows — noise).

Off-TPU the kernel runs under the Pallas interpreter like the training
kernels (``interpret=None`` auto-selects), so the CPU tier-1 suite
covers the identical code path.
"""

import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_dot_product_tpu.ops.pallas_attention import (
    _LOG2E, _NEG_BIG, _quantize_rows,
)

__all__ = ['flash_decode', 'decode_block_k']

# K-split cap: 1024 rows/block keeps the double-buffered K+V stream
# well inside VMEM at every head dim the repo uses (d=256 worst case:
# 2·(1024·256·2 B)·2 buffers ≈ 4 MB of the ~16 MB budget).
_BLOCK_K_CAP = 1024


def decode_block_k(t_max, cap=_BLOCK_K_CAP):
    """Largest usable K-split for a ``t_max``-row cache, or None when the
    kernel doesn't apply. The cache buffers are ALIASED outputs, so they
    cannot be padded — the split must divide ``t_max`` exactly. Any
    ``t_max <= cap`` is one split; larger caches take the biggest
    power-of-two divisor (serving caches are powers of two; an odd
    131071-row cache falls back to the XLA path rather than running a
    degenerate grid)."""
    if t_max <= cap:
        return t_max
    for bk in (1024, 512, 256, 128):
        if bk <= cap and t_max % bk == 0:
            return bk
    return None


def _pad_rows(x, mult):
    """Pad axis -2 up to a multiple of ``mult``."""
    n = x.shape[-2]
    target = -(-n // mult) * mult
    if target == n:
        return x
    pad = [(0, 0)] * x.ndim
    pad[-2] = (0, target - n)
    return jnp.pad(x, pad)


def _make_decode_kernel(bk, ns, n, group, g_pad, h_kv, window,
                        quantized, has_alibi, paged=False):
    """Kernel body; refs are ordered to match ``flash_decode``'s spec
    list below. Grid = (B·H_kv, ns) with the K split innermost; the
    running softmax state lives in scratch across splits.

    VERIFY-k: ``n`` is the static number of new rows per step (1 =
    classic decode). The per-(b, h_kv) query block carries ``n · group``
    rows laid out new-row-major (row ``j·group + g`` is query head ``g``
    of new row ``j``), so per-row masking reads the row's intra-step
    index ``j = row // group`` — new row ``j`` attends columns
    ``<= vt + j``, which is exactly the intra-step causal triangle among
    the k new rows plus the shared prefix. A third scalar-prefetch
    vector ``nn`` carries the PER-SLOT number of rows actually appended
    (mixed spec/non-spec batches: a non-spec slot rides the same program
    with ``nn = 1``); rows ``m >= nn`` are never substituted into scores
    or written back, and query rows past a slot's real count only ever
    produce don't-care outputs the caller discards.

    The PAGED variant is the same body plus ONE extra predicate: grid
    step ``ki`` is the LOGICAL page ordinal, so every mask/score/append
    computation below already speaks logical positions — the BlockSpec
    index maps (which translate logical ordinal → pool page, clamping
    unallocated/−1 entries to the sink) live in ``flash_decode``, and
    the body additionally gates its scoring block on
    ``pt_ref[slot·ns + ki] >= 0``: a −1 table entry means the slot does
    not hold that ordinal's page in THIS pool — beyond the fill on a
    single-pool cache, or owned by ANOTHER mesh shard on a sequence-
    sharded page table — and its sink-redirected bytes must not enter
    the softmax (their garbage scores would land below the causal fill
    and pollute the denominator). For a single pool the predicate is
    redundant with the fill check; for the sharded table it is the
    whole shard-local page-range view."""

    def kernel_body(vt_ref, ap_ref, nn_ref, *refs, pt_ref=None):
        b = pl.program_id(0)
        ki = pl.program_id(1)
        br = b // h_kv                          # cache batch row
        vt = vt_ref[br]                         # first new row's column
        ap = ap_ref[br]                         # append column (−1 none)
        nn = nn_ref[br]                         # rows appended (0..n)
        # The block(s) the append write targets — must equal the k/v OUT
        # BlockSpec index maps exactly (ap < 0 ⇒ a copy-through of
        # block 0, because Pallas writes every output block back and an
        # unwritten one would clobber the aliased cache with garbage).
        # n rows span at most TWO consecutive blocks (n <= bk is
        # enforced by flash_decode): the write index map clamps ki into
        # [wfirst, wlast], so the kernel writes the ref exactly when ki
        # lands on each physical block, right before Pallas flushes it.
        wfirst = jnp.where(ap >= 0, jnp.clip(ap // bk, 0, ns - 1), 0)
        wlast = jnp.where(
            ap >= 0,
            jnp.clip((ap + jnp.maximum(nn, 1) - 1) // bk, 0, ns - 1), 0)

        it = iter(refs)
        q_ref = next(it)
        sqf_ref = next(it) if quantized else None
        kn_ref = next(it)
        kqn_ref = next(it) if quantized else None
        ksn_ref = next(it) if quantized else None
        vn_ref = next(it)
        k_ref = next(it)
        kq_ref = next(it) if quantized else None
        ks_ref = next(it) if quantized else None
        v_ref = next(it)
        alibi_ref = next(it) if has_alibi else None
        (o_ref, m_ref, l_ref, ko_ref, vo_ref) = (
            next(it), next(it), next(it), next(it), next(it))
        kqo_ref = next(it) if quantized else None
        kso_ref = next(it) if quantized else None
        m_s, l_s, acc_s = next(it), next(it), next(it)

        @pl.when(ki == 0)
        def _():
            m_s[:] = jnp.full_like(m_s, _NEG_BIG)
            l_s[:] = jnp.zeros_like(l_s)
            acc_s[:] = jnp.zeros_like(acc_s)

        # Block-skip: no valid column in this split — strictly past the
        # LAST new row's fill (row n−1 attends up to vt + n − 1), or —
        # with a window — wholly before row 0's lookback (later rows
        # look back from later positions, so row 0's bound is the
        # earliest column any row can attend).
        run = ki * bk <= vt + (n - 1)
        if window is not None:
            run = jnp.logical_and(run, ki * bk + bk - 1 > vt - window)
        if pt_ref is not None:
            # Paged: only score pages this table actually holds — a −1
            # ordinal streams the sink (see flash_decode's index-map
            # clamp) and must stay out of the online softmax. On a
            # sequence-sharded page table this is the shard-local
            # page-range restriction; the cross-shard pmax/psum merge
            # of the (num, m, l) partials reassembles exact full
            # attention.
            run = jnp.logical_and(run, pt_ref[br * ns + ki] >= 0)

        @pl.when(run)
        def _():
            cols = (ki * bk
                    + jax.lax.broadcasted_iota(jnp.int32, (g_pad, bk), 1))
            # Intra-step row index: row j·group + g is new row j's head
            # g, so j = row // group (padded rows land past n — fully
            # masked below).
            jrow = (jax.lax.broadcasted_iota(jnp.int32, (g_pad, bk), 0)
                    // group)
            if quantized:
                # ks_ref blocks are (1, BK): the K-row scales already
                # laid out as a row vector (the training kernels'
                # convention — no in-kernel transpose/relayout).
                s = jax.lax.dot_general(
                    q_ref[0], kq_ref[0], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32).astype(jnp.float32)
                s = s * sqf_ref[0] * ks_ref[0]
                s_new = jax.lax.dot_general(
                    q_ref[0], kqn_ref[0], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32).astype(jnp.float32)
                s_new = s_new * sqf_ref[0] * ksn_ref[0, 0, 0]
            else:
                s = jax.lax.dot_general(
                    q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                s_new = jax.lax.dot_general(
                    q_ref[0], kn_ref[0], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
            # The appended rows' scores replace whatever the buffer held
            # at their columns (new row m lands at ap + m; the nn guard
            # keeps rows a mixed-batch slot did NOT append from leaking
            # in; ap == −1 matches no column: cols are ≥ 0 and nn is 0).
            for m in range(n):
                sel = jnp.logical_and(cols == ap + m, m < nn)
                s = jnp.where(sel, s_new[:, m:m + 1], s)
            rel = cols - vt - jrow                # ≤ 0 on valid columns
            if alibi_ref is not None:
                s = s + alibi_ref[0] * rel.astype(jnp.float32)
            masked = rel > 0
            if window is not None:
                masked = jnp.logical_or(masked, rel <= -window)
            s = jnp.where(masked, -jnp.inf, s)

            rows_v = (ki * bk
                      + jax.lax.broadcasted_iota(
                          jnp.int32, v_ref.shape[1:], 0))
            v = v_ref[0]
            for m in range(n):
                sel = jnp.logical_and(rows_v == ap + m, m < nn)
                v = jnp.where(sel, vn_ref[0, m], v)

            m_prev = m_s[:]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp2(s - m_new)
            corr = jnp.exp2(m_prev - m_new)
            m_s[:] = m_new
            l_s[:] = l_s[:] * corr + p.sum(axis=-1, keepdims=True)
            acc_s[:] = acc_s[:] * corr + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        # In-place append: substitute the new rows into the resident
        # block(s) and write them back — the ONLY cache blocks written
        # this step (every other aliased block keeps its bits
        # untouched). With n > 1 the rows may straddle one block
        # boundary; the write index map clamps ki into [wfirst, wlast],
        # so writing at both gives each physical block its substituted
        # content before Pallas flushes it.
        @pl.when(jnp.logical_or(ki == wfirst, ki == wlast))
        def _():
            rows_k = (ki * bk
                      + jax.lax.broadcasted_iota(
                          jnp.int32, k_ref.shape[1:], 0))
            rows_v = (ki * bk
                      + jax.lax.broadcasted_iota(
                          jnp.int32, v_ref.shape[1:], 0))
            ko, vo = k_ref[0], v_ref[0]
            for m in range(n):
                ink = jnp.logical_and(rows_k == ap + m, m < nn)
                inv = jnp.logical_and(rows_v == ap + m, m < nn)
                ko = jnp.where(ink, kn_ref[0, m], ko)
                vo = jnp.where(inv, vn_ref[0, m], vo)
            ko_ref[0] = ko
            vo_ref[0] = vo
            if quantized:
                cols_s = (ki * bk
                          + jax.lax.broadcasted_iota(
                              jnp.int32, ks_ref.shape[1:], 1))
                kqo, kso = kq_ref[0], ks_ref[0]
                for m in range(n):
                    sel = jnp.logical_and(rows_k == ap + m, m < nn)
                    kqo = jnp.where(sel, kqn_ref[0, m], kqo)
                    kso = jnp.where(
                        jnp.logical_and(cols_s == ap + m, m < nn),
                        ksn_ref[0, 0, m], kso)
                kqo_ref[0] = kqo
                kso_ref[0] = kso

        @pl.when(ki == ns - 1)
        def _():
            o_ref[0] = acc_s[:]
            m_ref[0] = m_s[:]
            l_ref[0] = l_s[:]

    if not paged:
        return kernel_body

    def kernel_paged(vt_ref, ap_ref, nn_ref, pt_ref, *refs):
        kernel_body(vt_ref, ap_ref, nn_ref, *refs, pt_ref=pt_ref)

    return kernel_paged


def flash_decode(q, k_new, v_new, cache_k, cache_v, valid_to, append_at,
                 *, n_new=None, page_table=None, k_q=None, k_scale=None,
                 scale=None, window=None, alibi_slopes=None,
                 qk_quant=None, interpret=None, block_k=None,
                 partials=False):
    """One fused decode step: in-place cache append + masked online-
    softmax attention of each slot's queries against its own prefix.

    ``q (B, H, k, d)``; ``k_new/v_new (B, H_kv, k, d·)`` the step's new
    rows per slot; ``cache_k/cache_v (B, H_kv, t_max, d·)`` the (static-
    shape) cache buffers, returned UPDATED — aliased in place on TPU,
    so jit callers should donate them. GQA is native: each group of
    ``H/H_kv`` query heads attends its cache head.

    VERIFY-k: ``k = q.shape[-2]`` may exceed 1 (draft-verify decoding's
    fused verify step): the k new rows append at consecutive columns
    ``append_at .. append_at + k − 1`` and query row ``j`` attends
    columns ``<= valid_to + j`` — the shared prefix plus the intra-step
    causal triangle among the new rows, each row with its own online-
    softmax state. ``k`` must not exceed the K split (the rows then
    span at most two blocks — both written in place, everything else
    untouched); the int8 mirror stays single-token (``qk_quant='int8'``
    requires ``k == 1`` — the XLA path covers quantized verify-k).
    ``n_new (B,) int32`` (optional): per-slot count of rows ACTUALLY
    appended (mixed spec/non-spec batches — a slot with ``n_new = 1``
    rides the verify program as a classic decode step; rows past a
    slot's count are neither appended nor scored into it, and its query
    rows past the count produce don't-care outputs). Default: k rows
    wherever ``append_at >= 0``.

    ``valid_to (B,) int32``: per slot, the highest cache column its
    FIRST query row attends (its own global position, localized by the
    caller for sharded slabs; −1 or less = fully masked row → zero
    output). ``append_at (B,) int32``: the local column where
    ``k_new/v_new`` row 0 lands, or −1 to append nothing (inactive
    slot / non-owning shard). When ``append_at[i] >= 0`` it must equal
    ``valid_to[i]`` (standard causal decode ordering: each query row
    attends the rows at and before its own append column).

    ``qk_quant='int8'`` requires the cache's append-time mirror
    (``k_q``/``k_scale``) and scores s8×s8→s32 with in-kernel
    dequantization — the mirror's halved K bytes become halved stream
    traffic. The mirror and the bf16 buffer are BOTH appended in place.

    ``page_table (B, pages_per_slot) int32``: PAGED mode —
    ``cache_k``/``cache_v`` are global ``(pages + 1, H_kv, page_size,
    d·)`` pools whose LAST row is the reserved write-sink page
    (``init_paged_cache`` reserves it) and each slot's K split streams
    the pool pages its table row names (−1 = ordinal not held by this
    pool → the sink, and the kernel's run-gate skips scoring it; a
    slot appending nothing also writes its mandatory block flush to
    the sink, so no grid row ever writes a live page it doesn't own).
    A −1 below the causal fill is how a SEQUENCE-SHARDED page table
    expresses "another mesh shard owns this ordinal": each shard calls
    this kernel on its local pool + local table (``partials=True``)
    and the ``(num, m, l)`` triples pmax/psum-merge into exact full
    attention — the paged ring-decode step. The K split IS
    the page size, the grid and kernel body are unchanged — paging
    costs one prefetched index lookup per block, not a new kernel —
    and aliasing still writes only the single append page. With
    ``qk_quant='int8'``, ``k_q``/``k_scale`` are the MIRROR POOLS
    (``(pages + 1, H_kv, page_size, d) int8`` /
    ``(pages + 1, H_kv, page_size, 1) f32``,
    ``init_paged_cache(qk_quant='int8')``): scoring streams the int8
    pages through the same page-table redirect — halved K traffic at
    paged concurrency — and the mirror pages are appended in place
    alongside the bf16 pool.

    Returns ``(out, cache_k, cache_v, k_q, k_scale)`` with
    ``out (B, H, k, dv)`` in ``cache_v.dtype`` — or, with
    ``partials=True``, ``((num, m, l), cache_k, cache_v, k_q, k_scale)``
    where ``num (B, H, k, dv) f32`` is the un-normalized context and
    ``m/l (B, H, k, 1)`` the base-2 running max / denominator per query
    row, for the flash-decoding cross-shard merge (pmax the maxes,
    rescale, psum).
    """
    b, h, n, d = q.shape
    h_kv = cache_k.shape[1]
    dv = cache_v.shape[-1]
    paged = page_table is not None
    if n < 1:
        raise ValueError(f'flash_decode needs at least one query row, '
                         f'got {n}')
    if h % h_kv:
        raise ValueError(f'query heads {h} must be a multiple of cache '
                         f'kv heads {h_kv}')
    quantized = qk_quant == 'int8'
    if qk_quant not in (None, 'int8'):
        raise ValueError(f"qk_quant must be None or 'int8', "
                         f'got {qk_quant!r}')
    if quantized and n != 1:
        raise ValueError(
            f"qk_quant='int8' is single-token in the fused kernel "
            f'(got {n} rows) — the XLA decode path covers quantized '
            f'verify-k')
    if quantized and (k_q is None or k_scale is None):
        raise ValueError(
            "qk_quant='int8' needs the cache's k_q/k_scale mirror — "
            "init_cache(qk_quant='int8') for the slab buffers, "
            "init_paged_cache(qk_quant='int8') for the mirror pools")
    if paged:
        n_pages, bk = cache_k.shape[0], cache_k.shape[2]
        ns = page_table.shape[1]            # logical pages per slot
        t_max = ns * bk
        if block_k not in (None, bk):
            raise ValueError(f'paged decode splits K at the page size '
                             f'{bk}; block_k={block_k} cannot differ')
    else:
        t_max = cache_k.shape[2]
        bk = block_k or decode_block_k(t_max)
        if bk is None or t_max % bk:
            raise ValueError(
                f'no usable K split for t_max={t_max} (block_k must '
                f'divide it); use the XLA decode path for this cache '
                f'shape')
        ns = t_max // bk
    if n > bk:
        raise ValueError(
            f'verify-k width {n} exceeds the K split {bk} '
            f'({"page size" if paged else "block"}) — k rows must span '
            f'at most two blocks; use the XLA decode path for wider '
            f'verify steps')
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    group = h // h_kv
    nb = b * h_kv

    # Query rows grouped per cache head, NEW-ROW-major (row j·group + g
    # = new row j, query head g — the layout the kernel's per-row
    # intra-step mask assumes), padded to the sublane multiple of their
    # kernel dtype; padded rows are sliced off the output.
    qg = jnp.swapaxes(q.reshape(b, h_kv, group, n, d), 2, 3
                      ).reshape(nb, n * group, d)
    rows = n * group
    sub = 32 if quantized else (16 if cache_k.dtype == jnp.bfloat16
                                else 8)
    g_pad = -(-rows // sub) * sub
    if quantized:
        qi, sq = _quantize_rows(qg, nb, rows, d)
        qf = _pad_rows(qi, sub)
        sqf = _pad_rows(sq * (scale * _LOG2E), sub)
        kni, kns = _quantize_rows(
            k_new.astype(cache_k.dtype).reshape(nb, 1, d), nb, 1, d)
    else:
        qf = _pad_rows(
            (qg.astype(jnp.float32) * (scale * _LOG2E)
             ).astype(cache_k.dtype), sub)

    knf = k_new.astype(cache_k.dtype).reshape(nb, n, d)
    vnf = v_new.astype(cache_v.dtype).reshape(nb, n, dv)
    if paged:
        # Pool flattening mirrors the slab's (B, H_kv) fold: pool page
        # p's head hh lives at flat row p·H_kv + hh, so one BlockSpec
        # row index addresses (page, head) exactly like (slot, head).
        kf = cache_k.reshape(n_pages * h_kv, bk, d)
        vf = cache_v.reshape(n_pages * h_kv, bk, dv)
        # The table rides the prefetch RAW (−1s intact): the kernel
        # body's run-gate reads the sign to skip ordinals this pool
        # does not hold — beyond-fill on a single pool, another shard's
        # range on a sequence-sharded table — while the index maps
        # below clamp −1 to the pool's reserved SINK row (last page,
        # never allocated — init_paged_cache): a skipped ordinal
        # streams sink garbage (never scored) and, crucially, never
        # WRITES a page another slot owns — Pallas flushes every
        # output block, and grid rows have no cross-row write ordering
        # on real TPU, so parking idle write-backs on a live page
        # would race an in-flight append.
        sink = n_pages - 1
        ptf = jnp.asarray(page_table, jnp.int32).reshape(-1)
    else:
        kf = cache_k.reshape(nb, t_max, d)
        vf = cache_v.reshape(nb, t_max, dv)
    valid_to = jnp.asarray(valid_to, jnp.int32)
    append_at = jnp.asarray(append_at, jnp.int32)
    # Per-slot appended-row count: callers without mixed batches get
    # the full k wherever an append happens at all.
    if n_new is None:
        nnv = jnp.where(append_at >= 0, n, 0).astype(jnp.int32)
    else:
        nnv = jnp.asarray(n_new, jnp.int32)

    def const_idx(bi, ki, *rs):
        return (bi, 0, 0)

    def _stream_blk(bi, ki, vt):
        # Never DMA past a slot's last useful block (the LAST new row
        # attends up to vt + n − 1): beyond-fill splits alias the
        # resident block (skipped in-kernel), so a half-empty slot
        # streams half the bytes.
        last = jnp.clip((vt[bi // h_kv] + (n - 1)) // bk, 0, ns - 1)
        return jnp.minimum(ki, last)

    def _write_blk(bi, ki, ap, nn):
        # The k appended rows span blocks [first, last] (at most two,
        # n <= bk); clamping ki into the span walks the write ref over
        # each physical block exactly when the kernel body writes it.
        br = bi // h_kv
        a = ap[br]
        first = jnp.clip(a // bk, 0, ns - 1)
        last = jnp.clip((a + jnp.maximum(nn[br], 1) - 1) // bk,
                        0, ns - 1)
        return jnp.where(a >= 0, jnp.clip(ki, first, last), 0)

    if paged:
        # The tentpole redirect: the index map translates the LOGICAL
        # block ordinal through the prefetched page-table row instead
        # of using it as the physical block — the gather that makes
        # paging nearly free (same DMA skip, same aliasing).
        def stream_idx(bi, ki, vt, ap, nn, pt):
            blk = _stream_blk(bi, ki, vt)
            pg = pt[(bi // h_kv) * ns + blk]
            # −1 (ordinal not held by this pool) → the sink page; the
            # kernel's run-gate skips scoring it.
            return (jnp.where(pg >= 0, pg, sink) * h_kv + bi % h_kv,
                    0, 0)

        def write_idx(bi, ki, vt, ap, nn, pt):
            # Appending nothing → write-back lands on the sink page,
            # never on a page some other slot is appending into; same
            # for a −1 table entry (the table rides RAW — clamp here).
            br = bi // h_kv
            a = ap[br]
            blk = _write_blk(bi, ki, ap, nn)
            pg = pt[br * ns + blk]
            page = jnp.where(jnp.logical_and(a >= 0, pg >= 0), pg, sink)
            return (page * h_kv + bi % h_kv, 0, 0)

        # Mirror-scale flat rows are (pages·H_kv, 1, page_size): one
        # K-split block per pool page, so the block index is always 0
        # and the ROW rides the same page-table redirect as the data
        # pages — the data-pool maps ARE the scale maps (one
        # definition, so a sink-redirect fix cannot miss its twin).
        stream_idx_row = stream_idx
        write_idx_row = write_idx
    else:
        def stream_idx(bi, ki, vt, ap, nn):
            return (bi, _stream_blk(bi, ki, vt), 0)

        def write_idx(bi, ki, vt, ap, nn):
            return (bi, _write_blk(bi, ki, ap, nn), 0)

        # The int8 scale mirror rides as a (nb, 1, t_max) ROW vector (a
        # size-1-axis reshape — a bitcast, not a transpose), blocked on
        # the LAST axis, so the kernel consumes (1, BK) scale rows
        # directly.
        def stream_idx_row(bi, ki, vt, ap, nn):
            return (bi, 0, _stream_blk(bi, ki, vt))

        def write_idx_row(bi, ki, vt, ap, nn):
            return (bi, 0, _write_blk(bi, ki, ap, nn))

    in_specs = [pl.BlockSpec((1, g_pad, d), const_idx)]
    args = [qf]
    if quantized:
        in_specs.append(pl.BlockSpec((1, g_pad, 1), const_idx))
        args.append(sqf)
    in_specs.append(pl.BlockSpec((1, n, d), const_idx))
    args.append(knf)
    if quantized:
        in_specs += [pl.BlockSpec((1, 1, d), const_idx),
                     pl.BlockSpec((1, 1, 1), const_idx)]
        args += [kni, kns.reshape(nb, 1, 1)]
    in_specs.append(pl.BlockSpec((1, n, dv), const_idx))
    args.append(vnf)
    # The bf16 K buffer: streamed for scoring in the plain path; in the
    # quantized path scoring reads the mirror instead, so K is fetched
    # ONLY at its write block (one DMA per slot, to seed the append).
    in_specs.append(pl.BlockSpec((1, bk, d),
                                 write_idx if quantized else stream_idx))
    k_in_pos = len(args)
    args.append(kf)
    kq_in_pos = ks_in_pos = None
    if quantized:
        if paged:
            # Mirror POOLS flatten exactly like the data pools: pool
            # page p's head hh at flat row p·H_kv + hh; the scale pool
            # folds its size-1 last axis into a (…, 1, page_size) row
            # vector per flat row (a bitcast, not a transpose).
            kqf = k_q.reshape(n_pages * h_kv, bk, d)
            ksf = k_scale.reshape(n_pages * h_kv, 1, bk)
        else:
            kqf = k_q.reshape(nb, t_max, d)
            ksf = k_scale.reshape(nb, 1, t_max)
        in_specs += [pl.BlockSpec((1, bk, d), stream_idx),
                     pl.BlockSpec((1, 1, bk), stream_idx_row)]
        kq_in_pos = len(args)
        args.append(kqf)
        ks_in_pos = len(args)
        args.append(ksf)
    in_specs.append(pl.BlockSpec((1, bk, dv), stream_idx))
    v_in_pos = len(args)
    args.append(vf)
    has_alibi = alibi_slopes is not None
    if has_alibi:
        # Per-query-head slopes, pre-folded by log2e (the kernel's
        # logits are in log2 units), laid out (nb, g_pad, 1) so slope
        # rows align with their grouped query rows (tiled over the n
        # new rows — row j·group + g carries head g's slope).
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(
            h_kv, group, 1) * _LOG2E
        slopes = jnp.broadcast_to(slopes[None, :, None],
                                  (b, h_kv, n, group, 1))
        in_specs.append(pl.BlockSpec((1, g_pad, 1), const_idx))
        args.append(_pad_rows(slopes.reshape(nb, n * group, 1), sub))

    out_specs = [
        pl.BlockSpec((1, g_pad, dv), const_idx),   # num
        pl.BlockSpec((1, g_pad, 1), const_idx),    # m
        pl.BlockSpec((1, g_pad, 1), const_idx),    # l
        pl.BlockSpec((1, bk, d), write_idx),       # k (aliased)
        pl.BlockSpec((1, bk, dv), write_idx),      # v (aliased)
    ]
    out_shape = [
        jax.ShapeDtypeStruct((nb, g_pad, dv), jnp.float32),
        jax.ShapeDtypeStruct((nb, g_pad, 1), jnp.float32),
        jax.ShapeDtypeStruct((nb, g_pad, 1), jnp.float32),
        jax.ShapeDtypeStruct(kf.shape, kf.dtype),
        jax.ShapeDtypeStruct(vf.shape, vf.dtype),
    ]
    # +n_prefetch: alias indices count the scalar-prefetch operands
    # (valid_to, append_at, n_new, and — paged — the flattened page
    # table).
    n_prefetch = 4 if paged else 3
    aliases = {n_prefetch + k_in_pos: 3, n_prefetch + v_in_pos: 4}
    if quantized:
        out_specs += [pl.BlockSpec((1, bk, d), write_idx),
                      pl.BlockSpec((1, 1, bk), write_idx_row)]
        out_shape += [jax.ShapeDtypeStruct(kqf.shape, kqf.dtype),
                      jax.ShapeDtypeStruct(ksf.shape, ksf.dtype)]
        aliases[n_prefetch + kq_in_pos] = 5
        aliases[n_prefetch + ks_in_pos] = 6

    kernel = _make_decode_kernel(bk, ns, n, group, g_pad, h_kv, window,
                                 quantized, has_alibi, paged=paged)
    prefetch = ((valid_to, append_at, nnv, ptf) if paged
                else (valid_to, append_at, nnv))
    outs = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=n_prefetch,
            grid=(nb, ns),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[pltpu.VMEM((g_pad, 1), jnp.float32),
                            pltpu.VMEM((g_pad, 1), jnp.float32),
                            pltpu.VMEM((g_pad, dv), jnp.float32)]),
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret)(*prefetch, *args)

    num, m, l, new_k, new_v = outs[:5]
    new_kq = new_ks = None
    if quantized:
        new_kq = outs[5].reshape(k_q.shape)
        new_ks = outs[6].reshape(k_scale.shape)   # same flat order
    new_k = new_k.reshape(cache_k.shape)
    new_v = new_v.reshape(cache_v.shape)

    def head_shape(x):
        # Rows are new-row-major per kv head: undo the (n, group) fold
        # back to (B, H, n, ·).
        x = x[:, :n * group].reshape(b, h_kv, n, group, x.shape[-1])
        return jnp.swapaxes(x, 2, 3).reshape(b, h, n, x.shape[-1])

    num, m, l = head_shape(num), head_shape(m), head_shape(l)
    if partials:
        return (num, m, l), new_k, new_v, new_kq, new_ks
    out = (num / jnp.where(l == 0.0, 1.0, l)).astype(cache_v.dtype)
    return out, new_k, new_v, new_kq, new_ks
