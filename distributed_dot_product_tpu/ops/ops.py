# -*- coding: utf-8 -*-
"""
Differentiable distributed matmul operators (custom-gradient layer).

TPU-native rebuild of the reference L3 layer (reference
multiplication/ops.py), which wraps each distributed matmul in a
``torch.autograd.Function`` whose backward is expressed in terms of the
other two kernels. Here each is a :func:`jax.custom_vjp` with the same VJP
pairings:

- ``matmul_nt``  (= ``RightTransposeMultiplication``, reference ops.py:19-37)
  fwd ``out = A·Bᵀ``; bwd ``dA = all(dOut, B)``, ``dB = tn(dOut, A)``.
- ``matmul_all`` (= ``FullMultiplication``, reference ops.py:40-54)
  fwd ``out = A·B``;  bwd ``dA = nt(dOut, B)``,  ``dB = tn(A, dOut)``.
- ``matmul_tn``  (= ``LeftTransposeMultiplication``, reference ops.py:57-71)
  fwd ``out = Aᵀ·B``; bwd ``dA = nt(B, dOut)``,  ``dB = all(A, dOut)``.

Two deliberate fixes over the reference (documented in SURVEY §2.1):

1. **Forward ``offset`` propagation.** The reference saves ``offset`` in
   ``ctx`` but silently drops it on the *forward* calls of both
   ``RightTransposeMultiplication`` (reference ops.py:25) and
   ``FullMultiplication`` (reference ops.py:45), which therefore always run
   with the default 32. Here ``offset`` applies to forward and backward.
2. **The ``LeftTransposeMultiplication`` left-gradient.** For
   ``out = AᵀB``: ``out_{ij} = Σ_k A_{ki} B_{kj}`` so
   ``dA = B·dOutᵀ = nt(B, dOut)``. The reference computes
   ``nt(dOut, B)`` (reference ops.py:69) — the transpose of the correct
   cotangent — and no reference test exercises it (SURVEY §4). We implement
   the correct VJP and verify it against full-array autodiff in
   ``tests/test_ops_grad.py``.

The ``offset`` and ``axis_name`` arguments are non-differentiable static
configuration (``nondiff_argnums``) — the analog of the reference's
``return grad_left, grad_right, None`` convention (reference ops.py:37).
"""

from functools import partial

import jax

from distributed_dot_product_tpu.ops.functions import (
    distributed_matmul_all, distributed_matmul_nt, distributed_matmul_tn,
)
from distributed_dot_product_tpu.utils.comm import SEQ_AXIS

__all__ = [
    'matmul_nt', 'matmul_all', 'matmul_tn',
    'RightTransposeMultiplication', 'FullMultiplication',
    'LeftTransposeMultiplication',
]


# --- A·Bᵀ -------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _nt(left, right, offset, axis_name, impl):
    return distributed_matmul_nt(left, right, offset, axis_name=axis_name,
                                 impl=impl)


def _nt_fwd(left, right, offset, axis_name, impl):
    return _nt(left, right, offset, axis_name, impl), (left, right)


def _nt_bwd(offset, axis_name, impl, residuals, g):
    left, right = residuals
    # out = L·Rᵀ  ⇒  dL = dOut·R,  dR = dOutᵀ·L  (reference ops.py:29-37).
    grad_left = distributed_matmul_all(g, right, offset, axis_name=axis_name,
                                       impl=impl)
    grad_right = distributed_matmul_tn(g, left, axis_name=axis_name)
    return grad_left, grad_right


_nt.defvjp(_nt_fwd, _nt_bwd)


def matmul_nt(left, right, offset=32, axis_name=SEQ_AXIS, impl='allgather'):
    """Differentiable ``A·Bᵀ`` on sequence shards ``(*, T/N, D)`` →
    ``(*, T/N, T)``. Reference ``RightTransposeMultiplication.apply``
    (reference ops.py:19-37)."""
    return _nt(left, right, offset, axis_name, impl)


# --- A·B --------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _full(left, right, offset, axis_name, impl):
    return distributed_matmul_all(left, right, offset, axis_name=axis_name,
                                  impl=impl)


def _full_fwd(left, right, offset, axis_name, impl):
    return _full(left, right, offset, axis_name, impl), (left, right)


def _full_bwd(offset, axis_name, impl, residuals, g):
    left, right = residuals
    # out = L·R  ⇒  dL = dOut·Rᵀ,  dR = Lᵀ·dOut  (reference ops.py:49-54).
    grad_left = distributed_matmul_nt(g, right, offset, axis_name=axis_name,
                                      impl=impl)
    grad_right = distributed_matmul_tn(left, g, axis_name=axis_name)
    return grad_left, grad_right


_full.defvjp(_full_fwd, _full_bwd)


def matmul_all(left, right, offset=32, axis_name=SEQ_AXIS,
               impl='allgather'):
    """Differentiable ``A·B`` on sequence shards ``(*, T/N, T) × (*, T/N, D)``
    → ``(*, T/N, D)``. Reference ``FullMultiplication.apply``
    (reference ops.py:40-54)."""
    return _full(left, right, offset, axis_name, impl)


# --- Aᵀ·B -------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _tn(left, right, offset, axis_name, impl):
    return distributed_matmul_tn(left, right, axis_name=axis_name)


def _tn_fwd(left, right, offset, axis_name, impl):
    return _tn(left, right, offset, axis_name, impl), (left, right)


def _tn_bwd(offset, axis_name, impl, residuals, g):
    left, right = residuals
    # out = Lᵀ·R  ⇒  dL = R·dOutᵀ = nt(R, dOut)  — operand order fixed
    # vs the reference's nt(dOut, R) (reference ops.py:69, see module
    # docstring);  dR = L·dOut = all(L, dOut)  (reference ops.py:70).
    grad_left = distributed_matmul_nt(right, g, offset, axis_name=axis_name,
                                      impl=impl)
    grad_right = distributed_matmul_all(left, g, offset,
                                        axis_name=axis_name, impl=impl)
    return grad_left, grad_right


_tn.defvjp(_tn_fwd, _tn_bwd)


def matmul_tn(left, right, offset=32, axis_name=SEQ_AXIS, impl='allgather'):
    """Differentiable ``Aᵀ·B`` on sequence shards ``(*, T/N, T) × (*, T/N, D)``
    → ``(*, T/N, D)``. Reference ``LeftTransposeMultiplication.apply``
    (reference ops.py:57-71).

    ``offset`` and ``impl`` configure the BACKWARD kernels only (the
    gradients are an nt and an all matmul, which have both knobs); the tn
    forward is a single fused matmul + ``psum_scatter`` with nothing to
    chunk or ring-rotate (reference functions.py:103 likewise has no
    offset). They are accepted so the three operators stay
    call-compatible."""
    return _tn(left, right, offset, axis_name, impl)


# ---------------------------------------------------------------------------
# API-parity aliases: the reference exposes these as autograd.Function
# classes used via ``.apply(left, right, offset)`` (reference module.py:61,
# 69). Thin shims so reference call sites read the same.
# ---------------------------------------------------------------------------

class RightTransposeMultiplication:
    """``.apply(left, right, offset)`` → ``matmul_nt`` (reference ops.py:19)."""
    apply = staticmethod(matmul_nt)


class FullMultiplication:
    """``.apply(left, right, offset)`` → ``matmul_all`` (reference ops.py:40)."""
    apply = staticmethod(matmul_all)


class LeftTransposeMultiplication:
    """``.apply(left, right, offset)`` → ``matmul_tn`` (reference ops.py:57)."""
    apply = staticmethod(matmul_tn)
