# -*- coding: utf-8 -*-
"""Dependency-free version info (importable by setuptools' metadata build
without jax present). The reference keeps VERSION_INFO in its __init__
(reference __init__.py:9-10); same convention, re-exported there."""

VERSION_INFO = (0, 1, 0, 'dev0')
__version__ = '.'.join(map(str, VERSION_INFO[:3])) + (
    '.' + VERSION_INFO[3] if len(VERSION_INFO) > 3 else '')
