# -*- coding: utf-8 -*-
"""
Token proposers for speculative (draft-verify) decoding — the
"guess k tokens" half of the scheme whose "check them in one step" half
is the engine's fused verify-k program.

Draft-verify decoding (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding"): a cheap proposer guesses k
continuation tokens, the target model scores all k+1 positions in ONE
verify step, and the longest prefix of guesses matching the target's
own (greedy) choices is committed — plus the one "free" token the
verify step computes after it. Greedy verification makes the scheme
EXACT: the committed stream is token-for-token the non-speculative
stream whatever the proposer emits; a bad proposer only costs wasted
verify width, never correctness. The scheduler therefore treats
proposers as untrusted accelerators — mixed spec/non-spec batches ride
the same verify program with per-slot counts.

Two proposers ship:

- :class:`NgramProposer` — self-drafting n-gram lookahead (a.k.a.
  prompt-lookup decoding): find the longest recent suffix of the
  slot's token history (prompt + emitted) that occurred earlier, and
  propose the tokens that followed that earlier occurrence. No model,
  no state, no device work — pure host lookup. Wins big exactly where
  decode is most wasteful: repetitive continuations (code, templated
  text, retrieval-grounded answers that quote the prompt).
- :class:`DraftEngineProposer` — a small draft model with its OWN
  per-slot KV cache and acceptance-prefix rollback, stepped k times to
  propose and rolled back to the committed prefix after each verify
  (the draft cache mirrors exactly the committed history, so draft
  guesses stay aligned with the target stream). Wraps any engine with
  the :class:`~distributed_dot_product_tpu.serve.engine.KernelEngine`
  surface; :func:`make_draft_engine` builds the default twin.
"""

import numpy as np

__all__ = ['Proposer', 'NgramProposer', 'DraftEngineProposer',
           'make_draft_engine', 'ngram_propose']


def ngram_propose(history, k, max_ngram=3):
    """Suffix-match lookahead over ``history`` (a 1-D int sequence):
    find the LONGEST suffix of length ``<= max_ngram`` that occurred
    earlier in the history and return up to ``k`` of the tokens that
    followed it. Among matches of one length, the most recent with a
    FULL ``k``-token continuation wins, falling back to the longest
    continuation found — a match sitting near the end of the history
    (the common case on a cyclic tail, exactly where lookahead pays
    most) would otherwise truncate the guess to a token or two.
    Returns ``[]`` when no suffix recurs (the slot then rides the tick
    as a plain non-spec decode). Pure host work, O(len · max_ngram)
    worst case."""
    h = np.asarray(history, np.int64)
    n = len(h)
    if k < 1 or n < 2:
        return []
    for length in range(min(max_ngram, n - 1), 0, -1):
        pattern = h[n - length:]
        # Candidate start positions of an EARLIER occurrence (the
        # suffix itself, ending at n, is excluded).
        starts = np.flatnonzero(h[:n - length] == pattern[0])
        best = None
        for s in starts[::-1]:                  # most recent first
            if s + length > n - 1:
                continue
            if np.array_equal(h[s:s + length], pattern):
                cont = h[s + length:s + length + k]
                if len(cont) == k:
                    return [int(t) for t in cont]
                if best is None or len(cont) > len(best):
                    best = cont
        if best is not None and len(best):
            return [int(t) for t in best]
    return []


class Proposer:
    """Interface the scheduler drives. All hooks default to no-ops so a
    stateless proposer only implements :meth:`propose_batch`.

    Lifecycle per slot: :meth:`start` when a request begins decoding in
    a slot (full prompt known — requeues restart here too), then per
    verify tick :meth:`propose_batch` → (scheduler verifies) →
    :meth:`commit` per slot → :meth:`end_step` once; :meth:`reset` when
    the slot frees (retire/evict/quarantine/preempt)."""

    def start(self, slot, history):
        """``history``: the full committed token list (prompt + emitted
        so far — nonempty; its last token is the slot's next input)."""

    def propose_batch(self, requests, k):
        """``requests``: list of ``(slot, history, cap)`` with ``cap <=
        k`` the most tokens that slot can use this tick. Returns
        ``{slot: [token, ...]}`` with each list at most ``cap`` long
        (missing/empty = no proposal — the slot decodes normally)."""
        raise NotImplementedError

    def commit(self, slot, committed, accepted):
        """``committed``: tokens just appended to the stream (the
        accepted proposals plus the free token); ``accepted``: how many
        PROPOSALS survived (``len(committed) - 1`` unless the stream
        hit a terminal condition mid-commit)."""

    def end_step(self):
        """Called once after all :meth:`commit` calls of a tick."""

    def reset(self, slot):
        """The slot was freed (or its request requeued)."""


class NgramProposer(Proposer):
    """Self-drafting n-gram lookahead (:func:`ngram_propose` per slot).
    Stateless — the history arrives with every propose call, so
    requeues, forks and slot reuse need no bookkeeping."""

    def __init__(self, max_ngram=3):
        if max_ngram < 1:
            raise ValueError(f'max_ngram must be >= 1, got {max_ngram}')
        self.max_ngram = max_ngram

    def propose_batch(self, requests, k):
        out = {}
        for slot, history, cap in requests:
            props = ngram_propose(history, min(cap, k), self.max_ngram)
            if props:
                out[slot] = props
        return out


def make_draft_engine(target, *, heads=None, head_dim=None, seed=None,
                      vocab=None):
    """The default draft twin of a target
    :class:`~distributed_dot_product_tpu.serve.engine.KernelEngine`:
    same slots/t_max/vocab (the draft cache mirrors the target's
    per-slot clocks; proposals must be target-vocabulary tokens), slab
    cache (the draft never shares prefixes), and — by default — the
    target's own shape and seed, i.e. a self-draft that accepts
    everything (the zero-risk demo of the machinery; a real deployment
    passes a smaller ``heads``/``head_dim`` or a distilled
    checkpoint's seed)."""
    from distributed_dot_product_tpu.serve.engine import KernelEngine
    return KernelEngine(
        slots=target.slots, t_max=target.t_max,
        vocab=vocab or target.vocab,
        heads=heads or target.heads,
        head_dim=head_dim or target.head_dim,
        prefill_chunk=target.prefill_chunk,
        seed=target.seed if seed is None else seed,
        decode_impl=target.decode_impl,
        # Always a slab: the draft never shares prefixes, and the env
        # paged knob (DDP_TPU_PAGED_CACHE) must not silently page the
        # twin when the target was constructed paged explicitly.
        cache_mode='slab')


class DraftEngineProposer(Proposer):
    """Draft-model proposer: a small greedy engine with its own
    per-slot KV cache, kept exactly in sync with the COMMITTED stream
    by acceptance-prefix rollback.

    Invariant between ticks: the draft cache of slot ``i`` holds the
    k/v of ``history[:-1]`` and ``history[-1]`` is the next input —
    the same convention as the target engine. Proposing runs the draft
    ``c_max + 1`` batched steps (step j appends the previous token and
    emits guess j; the extra step appends the LAST guess's k/v so a
    fully-accepted verify leaves nothing missing), and :meth:`commit` /
    :meth:`end_step` roll every slot back to ``pre + 1 + accepted`` —
    bit-identical to having decoded only the committed tokens."""

    def __init__(self, engine):
        self.engine = engine
        self._pre = np.zeros(engine.slots, np.int64)    # len before propose
        self._targets = {}                              # slot -> rollback len
        self._proposed = set()                          # slots of last batch

    def start(self, slot, history):
        self.engine.reset(slot)
        history = np.asarray(history, np.int32)
        body = history[:-1]
        chunk = self.engine.prefill_chunk
        for s in range(0, len(body), chunk):
            self.engine.prefill(slot, body[s:s + chunk])

    def propose_batch(self, requests, k):
        self._proposed = {slot for slot, _, _ in requests}
        if not requests:
            return {}
        eng = self.engine
        slots = eng.slots
        caps = np.zeros(slots, np.int64)
        cur = np.zeros(slots, np.int32)
        mask = np.zeros(slots, bool)
        for slot, history, cap in requests:
            caps[slot] = min(cap, k)
            cur[slot] = int(history[-1])
            mask[slot] = True
        self._pre[mask] = np.asarray(eng.lengths())[mask]
        out = {slot: [] for slot, _, _ in requests}
        c_max = int(caps.max())
        # Step j (1-based) appends the previous token's k/v and emits
        # guess j; a slot drafts while j <= cap and takes one extra
        # append-only step (j == cap + 1) so the last guess's k/v is
        # in the draft cache when the verify accepts it.
        for j in range(1, c_max + 2):
            act = mask & (caps + 1 >= j)
            if not act.any():
                break
            nxt, _ = eng.step(cur, act)
            for slot in out:
                if j <= caps[slot]:
                    out[slot].append(int(nxt[slot]))
            cur = np.where(act, nxt, cur)
        return {slot: props for slot, props in out.items() if props}

    def commit(self, slot, committed, accepted):
        # A slot the last propose_batch never drafted for has a stale
        # _pre anchor — leave its cache alone (guesses for it degrade
        # until its next start/propose; correctness never depends on
        # the draft state).
        if slot in self._proposed:
            self._targets[slot] = (int(self._pre[slot]) + 1
                                   + int(accepted))

    def end_step(self):
        if not self._targets:
            return
        big = np.iinfo(np.int32).max
        lengths = np.full(self.engine.slots, big, np.int64)
        for slot, tgt in self._targets.items():
            lengths[slot] = tgt
        self._targets.clear()
        self.engine.rollback(lengths)

    def reset(self, slot):
        self._targets.pop(slot, None)
        self._proposed.discard(slot)
        self.engine.reset(slot)
