# -*- coding: utf-8 -*-
"""
The compiled substrate the scheduler drives: a minimal greedy LM over
the KV-cache decode kernels (``models/decode.py``), batched across
decode SLOTS with per-slot lengths.

Why a dedicated engine instead of :class:`TransformerLM`: continuous
batching needs every batch row on its OWN sequence clock, which is
exactly what the per-slot cache (``init_slot_cache`` /
``append_kv_slots`` / per-slot-masked ``decode_attention``) provides at
the kernel level. The flax stack's decode surface shares one scalar
length across the batch (lockstep generation); threading per-slot
lengths through it is a model-side project — the serving layer's job is
the scheduling around the kernels, so it drives them directly: token
embedding → q/k/v projections → per-slot cache append → per-slot masked
attention → logits. Fixed seeded weights (serving robustness doesn't
need trained weights; determinism does).

Three compiled programs serve the whole lifecycle, shapes fixed at
construction so nothing ever retraces mid-serve:

- ``decode``: one token for EVERY slot (inactive slots masked out of
  the append; their outputs ignored) + per-slot all-finite verdict on
  the logits. The append+attend pair is the FUSED step
  (``models.decode.decode_step``): on the kernel path it is one Pallas
  program with the cache aliased in place, so the donated buffers are
  never copied. The fault injector's NaN mask is applied IN-PROGRAM so
  the quarantine predicate sees real NaNs from the compiled step.
- ``prefill``: one padded prompt chunk into one slot's cache rows (no
  attention — only the last prompt position's logits matter, and the
  scheduler feeds that token through ``decode``).
- ``reset``: zero one slot's rows and length (eviction/quarantine).

Every computation is batch-row independent (embedding lookups, row-wise
matmuls, per-slot masked attention, per-row argmax), so a request's
tokens depend only on its prompt and the seed — NOT on which slot it
lands in or what its neighbors are doing. The scheduler's bit-identity
guarantees (quarantine leaves other slots' streams untouched; a
requeued request regenerates the same tokens) rest on this property,
and the tests pin it.
"""

import itertools
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from distributed_dot_product_tpu.models.decode import (
    PageChecksums, PagePool, append_kv_slots, decode_step,
    init_paged_cache, init_slot_cache, paged_append_rows,
    paged_copy_attach, paged_reset_slot, paged_rollback_slots,
    paged_transfer_pages, reset_slot, rollback_slots, slots_all_finite,
)
from distributed_dot_product_tpu.obs import spans as obs_spans
from distributed_dot_product_tpu.obs.spans import span

__all__ = ['KernelEngine', 'PageCorruptionError']


class PageCorruptionError(RuntimeError):
    """A pool page's content no longer matches its recorded checksum.
    ``pages`` names the dirty pages, ``site`` the transfer/attach
    boundary that caught them ('scrub', 'attach', 'fork',
    'handoff_src', 'handoff_copy') — the router turns this into the
    `kv.corrupt` event + quarantine + heal arc."""

    def __init__(self, pages, site):
        self.pages = sorted(int(p) for p in pages)
        self.site = site
        super().__init__(
            f'KV page corruption at {site}: page(s) {self.pages} fail '
            f'checksum verification')


def _resolve_decode_impl(decode_impl):
    """Engine decode-path selection: an explicit argument wins; else the
    ``DDP_TPU_DECODE_KERNEL`` env knob (1/kernel → fused Pallas step,
    0/xla → portable step) — the hook ``scripts/smoke_serve.sh`` uses
    to prove the fault cocktail on the kernel path; else 'auto' (kernel
    on TPU, XLA elsewhere — see models/decode.decode_step)."""
    if decode_impl is not None:
        return decode_impl
    env = os.environ.get('DDP_TPU_DECODE_KERNEL', '').strip().lower()
    if env in ('1', 'true', 'kernel'):
        return 'kernel'
    if env in ('0', 'false', 'xla'):
        return 'xla'
    return 'auto'


def _resolve_weight_quant(weight_quant):
    """Weight-precision selection: explicit argument wins ('off'/None =
    float weights, 'int8' = per-output-channel int8 weights with
    in-program s8×s8→s32 dequant — models/dense.quantize_kernel's rule);
    else the ``DDP_TPU_WEIGHT_QUANT`` env knob — the deployment switch
    the quantized-serving benchmark rows flip."""
    if weight_quant is not None:
        if weight_quant == 'off':
            return None
        if weight_quant not in ('int8',):
            raise ValueError(f"weight_quant must be None/'off'/'int8', "
                             f'got {weight_quant!r}')
        return weight_quant
    env = os.environ.get('DDP_TPU_WEIGHT_QUANT', '').strip().lower()
    if env in ('1', 'true', 'int8'):
        return 'int8'
    return None


def _resolve_cache_mode(cache_mode):
    """Cache-layout selection: explicit argument wins; else the
    ``DDP_TPU_PAGED_CACHE`` env knob (1/paged → page-pool cache); else
    the slab reference layout."""
    if cache_mode is not None:
        if cache_mode not in ('slab', 'paged'):
            raise ValueError(f"cache_mode must be 'slab' or 'paged', "
                             f'got {cache_mode!r}')
        return cache_mode
    env = os.environ.get('DDP_TPU_PAGED_CACHE', '').strip().lower()
    if env in ('1', 'true', 'paged'):
        return 'paged'
    return 'slab'


class KernelEngine:
    """Greedy decode engine over ``slots`` independent sequences.

    ``prefill_chunk`` is the compiled chunk width for prompt ingestion
    (prompts append in ceil(len/chunk) calls — "chunked prefill", so a
    long prompt never monopolizes the loop between decode steps).

    ``decode_impl``: 'kernel' runs the decode step as the fused Pallas
    program (in-place aliased cache append + split-K attention —
    ops/pallas_decode.py; the three compiled programs then stop paying
    any cache round trip), 'xla' the portable append+einsum step, None
    reads ``DDP_TPU_DECODE_KERNEL`` then defaults to auto (kernel on
    TPU). Token streams are deterministic within an impl; the two
    impls agree to float tolerance (exp2 vs exp rounding), so
    bit-identity guarantees hold per-impl, not across.

    ``cache_mode='paged'`` (or ``DDP_TPU_PAGED_CACHE=1``) swaps the
    per-slot slab for the page-pool cache (``models/decode.py``
    ``PagedDecodeCache``): ``pages`` sizes the global pool (the memory
    budget — decoupled from ``slots × t_max``), ``page_size`` the page
    granularity (= the kernel's K split). The host :class:`PagePool`
    owns allocation; :meth:`step`/:meth:`prefill` auto-reserve the
    pages they need (raising on exhaustion), while the Scheduler calls
    :meth:`prepare_step`/:meth:`reserve_rows` itself so a deficit
    routes through its evict/preempt ladder instead of a raise.
    :meth:`register_prefix`/:meth:`start_with_prefix` give refcounted
    prefix sharing, :meth:`fork_slot` copy-on-write forks. Token
    streams are bit-identical to the slab engine per impl.

    ``weight_quant='int8'`` (or ``DDP_TPU_WEIGHT_QUANT=int8``) stores
    the four projection/head matrices int8 with per-output-channel
    scales (``models/dense.quantize_kernel``); every projection and
    the logits dot then quantize their activation rows on the fly and
    run s8×s8→s32 with the dequantization applied to the s32 result —
    half the weight bytes per step, deterministic streams (the
    bit-identity guarantees hold per weight_quant setting, exactly as
    they hold per decode impl), layout-oblivious (slab and paged
    engines with the same seed + weight_quant emit identical
    streams).
    """

    def __init__(self, slots, t_max, *, vocab=64, heads=2, head_dim=8,
                 prefill_chunk=8, seed=0, dtype=jnp.float32,
                 decode_impl=None, cache_mode=None, pages=None,
                 page_size=None, weight_quant=None, kv_checksums=True):
        if slots < 1 or t_max < 2:
            raise ValueError(f'need slots >= 1 and t_max >= 2, got '
                             f'{slots}/{t_max}')
        self.decode_impl = _resolve_decode_impl(decode_impl)
        self.cache_mode = _resolve_cache_mode(cache_mode)
        self.weight_quant = _resolve_weight_quant(weight_quant)
        self.slots = slots
        self.t_max = t_max
        self.vocab = vocab
        self.heads = heads
        self.head_dim = head_dim
        self.prefill_chunk = prefill_chunk
        self.seed = seed
        dim = heads * head_dim
        ks = jax.random.split(jax.random.key(seed), 5)
        scale = 1.0 / np.sqrt(dim)
        self._embed = jax.random.normal(ks[0], (vocab, dim), dtype) * scale
        self._wq = jax.random.normal(ks[1], (dim, dim), dtype) * scale
        self._wk = jax.random.normal(ks[2], (dim, dim), dtype) * scale
        self._wv = jax.random.normal(ks[3], (dim, dim), dtype) * scale
        self._wo = jax.random.normal(ks[4], (dim, vocab), dtype) * scale
        if self.weight_quant == 'int8':
            # Load/convert-time quantization — the engine analog of
            # models/dense.quantize_dense_params: weights stored int8
            # (half/quarter the bytes), per-output-channel scales. The
            # embedding stays float: it feeds a LOOKUP, not a matmul.
            from distributed_dot_product_tpu.models.dense import (
                quantize_kernel,
            )
            self._wq = quantize_kernel(self._wq)
            self._wk = quantize_kernel(self._wk)
            self._wv = quantize_kernel(self._wv)
            self._wo = quantize_kernel(self._wo)
        if self.cache_mode == 'paged':
            ps = page_size or min(16, t_max)
            if t_max % ps:
                raise ValueError(f'page_size {ps} must divide t_max '
                                 f'{t_max}')
            self.page_size = ps
            # Default pool = the slab's bytes; the paged win comes from
            # sizing `pages` to the MEMORY budget while raising `slots`
            # past what a slab of the same bytes could hold.
            n_pages = pages if pages is not None \
                else slots * (t_max // ps)
            self.pool = PagePool(n_pages, ps, slots, t_max // ps)
            self.cache = init_paged_cache(slots, heads, t_max, head_dim,
                                          pages=n_pages, page_size=ps,
                                          dtype=dtype)
            self._prefix_registry = {}
            self._prefix_counter = itertools.count()
            # Per-page integrity table: registry/transfer pages only,
            # digested at transfer boundaries on the host — never
            # inside a compiled program ("verify at transfer, never
            # per step"). kv_checksums=False is the no-integrity twin.
            self.checksums = PageChecksums() if kv_checksums else None
        else:
            self.page_size = None
            self.pool = None
            self.checksums = None
            self.cache = init_slot_cache(slots, heads, t_max, head_dim,
                                         dtype=dtype)
        self.verify_seconds = 0.0   # host wall time spent digesting
        # Donated caches: appends write in place — see models/decode.py's
        # performance note. One compiled program each for the lifetime —
        # and the retrace sentinel (analysis/retrace.py) enforces it:
        # shapes are fixed at construction, so more than budget traces
        # of one program means something un-cacheable leaked into the
        # step (the round-5 retrace-storm class). Budget 2: the real
        # trace plus one registry lowering / weak-type respin.
        from distributed_dot_product_tpu.analysis.retrace import (
            watch_traces,
        )
        self._decode = jax.jit(
            watch_traces(self._decode_impl, 'engine.decode', budget=2),
            donate_argnums=(0,))
        self._prefill = jax.jit(
            watch_traces(self._prefill_impl, 'engine.prefill', budget=2),
            donate_argnums=(0,))
        if self.cache_mode == 'paged':
            self._reset = jax.jit(
                watch_traces(paged_reset_slot, 'engine.reset', budget=2),
                donate_argnums=(0,))
            # The sharing primitives: CoW/fork/attach page copy (+
            # length set) and registry prefix prefill — each one fixed
            # compiled program, dispatched only on page crossings and
            # prefix/fork events, never per token.
            self._copy_attach = jax.jit(
                watch_traces(paged_copy_attach, 'engine.copy_attach',
                             budget=2),
                donate_argnums=(0,))
            self._prefix_fill = jax.jit(
                watch_traces(self._prefix_fill_impl,
                             'engine.prefix_fill', budget=2),
                donate_argnums=(0,))
        else:
            self._reset = jax.jit(
                watch_traces(reset_slot, 'engine.reset', budget=2),
                donate_argnums=(0,))
        # Speculative decoding programs, built LAZILY (a non-spec
        # engine never pays their traces): one verify program per
        # width W = k+1 and one rollback program per span, each a
        # fixed compiled shape under its own retrace budget.
        self._verifies = {}
        self._rollbacks = {}
        # Cross-cache KV handoff programs (disaggregated serving):
        # one per SOURCE pool shape — a topology has exactly one
        # prefill pool shape, so one program for the engine's life.
        self._transfers = {}

    # -- compiled bodies ------------------------------------------------
    def _dot(self, x, w):
        """``x (rows, in) · w`` — the one matmul body every engine
        program routes through, so a precision change cannot miss a
        call site. Float weights: a plain dot (the engine dtype is the
        accumulation dtype — f32 by default). int8 weights (``w`` is
        the ``(kernel_q, kernel_scale)`` pair): the SHARED
        ``models/dense.quantized_dot`` body — one definition of the
        s8×s8→s32 rule, so the engine's streams cannot drift from the
        module path's."""
        if self.weight_quant == 'int8':
            from distributed_dot_product_tpu.models.dense import (
                quantized_dot,
            )
            w_q, w_s = w
            return quantized_dot(x, w_q, w_s).astype(self._embed.dtype)
        return x @ w

    def _project(self, tokens):
        """tokens (S,) → q, k, v each (S, H, 1, D)."""
        s = tokens.shape[0]
        x = jnp.take(self._embed, tokens, axis=0)          # (S, dim)
        shape = (s, self.heads, 1, self.head_dim)
        return (self._dot(x, self._wq).reshape(shape),
                self._dot(x, self._wk).reshape(shape),
                self._dot(x, self._wv).reshape(shape))

    def _decode_impl(self, cache, tokens, active, poison):
        q, k, v = self._project(tokens)
        # Fused append+attend (one Pallas program on the kernel path —
        # the cache buffers are aliased in place and, with the jit
        # donation above, never copied).
        cache, out = decode_step(q, cache, k, v, slot_mask=active,
                                 impl=self.decode_impl)    # (S, H, 1, D)
        logits = self._dot(out.reshape(self.slots, -1),
                           self._wo)                       # (S, vocab)
        logits = jnp.where(poison[:, None], jnp.nan, logits)
        finite = slots_all_finite(logits)
        # Fully-masked argmax input for a poisoned row would be NaN-
        # ordered garbage; the scheduler discards non-finite slots'
        # tokens, so the value only needs to be deterministic.
        next_tok = jnp.argmax(
            jnp.where(jnp.isfinite(logits), logits, -jnp.inf),
            axis=-1).astype(jnp.int32)
        return cache, next_tok, finite

    def _verify_impl(self, cache, tokens, counts, active, poison):
        """Verify-k body (speculative decoding's fused verify):
        ``tokens (S, W)`` — per slot, row 0 the committed input token
        and rows 1.. the proposed continuation, ``counts[i]`` of the W
        rows real (1 = a plain non-spec slot riding the same program).
        Projections, head reshapes and the logits dot all run PER
        COLUMN with the exact ``(S, dim)`` shapes of the n=1 program —
        XLA lowers an (S, dim) and an (S·W, dim) matmul with different
        accumulation orders, and the committed stream must be the n=1
        stream bit for bit wherever the math allows it. The fused
        append+attend step keeps the same per-row identity
        (models/decode.py: a verify-k step == counts sequential
        steps)."""
        w = tokens.shape[1]
        qs, ks, vs = zip(*(self._project(tokens[:, j])
                           for j in range(w)))
        q = jnp.concatenate(qs, axis=2)            # (S, H, W, D)
        k = jnp.concatenate(ks, axis=2)
        v = jnp.concatenate(vs, axis=2)
        cache, out = decode_step(q, cache, k, v, slot_mask=active,
                                 counts=counts, impl=self.decode_impl)
        logits = jnp.stack(
            [self._dot(out[:, :, j].reshape(self.slots, -1), self._wo)
             for j in range(w)], axis=1)           # (S, W, vocab)
        logits = jnp.where(poison[:, None, None], jnp.nan, logits)
        finite = slots_all_finite(logits)
        next_tok = jnp.argmax(
            jnp.where(jnp.isfinite(logits), logits, -jnp.inf),
            axis=-1).astype(jnp.int32)             # (S, W)
        return cache, next_tok, finite

    def _project_kv(self, tokens):
        """Chunk tokens ``(C,)`` → cache-layout k, v each ``(H, C, D)``
        — the ONE projection both prefill paths share (a projection
        change must hit slot prefill and registry prefix fill alike,
        or shared-prefix pages would attend with different K/V)."""
        x = jnp.take(self._embed, tokens, axis=0)          # (C, dim)
        c = tokens.shape[0]
        k = jnp.moveaxis(self._dot(x, self._wk).reshape(
            c, self.heads, self.head_dim), 0, 1)           # (H, C, D)
        v = jnp.moveaxis(self._dot(x, self._wv).reshape(
            c, self.heads, self.head_dim), 0, 1)
        return k, v

    def _prefill_impl(self, cache, slot, tokens, count):
        """Append ``count`` of the ``prefill_chunk`` padded ``tokens``
        into ``slot``'s rows. Projections are computed once and
        broadcast — the masked write only lands on the one slot."""
        k, v = self._project_kv(tokens)
        k = jnp.broadcast_to(k[None], (self.slots,) + k.shape)
        v = jnp.broadcast_to(v[None], (self.slots,) + v.shape)
        sel = jnp.arange(self.slots) == slot
        counts = jnp.where(sel, count, 0).astype(jnp.int32)
        return append_kv_slots(cache, k, v, slot_mask=sel, counts=counts)

    def _prefix_fill_impl(self, cache, tokens, count, page_row, start):
        """Registry prefill: project one padded chunk and scatter its
        first ``count`` rows into the REGISTRY-owned ``page_row`` pages
        at logical positions ``start..`` — no slot, no length."""
        k, v = self._project_kv(tokens)
        return paged_append_rows(cache, k, v, page_row, start, count)

    # -- host surface (numpy in, numpy out) -----------------------------
    def step(self, tokens, active, poison=None, request_ids=None):
        """One decode step for all slots. ``tokens (S,) int`` — each
        ACTIVE slot's input token (its previous output, or the last
        prompt token right after prefill); inactive entries ignored.
        Returns ``(next_tokens (S,), finite (S,))`` numpy arrays.

        ``request_ids`` (optional, per-slot) is observability-only: it
        labels the host-side span so a profiler/span tree ties a decode
        dispatch back to the requests it served — it never reaches the
        compiled program (strings can't; the program is id-oblivious by
        design)."""
        poison = (np.zeros(self.slots, bool) if poison is None
                  else np.asarray(poison, bool))
        if self.cache_mode == 'paged':
            # Auto-prepare only when something actually needs a page
            # (a vectorized check — the scheduler's _ensure_pages
            # already prepared, so the per-token hot path pays one
            # numpy mask, not a per-slot Python loop). Direct callers
            # just work; exhaustion raises here because a bare loop
            # has no evict/preempt ladder to resolve it.
            act = np.asarray(active, bool)
            if not self._writable_mask(act).all():
                ok = self.prepare_step(act)
                if not ok.all():
                    bad = np.nonzero(~ok)[0]
                    raise RuntimeError(
                        f'page pool exhausted for slot(s) '
                        f'{bad.tolist()} ({self.pool.free_pages} pages '
                        f'free) — retire or evict sequences (the '
                        f'Scheduler ladder does), or size the pool '
                        f'larger')
            self._sync_page_table()
        # Span attrs are built ONLY when spans are on: this is the
        # per-token hot path, and the disabled default must not pay a
        # per-step tuple build for labels nobody will read.
        ids = (tuple(r for r in (request_ids or ()) if r)
               if obs_spans.enabled() else ())
        with span('engine.decode_step', requests=ids):
            self.cache, tok, finite = self._decode(
                self.cache, jnp.asarray(tokens, jnp.int32),
                jnp.asarray(active, bool), jnp.asarray(poison))
            if self.cache_mode == 'paged':
                self.pool.lengths[np.asarray(active, bool)] += 1
            return np.asarray(tok), np.asarray(finite)

    def _verify_program(self, w):
        """One compiled verify program per width W = k+1, built lazily
        under its own retrace budget (the width is a compile-time
        shape; a serving run uses ONE k, so one program — the dict
        exists for benchmarks sweeping k in-process)."""
        prog = self._verifies.get(w)
        if prog is None:
            from distributed_dot_product_tpu.analysis.retrace import (
                watch_traces,
            )
            prog = self._verifies[w] = jax.jit(
                watch_traces(self._verify_impl, f'engine.verify_w{w}',
                             budget=2),
                donate_argnums=(0,))
        return prog

    def verify_step(self, tokens, counts, active, poison=None,
                    request_ids=None):
        """One fused verify-k step for all slots: ``tokens (S, W)
        int`` — per ACTIVE slot, ``[input_token, p_1, .., p_c, pad]``
        with ``counts[i] = c_i + 1`` rows real (1 = plain decode: a
        mixed spec/non-spec batch rides one program). Returns
        ``(next_tokens (S, W), finite (S,))``: ``next_tokens[i, j]``
        is the greedy target token AFTER consuming input row j — the
        caller accepts the longest prefix with ``p_{j+1} ==
        next_tokens[i, j]``, commits one extra "free" token, and rolls
        the cache back to the accepted prefix (:meth:`rollback`).
        Rows past ``counts[i]`` are don't-care outputs.

        The cache appends ``counts[i]`` rows per active slot (paged
        engines auto-reserve the pages, raising on exhaustion — the
        Scheduler reserves through its evict/preempt ladder instead)."""
        tokens = np.asarray(tokens, np.int32)
        s, w = tokens.shape
        if s != self.slots:
            raise ValueError(f'tokens rows {s} != slots {self.slots}')
        counts = np.clip(np.asarray(counts, np.int64), 0, w)
        act = np.asarray(active, bool)
        poison = (np.zeros(self.slots, bool) if poison is None
                  else np.asarray(poison, bool))
        if self.cache_mode == 'paged':
            for i in np.nonzero(act)[0]:
                c = int(counts[i])
                if c and not self.reserve_rows(int(i), c):
                    raise RuntimeError(
                        f'page pool exhausted reserving {c} verify '
                        f'rows for slot {int(i)} '
                        f'({self.pool.free_pages} pages free) — '
                        f'retire or evict sequences (the Scheduler '
                        f'ladder does), or size the pool larger')
            self._sync_page_table()
        ids = (tuple(r for r in (request_ids or ()) if r)
               if obs_spans.enabled() else ())
        with span('engine.verify_step', requests=ids, width=w):
            self.cache, tok, finite = self._verify_program(w)(
                self.cache, jnp.asarray(tokens),
                jnp.asarray(counts, jnp.int32), jnp.asarray(act),
                jnp.asarray(poison))
            if self.cache_mode == 'paged':
                self.pool.lengths[act] += counts[act]
            return np.asarray(tok), np.asarray(finite)

    def _rollback_program(self, span_rows):
        prog = self._rollbacks.get(span_rows)
        if prog is None:
            from distributed_dot_product_tpu.analysis.retrace import (
                watch_traces,
            )
            if self.cache_mode == 'paged':
                def body(cache, lengths):
                    return paged_rollback_slots(cache, lengths,
                                                span_rows)
            else:
                def body(cache, lengths):
                    return rollback_slots(cache, lengths,
                                          span=span_rows)
            prog = self._rollbacks[span_rows] = jax.jit(
                watch_traces(body, f'engine.rollback_s{span_rows}',
                             budget=2),
                donate_argnums=(0,))
        return prog

    def rollback(self, lengths):
        """Acceptance-prefix rollback: truncate each slot to
        ``lengths[i]`` rows and zero the rejected tail —
        ``min(current, target)`` semantics, so a past-fill sentinel
        (e.g. ``np.iinfo(np.int32).max``) leaves a slot untouched and
        ONE batched call serves a mixed tick. The zeroing is surgical
        (a span-bounded scatter, not a cache rewrite); spans compile
        per power-of-two bucket, so a whole serving run uses one or
        two programs. Paged engines additionally return now-empty tail
        pages to the pool (refcount--, freed pages zeroed — the alloc
        invariant) and resync the device page table."""
        tgt = np.asarray(lengths, np.int64)
        cur = (self.pool.lengths.astype(np.int64)
               if self.cache_mode == 'paged'
               else np.asarray(self.cache.length, np.int64))
        new = np.minimum(cur, tgt)
        need = int((cur - new).max()) if cur.size else 0
        if need == 0:
            return
        bucket = 1 << (need - 1).bit_length()
        with span('engine.rollback', rows=need):
            self.cache = self._rollback_program(bucket)(
                self.cache, jnp.asarray(new, jnp.int32))
        if self.cache_mode == 'paged':
            freed = []
            for i in np.nonzero(cur > new)[0]:
                freed += self.pool.truncate(int(i), int(new[i]))
            if freed:
                self._zero_freed(freed)
            self._sync_page_table()

    def prefill(self, slot, tokens, request_id=None):
        """Append one prompt chunk (``len(tokens) <= prefill_chunk``)
        into ``slot``. Pads to the compiled chunk width; padded rows
        never land (counts mask). ``request_id`` labels the span only
        (see :meth:`step`)."""
        n = len(tokens)
        if n > self.prefill_chunk:
            raise ValueError(f'chunk of {n} exceeds prefill_chunk='
                             f'{self.prefill_chunk}')
        buf = np.zeros(self.prefill_chunk, np.int32)
        buf[:n] = np.asarray(tokens, np.int32)
        if self.cache_mode == 'paged':
            # Auto-reserve the chunk's pages (no-op when the scheduler
            # already reserved the whole prompt at admission).
            pos = int(self.pool.lengths[slot])
            if (pos + n) > int(self.pool.counts[slot]) * self.page_size \
                    and not self.reserve_rows(slot, n):
                raise RuntimeError(
                    f'page pool exhausted prefilling rows '
                    f'[{pos}, {pos + n}) of slot {slot} '
                    f'({self.pool.free_pages} pages free)')
            self._sync_page_table()
        with span('engine.prefill', slot=int(slot),
                  request=request_id or ''):
            self.cache = self._prefill(self.cache, jnp.int32(slot),
                                       jnp.asarray(buf), jnp.int32(n))
        if self.cache_mode == 'paged':
            self.pool.lengths[slot] += n

    def _zero_freed(self, freed, slot=-1):
        """Zero freed pool pages (and clear ``slot``'s rows/length when
        one is named; slot −1 touches no slot) through the ONE compiled
        reset program — the freed-page zeroing contract lives here."""
        vec = np.full(self.pool.pages_per_slot, -1, np.int32)
        vec[:len(freed)] = freed
        self.cache = self._reset(self.cache, jnp.int32(slot),
                                 jnp.asarray(vec))
        if self.checksums is not None:
            self.checksums.drop(freed)

    def reset(self, slot):
        """Evict ``slot`` (zero rows + length); other slots untouched.
        Paged: drops the slot's page references and zeroes exactly the
        pages that reached refcount 0 (still-shared prefix/fork pages
        keep their bits — they are someone else's context)."""
        if self.cache_mode == 'paged':
            self._zero_freed(self.pool.release(slot), slot)
            self._sync_page_table()
        else:
            self.cache = self._reset(self.cache, jnp.int32(slot))

    def lengths(self):
        # np.array, NOT np.asarray: on the CPU backend asarray is a
        # ZERO-COPY view of the device buffer, and every engine program
        # donates the cache — the next step would recycle the buffer
        # under the caller's snapshot. The verify-k commit loop anchors
        # its rollback targets on this vector across exactly such a
        # donating call, so a view here silently inflates every target
        # by the committed width (one token per slot per step leaks).
        return np.array(self.cache.length)

    # -- paged-pool surface (cache_mode='paged') ------------------------
    def _sync_page_table(self):
        if self.pool.dirty:
            self.cache = self.cache._replace(
                page_table=jnp.asarray(self.pool.table))
            self.pool.dirty = False

    def _apply_copies(self, copies):
        for src, dst in copies:
            self.cache = self._copy_attach(
                self.cache, jnp.int32(src), jnp.int32(dst),
                jnp.int32(-1), jnp.int32(0))

    def _writable_mask(self, active):
        """Per active slot: does a PRIVATE page already cover its next
        append position (the prepare_step()/reserve_rows()
        postcondition)? Vectorized — this is the per-token fast path
        that lets step() skip re-preparing when the scheduler already
        did. A slot AT ``t_max`` counts as writable: there is no page
        to prepare — the device write drops while the length advances
        (the slab engine's frozen-write contract), so stepping it must
        not raise."""
        idx = np.nonzero(active)[0]
        ok = np.ones(len(active), bool)
        if not idx.size:
            return ok
        pool = self.pool
        pi = pool.lengths[idx] // self.page_size
        full = pi >= pool.pages_per_slot
        pg = pool.table[idx, np.minimum(pi, pool.pages_per_slot - 1)]
        good = (pg >= 0)
        good &= pool.refcount[np.maximum(pg, 0)] == 1
        ok[idx] = full | good
        return ok

    def prepare_step(self, active):
        """Make every active slot's next append position writable:
        allocate the page a slot crossing a page boundary needs, and
        copy-on-write any shared append page (first divergent append
        after a fork/prefix attach). Returns a ``(slots,) bool`` mask —
        False means the pool is EXHAUSTED for that slot and nothing was
        allocated; the scheduler owns the evict/preempt policy. A slot
        already at ``t_max`` is True (``'full'``): nothing to allocate,
        its append drops on device like the slab path's."""
        active = np.asarray(active, bool)
        ok = np.ones(self.slots, bool)
        # Vectorized fast path first: the per-token cost is one numpy
        # mask; the Python allocator loop below runs only for slots
        # that actually need a page (boundary crossing or shared
        # append page) — the same contract step()'s auto-prepare uses.
        todo = active & ~self._writable_mask(active)
        for i in np.nonzero(todo)[0]:
            st, src, dst = self.pool.prepare_append(int(i))
            if st == 'exhausted':
                ok[i] = False
            elif st == 'cow':
                self._apply_copies([(src, dst)])
        self._sync_page_table()
        return ok

    def reserve_rows(self, slot, rows):
        """Admission-time reservation: every page covering ``slot``'s
        next ``rows`` logical rows (so chunked prefill can never fail
        mid-prompt). False = pool exhausted, nothing changed."""
        ok, copies = self.pool.reserve_rows(slot, rows)
        if ok:
            self._apply_copies(copies)
            self._sync_page_table()
        return ok

    def register_prefix(self, tokens):
        """Prefill ``tokens`` ONCE into registry-owned pool pages and
        return a prefix id. Sequences started with
        :meth:`start_with_prefix` share the prefix's full pages
        read-only (refcounted) — N sequences riding a system prompt
        cost its pages once plus one partial tail page each."""
        if self.cache_mode != 'paged':
            raise ValueError("prefix sharing needs cache_mode='paged'")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = len(tokens)
        if n < 1:
            raise ValueError('empty prefix')
        if n + 1 > self.t_max:
            raise ValueError(f'prefix of {n} tokens leaves no room to '
                             f'generate in a t_max={self.t_max} cache')
        needed = self.pool.pages_for_rows(n)
        pages = self.pool.alloc_block(needed)
        if pages is None:
            raise RuntimeError(
                f'page pool exhausted registering a {n}-token '
                f'prefix ({needed} pages needed, '
                f'{self.pool.free_pages} free)')
        row = np.full(self.pool.pages_per_slot, -1, np.int32)
        row[:needed] = pages
        row_j = jnp.asarray(row)
        for start in range(0, n, self.prefill_chunk):
            chunk = tokens[start:start + self.prefill_chunk]
            buf = np.zeros(self.prefill_chunk, np.int32)
            buf[:len(chunk)] = chunk
            self.cache = self._prefix_fill(
                self.cache, jnp.asarray(buf), jnp.int32(len(chunk)),
                row_j, jnp.int32(start))
        return self._register_pages(pages, n)

    def _register_pages(self, pages, n):
        """Enter ``pages`` (already allocated and filled, covering
        ``n`` rows) into the prefix registry — the one place prefix
        ids are minted, shared by :meth:`register_prefix` (local
        prefill) and :meth:`adopt_prefix` (cross-cache handoff)."""
        pid = next(self._prefix_counter)
        self._prefix_registry[pid] = (pages, n)
        self._checksum_record(pages)
        return pid

    # -- page integrity (host-side, transfer boundaries only) -----------
    def _checksum_record(self, pages):
        if self.checksums is not None:
            t0 = time.perf_counter()
            self.checksums.record(self.cache, pages)
            self.verify_seconds += time.perf_counter() - t0

    def verify_pages(self, pages=None):
        """Re-digest ``pages`` (default: every tracked page — the
        scrub) against the recorded checksums. Returns the sorted
        dirty-page list without raising; [] when clean or when
        checksums are disabled. Host work only."""
        if self.checksums is None:
            return []
        t0 = time.perf_counter()
        bad = self.checksums.verify(self.cache, pages)
        self.verify_seconds += time.perf_counter() - t0
        return bad

    def verify_prefix(self, prefix_id):
        """Scrub one registered prefix's pages (dirty list, no raise)."""
        pages, _ = self._prefix_registry[prefix_id]
        return self.verify_pages(pages)

    def check_pages(self, pages, site):
        """Raise :class:`PageCorruptionError` naming ``site`` if any of
        ``pages`` fails verification (untracked pages are skipped)."""
        bad = self.verify_pages(pages)
        if bad:
            raise PageCorruptionError(bad, site)

    def quarantine_pages(self, pages):
        """Withdraw dirty pages from circulation (they never return to
        the free list) and forget their digests so scrubs stop
        re-flagging them. Returns the pages newly quarantined."""
        if self.checksums is not None:
            self.checksums.drop(pages)
        return self.pool.quarantine(pages)

    def slots_sharing(self, pages):
        """Slots whose page tables name any of ``pages`` — the live
        victims of a corruption verdict."""
        if self.pool is None:
            return []
        bad = {int(p) for p in pages}
        hit = []
        for slot in range(self.slots):
            n = int(self.pool.counts[slot])
            if any(int(self.pool.table[slot, i]) in bad
                   for i in range(n)):
                hit.append(slot)
        return hit

    def prefixes_on(self, pages):
        """Registered prefix ids built on any of ``pages`` — the
        entries a corruption verdict must invalidate."""
        bad = {int(p) for p in pages}
        return [pid for pid, (pgs, _) in self._prefix_registry.items()
                if bad.intersection(int(p) for p in pgs)]

    def _transfer_program(self, src_shape):
        prog = self._transfers.get(src_shape)
        if prog is None:
            from distributed_dot_product_tpu.analysis.retrace import (
                watch_traces,
            )
            prog = self._transfers[src_shape] = jax.jit(
                watch_traces(paged_transfer_pages, 'engine.adopt',
                             budget=2),
                donate_argnums=(0,))
        return prog

    def adopt_prefix(self, src_cache, src_pages, length,
                     src_checksums=None):
        """The prefill→decode KV handoff (disaggregated serving): copy
        ``length`` rows living in ``src_pages`` of ANOTHER paged cache
        (a prefill pool's — same page size and head geometry, its own
        pool size) into freshly allocated pages of THIS engine's pool
        and register them as a shared prefix. One compiled program
        moves whole pages — the transfer unit is the page, exactly as
        :meth:`register_prefix`'s product is, so sequences started
        with :meth:`start_with_prefix` cannot tell a handed-off prefix
        from a locally prefilled one. Raises on pool exhaustion (the
        router checks headroom first) and on geometry mismatch.

        ``src_checksums`` (the source pool's :class:`PageChecksums`)
        makes the handoff end-to-end verifiable: the source pages are
        verified BEFORE the transfer (dirty source →
        :class:`PageCorruptionError` at site 'handoff_src') and the
        landed copies' KV digests are compared to the source's AFTER
        (a corrupted transfer → site 'handoff_copy', with the adopted
        prefix unregistered — never handed to a caller). Only
        ``kv_crc`` crosses caches: the destination int8 mirror is
        re-quantized from the adopted K with eps-scale tail rows, so
        mirror bytes legitimately differ between pools."""
        if self.cache_mode != 'paged':
            raise ValueError("prefix adoption needs cache_mode='paged'")
        if src_cache.page_size != self.page_size:
            raise ValueError(
                f'page-size mismatch: source {src_cache.page_size} vs '
                f'{self.page_size} — the page is the transfer unit, '
                f'both pools must agree')
        if src_cache.k_pool.shape[1:] != self.cache.k_pool.shape[1:] \
                or src_cache.v_pool.shape[1:] != self.cache.v_pool.shape[1:]:
            raise ValueError(
                f'KV geometry mismatch: source pages '
                f'{src_cache.k_pool.shape[1:]} vs '
                f'{self.cache.k_pool.shape[1:]}')
        if length < 1 or length + 1 > self.t_max:
            raise ValueError(f'prefix of {length} rows leaves no room '
                             f'to generate in a t_max={self.t_max} '
                             f'cache')
        src_pages = [int(p) for p in src_pages]
        needed = self.pool.pages_for_rows(length)
        if len(src_pages) != needed:
            raise ValueError(f'{len(src_pages)} source pages for '
                             f'{length} rows (need {needed})')
        if src_checksums is not None:
            t0 = time.perf_counter()
            bad = src_checksums.verify(src_cache, src_pages)
            self.verify_seconds += time.perf_counter() - t0
            if bad:
                raise PageCorruptionError(bad, 'handoff_src')
        pages = self.pool.alloc_block(needed)
        if pages is None:
            raise RuntimeError(
                f'page pool exhausted adopting a {length}-row prefix '
                f'({needed} pages needed, {self.pool.free_pages} free)')
        # Fixed-width −1-padded vectors: one compiled transfer program
        # per source pool shape, whatever the prefix length.
        width = max(self.pool.pages_per_slot, needed)
        vec_src = np.full(width, -1, np.int32)
        vec_dst = np.full(width, -1, np.int32)
        vec_src[:needed] = src_pages
        vec_dst[:needed] = pages
        key = (src_cache.k_pool.shape, src_cache.v_pool.shape, width)
        self.cache = self._transfer_program(key)(
            self.cache, src_cache.k_pool, src_cache.v_pool,
            jnp.asarray(vec_src), jnp.asarray(vec_dst))
        pid = self._register_pages(pages, length)
        if self.checksums is not None and src_checksums is not None:
            # Landed-copy verification: the transfer moves whole pages
            # (unfilled tail rows are zero on both sides), so the KV
            # digest must survive the copy bit-exactly.
            bad = []
            for sp, dp in zip(src_pages, pages):
                want = src_checksums.get(sp)
                have = self.checksums.get(dp)
                if want is not None and have is not None \
                        and have[0] != want[0]:
                    bad.append(dp)
            if bad:
                self.unregister_prefix(pid)
                raise PageCorruptionError(bad, 'handoff_copy')
        return pid

    def prefix_length(self, prefix_id):
        return self._prefix_registry[prefix_id][1]

    def unregister_prefix(self, prefix_id):
        """Release the registry's page references; pages still shared
        by live sequences survive until those retire."""
        pages, _ = self._prefix_registry.pop(prefix_id)
        freed = self.pool.release_pages(pages)
        if freed:
            self._zero_freed(freed)

    def start_with_prefix(self, slot, prefix_id):
        """Point an EMPTY slot at a registered prefix: full pages
        shared (refcount++), partial tail page copied private, length
        set — the slot then prefills/decodes its own continuation.
        False = pool exhausted (no tail page available). The prefix's
        pages are verified first — attaching a sequence to a corrupted
        prefix raises before any token can read it."""
        pages, plen = self._prefix_registry[prefix_id]
        self.check_pages(pages, 'attach')
        ok, src, dst = self.pool.attach(slot, pages, plen)
        if not ok:
            return False
        self.cache = self._copy_attach(self.cache, jnp.int32(src),
                                       jnp.int32(dst), jnp.int32(slot),
                                       jnp.int32(plen))
        self._sync_page_table()
        return True

    def fork_slot(self, src, dst):
        """Copy-on-write fork for parallel sampling: ``dst`` (an empty
        slot) shares ``src``'s full pages and gets a private copy of
        the partial tail page — O(1 page) device work however long the
        context. False = pool exhausted. The source's TRACKED pages
        (shared prefix pages — private append pages are out of
        coverage) are verified before the branch shares them."""
        if self.checksums is not None:
            shared = [int(self.pool.table[src, i])
                      for i in range(int(self.pool.counts[src]))]
            self.check_pages(shared, 'fork')
        ok, tail_src, tail_dst = self.pool.fork(src, dst)
        if not ok:
            return False
        self.cache = self._copy_attach(
            self.cache, jnp.int32(tail_src), jnp.int32(tail_dst),
            jnp.int32(dst), jnp.int32(int(self.pool.lengths[dst])))
        self._sync_page_table()
        return True

    @property
    def weight_bytes(self):
        """Bytes of the four projection/head matrices a decode step
        streams (int8 engines count the int8 kernels + their scales) —
        the weights column of the quantized-vs-float benchmark twins.
        The embedding is excluded: a step gathers S rows of it, not
        the table."""
        from distributed_dot_product_tpu.models.dense import (
            dense_param_bytes,
        )
        return dense_param_bytes(
            [self._wq, self._wk, self._wv, self._wo])

    @property
    def free_pages(self):
        return self.pool.free_pages if self.pool is not None else None

    @property
    def pinned_pages(self):
        """Distinct pool pages the prefix registry holds a permanent
        reference on — they can never return to the free list while
        their prefix stays registered (each prefix allocates fresh
        pages, so the per-prefix page lists are disjoint). 0 on slab
        engines, like the other probe-any-engine accessors."""
        if self.pool is None:
            return 0
        return sum(len(pages)
                   for pages, _ in self._prefix_registry.values())

    @property
    def capacity_tokens(self):
        """Most rows ONE fresh sequence can ever hold: the per-slot
        table reach capped by the pool itself."""
        if self.pool is None:
            return self.t_max
        return min(self.t_max, self.pool.pages * self.page_size)

    def slot_pages(self, slot):
        return self.pool.slot_pages(slot) if self.pool is not None else 0

    def cache_stats(self):
        """Occupancy snapshot for the scheduler's gauges. A slab
        engine has no pool (everything statically reserved) — report
        zeros so generic dashboard code can probe any engine, matching
        the ``free_pages``/``slot_pages`` guards."""
        pool = self.pool
        if pool is None:
            return {'pages': 0, 'pages_used': 0, 'pages_free': 0,
                    'shared_pages': 0, 'page_size': 0,
                    'pages_quarantined': 0}
        return {'pages': pool.pages, 'pages_used': pool.used_pages,
                'pages_free': pool.free_pages,
                'shared_pages': pool.shared_pages,
                'page_size': pool.page_size,
                'pages_quarantined': len(pool.quarantined)}


def graphlint_entrypoints():
    """Static-analysis registration hook (analysis/registry.py): the
    serving engine's batched decode step — the program the continuous-
    batching scheduler drives per tick — checked for real cache
    donation/aliasing and surgical per-slot writes on the exact jitted
    callable the engine holds."""

    def engine_decode():
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        eng = KernelEngine(slots=2, t_max=16, decode_impl='xla')
        tokens = jnp.zeros((2,), jnp.int32)
        active = jnp.ones((2,), bool)
        poison = jnp.zeros((2,), bool)
        return TraceSpec(
            name='serve.engine_decode', fn=eng._decode,
            args=(eng.cache, tokens, active, poison),
            prejitted=True,
            cache_in=lambda a: [a[0].k, a[0].v],
            cache_out=lambda o: [o[0].k, o[0].v],
            expect_donation=True, min_donated=2)

    def engine_decode_paged():
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        eng = KernelEngine(slots=2, t_max=16, decode_impl='xla',
                           cache_mode='paged', page_size=8, pages=3)
        active = jnp.ones((2,), bool)
        assert eng.prepare_step(np.ones(2, bool)).all()
        tokens = jnp.zeros((2,), jnp.int32)
        poison = jnp.zeros((2,), bool)
        return TraceSpec(
            name='serve.engine_decode_paged', fn=eng._decode,
            args=(eng.cache, tokens, active, poison),
            prejitted=True,
            cache_in=lambda a: [a[0].k_pool, a[0].v_pool],
            cache_out=lambda o: [o[0].k_pool, o[0].v_pool],
            expect_donation=True, min_donated=2)

    def engine_decode_wq8():
        # The int8-WEIGHT serving program: same decode step, weights
        # stored int8 — the s8×s8→s32 projection dots must request
        # their i32 accumulator and the cache contracts must survive
        # the precision change.
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        eng = KernelEngine(slots=2, t_max=16, decode_impl='xla',
                           weight_quant='int8')
        tokens = jnp.zeros((2,), jnp.int32)
        active = jnp.ones((2,), bool)
        poison = jnp.zeros((2,), bool)
        return TraceSpec(
            name='serve.engine_decode_wq8', fn=eng._decode,
            args=(eng.cache, tokens, active, poison),
            prejitted=True,
            cache_in=lambda a: [a[0].k, a[0].v],
            cache_out=lambda o: [o[0].k, o[0].v],
            expect_donation=True, min_donated=2)

    return {'serve.engine_decode': engine_decode,
            'serve.engine_decode_paged': engine_decode_paged,
            'serve.engine_decode_wq8': engine_decode_wq8}
