# -*- coding: utf-8 -*-
"""
The compiled substrate the scheduler drives: a minimal greedy LM over
the KV-cache decode kernels (``models/decode.py``), batched across
decode SLOTS with per-slot lengths.

Why a dedicated engine instead of :class:`TransformerLM`: continuous
batching needs every batch row on its OWN sequence clock, which is
exactly what the per-slot cache (``init_slot_cache`` /
``append_kv_slots`` / per-slot-masked ``decode_attention``) provides at
the kernel level. The flax stack's decode surface shares one scalar
length across the batch (lockstep generation); threading per-slot
lengths through it is a model-side project — the serving layer's job is
the scheduling around the kernels, so it drives them directly: token
embedding → q/k/v projections → per-slot cache append → per-slot masked
attention → logits. Fixed seeded weights (serving robustness doesn't
need trained weights; determinism does).

Three compiled programs serve the whole lifecycle, shapes fixed at
construction so nothing ever retraces mid-serve:

- ``decode``: one token for EVERY slot (inactive slots masked out of
  the append; their outputs ignored) + per-slot all-finite verdict on
  the logits. The append+attend pair is the FUSED step
  (``models.decode.decode_step``): on the kernel path it is one Pallas
  program with the cache aliased in place, so the donated buffers are
  never copied. The fault injector's NaN mask is applied IN-PROGRAM so
  the quarantine predicate sees real NaNs from the compiled step.
- ``prefill``: one padded prompt chunk into one slot's cache rows (no
  attention — only the last prompt position's logits matter, and the
  scheduler feeds that token through ``decode``).
- ``reset``: zero one slot's rows and length (eviction/quarantine).

Every computation is batch-row independent (embedding lookups, row-wise
matmuls, per-slot masked attention, per-row argmax), so a request's
tokens depend only on its prompt and the seed — NOT on which slot it
lands in or what its neighbors are doing. The scheduler's bit-identity
guarantees (quarantine leaves other slots' streams untouched; a
requeued request regenerates the same tokens) rest on this property,
and the tests pin it.
"""

import itertools
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from distributed_dot_product_tpu.models.decode import (
    PageChecksums, PagedDecodeCache, PagePool, ShardedPageTable,
    append_kv_slots, decode_step, init_paged_cache, init_slot_cache,
    init_sharded_paged_cache, paged_append_rows, paged_copy_attach,
    paged_reset_slot, paged_rollback_slots, paged_transfer_pages,
    reset_slot, rollback_slots, slots_all_finite,
)
from distributed_dot_product_tpu.obs import spans as obs_spans
from distributed_dot_product_tpu.obs.spans import span
from distributed_dot_product_tpu.serve.errors import ServeContractError

__all__ = ['KernelEngine', 'PageCorruptionError']


class PageCorruptionError(RuntimeError):
    """A pool page's content no longer matches its recorded checksum.
    ``pages`` names the dirty pages, ``site`` the transfer/attach
    boundary that caught them ('scrub', 'attach', 'fork',
    'handoff_src', 'handoff_copy') — the router turns this into the
    `kv.corrupt` event + quarantine + heal arc."""

    def __init__(self, pages, site, shards=None):
        self.pages = sorted(int(p) for p in pages)
        self.site = site
        # kv_shards engines name the owning mesh member(s) of the dirty
        # pages (page ids are then STACKED-row ids); None on unsharded
        # engines — the router forwards this into `kv.corrupt`.
        self.shards = (sorted({int(s) for s in shards})
                       if shards else None)
        msg = (f'KV page corruption at {site}: page(s) {self.pages} '
               f'fail checksum verification')
        if self.shards:
            msg += f' (kv shard(s) {self.shards})'
        super().__init__(msg)


def _resolve_decode_impl(decode_impl):
    """Engine decode-path selection: an explicit argument wins; else the
    ``DDP_TPU_DECODE_KERNEL`` env knob (1/kernel → fused Pallas step,
    0/xla → portable step) — the hook ``scripts/smoke_serve.sh`` uses
    to prove the fault cocktail on the kernel path; else 'auto' (kernel
    on TPU, XLA elsewhere — see models/decode.decode_step)."""
    if decode_impl is not None:
        return decode_impl
    env = os.environ.get('DDP_TPU_DECODE_KERNEL', '').strip().lower()
    if env in ('1', 'true', 'kernel'):
        return 'kernel'
    if env in ('0', 'false', 'xla'):
        return 'xla'
    return 'auto'


def _resolve_weight_quant(weight_quant):
    """Weight-precision selection: explicit argument wins ('off'/None =
    float weights, 'int8' = per-output-channel int8 weights with
    in-program s8×s8→s32 dequant — models/dense.quantize_kernel's rule);
    else the ``DDP_TPU_WEIGHT_QUANT`` env knob — the deployment switch
    the quantized-serving benchmark rows flip."""
    if weight_quant is not None:
        if weight_quant == 'off':
            return None
        if weight_quant not in ('int8',):
            raise ValueError(f"weight_quant must be None/'off'/'int8', "
                             f'got {weight_quant!r}')
        return weight_quant
    env = os.environ.get('DDP_TPU_WEIGHT_QUANT', '').strip().lower()
    if env in ('1', 'true', 'int8'):
        return 'int8'
    return None


def _resolve_cache_mode(cache_mode):
    """Cache-layout selection: explicit argument wins; else the
    ``DDP_TPU_PAGED_CACHE`` env knob (1/paged → page-pool cache); else
    the slab reference layout."""
    if cache_mode is not None:
        if cache_mode not in ('slab', 'paged'):
            raise ValueError(f"cache_mode must be 'slab' or 'paged', "
                             f'got {cache_mode!r}')
        return cache_mode
    env = os.environ.get('DDP_TPU_PAGED_CACHE', '').strip().lower()
    if env in ('1', 'true', 'paged'):
        return 'paged'
    return 'slab'


class KernelEngine:
    """Greedy decode engine over ``slots`` independent sequences.

    ``prefill_chunk`` is the compiled chunk width for prompt ingestion
    (prompts append in ceil(len/chunk) calls — "chunked prefill", so a
    long prompt never monopolizes the loop between decode steps).

    ``decode_impl``: 'kernel' runs the decode step as the fused Pallas
    program (in-place aliased cache append + split-K attention —
    ops/pallas_decode.py; the three compiled programs then stop paying
    any cache round trip), 'xla' the portable append+einsum step, None
    reads ``DDP_TPU_DECODE_KERNEL`` then defaults to auto (kernel on
    TPU). Token streams are deterministic within an impl; the two
    impls agree to float tolerance (exp2 vs exp rounding), so
    bit-identity guarantees hold per-impl, not across.

    ``cache_mode='paged'`` (or ``DDP_TPU_PAGED_CACHE=1``) swaps the
    per-slot slab for the page-pool cache (``models/decode.py``
    ``PagedDecodeCache``): ``pages`` sizes the global pool (the memory
    budget — decoupled from ``slots × t_max``), ``page_size`` the page
    granularity (= the kernel's K split). The host :class:`PagePool`
    owns allocation; :meth:`step`/:meth:`prefill` auto-reserve the
    pages they need (raising on exhaustion), while the Scheduler calls
    :meth:`prepare_step`/:meth:`reserve_rows` itself so a deficit
    routes through its evict/preempt ladder instead of a raise.
    :meth:`register_prefix`/:meth:`start_with_prefix` give refcounted
    prefix sharing, :meth:`fork_slot` copy-on-write forks. Token
    streams are bit-identical to the slab engine per impl.

    ``kv_shards=N`` (paged engines only) shards every stream's page
    table across an N-wide ``seq`` mesh — cluster-scale long context:
    each mesh member owns a CONTIGUOUS run of the logical page
    ordinals (:class:`~distributed_dot_product_tpu.models.decode
    .ShardedPageTable`), runs the decode step over only its own pages,
    and the per-shard flash partials pmax/psum-merge into the exact
    full-attention result. ``pages`` then sizes each PER-SHARD pool,
    so ``capacity_tokens`` scales linearly with N. The host surface
    speaks GLOBAL page ids (= stacked pool rows); checksums are kept
    per owning shard; prefixes arrive via the shard-local
    :meth:`adopt_prefix` handoff (``register_prefix``, ``fork_slot``
    and ``verify_step`` raise — run those on unsharded replicas).
    Needs N devices (the 8-dev CPU mesh in tests/CI).

    ``weight_quant='int8'`` (or ``DDP_TPU_WEIGHT_QUANT=int8``) stores
    the four projection/head matrices int8 with per-output-channel
    scales (``models/dense.quantize_kernel``); every projection and
    the logits dot then quantize their activation rows on the fly and
    run s8×s8→s32 with the dequantization applied to the s32 result —
    half the weight bytes per step, deterministic streams (the
    bit-identity guarantees hold per weight_quant setting, exactly as
    they hold per decode impl), layout-oblivious (slab and paged
    engines with the same seed + weight_quant emit identical
    streams).
    """

    def __init__(self, slots, t_max, *, vocab=64, heads=2, head_dim=8,
                 prefill_chunk=8, seed=0, dtype=jnp.float32,
                 decode_impl=None, cache_mode=None, pages=None,
                 page_size=None, weight_quant=None, kv_checksums=True,
                 kv_shards=1):
        if slots < 1 or t_max < 2:
            raise ValueError(f'need slots >= 1 and t_max >= 2, got '
                             f'{slots}/{t_max}')
        self.decode_impl = _resolve_decode_impl(decode_impl)
        self.cache_mode = _resolve_cache_mode(cache_mode)
        self.kv_shards = int(kv_shards)
        if self.kv_shards < 1:
            raise ValueError(f'kv_shards must be >= 1, got {kv_shards}')
        if self.kv_shards > 1 and self.cache_mode != 'paged':
            raise ValueError("kv_shards > 1 needs cache_mode='paged' — "
                             'the sequence-sharded KV is a sharded page '
                             'table, there is no sharded slab')
        self.weight_quant = _resolve_weight_quant(weight_quant)
        self.slots = slots
        self.t_max = t_max
        self.vocab = vocab
        self.heads = heads
        self.head_dim = head_dim
        self.prefill_chunk = prefill_chunk
        self.seed = seed
        dim = heads * head_dim
        ks = jax.random.split(jax.random.key(seed), 5)
        scale = 1.0 / np.sqrt(dim)
        self._embed = jax.random.normal(ks[0], (vocab, dim), dtype) * scale
        self._wq = jax.random.normal(ks[1], (dim, dim), dtype) * scale
        self._wk = jax.random.normal(ks[2], (dim, dim), dtype) * scale
        self._wv = jax.random.normal(ks[3], (dim, dim), dtype) * scale
        self._wo = jax.random.normal(ks[4], (dim, vocab), dtype) * scale
        if self.weight_quant == 'int8':
            # Load/convert-time quantization — the engine analog of
            # models/dense.quantize_dense_params: weights stored int8
            # (half/quarter the bytes), per-output-channel scales. The
            # embedding stays float: it feeds a LOOKUP, not a matmul.
            from distributed_dot_product_tpu.models.dense import (
                quantize_kernel,
            )
            self._wq = quantize_kernel(self._wq)
            self._wk = quantize_kernel(self._wk)
            self._wv = quantize_kernel(self._wv)
            self._wo = quantize_kernel(self._wo)
        if self.cache_mode == 'paged':
            ps = page_size or min(16, t_max)
            if t_max % ps:
                raise ValueError(f'page_size {ps} must divide t_max '
                                 f'{t_max}')
            self.page_size = ps
            if self.kv_shards > 1:
                # Cluster-scale long context: one ShardedPageTable over
                # kv_shards PagePools (contiguous ordinal ownership),
                # the STACKED device cache placed P(SEQ_AXIS) over a
                # seq mesh. `pages` sizes each PER-SHARD pool, so
                # capacity_tokens sums linearly across the mesh.
                from distributed_dot_product_tpu.parallel.mesh import (
                    seq_mesh,
                )
                from distributed_dot_product_tpu.utils.comm import (
                    SEQ_AXIS,
                )
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                pps = t_max // ps
                if self.kv_shards > pps:
                    raise ValueError(
                        f'kv_shards {self.kv_shards} exceeds the '
                        f'{pps} logical page ordinals of t_max='
                        f'{t_max}/page_size={ps} — some shards would '
                        f'own an empty range')
                n_pages = pages if pages is not None \
                    else -(-slots * pps // self.kv_shards)
                self.pool = ShardedPageTable(self.kv_shards, n_pages,
                                             ps, slots, pps)
                self._mesh = seq_mesh(self.kv_shards)
                self._seq_axis = SEQ_AXIS
                self._pt_sharding = NamedSharding(self._mesh,
                                                 P(SEQ_AXIS))
                self._cache_sharding = PagedDecodeCache(
                    k_pool=self._pt_sharding,
                    v_pool=self._pt_sharding,
                    page_table=self._pt_sharding,
                    length=NamedSharding(self._mesh, P()),
                    k_q_pool=None, k_scale_pool=None)
                self.cache = jax.device_put(
                    init_sharded_paged_cache(
                        self.kv_shards, slots, heads, t_max, head_dim,
                        pages_per_shard=n_pages, page_size=ps,
                        dtype=dtype),
                    self._cache_sharding)
            else:
                # Default pool = the slab's bytes; the paged win comes
                # from sizing `pages` to the MEMORY budget while
                # raising `slots` past what a slab of the same bytes
                # could hold.
                n_pages = pages if pages is not None \
                    else slots * (t_max // ps)
                self.pool = PagePool(n_pages, ps, slots, t_max // ps)
                self.cache = init_paged_cache(slots, heads, t_max,
                                              head_dim, pages=n_pages,
                                              page_size=ps, dtype=dtype)
            self._prefix_registry = {}
            self._prefix_counter = itertools.count()
            # Per-page integrity table: registry/transfer pages only,
            # digested at transfer boundaries on the host — never
            # inside a compiled program ("verify at transfer, never
            # per step"). kv_checksums=False is the no-integrity twin.
            # kv_shards engines keep ONE table PER OWNING SHARD, keyed
            # by shard-local page ids (satellite: checksums stay
            # coherent under sharding).
            if not kv_checksums:
                self.checksums = None
            elif self.kv_shards > 1:
                self.checksums = [PageChecksums()
                                  for _ in range(self.kv_shards)]
            else:
                self.checksums = PageChecksums()
        else:
            self.page_size = None
            self.pool = None
            self.checksums = None
            self.cache = init_slot_cache(slots, heads, t_max, head_dim,
                                         dtype=dtype)
        self.verify_seconds = 0.0   # host wall time spent digesting
        # Dispatch-floor accounting (ROADMAP item 5): cumulative REAL
        # wall seconds spent INSIDE compiled-program invocations
        # (decode / verify / prefill / rollback). The scheduler diffs
        # this across a tick to split tick wall time into device
        # compute vs host-loop overhead (serve.dispatch events,
        # serve.dispatch_overhead_seconds histogram). Monotone
        # counter, never reset — consumers take deltas.
        self.program_seconds = 0.0
        # Donated caches: appends write in place — see models/decode.py's
        # performance note. One compiled program each for the lifetime —
        # and the retrace sentinel (analysis/retrace.py) enforces it:
        # shapes are fixed at construction, so more than budget traces
        # of one program means something un-cacheable leaked into the
        # step (the round-5 retrace-storm class). Budget 2: the real
        # trace plus one registry lowering / weak-type respin.
        from distributed_dot_product_tpu.analysis.retrace import (
            watch_traces,
        )
        if self.cache_mode == 'paged' and self.kv_shards > 1:
            # Every kv_shards program is the SAME paged body the
            # unsharded engine runs, wrapped in ONE shard_map: each
            # mesh member squeezes its (1, slots, pps) page-table
            # block to the ordinary local view, runs the paged body
            # over its own pool block (non-owned ordinals are −1, so
            # their appends/copies drop on device), and re-expands.
            # The decode body additionally passes the mesh axis so
            # decode_step pmax/psum-merges the per-shard flash
            # partials into the exact full-attention result — the
            # paged ring/context-parallel decode step.
            from jax.sharding import PartitionSpec as P
            cspec = self._cache_pspec()
            rep, shv = P(), P(self._seq_axis)
            self._decode = jax.jit(
                watch_traces(self._sharded_program(
                    self._decode_body_sharded,
                    (cspec, rep, rep, rep), (cspec, rep, rep)),
                    'engine.decode', budget=2),
                donate_argnums=(0,))
            self._prefill = jax.jit(
                watch_traces(self._sharded_program(
                    self._prefill_body_sharded,
                    (cspec, rep, rep, rep), cspec),
                    'engine.prefill', budget=2),
                donate_argnums=(0,))
            self._reset = jax.jit(
                watch_traces(self._sharded_program(
                    self._reset_body_sharded,
                    (cspec, rep, shv), cspec),
                    'engine.reset', budget=2),
                donate_argnums=(0,))
            self._copy_attach = jax.jit(
                watch_traces(self._sharded_program(
                    self._copy_attach_body_sharded,
                    (cspec, shv, shv, rep, rep), cspec),
                    'engine.copy_attach', budget=2),
                donate_argnums=(0,))
            # register_prefix is rejected under kv_shards (shared
            # prefixes arrive via the shard-local handoff), so no
            # local prefix-fill program exists to mis-call.
            self._prefix_fill = None
        else:
            self._decode = jax.jit(
                watch_traces(self._decode_impl, 'engine.decode',
                             budget=2),
                donate_argnums=(0,))
            self._prefill = jax.jit(
                watch_traces(self._prefill_impl, 'engine.prefill',
                             budget=2),
                donate_argnums=(0,))
            if self.cache_mode == 'paged':
                self._reset = jax.jit(
                    watch_traces(paged_reset_slot, 'engine.reset',
                                 budget=2),
                    donate_argnums=(0,))
                # The sharing primitives: CoW/fork/attach page copy (+
                # length set) and registry prefix prefill — each one
                # fixed compiled program, dispatched only on page
                # crossings and prefix/fork events, never per token.
                self._copy_attach = jax.jit(
                    watch_traces(paged_copy_attach,
                                 'engine.copy_attach', budget=2),
                    donate_argnums=(0,))
                self._prefix_fill = jax.jit(
                    watch_traces(self._prefix_fill_impl,
                                 'engine.prefix_fill', budget=2),
                    donate_argnums=(0,))
            else:
                self._reset = jax.jit(
                    watch_traces(reset_slot, 'engine.reset', budget=2),
                    donate_argnums=(0,))
        # Speculative decoding programs, built LAZILY (a non-spec
        # engine never pays their traces): one verify program per
        # width W = k+1 and one rollback program per span, each a
        # fixed compiled shape under its own retrace budget.
        self._verifies = {}
        self._rollbacks = {}
        # Cross-cache KV handoff programs (disaggregated serving):
        # one per SOURCE pool shape — a topology has exactly one
        # prefill pool shape, so one program for the engine's life.
        self._transfers = {}

    # -- compiled bodies ------------------------------------------------
    def _dot(self, x, w):
        """``x (rows, in) · w`` — the one matmul body every engine
        program routes through, so a precision change cannot miss a
        call site. Float weights: a plain dot (the engine dtype is the
        accumulation dtype — f32 by default). int8 weights (``w`` is
        the ``(kernel_q, kernel_scale)`` pair): the SHARED
        ``models/dense.quantized_dot`` body — one definition of the
        s8×s8→s32 rule, so the engine's streams cannot drift from the
        module path's."""
        if self.weight_quant == 'int8':
            from distributed_dot_product_tpu.models.dense import (
                quantized_dot,
            )
            w_q, w_s = w
            return quantized_dot(x, w_q, w_s).astype(self._embed.dtype)
        return x @ w

    def _project(self, tokens):
        """tokens (S,) → q, k, v each (S, H, 1, D)."""
        s = tokens.shape[0]
        x = jnp.take(self._embed, tokens, axis=0)          # (S, dim)
        shape = (s, self.heads, 1, self.head_dim)
        return (self._dot(x, self._wq).reshape(shape),
                self._dot(x, self._wk).reshape(shape),
                self._dot(x, self._wv).reshape(shape))

    def _decode_impl(self, cache, tokens, active, poison,
                     axis_name=None):
        q, k, v = self._project(tokens)
        # Fused append+attend (one Pallas program on the kernel path —
        # the cache buffers are aliased in place and, with the jit
        # donation above, never copied). With `axis_name` (a kv_shards
        # engine's shard_map body) the step runs over this member's
        # page range only and flash-merges partials across the mesh.
        cache, out = decode_step(q, cache, k, v, slot_mask=active,
                                 impl=self.decode_impl,
                                 axis_name=axis_name)      # (S, H, 1, D)
        logits = self._dot(out.reshape(self.slots, -1),
                           self._wo)                       # (S, vocab)
        logits = jnp.where(poison[:, None], jnp.nan, logits)
        finite = slots_all_finite(logits)
        # Fully-masked argmax input for a poisoned row would be NaN-
        # ordered garbage; the scheduler discards non-finite slots'
        # tokens, so the value only needs to be deterministic.
        next_tok = jnp.argmax(
            jnp.where(jnp.isfinite(logits), logits, -jnp.inf),
            axis=-1).astype(jnp.int32)
        return cache, next_tok, finite

    def _verify_impl(self, cache, tokens, counts, active, poison):
        """Verify-k body (speculative decoding's fused verify):
        ``tokens (S, W)`` — per slot, row 0 the committed input token
        and rows 1.. the proposed continuation, ``counts[i]`` of the W
        rows real (1 = a plain non-spec slot riding the same program).
        Projections, head reshapes and the logits dot all run PER
        COLUMN with the exact ``(S, dim)`` shapes of the n=1 program —
        XLA lowers an (S, dim) and an (S·W, dim) matmul with different
        accumulation orders, and the committed stream must be the n=1
        stream bit for bit wherever the math allows it. The fused
        append+attend step keeps the same per-row identity
        (models/decode.py: a verify-k step == counts sequential
        steps)."""
        w = tokens.shape[1]
        qs, ks, vs = zip(*(self._project(tokens[:, j])
                           for j in range(w)))
        q = jnp.concatenate(qs, axis=2)            # (S, H, W, D)
        k = jnp.concatenate(ks, axis=2)
        v = jnp.concatenate(vs, axis=2)
        cache, out = decode_step(q, cache, k, v, slot_mask=active,
                                 counts=counts, impl=self.decode_impl)
        logits = jnp.stack(
            [self._dot(out[:, :, j].reshape(self.slots, -1), self._wo)
             for j in range(w)], axis=1)           # (S, W, vocab)
        logits = jnp.where(poison[:, None, None], jnp.nan, logits)
        finite = slots_all_finite(logits)
        next_tok = jnp.argmax(
            jnp.where(jnp.isfinite(logits), logits, -jnp.inf),
            axis=-1).astype(jnp.int32)             # (S, W)
        return cache, next_tok, finite

    def _project_kv(self, tokens):
        """Chunk tokens ``(C,)`` → cache-layout k, v each ``(H, C, D)``
        — the ONE projection both prefill paths share (a projection
        change must hit slot prefill and registry prefix fill alike,
        or shared-prefix pages would attend with different K/V)."""
        x = jnp.take(self._embed, tokens, axis=0)          # (C, dim)
        c = tokens.shape[0]
        k = jnp.moveaxis(self._dot(x, self._wk).reshape(
            c, self.heads, self.head_dim), 0, 1)           # (H, C, D)
        v = jnp.moveaxis(self._dot(x, self._wv).reshape(
            c, self.heads, self.head_dim), 0, 1)
        return k, v

    def _prefill_impl(self, cache, slot, tokens, count):
        """Append ``count`` of the ``prefill_chunk`` padded ``tokens``
        into ``slot``'s rows. Projections are computed once and
        broadcast — the masked write only lands on the one slot."""
        k, v = self._project_kv(tokens)
        k = jnp.broadcast_to(k[None], (self.slots,) + k.shape)
        v = jnp.broadcast_to(v[None], (self.slots,) + v.shape)
        sel = jnp.arange(self.slots) == slot
        counts = jnp.where(sel, count, 0).astype(jnp.int32)
        return append_kv_slots(cache, k, v, slot_mask=sel, counts=counts)

    def _prefix_fill_impl(self, cache, tokens, count, page_row, start):
        """Registry prefill: project one padded chunk and scatter its
        first ``count`` rows into the REGISTRY-owned ``page_row`` pages
        at logical positions ``start..`` — no slot, no length."""
        k, v = self._project_kv(tokens)
        return paged_append_rows(cache, k, v, page_row, start, count)

    # -- kv_shards shard_map plumbing (cache_mode='paged', shards>1) ----
    def _cache_pspec(self):
        """PartitionSpec pytree of the stacked sharded cache: pools and
        page-table blocks P(seq) on axis 0, the fill vector replicated
        (it is a global property every member advances identically)."""
        from jax.sharding import PartitionSpec as P
        ax = self._seq_axis
        return PagedDecodeCache(k_pool=P(ax), v_pool=P(ax),
                                page_table=P(ax), length=P(),
                                k_q_pool=None, k_scale_pool=None)

    def _sharded_program(self, body, in_specs, out_specs):
        return jax.shard_map(body, mesh=self._mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)

    def _decode_body_sharded(self, cache, tokens, active, poison):
        local = cache._replace(page_table=cache.page_table[0])
        local, tok, finite = self._decode_impl(
            local, tokens, active, poison, axis_name=self._seq_axis)
        return (local._replace(page_table=local.page_table[None]),
                tok, finite)

    def _prefill_body_sharded(self, cache, slot, tokens, count):
        # The unsharded prefill body verbatim on the local view:
        # rows whose page ordinal another shard owns scatter through a
        # −1 table entry and drop — each member keeps exactly its own
        # slice of the prompt, no cross-member traffic at all.
        local = cache._replace(page_table=cache.page_table[0])
        out = self._prefill_impl(local, slot, tokens, count)
        return out._replace(page_table=out.page_table[None])

    def _reset_body_sharded(self, cache, slot, freed):
        # `freed` is (kv_shards, pages_per_slot) stacked per-shard
        # freed-page vectors (−1-padded); each member zeroes its own.
        local = cache._replace(page_table=cache.page_table[0])
        out = paged_reset_slot(local, slot, freed[0])
        return out._replace(page_table=out.page_table[None])

    def _copy_attach_body_sharded(self, cache, src, dst, slot, length):
        # `src`/`dst` are (kv_shards,) stacked per-shard scalars (−1 =
        # no copy on that member) — one program serves CoW copies and
        # attach tail copies wherever the page lives.
        local = cache._replace(page_table=cache.page_table[0])
        out = paged_copy_attach(local, src[0], dst[0], slot, length)
        return out._replace(page_table=out.page_table[None])

    def _gpage(self, shard, page):
        """Shard-local page id → GLOBAL page id (= the page's stacked
        pool row — each member's block ends with its own sink row).
        Global ids are what the kv_shards engine's host surface speaks
        (registry, checksums verdicts, quarantine), so the router/
        scheduler page arithmetic works unchanged. The stride layout
        itself lives in :meth:`ShardedPageTable.gpage` — flowlint's
        shard-ownership rule keeps it from leaking back here."""
        return self.pool.gpage(shard, page)

    def _gsplit(self, gpage):
        """GLOBAL page id → ``(shard, local page)``."""
        return self.pool.gsplit(gpage)

    def page_shard(self, page):
        """Mesh member owning GLOBAL page id ``page`` on a kv_shards
        engine; None on unsharded engines (the router's kv.corrupt
        shard naming probes any engine through this)."""
        if self.kv_shards <= 1:
            return None
        return self.pool.page_shard(page)

    # -- host surface (numpy in, numpy out) -----------------------------
    def step(self, tokens, active, poison=None, request_ids=None):
        """One decode step for all slots. ``tokens (S,) int`` — each
        ACTIVE slot's input token (its previous output, or the last
        prompt token right after prefill); inactive entries ignored.
        Returns ``(next_tokens (S,), finite (S,))`` numpy arrays.

        ``request_ids`` (optional, per-slot) is observability-only: it
        labels the host-side span so a profiler/span tree ties a decode
        dispatch back to the requests it served — it never reaches the
        compiled program (strings can't; the program is id-oblivious by
        design)."""
        poison = (np.zeros(self.slots, bool) if poison is None
                  else np.asarray(poison, bool))
        if self.cache_mode == 'paged':
            # Auto-prepare only when something actually needs a page
            # (a vectorized check — the scheduler's _ensure_pages
            # already prepared, so the per-token hot path pays one
            # numpy mask, not a per-slot Python loop). Direct callers
            # just work; exhaustion raises here because a bare loop
            # has no evict/preempt ladder to resolve it.
            act = np.asarray(active, bool)
            if not self._writable_mask(act).all():
                ok = self.prepare_step(act)
                if not ok.all():
                    bad = np.nonzero(~ok)[0]
                    by_shard = (
                        f', free by shard '
                        f'{self.pool.free_pages_by_shard} — one '
                        f"shard's contiguous range is out of pages "
                        f'even though others have headroom'
                        if self.kv_shards > 1 else '')
                    raise RuntimeError(
                        f'page pool exhausted for slot(s) '
                        f'{bad.tolist()} ({self.pool.free_pages} pages '
                        f'free{by_shard}) — retire or evict sequences '
                        f'(the Scheduler ladder does), or size the '
                        f'pool larger')
            self._sync_page_table()
        # Span attrs are built ONLY when spans are on: this is the
        # per-token hot path, and the disabled default must not pay a
        # per-step tuple build for labels nobody will read.
        ids = (tuple(r for r in (request_ids or ()) if r)
               if obs_spans.enabled() else ())
        with span('engine.decode_step', requests=ids):
            # Timed through the host round-trip (np.asarray blocks on
            # the async dispatch) — program_seconds measures the wall
            # time the loop actually waits on the device, the quantity
            # the dispatch-floor split subtracts from tick time.
            t0 = time.perf_counter()
            self.cache, tok, finite = self._decode(
                self.cache, jnp.asarray(tokens, jnp.int32),
                jnp.asarray(active, bool), jnp.asarray(poison))
            out = np.asarray(tok), np.asarray(finite)
            self.program_seconds += time.perf_counter() - t0
            if self.cache_mode == 'paged':
                self.pool.lengths[np.asarray(active, bool)] += 1
            return out

    def _verify_program(self, w):
        """One compiled verify program per width W = k+1, built lazily
        under its own retrace budget (the width is a compile-time
        shape; a serving run uses ONE k, so one program — the dict
        exists for benchmarks sweeping k in-process)."""
        prog = self._verifies.get(w)
        if prog is None:
            from distributed_dot_product_tpu.analysis.retrace import (
                watch_traces,
            )
            prog = self._verifies[w] = jax.jit(
                watch_traces(self._verify_impl, f'engine.verify_w{w}',
                             budget=2),
                donate_argnums=(0,))
        return prog

    def verify_step(self, tokens, counts, active, poison=None,
                    request_ids=None):
        """One fused verify-k step for all slots: ``tokens (S, W)
        int`` — per ACTIVE slot, ``[input_token, p_1, .., p_c, pad]``
        with ``counts[i] = c_i + 1`` rows real (1 = plain decode: a
        mixed spec/non-spec batch rides one program). Returns
        ``(next_tokens (S, W), finite (S,))``: ``next_tokens[i, j]``
        is the greedy target token AFTER consuming input row j — the
        caller accepts the longest prefix with ``p_{j+1} ==
        next_tokens[i, j]``, commits one extra "free" token, and rolls
        the cache back to the accepted prefix (:meth:`rollback`).
        Rows past ``counts[i]`` are don't-care outputs.

        The cache appends ``counts[i]`` rows per active slot (paged
        engines auto-reserve the pages, raising on exhaustion — the
        Scheduler reserves through its evict/preempt ladder instead)."""
        if self.kv_shards > 1:
            raise ServeContractError(
                'verify_step (speculative decoding) is not supported '
                'with kv_shards > 1 — the sharded ring-decode step is '
                'single-token; run spec decode on unsharded replicas')
        tokens = np.asarray(tokens, np.int32)
        s, w = tokens.shape
        if s != self.slots:
            raise ServeContractError(
                f'tokens rows {s} != slots {self.slots}')
        counts = np.clip(np.asarray(counts, np.int64), 0, w)
        act = np.asarray(active, bool)
        poison = (np.zeros(self.slots, bool) if poison is None
                  else np.asarray(poison, bool))
        if self.cache_mode == 'paged':
            for i in np.nonzero(act)[0]:
                c = int(counts[i])
                if c and not self.reserve_rows(int(i), c):
                    raise RuntimeError(
                        f'page pool exhausted reserving {c} verify '
                        f'rows for slot {int(i)} '
                        f'({self.pool.free_pages} pages free) — '
                        f'retire or evict sequences (the Scheduler '
                        f'ladder does), or size the pool larger')
            self._sync_page_table()
        ids = (tuple(r for r in (request_ids or ()) if r)
               if obs_spans.enabled() else ())
        with span('engine.verify_step', requests=ids, width=w):
            t0 = time.perf_counter()
            self.cache, tok, finite = self._verify_program(w)(
                self.cache, jnp.asarray(tokens),
                jnp.asarray(counts, jnp.int32), jnp.asarray(act),
                jnp.asarray(poison))
            out = np.asarray(tok), np.asarray(finite)
            self.program_seconds += time.perf_counter() - t0
            if self.cache_mode == 'paged':
                self.pool.lengths[act] += counts[act]
            return out

    def _rollback_program(self, span_rows):
        prog = self._rollbacks.get(span_rows)
        if prog is None:
            from distributed_dot_product_tpu.analysis.retrace import (
                watch_traces,
            )
            if self.cache_mode == 'paged' and self.kv_shards > 1:
                from jax.sharding import PartitionSpec as P

                def _body(cache, lengths):
                    local = cache._replace(
                        page_table=cache.page_table[0])
                    out = paged_rollback_slots(local, lengths,
                                               span_rows)
                    return out._replace(
                        page_table=out.page_table[None])

                body = self._sharded_program(
                    _body, (self._cache_pspec(), P()),
                    self._cache_pspec())
            elif self.cache_mode == 'paged':
                def body(cache, lengths):
                    return paged_rollback_slots(cache, lengths,
                                                span_rows)
            else:
                def body(cache, lengths):
                    return rollback_slots(cache, lengths,
                                          span=span_rows)
            prog = self._rollbacks[span_rows] = jax.jit(
                watch_traces(body, f'engine.rollback_s{span_rows}',
                             budget=2),
                donate_argnums=(0,))
        return prog

    def rollback(self, lengths):
        """Acceptance-prefix rollback: truncate each slot to
        ``lengths[i]`` rows and zero the rejected tail —
        ``min(current, target)`` semantics, so a past-fill sentinel
        (e.g. ``np.iinfo(np.int32).max``) leaves a slot untouched and
        ONE batched call serves a mixed tick. The zeroing is surgical
        (a span-bounded scatter, not a cache rewrite); spans compile
        per power-of-two bucket, so a whole serving run uses one or
        two programs. Paged engines additionally return now-empty tail
        pages to the pool (refcount--, freed pages zeroed — the alloc
        invariant) and resync the device page table."""
        tgt = np.asarray(lengths, np.int64)
        cur = (self.pool.lengths.astype(np.int64)
               if self.cache_mode == 'paged'
               else np.asarray(self.cache.length, np.int64))
        new = np.minimum(cur, tgt)
        need = int((cur - new).max()) if cur.size else 0
        if need == 0:
            return
        bucket = 1 << (need - 1).bit_length()
        with span('engine.rollback', rows=need):
            t0 = time.perf_counter()
            self.cache = self._rollback_program(bucket)(
                self.cache, jnp.asarray(new, jnp.int32))
            self.program_seconds += time.perf_counter() - t0
        if self.cache_mode == 'paged':
            if self.kv_shards > 1:
                freed = {}
                for i in np.nonzero(cur > new)[0]:
                    for s, pgs in self.pool.truncate(
                            int(i), int(new[i])).items():
                        freed.setdefault(s, []).extend(pgs)
                if freed:
                    self._zero_freed_sharded(freed)
            else:
                freed = []
                for i in np.nonzero(cur > new)[0]:
                    freed += self.pool.truncate(int(i), int(new[i]))
                if freed:
                    self._zero_freed(freed)
            self._sync_page_table()

    def prefill(self, slot, tokens, request_id=None):
        """Append one prompt chunk (``len(tokens) <= prefill_chunk``)
        into ``slot``. Pads to the compiled chunk width; padded rows
        never land (counts mask). ``request_id`` labels the span only
        (see :meth:`step`)."""
        n = len(tokens)
        if n > self.prefill_chunk:
            raise ServeContractError(
                f'chunk of {n} exceeds prefill_chunk='
                f'{self.prefill_chunk}')
        buf = np.zeros(self.prefill_chunk, np.int32)
        buf[:n] = np.asarray(tokens, np.int32)
        if self.cache_mode == 'paged':
            # Auto-reserve the chunk's pages (no-op when the scheduler
            # already reserved the whole prompt at admission).
            pos = int(self.pool.lengths[slot])
            covered = (self.pool.covered_rows(slot)
                       if self.kv_shards > 1
                       else int(self.pool.counts[slot]) * self.page_size)
            if (pos + n) > covered and not self.reserve_rows(slot, n):
                raise RuntimeError(
                    f'page pool exhausted prefilling rows '
                    f'[{pos}, {pos + n}) of slot {slot} '
                    f'({self.pool.free_pages} pages free)')
            self._sync_page_table()
        with span('engine.prefill', slot=int(slot),
                  request=request_id or ''):
            t0 = time.perf_counter()
            self.cache = self._prefill(self.cache, jnp.int32(slot),
                                       jnp.asarray(buf), jnp.int32(n))
            self.program_seconds += time.perf_counter() - t0
        if self.cache_mode == 'paged':
            self.pool.lengths[slot] += n

    def _zero_freed(self, freed, slot=-1):
        """Zero freed pool pages (and clear ``slot``'s rows/length when
        one is named; slot −1 touches no slot) through the ONE compiled
        reset program — the freed-page zeroing contract lives here."""
        vec = np.full(self.pool.pages_per_slot, -1, np.int32)
        vec[:len(freed)] = freed
        self.cache = self._reset(self.cache, jnp.int32(slot),
                                 jnp.asarray(vec))
        if self.checksums is not None:
            self.checksums.drop(freed)

    def _zero_freed_sharded(self, freed, slot=-1):
        """kv_shards twin of :meth:`_zero_freed`: ``freed`` is
        ``{shard: [local pages]}``; the stacked per-shard vectors go
        through the ONE sharded reset program (each member zeroes its
        own list), and each shard's checksum table forgets its own."""
        vec = np.full((self.kv_shards, self.pool.pages_per_slot), -1,
                      np.int32)
        for s, pages in freed.items():
            vec[s, :len(pages)] = pages
        self.cache = self._reset(self.cache, jnp.int32(slot),
                                 jnp.asarray(vec))
        if self.checksums is not None:
            for s, pages in freed.items():
                self.checksums[s].drop(pages)

    def reset(self, slot):
        """Evict ``slot`` (zero rows + length); other slots untouched.
        Paged: drops the slot's page references and zeroes exactly the
        pages that reached refcount 0 (still-shared prefix/fork pages
        keep their bits — they are someone else's context)."""
        if self.cache_mode == 'paged':
            if self.kv_shards > 1:
                self._zero_freed_sharded(self.pool.release(slot), slot)
            else:
                self._zero_freed(self.pool.release(slot), slot)
            self._sync_page_table()
        else:
            self.cache = self._reset(self.cache, jnp.int32(slot))

    def lengths(self):
        # np.array, NOT np.asarray: on the CPU backend asarray is a
        # ZERO-COPY view of the device buffer, and every engine program
        # donates the cache — the next step would recycle the buffer
        # under the caller's snapshot. The verify-k commit loop anchors
        # its rollback targets on this vector across exactly such a
        # donating call, so a view here silently inflates every target
        # by the committed width (one token per slot per step leaks).
        return np.array(self.cache.length)

    # -- paged-pool surface (cache_mode='paged') ------------------------
    def _sync_page_table(self):
        if self.pool.dirty:
            if self.kv_shards > 1:
                # Stacked local views, explicitly re-placed P(seq) so
                # the donated device mirror never bounces through a
                # single-device layout on its way into the programs.
                pt = jax.device_put(
                    jnp.asarray(self.pool.local_tables()),
                    self._pt_sharding)
            else:
                pt = jnp.asarray(self.pool.table)
            self.cache = self.cache._replace(page_table=pt)
            self.pool.dirty = False

    def _apply_copies(self, copies):
        if self.kv_shards > 1:
            # (shard, src, dst) triples → stacked per-shard scalar
            # vectors (−1 = no copy on that member).
            for s, src, dst in copies:
                vs = np.full(self.kv_shards, -1, np.int32)
                vd = np.full(self.kv_shards, -1, np.int32)
                vs[s], vd[s] = src, dst
                self.cache = self._copy_attach(
                    self.cache, jnp.asarray(vs), jnp.asarray(vd),
                    jnp.int32(-1), jnp.int32(0))
            return
        for src, dst in copies:
            self.cache = self._copy_attach(
                self.cache, jnp.int32(src), jnp.int32(dst),
                jnp.int32(-1), jnp.int32(0))

    def _writable_mask(self, active):
        """Per active slot: does a PRIVATE page already cover its next
        append position (the prepare_step()/reserve_rows()
        postcondition)? Vectorized — this is the per-token fast path
        that lets step() skip re-preparing when the scheduler already
        did. A slot AT ``t_max`` counts as writable: there is no page
        to prepare — the device write drops while the length advances
        (the slab engine's frozen-write contract), so stepping it must
        not raise."""
        idx = np.nonzero(active)[0]
        ok = np.ones(len(active), bool)
        if not idx.size:
            return ok
        pool = self.pool
        if self.kv_shards > 1:
            # Route each slot's append ordinal to its OWNING shard's
            # table/refcount (slots are few; the owner lookup is the
            # cost of the sharded layout's locality).
            for i in idx:
                pi = int(pool.lengths[i]) // self.page_size
                if pi >= pool.pages_per_slot:
                    continue                    # full: writable no-op
                sp = pool.shards[pool.owner(pi)]
                pg = int(sp.table[i, pi])
                ok[i] = pg >= 0 and int(sp.refcount[pg]) == 1
            return ok
        pi = pool.lengths[idx] // self.page_size
        full = pi >= pool.pages_per_slot
        pg = pool.table[idx, np.minimum(pi, pool.pages_per_slot - 1)]
        good = (pg >= 0)
        good &= pool.refcount[np.maximum(pg, 0)] == 1
        ok[idx] = full | good
        return ok

    def prepare_step(self, active):
        """Make every active slot's next append position writable:
        allocate the page a slot crossing a page boundary needs, and
        copy-on-write any shared append page (first divergent append
        after a fork/prefix attach). Returns a ``(slots,) bool`` mask —
        False means the pool is EXHAUSTED for that slot and nothing was
        allocated; the scheduler owns the evict/preempt policy. A slot
        already at ``t_max`` is True (``'full'``): nothing to allocate,
        its append drops on device like the slab path's."""
        active = np.asarray(active, bool)
        ok = np.ones(self.slots, bool)
        # Vectorized fast path first: the per-token cost is one numpy
        # mask; the Python allocator loop below runs only for slots
        # that actually need a page (boundary crossing or shared
        # append page) — the same contract step()'s auto-prepare uses.
        todo = active & ~self._writable_mask(active)
        for i in np.nonzero(todo)[0]:
            if self.kv_shards > 1:
                # The sharded pool names WHICH shard's contiguous
                # range answered (exhaustion there is typed back
                # through the scheduler's evict/preempt ladder even
                # while other shards have headroom — never a stall).
                st, sh, src, dst = self.pool.prepare_append(int(i))
                if st == 'exhausted':
                    ok[i] = False
                elif st == 'cow':
                    self._apply_copies([(sh, src, dst)])
                continue
            st, src, dst = self.pool.prepare_append(int(i))
            if st == 'exhausted':
                ok[i] = False
            elif st == 'cow':
                self._apply_copies([(src, dst)])
        self._sync_page_table()
        return ok

    def reserve_rows(self, slot, rows):
        """Admission-time reservation: every page covering ``slot``'s
        next ``rows`` logical rows (so chunked prefill can never fail
        mid-prompt). False = pool exhausted, nothing changed."""
        ok, copies = self.pool.reserve_rows(slot, rows)
        if ok:
            self._apply_copies(copies)
            self._sync_page_table()
        return ok

    def register_prefix(self, tokens):
        """Prefill ``tokens`` ONCE into registry-owned pool pages and
        return a prefix id. Sequences started with
        :meth:`start_with_prefix` share the prefix's full pages
        read-only (refcounted) — N sequences riding a system prompt
        cost its pages once plus one partial tail page each."""
        if self.cache_mode != 'paged':
            raise ValueError("prefix sharing needs cache_mode='paged'")
        if self.kv_shards > 1:
            raise ValueError(
                'register_prefix (local prefix prefill) is not '
                'supported with kv_shards > 1 — shared prefixes arrive '
                'through the shard-local prefill→decode handoff '
                '(adopt_prefix)')
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = len(tokens)
        if n < 1:
            raise ValueError('empty prefix')
        if n + 1 > self.t_max:
            raise ValueError(f'prefix of {n} tokens leaves no room to '
                             f'generate in a t_max={self.t_max} cache')
        needed = self.pool.pages_for_rows(n)
        pages = self.pool.alloc_block(needed)
        if pages is None:
            raise RuntimeError(
                f'page pool exhausted registering a {n}-token '
                f'prefix ({needed} pages needed, '
                f'{self.pool.free_pages} free)')
        row = np.full(self.pool.pages_per_slot, -1, np.int32)
        row[:needed] = pages
        row_j = jnp.asarray(row)
        for start in range(0, n, self.prefill_chunk):
            chunk = tokens[start:start + self.prefill_chunk]
            buf = np.zeros(self.prefill_chunk, np.int32)
            buf[:len(chunk)] = chunk
            self.cache = self._prefix_fill(
                self.cache, jnp.asarray(buf), jnp.int32(len(chunk)),
                row_j, jnp.int32(start))
        return self._register_pages(pages, n)

    def _register_pages(self, pages, n):
        """Enter ``pages`` (already allocated and filled, covering
        ``n`` rows) into the prefix registry — the one place prefix
        ids are minted, shared by :meth:`register_prefix` (local
        prefill) and :meth:`adopt_prefix` (cross-cache handoff)."""
        pid = next(self._prefix_counter)
        self._prefix_registry[pid] = (pages, n)
        self._checksum_record(pages)
        return pid

    # -- page integrity (host-side, transfer boundaries only) -----------
    def _by_shard(self, pages):
        """Group GLOBAL page ids → ``{shard: [local pages]}`` (kv_shards
        surface; the order within a shard follows the input)."""
        per = {}
        for g in pages:
            s, p = self._gsplit(g)
            per.setdefault(s, []).append(p)
        return per

    def _checksum_record(self, pages):
        if self.checksums is None:
            return
        t0 = time.perf_counter()
        if self.kv_shards > 1:
            # Per-owning-shard tables, keyed by LOCAL page ids; the
            # digest reads the page's stacked pool row. A shard's
            # table never holds another shard's pages — the coherence
            # contract the sharded transfer boundaries maintain.
            for s, locs in self._by_shard(pages).items():
                tab = self.checksums[s]
                for p in locs:
                    tab.record_at(self.cache, p,
                                  row=self._gpage(s, p))
        else:
            self.checksums.record(self.cache, pages)
        self.verify_seconds += time.perf_counter() - t0

    def verify_pages(self, pages=None):
        """Re-digest ``pages`` (default: every tracked page — the
        scrub) against the recorded checksums. Returns the sorted
        dirty-page list without raising; [] when clean or when
        checksums are disabled. Host work only. kv_shards engines
        speak GLOBAL page ids here (in and out)."""
        if self.checksums is None:
            return []
        t0 = time.perf_counter()
        if self.kv_shards > 1:
            bad = []
            per = (self._by_shard(pages) if pages is not None
                   else {s: tab.pages()
                         for s, tab in enumerate(self.checksums)})
            for s, locs in per.items():
                tab = self.checksums[s]
                for p in locs:
                    want = tab.get(p)
                    if want is not None and PageChecksums.digest(
                            self.cache,
                            self._gpage(s, p)) != want:
                        bad.append(self._gpage(s, p))
            bad = sorted(bad)
        else:
            bad = self.checksums.verify(self.cache, pages)
        self.verify_seconds += time.perf_counter() - t0
        return bad

    def verify_prefix(self, prefix_id):
        """Scrub one registered prefix's pages (dirty list, no raise)."""
        pages, _ = self._prefix_registry[prefix_id]
        return self.verify_pages(pages)

    def check_pages(self, pages, site):
        """Raise :class:`PageCorruptionError` naming ``site`` if any of
        ``pages`` fails verification (untracked pages are skipped). On
        kv_shards engines the error also names the dirty shard(s)."""
        bad = self.verify_pages(pages)
        if bad:
            shards = ([self.page_shard(p) for p in bad]
                      if self.kv_shards > 1 else None)
            raise PageCorruptionError(bad, site, shards=shards)

    def quarantine_pages(self, pages):
        """Withdraw dirty pages from circulation (they never return to
        the free list) and forget their digests so scrubs stop
        re-flagging them. Returns the pages newly quarantined —
        GLOBAL ids in and out on kv_shards engines, routed to each
        page's owning shard."""
        if self.kv_shards > 1:
            newly = []
            for s, locs in self._by_shard(pages).items():
                if self.checksums is not None:
                    self.checksums[s].drop(locs)
                newly += [self._gpage(s, p)
                          for p in self.pool.quarantine(s, locs)]
            return sorted(newly)
        if self.checksums is not None:
            self.checksums.drop(pages)
        return self.pool.quarantine(pages)

    def slots_sharing(self, pages):
        """Slots whose page tables name any of ``pages`` — the live
        victims of a corruption verdict."""
        if self.pool is None:
            return []
        if self.kv_shards > 1:
            per = {s: set(locs)
                   for s, locs in self._by_shard(pages).items()}
            hit = []
            for slot in range(self.slots):
                for s, locs in per.items():
                    sp = self.pool.shards[s]
                    if any(int(sp.table[slot, i]) in locs
                           for i in range(int(sp.counts[slot]))):
                        hit.append(slot)
                        break
            return hit
        bad = {int(p) for p in pages}
        hit = []
        for slot in range(self.slots):
            n = int(self.pool.counts[slot])
            if any(int(self.pool.table[slot, i]) in bad
                   for i in range(n)):
                hit.append(slot)
        return hit

    def prefixes_on(self, pages):
        """Registered prefix ids built on any of ``pages`` — the
        entries a corruption verdict must invalidate."""
        bad = {int(p) for p in pages}
        return [pid for pid, (pgs, _) in self._prefix_registry.items()
                if bad.intersection(int(p) for p in pgs)]

    def _transfer_program(self, src_shape):
        prog = self._transfers.get(src_shape)
        if prog is None:
            from distributed_dot_product_tpu.analysis.retrace import (
                watch_traces,
            )
            if self.kv_shards > 1:
                # Shard-local handoff: source pages arrive as a
                # shard-STACKED slab (kv_shards, width, ...) laid out
                # P(seq) — each mesh member holds, and copies from,
                # ONLY the pages whose ordinals it owns. No member
                # ever materializes the full sequence; the transfer
                # unit stays the page.
                from jax.sharding import PartitionSpec as P

                def _body(cache, src_k, src_v, vsrc, vdst):
                    local = cache._replace(
                        page_table=cache.page_table[0])
                    out = paged_transfer_pages(local, src_k[0],
                                               src_v[0],
                                               vsrc[0], vdst[0])
                    return out._replace(
                        page_table=out.page_table[None])

                fn = self._sharded_program(
                    _body,
                    (self._cache_pspec(), P(self._seq_axis),
                     P(self._seq_axis),
                     P(self._seq_axis), P(self._seq_axis)),
                    self._cache_pspec())
            else:
                fn = paged_transfer_pages
            prog = self._transfers[src_shape] = jax.jit(
                watch_traces(fn, 'engine.adopt', budget=2),
                donate_argnums=(0,))
        return prog

    def adopt_prefix(self, src_cache, src_pages, length,
                     src_checksums=None):
        """The prefill→decode KV handoff (disaggregated serving): copy
        ``length`` rows living in ``src_pages`` of ANOTHER paged cache
        (a prefill pool's — same page size and head geometry, its own
        pool size) into freshly allocated pages of THIS engine's pool
        and register them as a shared prefix. One compiled program
        moves whole pages — the transfer unit is the page, exactly as
        :meth:`register_prefix`'s product is, so sequences started
        with :meth:`start_with_prefix` cannot tell a handed-off prefix
        from a locally prefilled one. Raises on pool exhaustion (the
        router checks headroom first) and on geometry mismatch.

        ``src_checksums`` (the source pool's :class:`PageChecksums`)
        makes the handoff end-to-end verifiable: the source pages are
        verified BEFORE the transfer (dirty source →
        :class:`PageCorruptionError` at site 'handoff_src') and the
        landed copies' KV digests are compared to the source's AFTER
        (a corrupted transfer → site 'handoff_copy', with the adopted
        prefix unregistered — never handed to a caller). Only
        ``kv_crc`` crosses caches: the destination int8 mirror is
        re-quantized from the adopted K with eps-scale tail rows, so
        mirror bytes legitimately differ between pools."""
        if self.cache_mode != 'paged':
            raise ValueError("prefix adoption needs cache_mode='paged'")
        if src_cache.page_size != self.page_size:
            raise ValueError(
                f'page-size mismatch: source {src_cache.page_size} vs '
                f'{self.page_size} — the page is the transfer unit, '
                f'both pools must agree')
        if src_cache.k_pool.shape[1:] != self.cache.k_pool.shape[1:] \
                or src_cache.v_pool.shape[1:] != self.cache.v_pool.shape[1:]:
            raise ValueError(
                f'KV geometry mismatch: source pages '
                f'{src_cache.k_pool.shape[1:]} vs '
                f'{self.cache.k_pool.shape[1:]}')
        if length < 1 or length + 1 > self.t_max:
            raise ValueError(f'prefix of {length} rows leaves no room '
                             f'to generate in a t_max={self.t_max} '
                             f'cache')
        src_pages = [int(p) for p in src_pages]
        needed = self.pool.pages_for_rows(length)
        if len(src_pages) != needed:
            raise ValueError(f'{len(src_pages)} source pages for '
                             f'{length} rows (need {needed})')
        if src_checksums is not None:
            t0 = time.perf_counter()
            bad = src_checksums.verify(src_cache, src_pages)
            self.verify_seconds += time.perf_counter() - t0
            if bad:
                raise PageCorruptionError(bad, 'handoff_src')
        if self.kv_shards > 1:
            return self._adopt_prefix_sharded(
                src_cache, src_pages, length, src_checksums, needed)
        pages = self.pool.alloc_block(needed)
        if pages is None:
            raise RuntimeError(
                f'page pool exhausted adopting a {length}-row prefix '
                f'({needed} pages needed, {self.pool.free_pages} free)')
        # Fixed-width −1-padded vectors: one compiled transfer program
        # per source pool shape, whatever the prefix length.
        width = max(self.pool.pages_per_slot, needed)
        vec_src = np.full(width, -1, np.int32)
        vec_dst = np.full(width, -1, np.int32)
        vec_src[:needed] = src_pages
        vec_dst[:needed] = pages
        key = (src_cache.k_pool.shape, src_cache.v_pool.shape, width)
        self.cache = self._transfer_program(key)(
            self.cache, src_cache.k_pool, src_cache.v_pool,
            jnp.asarray(vec_src), jnp.asarray(vec_dst))
        pid = self._register_pages(pages, length)
        if self.checksums is not None and src_checksums is not None:
            # Landed-copy verification: the transfer moves whole pages
            # (unfilled tail rows are zero on both sides), so the KV
            # digest must survive the copy bit-exactly.
            bad = []
            for sp, dp in zip(src_pages, pages):
                want = src_checksums.get(sp)
                have = self.checksums.get(dp)
                if want is not None and have is not None \
                        and have[0] != want[0]:
                    bad.append(dp)
            if bad:
                self.unregister_prefix(pid)
                raise PageCorruptionError(bad, 'handoff_copy')
        return pid

    def _adopt_prefix_sharded(self, src_cache, src_pages, length,
                              src_checksums, needed):
        """kv_shards tail of :meth:`adopt_prefix` (validation and the
        source verify already ran): allocate, per shard, exactly the
        pages covering the ordinals that shard OWNS, then run ONE
        stacked transfer program in which each mesh member copies only
        its own ordinals' source pages into its own pool block — the
        shard-local handoff, page-granular, with no full-sequence
        gather anywhere. All-or-nothing allocation: any shard's
        exhaustion rolls the other shards' fresh blocks back."""
        alloc = {}                       # shard -> local pages, by ordinal
        for s in range(self.kv_shards):
            lo, hi = self.pool.owned_range(s)
            k = max(0, min(hi, needed) - lo)
            if k == 0:
                continue
            pgs = self.pool.shards[s].alloc_block(k)
            if pgs is None:
                for s2, got in alloc.items():
                    self.pool.shards[s2].release_pages(got)
                raise RuntimeError(
                    f'page pool exhausted adopting a {length}-row '
                    f'prefix: shard {s} has '
                    f'{self.pool.shards[s].free_pages} of the {k} '
                    f'pages its ordinal range [{lo}, {min(hi, needed)})'
                    f' needs (free by shard '
                    f'{self.pool.free_pages_by_shard})')
            alloc[s] = pgs
        width = self.pool.pages_per_slot
        vec_src = np.full((self.kv_shards, width), -1, np.int32)
        vec_dst = np.full((self.kv_shards, width), -1, np.int32)
        sel = np.zeros((self.kv_shards, width), np.int64)
        gpages = [0] * needed
        for s, pgs in alloc.items():
            lo, _ = self.pool.owned_range(s)
            for j, p in enumerate(pgs):
                sel[s, j] = src_pages[lo + j]
                vec_src[s, j] = j          # row WITHIN the staged slab
                vec_dst[s, j] = p
                gpages[lo + j] = self._gpage(s, p)
        # Stage only the referenced source pages, shard-stacked and
        # laid out P(seq) on THIS engine's mesh: each member receives
        # exactly the pages covering its own ordinal range (the
        # single-controller analog of a per-shard point-to-point send
        # — the source pool may live on a different mesh entirely).
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        slab_sh = NamedSharding(self._mesh, P(self._seq_axis))
        flat = sel.reshape(-1)
        src_k = jax.device_put(
            jnp.asarray(np.asarray(src_cache.k_pool)[flat]).reshape(
                self.kv_shards, width, *src_cache.k_pool.shape[1:]),
            slab_sh)
        src_v = jax.device_put(
            jnp.asarray(np.asarray(src_cache.v_pool)[flat]).reshape(
                self.kv_shards, width, *src_cache.v_pool.shape[1:]),
            slab_sh)
        key = (src_k.shape, src_v.shape, width)
        self.cache = self._transfer_program(key)(
            self.cache, src_k, src_v,
            jnp.asarray(vec_src), jnp.asarray(vec_dst))
        pid = self._register_pages(gpages, length)
        if self.checksums is not None and src_checksums is not None:
            # Landed-copy verification, per owning shard: each landed
            # page's KV digest (recorded against its stacked row just
            # above) must equal the source page's.
            bad = []
            for o, g in enumerate(gpages):
                want = src_checksums.get(src_pages[o])
                s, p = self._gsplit(g)
                have = self.checksums[s].get(p)
                if want is not None and have is not None \
                        and have[0] != want[0]:
                    bad.append(g)
            if bad:
                self.unregister_prefix(pid)
                raise PageCorruptionError(
                    bad, 'handoff_copy',
                    shards=[self.page_shard(g) for g in bad])
        return pid

    def prefix_length(self, prefix_id):
        return self._prefix_registry[prefix_id][1]

    def unregister_prefix(self, prefix_id):
        """Release the registry's page references; pages still shared
        by live sequences survive until those retire."""
        pages, _ = self._prefix_registry.pop(prefix_id)
        if self.kv_shards > 1:
            freed = {}
            for s, locs in self._by_shard(pages).items():
                got = self.pool.release_pages_on(s, locs)
                if got:
                    freed[s] = got
            if freed:
                self._zero_freed_sharded(freed)
            return
        freed = self.pool.release_pages(pages)
        if freed:
            self._zero_freed(freed)

    def start_with_prefix(self, slot, prefix_id):
        """Point an EMPTY slot at a registered prefix: full pages
        shared (refcount++), partial tail page copied private, length
        set — the slot then prefills/decodes its own continuation.
        False = pool exhausted (no tail page available). The prefix's
        pages are verified first — attaching a sequence to a corrupted
        prefix raises before any token can read it. kv_shards engines
        attach per owning shard (the tail copy lands on the tail
        ordinal's owner)."""
        pages, plen = self._prefix_registry[prefix_id]
        self.check_pages(pages, 'attach')
        if self.kv_shards > 1:
            ord_pages = np.full(self.pool.pages_per_slot, -1, np.int32)
            for o, g in enumerate(pages):
                ord_pages[o] = self._gsplit(g)[1]
            ok, tsh, tsrc, tdst = self.pool.attach(slot, ord_pages,
                                                   plen)
            if not ok:
                return False
            vs = np.full(self.kv_shards, -1, np.int32)
            vd = np.full(self.kv_shards, -1, np.int32)
            if tsh >= 0:
                vs[tsh], vd[tsh] = tsrc, tdst
            self.cache = self._copy_attach(
                self.cache, jnp.asarray(vs), jnp.asarray(vd),
                jnp.int32(slot), jnp.int32(plen))
            self._sync_page_table()
            return True
        ok, src, dst = self.pool.attach(slot, pages, plen)
        if not ok:
            return False
        self.cache = self._copy_attach(self.cache, jnp.int32(src),
                                       jnp.int32(dst), jnp.int32(slot),
                                       jnp.int32(plen))
        self._sync_page_table()
        return True

    def fork_slot(self, src, dst):
        """Copy-on-write fork for parallel sampling: ``dst`` (an empty
        slot) shares ``src``'s full pages and gets a private copy of
        the partial tail page — O(1 page) device work however long the
        context. False = pool exhausted. The source's TRACKED pages
        (shared prefix pages — private append pages are out of
        coverage) are verified before the branch shares them."""
        if self.kv_shards > 1:
            raise ValueError(
                'fork_slot (copy-on-write forks) is not supported with '
                'kv_shards > 1 — run parallel sampling on unsharded '
                'replicas')
        if self.checksums is not None:
            shared = [int(self.pool.table[src, i])
                      for i in range(int(self.pool.counts[src]))]
            self.check_pages(shared, 'fork')
        ok, tail_src, tail_dst = self.pool.fork(src, dst)
        if not ok:
            return False
        self.cache = self._copy_attach(
            self.cache, jnp.int32(tail_src), jnp.int32(tail_dst),
            jnp.int32(dst), jnp.int32(int(self.pool.lengths[dst])))
        self._sync_page_table()
        return True

    @property
    def weight_bytes(self):
        """Bytes of the four projection/head matrices a decode step
        streams (int8 engines count the int8 kernels + their scales) —
        the weights column of the quantized-vs-float benchmark twins.
        The embedding is excluded: a step gathers S rows of it, not
        the table."""
        from distributed_dot_product_tpu.models.dense import (
            dense_param_bytes,
        )
        return dense_param_bytes(
            [self._wq, self._wk, self._wv, self._wo])

    @property
    def free_pages(self):
        return self.pool.free_pages if self.pool is not None else None

    @property
    def pinned_pages(self):
        """Distinct pool pages the prefix registry holds a permanent
        reference on — they can never return to the free list while
        their prefix stays registered (each prefix allocates fresh
        pages, so the per-prefix page lists are disjoint). 0 on slab
        engines, like the other probe-any-engine accessors."""
        if self.pool is None:
            return 0
        return sum(len(pages)
                   for pages, _ in self._prefix_registry.values())

    @property
    def capacity_tokens(self):
        """Most rows ONE fresh sequence can ever hold: the per-slot
        table reach capped by the pool itself."""
        if self.pool is None:
            return self.t_max
        return min(self.t_max, self.pool.pages * self.page_size)

    def slot_pages(self, slot):
        return self.pool.slot_pages(slot) if self.pool is not None else 0

    def cache_stats(self):
        """Occupancy snapshot for the scheduler's gauges. A slab
        engine has no pool (everything statically reserved) — report
        zeros so generic dashboard code can probe any engine, matching
        the ``free_pages``/``slot_pages`` guards."""
        pool = self.pool
        if pool is None:
            return {'pages': 0, 'pages_used': 0, 'pages_free': 0,
                    'shared_pages': 0, 'page_size': 0,
                    'pages_quarantined': 0}
        out = {'pages': pool.pages, 'pages_used': pool.used_pages,
               'pages_free': pool.free_pages,
               'shared_pages': pool.shared_pages,
               'page_size': pool.page_size,
               'pages_quarantined': len(pool.quarantined)}
        if self.kv_shards > 1:
            # Shard-aware occupancy: the aggregate rows above already
            # sum across shards; the per-shard free vector is what an
            # operator needs to see a single shard's range running dry
            # while the aggregate still looks healthy.
            out['kv_shards'] = self.kv_shards
            out['pages_free_by_shard'] = pool.free_pages_by_shard
        return out

    # -- chaos seam (utils/faults.py page_corrupt knob) -----------------
    def tracked_pages(self):
        """Registry-tracked pages, sorted (GLOBAL ids on kv_shards
        engines) — the population the page_corrupt chaos knob indexes
        so a seeded trace corrupts the same prefix page whatever the
        pool's allocation history."""
        return sorted({int(p)
                       for pages, _ in self._prefix_registry.values()
                       for p in pages})

    def flip_page_bit(self, page):
        """Flip an EXPONENT bit of ``page``'s first K value (byte 3 of
        a little-endian float32) host-side — the chaos injector's
        corruption primitive. The corruption is semantically loud: an
        undetected flip changes delivered tokens, which is exactly
        what the no-integrity twin must demonstrate; the checksum does
        not care which bit flipped. On kv_shards engines ``page`` is
        the GLOBAL id, which IS the stacked pool row, and the rebuilt
        buffer is re-placed on the mesh so the donated decode step
        keeps its layout."""
        k_pool = np.array(self.cache.k_pool)
        k_pool[int(page)].reshape(-1).view(np.uint8)[3] ^= 0x40
        # jnp.array (NOT asarray): the device buffer must OWN its
        # bytes. On CPU asarray can alias the numpy host copy, and the
        # next decode step donates the cache buffer — XLA would free
        # memory Python owns.
        buf = jnp.array(k_pool)
        if self.kv_shards > 1:
            buf = jax.device_put(buf, self._pt_sharding)
        self.cache = self.cache._replace(k_pool=buf)


def graphlint_entrypoints():
    """Static-analysis registration hook (analysis/registry.py): the
    serving engine's batched decode step — the program the continuous-
    batching scheduler drives per tick — checked for real cache
    donation/aliasing and surgical per-slot writes on the exact jitted
    callable the engine holds."""

    def engine_decode():
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        eng = KernelEngine(slots=2, t_max=16, decode_impl='xla')
        tokens = jnp.zeros((2,), jnp.int32)
        active = jnp.ones((2,), bool)
        poison = jnp.zeros((2,), bool)
        return TraceSpec(
            name='serve.engine_decode', fn=eng._decode,
            args=(eng.cache, tokens, active, poison),
            prejitted=True,
            cache_in=lambda a: [a[0].k, a[0].v],
            cache_out=lambda o: [o[0].k, o[0].v],
            expect_donation=True, min_donated=2)

    def engine_decode_paged():
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        eng = KernelEngine(slots=2, t_max=16, decode_impl='xla',
                           cache_mode='paged', page_size=8, pages=3)
        active = jnp.ones((2,), bool)
        assert eng.prepare_step(np.ones(2, bool)).all()
        tokens = jnp.zeros((2,), jnp.int32)
        poison = jnp.zeros((2,), bool)
        return TraceSpec(
            name='serve.engine_decode_paged', fn=eng._decode,
            args=(eng.cache, tokens, active, poison),
            prejitted=True,
            cache_in=lambda a: [a[0].k_pool, a[0].v_pool],
            cache_out=lambda o: [o[0].k_pool, o[0].v_pool],
            expect_donation=True, min_donated=2)

    def engine_decode_wq8():
        # The int8-WEIGHT serving program: same decode step, weights
        # stored int8 — the s8×s8→s32 projection dots must request
        # their i32 accumulator and the cache contracts must survive
        # the precision change.
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        eng = KernelEngine(slots=2, t_max=16, decode_impl='xla',
                           weight_quant='int8')
        tokens = jnp.zeros((2,), jnp.int32)
        active = jnp.ones((2,), bool)
        poison = jnp.zeros((2,), bool)
        return TraceSpec(
            name='serve.engine_decode_wq8', fn=eng._decode,
            args=(eng.cache, tokens, active, poison),
            prejitted=True,
            cache_in=lambda a: [a[0].k, a[0].v],
            cache_out=lambda o: [o[0].k, o[0].v],
            expect_donation=True, min_donated=2)

    def engine_decode_kv_sharded():
        # The cluster-scale long-context serving program: the SAME
        # engine decode body shard_mapped over the seq mesh with the
        # page table split 2 ways — cache aliasing must survive the
        # shard_map boundary (donation of the stacked sharded pools)
        # and the flash-partials merge must keep its collectives on
        # the declared mesh axis.
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        from distributed_dot_product_tpu.utils.comm import SEQ_AXIS
        eng = KernelEngine(slots=2, t_max=32, decode_impl='xla',
                           cache_mode='paged', page_size=8, pages=3,
                           kv_shards=2)
        assert eng.prepare_step(np.ones(2, bool)).all()
        eng._sync_page_table()
        tokens = jnp.zeros((2,), jnp.int32)
        active = jnp.ones((2,), bool)
        poison = jnp.zeros((2,), bool)
        return TraceSpec(
            name='serve.engine_decode_kv_sharded', fn=eng._decode,
            args=(eng.cache, tokens, active, poison),
            prejitted=True, mesh_axes=(SEQ_AXIS,),
            cache_in=lambda a: [a[0].k_pool, a[0].v_pool],
            cache_out=lambda o: [o[0].k_pool, o[0].v_pool],
            expect_donation=True, min_donated=2)

    return {'serve.engine_decode': engine_decode,
            'serve.engine_decode_paged': engine_decode_paged,
            'serve.engine_decode_wq8': engine_decode_wq8,
            'serve.engine_decode_kv_sharded': engine_decode_kv_sharded}
