# -*- coding: utf-8 -*-
"""
The compiled substrate the scheduler drives: a minimal greedy LM over
the KV-cache decode kernels (``models/decode.py``), batched across
decode SLOTS with per-slot lengths.

Why a dedicated engine instead of :class:`TransformerLM`: continuous
batching needs every batch row on its OWN sequence clock, which is
exactly what the per-slot cache (``init_slot_cache`` /
``append_kv_slots`` / per-slot-masked ``decode_attention``) provides at
the kernel level. The flax stack's decode surface shares one scalar
length across the batch (lockstep generation); threading per-slot
lengths through it is a model-side project — the serving layer's job is
the scheduling around the kernels, so it drives them directly: token
embedding → q/k/v projections → per-slot cache append → per-slot masked
attention → logits. Fixed seeded weights (serving robustness doesn't
need trained weights; determinism does).

Three compiled programs serve the whole lifecycle, shapes fixed at
construction so nothing ever retraces mid-serve:

- ``decode``: one token for EVERY slot (inactive slots masked out of
  the append; their outputs ignored) + per-slot all-finite verdict on
  the logits. The append+attend pair is the FUSED step
  (``models.decode.decode_step``): on the kernel path it is one Pallas
  program with the cache aliased in place, so the donated buffers are
  never copied. The fault injector's NaN mask is applied IN-PROGRAM so
  the quarantine predicate sees real NaNs from the compiled step.
- ``prefill``: one padded prompt chunk into one slot's cache rows (no
  attention — only the last prompt position's logits matter, and the
  scheduler feeds that token through ``decode``).
- ``reset``: zero one slot's rows and length (eviction/quarantine).

Every computation is batch-row independent (embedding lookups, row-wise
matmuls, per-slot masked attention, per-row argmax), so a request's
tokens depend only on its prompt and the seed — NOT on which slot it
lands in or what its neighbors are doing. The scheduler's bit-identity
guarantees (quarantine leaves other slots' streams untouched; a
requeued request regenerates the same tokens) rest on this property,
and the tests pin it.
"""

import os

import numpy as np

import jax
import jax.numpy as jnp

from distributed_dot_product_tpu.models.decode import (
    append_kv_slots, decode_step, init_slot_cache, reset_slot,
    slots_all_finite,
)
from distributed_dot_product_tpu.obs import spans as obs_spans
from distributed_dot_product_tpu.obs.spans import span

__all__ = ['KernelEngine']


def _resolve_decode_impl(decode_impl):
    """Engine decode-path selection: an explicit argument wins; else the
    ``DDP_TPU_DECODE_KERNEL`` env knob (1/kernel → fused Pallas step,
    0/xla → portable step) — the hook ``scripts/smoke_serve.sh`` uses
    to prove the fault cocktail on the kernel path; else 'auto' (kernel
    on TPU, XLA elsewhere — see models/decode.decode_step)."""
    if decode_impl is not None:
        return decode_impl
    env = os.environ.get('DDP_TPU_DECODE_KERNEL', '').strip().lower()
    if env in ('1', 'true', 'kernel'):
        return 'kernel'
    if env in ('0', 'false', 'xla'):
        return 'xla'
    return 'auto'


class KernelEngine:
    """Greedy decode engine over ``slots`` independent sequences.

    ``prefill_chunk`` is the compiled chunk width for prompt ingestion
    (prompts append in ceil(len/chunk) calls — "chunked prefill", so a
    long prompt never monopolizes the loop between decode steps).

    ``decode_impl``: 'kernel' runs the decode step as the fused Pallas
    program (in-place aliased cache append + split-K attention —
    ops/pallas_decode.py; the three compiled programs then stop paying
    any cache round trip), 'xla' the portable append+einsum step, None
    reads ``DDP_TPU_DECODE_KERNEL`` then defaults to auto (kernel on
    TPU). Token streams are deterministic within an impl; the two
    impls agree to float tolerance (exp2 vs exp rounding), so
    bit-identity guarantees hold per-impl, not across.
    """

    def __init__(self, slots, t_max, *, vocab=64, heads=2, head_dim=8,
                 prefill_chunk=8, seed=0, dtype=jnp.float32,
                 decode_impl=None):
        if slots < 1 or t_max < 2:
            raise ValueError(f'need slots >= 1 and t_max >= 2, got '
                             f'{slots}/{t_max}')
        self.decode_impl = _resolve_decode_impl(decode_impl)
        self.slots = slots
        self.t_max = t_max
        self.vocab = vocab
        self.heads = heads
        self.head_dim = head_dim
        self.prefill_chunk = prefill_chunk
        dim = heads * head_dim
        ks = jax.random.split(jax.random.key(seed), 5)
        scale = 1.0 / np.sqrt(dim)
        self._embed = jax.random.normal(ks[0], (vocab, dim), dtype) * scale
        self._wq = jax.random.normal(ks[1], (dim, dim), dtype) * scale
        self._wk = jax.random.normal(ks[2], (dim, dim), dtype) * scale
        self._wv = jax.random.normal(ks[3], (dim, dim), dtype) * scale
        self._wo = jax.random.normal(ks[4], (dim, vocab), dtype) * scale
        self.cache = init_slot_cache(slots, heads, t_max, head_dim,
                                     dtype=dtype)
        # Donated caches: appends write in place — see models/decode.py's
        # performance note. One compiled program each for the lifetime —
        # and the retrace sentinel (analysis/retrace.py) enforces it:
        # shapes are fixed at construction, so more than budget traces
        # of one program means something un-cacheable leaked into the
        # step (the round-5 retrace-storm class). Budget 2: the real
        # trace plus one registry lowering / weak-type respin.
        from distributed_dot_product_tpu.analysis.retrace import (
            watch_traces,
        )
        self._decode = jax.jit(
            watch_traces(self._decode_impl, 'engine.decode', budget=2),
            donate_argnums=(0,))
        self._prefill = jax.jit(
            watch_traces(self._prefill_impl, 'engine.prefill', budget=2),
            donate_argnums=(0,))
        self._reset = jax.jit(
            watch_traces(reset_slot, 'engine.reset', budget=2),
            donate_argnums=(0,))

    # -- compiled bodies ------------------------------------------------
    def _project(self, tokens):
        """tokens (S,) → q, k, v each (S, H, 1, D)."""
        s = tokens.shape[0]
        x = jnp.take(self._embed, tokens, axis=0)          # (S, dim)
        shape = (s, self.heads, 1, self.head_dim)
        return ((x @ self._wq).reshape(shape),
                (x @ self._wk).reshape(shape),
                (x @ self._wv).reshape(shape))

    def _decode_impl(self, cache, tokens, active, poison):
        q, k, v = self._project(tokens)
        # Fused append+attend (one Pallas program on the kernel path —
        # the cache buffers are aliased in place and, with the jit
        # donation above, never copied).
        cache, out = decode_step(q, cache, k, v, slot_mask=active,
                                 impl=self.decode_impl)    # (S, H, 1, D)
        logits = out.reshape(self.slots, -1) @ self._wo    # (S, vocab)
        logits = jnp.where(poison[:, None], jnp.nan, logits)
        finite = slots_all_finite(logits)
        # Fully-masked argmax input for a poisoned row would be NaN-
        # ordered garbage; the scheduler discards non-finite slots'
        # tokens, so the value only needs to be deterministic.
        next_tok = jnp.argmax(
            jnp.where(jnp.isfinite(logits), logits, -jnp.inf),
            axis=-1).astype(jnp.int32)
        return cache, next_tok, finite

    def _prefill_impl(self, cache, slot, tokens, count):
        """Append ``count`` of the ``prefill_chunk`` padded ``tokens``
        into ``slot``'s rows. Projections are computed once and
        broadcast — the masked write only lands on the one slot."""
        x = jnp.take(self._embed, tokens, axis=0)          # (C, dim)
        c = tokens.shape[0]
        k = jnp.moveaxis((x @ self._wk).reshape(
            c, self.heads, self.head_dim), 0, 1)           # (H, C, D)
        v = jnp.moveaxis((x @ self._wv).reshape(
            c, self.heads, self.head_dim), 0, 1)
        k = jnp.broadcast_to(k[None], (self.slots,) + k.shape)
        v = jnp.broadcast_to(v[None], (self.slots,) + v.shape)
        sel = jnp.arange(self.slots) == slot
        counts = jnp.where(sel, count, 0).astype(jnp.int32)
        return append_kv_slots(cache, k, v, slot_mask=sel, counts=counts)

    # -- host surface (numpy in, numpy out) -----------------------------
    def step(self, tokens, active, poison=None, request_ids=None):
        """One decode step for all slots. ``tokens (S,) int`` — each
        ACTIVE slot's input token (its previous output, or the last
        prompt token right after prefill); inactive entries ignored.
        Returns ``(next_tokens (S,), finite (S,))`` numpy arrays.

        ``request_ids`` (optional, per-slot) is observability-only: it
        labels the host-side span so a profiler/span tree ties a decode
        dispatch back to the requests it served — it never reaches the
        compiled program (strings can't; the program is id-oblivious by
        design)."""
        poison = (np.zeros(self.slots, bool) if poison is None
                  else np.asarray(poison, bool))
        # Span attrs are built ONLY when spans are on: this is the
        # per-token hot path, and the disabled default must not pay a
        # per-step tuple build for labels nobody will read.
        ids = (tuple(r for r in (request_ids or ()) if r)
               if obs_spans.enabled() else ())
        with span('engine.decode_step', requests=ids):
            self.cache, tok, finite = self._decode(
                self.cache, jnp.asarray(tokens, jnp.int32),
                jnp.asarray(active, bool), jnp.asarray(poison))
            return np.asarray(tok), np.asarray(finite)

    def prefill(self, slot, tokens, request_id=None):
        """Append one prompt chunk (``len(tokens) <= prefill_chunk``)
        into ``slot``. Pads to the compiled chunk width; padded rows
        never land (counts mask). ``request_id`` labels the span only
        (see :meth:`step`)."""
        n = len(tokens)
        if n > self.prefill_chunk:
            raise ValueError(f'chunk of {n} exceeds prefill_chunk='
                             f'{self.prefill_chunk}')
        buf = np.zeros(self.prefill_chunk, np.int32)
        buf[:n] = np.asarray(tokens, np.int32)
        with span('engine.prefill', slot=int(slot),
                  request=request_id or ''):
            self.cache = self._prefill(self.cache, jnp.int32(slot),
                                       jnp.asarray(buf), jnp.int32(n))

    def reset(self, slot):
        """Evict ``slot`` (zero rows + length); other slots untouched."""
        self.cache = self._reset(self.cache, jnp.int32(slot))

    def lengths(self):
        return np.asarray(self.cache.length)


def graphlint_entrypoints():
    """Static-analysis registration hook (analysis/registry.py): the
    serving engine's batched decode step — the program the continuous-
    batching scheduler drives per tick — checked for real cache
    donation/aliasing and surgical per-slot writes on the exact jitted
    callable the engine holds."""

    def engine_decode():
        from distributed_dot_product_tpu.analysis.registry import (
            TraceSpec,
        )
        eng = KernelEngine(slots=2, t_max=16, decode_impl='xla')
        tokens = jnp.zeros((2,), jnp.int32)
        active = jnp.ones((2,), bool)
        poison = jnp.zeros((2,), bool)
        return TraceSpec(
            name='serve.engine_decode', fn=eng._decode,
            args=(eng.cache, tokens, active, poison),
            prejitted=True,
            cache_in=lambda a: [a[0].k, a[0].v],
            cache_out=lambda o: [o[0].k, o[0].v],
            expect_donation=True, min_donated=2)

    return {'serve.engine_decode': engine_decode}
