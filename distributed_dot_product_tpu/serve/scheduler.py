# -*- coding: utf-8 -*-
"""
Continuous-batching decode scheduler — the serving loop that keeps the
compiled decode step full and survives the traffic that tries to kill
it.

Design (the standard continuous-batching shape, scaled to this repo's
kernels): the engine owns ``S`` fixed decode slots over ONE donated
per-slot KV cache (``models/decode.py``: ``init_slot_cache`` /
``append_kv_slots`` / per-slot-masked ``decode_attention``). Every tick:

1. **Admit**: free slots pull from the bounded admission queue
   (``admission.py`` — typed rejection, deadlines, token budgets,
   degradation). Requests that expired while queued are finalized with
   a typed reason, never silently dropped.
2. **Chunked prefill**: each prefilling slot appends ONE prompt chunk
   (``engine.prefill_chunk`` wide) between decode steps, so a long
   prompt interleaves with live decoding instead of stalling it. The
   prompt's last token then enters the decode step like any other
   input token — same compiled program end to end.
3. **Decode**: one batched step for ALL active slots. The per-slot
   all-finite verdict comes back with the tokens; a non-finite slot is
   **quarantined** (slot reset + request requeued from scratch, bounded
   by ``max_requeues``) while every other slot's stream continues
   bit-identically — one poisoned sequence must not fail the batch.
4. **Retire**: completed / expired / abandoned sequences free their
   slot (``reset_slot`` — zero rows, no reallocation).

Failure-handling ladder at submit, in order: admit → admit degraded
(token budget capped under queue pressure) → evict the longest-idle
running sequence and admit → reject with typed ``QUEUE_FULL``.

Paged engines (``cache_mode='paged'``) plug PAGE EXHAUSTION into the
same ladder: pool pressure degrades budgets like queue pressure,
admission reserves a request's prompt pages up front (head-of-line
waits when the pool is full), a mid-stream page deficit first evicts
the longest-idle OTHER slot and then preempts/requeues the needy one
(typed ``CACHE_EXHAUSTED`` once retries are spent), and requests can
ride registered shared prefixes (``submit(prefix_id=...)``) or fork
mid-stream (:meth:`Scheduler.fork`). Occupancy gauges
(``serve.cache.pages_used/pages_free/shared_pages``) refresh per tick.

Liveness is judged OUTSIDE the loop: the scheduler heartbeats the
:class:`~distributed_dot_product_tpu.serve.health.HealthMonitor` every
tick and a watchdog thread flags a stuck compiled step (no heartbeat)
as STALLED/NOT_READY; the first post-stall tick restores READY.

Fault injection (``utils/faults.py`` ``ServeFaultInjector``, or the
``DDP_TPU_FAULT_STUCK_STEP`` / ``..._NAN_DECODE_STEP`` /
``..._ABANDON_REQUEST`` env knobs when none is passed) drives every one
of these paths deterministically in CPU tests.
"""

import dataclasses
import enum
import os
import time
from typing import Callable, Dict, Optional

import numpy as np

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.obs import flight as obs_flight
from distributed_dot_product_tpu.obs import spans as obs_spans
from distributed_dot_product_tpu.obs.devmon import CaptureInFlight
from distributed_dot_product_tpu.obs.spans import span
from distributed_dot_product_tpu.serve.admission import (
    AdmissionController, RejectedError, RejectReason, Request,
    RequestResult,
)
from distributed_dot_product_tpu.serve.engine import PageCorruptionError
from distributed_dot_product_tpu.serve.errors import ServeContractError
from distributed_dot_product_tpu.serve.health import (
    HealthMonitor, Liveness, Readiness,
)
from distributed_dot_product_tpu.utils import faults as faults_lib
from distributed_dot_product_tpu.utils import tracing

__all__ = ['ServeConfig', 'Scheduler']

# determlint (analysis/determlint.py): everything reachable from the
# tick and the submit path must derive time from the injected clock —
# the seeded bit-reproducible-replay contract. The two deliberate
# real-time reads (the step-duration histogram, the profile cooldown)
# are declared in determlint.REAL_TIME_CONTRACT with their reasons.
GRAPHLINT_TICK_ROOTS = ('Scheduler.step', 'Scheduler.submit')


@dataclasses.dataclass
class ServeConfig:
    """Knobs of the serving loop. ``queue_limit``/``max_new_tokens``/
    ``degrade_watermark``/``degraded_max_new_tokens`` parameterize
    admission (see admission.py). ``evict_before_reject``: try freeing
    the longest-idle slot (idle ≥ ``min_evict_idle`` seconds) before
    shedding a submit with QUEUE_FULL. ``max_requeues`` bounds
    NaN-quarantine retries per request. ``stall_timeout`` is the
    watchdog's no-heartbeat threshold (``watchdog=False`` disables the
    thread — e.g. under a virtual clock that would never beat in real
    time)."""
    queue_limit: int = 8
    max_new_tokens: int = 16
    degrade_watermark: float = 0.75
    degraded_max_new_tokens: Optional[int] = None
    evict_before_reject: bool = True
    min_evict_idle: float = 0.0
    max_requeues: int = 2
    eos_id: Optional[int] = None
    stall_timeout: float = 2.0
    watchdog: bool = True
    watchdog_poll: Optional[float] = None
    # Adaptive profiling (needs a `profiler` on the Scheduler):
    # when the serve.ttft reservoir p99 exceeds `profile_ttft_p99`
    # seconds, capture ONE bounded jax.profiler trace of
    # `profile_seconds`, then hold off `profile_cooldown` REAL seconds
    # — the profile of a latency regression is taken while it happens,
    # never two at once, never a capture storm.
    profile_ttft_p99: Optional[float] = None
    profile_seconds: float = 2.0
    profile_cooldown: float = 60.0
    # Speculative decoding (serve/spec.py): 'ngram' (self-drafting
    # suffix lookup — no extra model) or 'draft' (a small draft-model
    # twin with its own cache + rollback); None consults the
    # DDP_TPU_SPEC env knob, 'off' disables even when the knob is set.
    # Greedy verification keeps the committed stream token-for-token
    # IDENTICAL to the non-spec stream (per decode impl) — a proposer
    # is an untrusted accelerator, never a correctness input.
    # `spec_k`: most proposals per slot per verify step (verify width
    # k+1 — ONE compiled program per k). `spec_max_ngram`: longest
    # suffix the ngram proposer matches on.
    spec: Optional[str] = None
    spec_k: int = 4
    spec_max_ngram: int = 3
    # Incident flight recorder (obs/flight.py — resolved process-wide
    # at trigger time, like the active event log): auto-dump a
    # post-mortem bundle on a watchdog stall, on an unhandled
    # scheduler-loop exception, and on a NaN-quarantine storm
    # (`flight_nan_storm` quarantines within `flight_nan_window`
    # decode steps). All no-ops while no recorder is installed.
    flight_dump_on_stall: bool = True
    flight_dump_on_exception: bool = True
    flight_nan_storm: int = 3
    flight_nan_window: int = 20
    # Anomaly watchdog (obs/anomaly.py): True arms the stock catalog
    # (TTFT p99, tokens/s, queue depth, pages_free, reject rate) over
    # this scheduler's registry, evaluated from the tick (throttled in
    # REAL time). Pass a built AnomalyWatchdog for custom watches.
    anomaly: bool = False
    # Pay the profiler's one-time native init (~14 s first
    # `start_trace` on this container — PR 6's measurement) at
    # SCHEDULER CONSTRUCTION instead of inside the first
    # anomaly/adaptive capture, which would otherwise spend its whole
    # bounded window on init and record nothing of the regression.
    profile_warmup: bool = False
    # Scheduling policy (serve/policy.py): a PolicyConfig arms
    # priority classes + per-tenant fair-share admission,
    # deadline-aware eviction, and TTFT-tuned prefill interleaving.
    # None keeps the mechanical FIFO / longest-idle behavior.
    policy: Optional[object] = None


class _SlotState(enum.Enum):
    FREE = 'free'
    PREFILL = 'prefill'
    ACTIVE = 'active'


@dataclasses.dataclass
class _Slot:
    index: int
    state: _SlotState = _SlotState.FREE
    request: Optional[Request] = None
    prefill_pos: int = 0
    input_token: int = 0
    produced: int = 0
    last_progress: float = 0.0
    last_token_at: Optional[float] = None   # per-token latency anchor


class Scheduler:
    """Drive ``engine`` (a :class:`~distributed_dot_product_tpu.serve
    .engine.KernelEngine` or anything with its surface) under the
    policy in ``config``.

    Usage::

        sched = Scheduler(KernelEngine(slots=4, t_max=256), ServeConfig())
        try:
            req = sched.submit(prompt, max_new_tokens=32,
                               deadline=clock() + 1.0)
        except RejectedError as e:
            ...                       # e.reason is typed
        sched.run_until_idle()
        sched.results[req.id]         # RequestResult
        sched.close()

    ``clock`` is the deadline/idleness clock (injectable — tests run
    virtual time); the watchdog always measures real time.
    ``on_tick(scheduler)`` runs at the end of every tick (tests advance
    their virtual clock there)."""

    def __init__(self, engine, config: Optional[ServeConfig] = None, *,
                 fault_injector=None, clock=time.monotonic,
                 registry: Optional[tracing.MetricsRegistry] = None,
                 health: Optional[HealthMonitor] = None,
                 on_tick: Optional[Callable] = None, event_log=None,
                 profiler=None, proposer=None, anomaly=None):
        self.engine = engine
        # Paged engines gate admission by FREE PAGES, not free slots,
        # and join page exhaustion into the degrade→evict→reject
        # ladder (plus the mid-stream preemption rung in _ensure_pages).
        self._paged = getattr(engine, 'cache_mode', 'slab') == 'paged'
        # Optional obs.devmon.ProfileCapture for the adaptive
        # ttft-p99 trigger (cfg.profile_ttft_p99 arms it).
        self.profiler = profiler
        self._last_capture_at: Optional[float] = None
        self._ttft_dirty = False
        self.cfg = config or ServeConfig()
        self.clock = clock
        self.on_tick = on_tick
        self.registry = registry or tracing.get_registry()
        # Observability event sink: an explicit EventLog, or (when None)
        # whatever log is ACTIVE at emit time (obs/events.py) — so
        # `with obs.activate(log):` instruments an existing scheduler.
        self.event_log = event_log
        self.admission = AdmissionController(
            queue_limit=self.cfg.queue_limit, t_max=engine.t_max,
            max_new_tokens=self.cfg.max_new_tokens,
            degrade_watermark=self.cfg.degrade_watermark,
            degraded_max_new_tokens=self.cfg.degraded_max_new_tokens,
            clock=clock, registry=self.registry, event_log=event_log,
            capacity_tokens=(engine.capacity_tokens if self._paged
                             else None))
        # None = "consult the env knobs" (a shell faults a real run);
        # False = explicitly unfaulted even when knobs are set (the
        # clean reference run a fault-isolation audit compares against).
        if fault_injector is None:
            plan = faults_lib.serve_plan_from_env()
            fault_injector = (faults_lib.ServeFaultInjector(plan)
                              if plan.any() else None)
        self.injector = fault_injector or None
        if self.injector is not None and event_log is not None \
                and getattr(self.injector, 'event_log', None) is None:
            # Injections land in the same stream as the lifecycle they
            # disrupt (the injector alone can't know the sink).
            self.injector.event_log = event_log
        self.health = health or HealthMonitor(
            stall_timeout=self.cfg.stall_timeout,
            poll_interval=self.cfg.watchdog_poll, registry=self.registry,
            event_log=event_log)
        # Incident wiring: the watchdog's dangling on_stall hook now
        # drives the flight recorder — a stall's post-mortem bundle is
        # written WHILE the loop is wedged (the watchdog thread runs
        # free), capturing the stuck thread's stack. Never stomps a
        # caller-installed callback (mirror of the injector.event_log
        # rule).
        if self.cfg.flight_dump_on_stall and self.health.on_stall is None:
            self.health.on_stall = self._on_stall
        if self.cfg.watchdog:
            self.health.start()
        self._slots = [_Slot(i) for i in range(engine.slots)]
        self.results: Dict[str, RequestResult] = {}
        self._step_idx = 0
        self._admit_counter = 0
        self._closed = False
        reg = self.registry
        self._c = {name: reg.counter(f'serve.{name}') for name in
                   ('completed', 'evicted', 'nan_quarantined', 'requeued',
                    'abandoned', 'deadline_expired', 'failed',
                    'decode_steps', 'tokens_generated')}
        self._g_active = reg.gauge('serve.active_slots')
        if self._paged:
            # Cache-occupancy surface (tick-refreshed, /metrics-
            # rendered): pool fill, free headroom, and the sharing win
            # (pages referenced more than once). The histogram records
            # pages held per request at retirement.
            self._c_preempted = reg.counter('serve.cache_preempted')
            self._g_pages_used = reg.gauge('serve.cache.pages_used')
            self._g_pages_free = reg.gauge('serve.cache.pages_free')
            self._g_shared = reg.gauge('serve.cache.shared_pages')
            self._h_req_pages = reg.histogram(
                'serve.cache.request_pages', buckets=())
        self._c_profile = reg.counter('serve.profile_triggers')
        self._h_step = reg.histogram('serve.step_seconds')
        # Dispatch-floor split (ROADMAP item 5): per decode tick, REAL
        # tick wall time partitions into device-program seconds (the
        # engine.program_seconds delta across the tick) and host-loop
        # overhead — the ~0.212 ms/step floor multi-tick decode would
        # attack. Mirrored per tick into serve.dispatch events so the
        # split survives in the JSONL (obs critpath reads it back).
        self._h_device = reg.histogram('serve.device_seconds')
        self._h_dispatch = reg.histogram(
            'serve.dispatch_overhead_seconds')
        # Device seconds of the CURRENT tick's decode/verify dispatch,
        # set right after the program returns and cleared at tick end —
        # _commit_token stamps it on every serve.decode it emits (the
        # per-token device share, additive field).
        self._tick_device = None
        # Speculative decoding: an explicit `proposer` object wins,
        # else cfg.spec names a built-in, else the DDP_TPU_SPEC env
        # knob (the smoke/CI hook). A spec tick runs the fused
        # verify-k program; ticks with no proposals ride the plain
        # n=1 program — mixed batches share one verify dispatch with
        # per-slot counts.
        # Scheduling policy (serve/policy.py): fair-share/priority
        # admission, deadline-aware eviction, TTFT-tuned prefill
        # interleave. Built once; every decision recomputes from live
        # state, so controller knob changes need no resync.
        if self.cfg.policy is not None:
            from distributed_dot_product_tpu.serve.policy import (
                SchedulingPolicy,
            )
            self._policy = SchedulingPolicy(self.cfg.policy)
        else:
            self._policy = None
        self._proposer = (proposer if proposer is not None
                          else self._resolve_proposer())
        if self._proposer is not None:
            # Token-count histograms (time buckets make no sense):
            # proposed vs accepted per verify step — the amortization
            # the whole scheme is judged by (committed tokens/step =
            # accepted mean + 1).
            self._h_spec_prop = reg.histogram(
                'serve.spec.proposed_per_step', buckets=())
            self._h_spec_acc = reg.histogram(
                'serve.spec.accepted_per_step', buckets=())
        # Request-timeline histograms: the latency decomposition a
        # continuous-batching server is judged by. All measured on the
        # scheduler's own clock and ALSO stamped into the event log, so
        # `obs.timeline(request_id)` reconstructs the same numbers.
        self._h_queue = reg.histogram('serve.queue_wait_seconds')
        self._h_ttft = reg.histogram('serve.ttft_seconds')
        self._h_token = reg.histogram('serve.token_seconds')
        self._h_request = reg.histogram('serve.request_seconds')
        # Tenant-labeled twins of the latency histograms, created
        # lazily per tenant seen and cached here (registry get-or-
        # create takes a lock — not a per-token cost we want).
        self._tenant_series: Dict[tuple, object] = {}
        # NaN-quarantine storm window: decode-step indices of recent
        # quarantines — `flight_nan_storm` of them within
        # `flight_nan_window` steps triggers one post-mortem dump.
        self._quarantine_steps = []
        # Anomaly watchdog: an explicit one wins; cfg.anomaly=True
        # builds the stock catalog over THIS scheduler's registry.
        if anomaly is not None:
            self._anomaly = anomaly
        elif self.cfg.anomaly:
            from distributed_dot_product_tpu.obs.anomaly import (
                AnomalyWatchdog, default_watches,
            )
            self._anomaly = AnomalyWatchdog(
                self.registry,
                default_watches(queue_limit=self.cfg.queue_limit,
                                paged=self._paged),
                profiler=self.profiler, event_log=event_log)
        else:
            self._anomaly = None
        if self.profiler is not None and self.cfg.profile_warmup:
            self.profiler.warmup()
        # Every post-mortem bundle (including an HTTP /dump with no
        # scheduler in hand) embeds this scheduler's introspection.
        # ONE bound-method object, captured here: attribute access
        # mints a fresh one each time, which would break the
        # ownership check in remove_provider at close() (the same
        # identity rule FaultInjector._hook documents).
        self._introspection_hook = self.introspection
        obs_flight.add_provider('scheduler', self._introspection_hook)

    def _tenant_hist(self, name, tenant):
        """The ``tenant=``-labeled series of a latency family — same
        family name as the aggregate, so /metrics renders per-tenant
        quantiles/buckets an external Prometheus can alert on."""
        key = (name, tenant)
        h = self._tenant_series.get(key)
        if h is None:
            h = self._tenant_series[key] = self.registry.histogram(
                name, labels={'tenant': tenant})
        return h

    def _resolve_proposer(self):
        """Build the configured proposer: cfg.spec wins, else the
        DDP_TPU_SPEC env knob; 'off'/'none' explicitly disables."""
        name = self.cfg.spec
        if name is None:
            name = os.environ.get('DDP_TPU_SPEC', '').strip().lower() \
                or None
        if name in (None, '', 'off', 'none', '0'):
            return None
        from distributed_dot_product_tpu.serve.spec import (
            DraftEngineProposer, NgramProposer, make_draft_engine,
        )
        if name == 'ngram':
            return NgramProposer(max_ngram=self.cfg.spec_max_ngram)
        if name == 'draft':
            return DraftEngineProposer(make_draft_engine(self.engine))
        raise ValueError(f"spec must be 'ngram', 'draft' or 'off', "
                         f'got {name!r}')

    def _spec_start(self, slot: _Slot):
        """A request began (or resumed) decoding in ``slot``: hand the
        proposer the full committed history (prompt + any tokens a
        fork inherited)."""
        if self._proposer is not None:
            self._proposer.start(slot.index,
                                 list(slot.request.prompt)
                                 + slot.request.tokens)

    def _emit(self, event, **fields):
        """Into the explicit event log, else the active one, else
        nowhere (one None-check when observability is off)."""
        log = (self.event_log if self.event_log is not None
               else obs_events.get_active())
        if log is not None:
            log.emit(event, **fields)

    # -- incident flight recorder (obs/flight.py) ----------------------
    def introspection(self):
        """Point-in-time scheduler state for a post-mortem bundle:
        the slot table, queue depth, step index, engine cache stats.
        Read WITHOUT locks — this runs from the watchdog thread while
        the loop may be wedged mid-step, and a slightly torn view of
        host bookkeeping beats a dump that deadlocks."""
        slots = []
        for slot in self._slots:
            req = slot.request
            slots.append({
                'index': slot.index, 'state': slot.state.value,
                'request_id': req.id if req is not None else None,
                'tenant': req.tenant if req is not None else None,
                'produced': slot.produced,
                'prefill_pos': slot.prefill_pos,
                'requeues': req.requeues if req is not None else None,
                'last_progress': slot.last_progress,
            })
        out = {
            'step_idx': self._step_idx,
            'queue_depth': self.admission.depth,
            'queue_limit': self.cfg.queue_limit,
            'slots': slots,
            'results': len(self.results),
            'liveness': self.health.liveness.value,
            'readiness': self.health.readiness.value,
            'last_beat_age_s': self.health.last_beat_age(),
            'proposer': (type(self._proposer).__name__
                         if self._proposer is not None else None),
            'cache_mode': getattr(self.engine, 'cache_mode', 'slab'),
        }
        try:
            out['cache_stats'] = self.engine.cache_stats()
        except (AttributeError, TypeError):
            # An engine without the introspection surface is fine.
            out['cache_stats'] = None
        return out

    def _flight_dump(self, trigger, reason=''):
        """One rate-limited post-mortem bundle through the process
        flight recorder (no-op while none is installed — checked
        BEFORE building the introspection section, so the disabled
        path never materializes it). Never raises: the black box must
        not take down the loop it is recording."""
        rec = obs_flight.get_recorder()
        if rec is None:
            return None
        try:
            return rec.maybe_dump(
                trigger=trigger, reason=reason,
                sections={'scheduler': self.introspection()})
        except Exception as e:
            tracing.log_exception('scheduler.flight_dump', e,
                                  registry=self.registry)
            return None

    def _on_stall(self):
        """Watchdog-thread stall callback: dump the black box WHILE
        the loop is stuck (the bundle's stacks.json shows where)."""
        age = self.health.last_beat_age()
        self._flight_dump(
            'stall',
            reason=f'no heartbeat for '
                   f'{age:.2f}s (timeout {self.cfg.stall_timeout:.2f}s)'
                   if age is not None else 'watchdog stall')

    # -- submission surface --------------------------------------------
    def submit(self, prompt, *, max_new_tokens=None, deadline=None,
               request_id=None, prefix_id=None, tenant=None) -> Request:
        """Admit one request or raise a typed
        :class:`~distributed_dot_product_tpu.serve.admission
        .RejectedError`. Applies the full backpressure ladder (degrade →
        evict → reject). ``prefix_id`` (paged engines): a registered
        shared prefix the prompt CONTINUES — its pages are shared, the
        budget math covers prefix + prompt. ``tenant`` labels the
        request for multi-tenant accounting (admit/reject events,
        tenant-labeled metrics; default tenant ``'default'``)."""
        if prefix_id is not None and not self._paged:
            raise ServeContractError(
                "prefix_id needs a paged engine (cache_mode='paged')")
        req = Request(prompt=prompt,
                      max_new_tokens=max_new_tokens
                      or self.cfg.max_new_tokens,
                      deadline=deadline, id=request_id or '',
                      prefix_id=prefix_id, tenant=tenant or 'default')
        req.submitted_at = self.clock()
        try:
            if prefix_id is not None:
                try:
                    req.prefix_len = self.engine.prefix_length(
                        prefix_id)
                except KeyError:
                    self.admission.reject(
                        RejectReason.PREFIX_UNREGISTERED,
                        f'request {req.id}: prefix id {prefix_id!r} '
                        f'is not registered', request_id=req.id,
                        tenant=req.tenant)
            self.admission.validate(req)
            pressure, source = self._pressure_info()
            self.admission.maybe_degrade(req, pressure=pressure,
                                         reason=source)
            if self.admission.full and self.cfg.evict_before_reject:
                # Freeing a slot lets a queued request promote out of
                # the queue, which is what makes room for this one.
                if self._evict_longest_idle():
                    self._admit_into_free_slots()
            self.admission.push(req)
        finally:
            self._update_readiness()
        return req

    def cancel(self, request_id):
        """Mid-stream client abandon: the request's slot frees at the
        next tick (queued requests resolve when they reach the head).
        Returns False for an unknown/already-finished id."""
        for slot in self._slots:
            if slot.request is not None \
                    and slot.request.id == request_id:
                slot.request.cancelled = True
                return True
        for req in list(self.admission._queue):
            if req.id == request_id:
                req.cancelled = True
                return True
        return False

    # -- scheduling internals ------------------------------------------
    def _finalize_request(self, req: Request, status,
                          reason: Optional[RejectReason] = None):
        finished_at = self.clock()
        total = max(0.0, finished_at - req.submitted_at)
        self._h_request.observe(total)
        if status == 'rejected':
            # Shed while queued: the timeline ends in a typed reject,
            # never a retire (it never held a slot).
            self._emit('serve.reject', request_id=req.id,
                       reason=reason.value if reason else None,
                       queued=True, total_seconds=total,
                       tenant=req.tenant)
        else:
            self._emit('serve.retire', request_id=req.id, status=status,
                       reason=reason.value if reason else None,
                       tokens=len(req.tokens), total_seconds=total,
                       tenant=req.tenant)
        self.results[req.id] = RequestResult(
            id=req.id, status=status, tokens=list(req.tokens),
            prompt_len=len(req.prompt), reason=reason,
            requeues=req.requeues, degraded=req.degraded,
            finished_at=finished_at, tenant=req.tenant)

    def _observe_slot_pages(self, slot: _Slot):
        if self._paged:
            self._h_req_pages.observe(self.engine.slot_pages(slot.index))

    def _finish(self, slot: _Slot, status,
                reason: Optional[RejectReason] = None):
        """Retire a slot's request with a terminal status and free the
        slot (rows zeroed — the next sequence starts clean)."""
        if status == 'evicted':
            self._emit('serve.evict', request_id=slot.request.id,
                       slot=slot.index)
        self._observe_slot_pages(slot)       # pages held AT retirement
        self._finalize_request(slot.request, status, reason)
        if status in self._c:
            self._c[status].inc()
        self._clear_slot(slot)

    def _clear_slot(self, slot: _Slot):
        """Free a slot without finalizing its request (quarantine and
        preempt share this arc; _finish owns the terminal one). No
        page observation here: serve.cache.request_pages records
        occupancy at RETIREMENT only — a requeued request's mid-flight
        partial fills would skew the distribution low."""
        self.engine.reset(slot.index)
        if self._proposer is not None:
            self._proposer.reset(slot.index)
        slot.state = _SlotState.FREE
        slot.request = None
        slot.produced = 0
        slot.prefill_pos = 0

    def _requeue(self, req: Request):
        """Retry an already-admitted request from scratch: the greedy
        stream is deterministic, so the retry regenerates exactly what
        the fault/preemption dropped. Its first token is a fresh TTFT
        observation, not a token gap."""
        req.requeues += 1
        req.tokens = []
        req.first_token_at = None
        self._c['requeued'].inc()
        self.admission.push_front(req)

    def _quarantine(self, slot: _Slot):
        """Non-finite logits in ONE slot: reset it and retry the request
        from scratch — or fail it with a typed status once
        ``max_requeues`` is exhausted. Other slots are untouched by
        construction (per-slot cache + row-independent engine), which
        the tests pin bit-exactly."""
        req = slot.request
        self._c['nan_quarantined'].inc()
        self._clear_slot(slot)
        requeued = req.requeues < self.cfg.max_requeues
        self._emit('serve.quarantine', request_id=req.id,
                   slot=slot.index, requeued=requeued)
        if requeued:
            self._requeue(req)
        else:
            self._c['failed'].inc()
            self._finalize_request(req, 'failed_nan')
        # Quarantine-storm trigger: one transient NaN is routine; a
        # cluster of them inside a short step window is an incident —
        # dump the black box while the poisoned state is still live.
        self._quarantine_steps.append(self._step_idx)
        window = [s for s in self._quarantine_steps
                  if s > self._step_idx - self.cfg.flight_nan_window]
        self._quarantine_steps = window
        if len(window) >= self.cfg.flight_nan_storm:
            self._flight_dump(
                'nan_storm',
                reason=f'{len(window)} quarantines within the last '
                       f'{self.cfg.flight_nan_window} decode steps')

    def _ensure_pages(self):
        """Page-deficit ladder, run before every decode tick: make each
        active slot's append page writable (``engine.prepare_step`` —
        allocation on page crossings, copy-on-write on shared pages).
        On pool exhaustion: evict the longest-idle OTHER busy slot to
        free pages and retry; when no other slot can yield, PREEMPT the
        needy slot itself — requeued from scratch like a quarantine
        (bounded by ``max_requeues``), then terminally evicted with the
        typed CACHE_EXHAUSTED reason. Each rung frees at least one
        slot, so the loop terminates."""
        while True:
            active = np.array([s.state is _SlotState.ACTIVE
                               for s in self._slots])
            if not active.any():
                return
            ok = self.engine.prepare_step(active)
            deficit = [s for s in self._slots
                       if active[s.index] and not ok[s.index]]
            if not deficit:
                return
            exclude = {s.index for s in deficit}
            if self.cfg.evict_before_reject \
                    and self._evict_longest_idle(exclude=exclude):
                continue
            self._preempt(deficit[0])

    def _preempt(self, slot: _Slot):
        """Page exhaustion landed on THIS slot: free it and retry the
        request from scratch, or evict it with the typed
        CACHE_EXHAUSTED reason once ``max_requeues`` is spent."""
        req = slot.request
        self._c_preempted.inc()
        requeued = req.requeues < self.cfg.max_requeues
        self._emit('serve.preempt', request_id=req.id, slot=slot.index,
                   requeued=requeued)
        if requeued:
            self._clear_slot(slot)
            self._requeue(req)
        else:
            self._finish(slot, 'evicted', RejectReason.CACHE_EXHAUSTED)

    def fork(self, request_id, *, request_id_new=None,
             max_new_tokens=None) -> Request:
        """Fork an actively decoding request into a free slot (parallel
        sampling): the branch shares the source's full pages read-only
        and copies only the partial tail page (engine.fork_slot), then
        continues decoding independently with its own budget. Raises a
        typed :class:`RejectedError` — QUEUE_FULL without a free slot,
        CACHE_EXHAUSTED without a free page."""
        if not self._paged:
            raise ValueError("fork needs a paged engine "
                             "(cache_mode='paged')")
        src = next((s for s in self._slots if s.request is not None
                    and s.request.id == request_id), None)
        if src is None or src.state is not _SlotState.ACTIVE:
            raise ValueError(f'fork needs an actively decoding request;'
                             f' {request_id!r} is not one')
        free = next((s for s in self._slots
                     if s.state is _SlotState.FREE), None)
        if free is None:
            raise RejectedError(
                RejectReason.QUEUE_FULL,
                f'no free slot to fork {request_id} into')
        if not self.engine.fork_slot(src.index, free.index):
            raise RejectedError(
                RejectReason.CACHE_EXHAUSTED,
                f'page pool exhausted forking {request_id}')
        now = self.clock()
        orig = src.request
        req = Request(prompt=orig.prompt,
                      max_new_tokens=max_new_tokens
                      or orig.max_new_tokens,
                      deadline=orig.deadline, id=request_id_new or '',
                      prefix_id=orig.prefix_id,
                      prefix_len=orig.prefix_len, tenant=orig.tenant)
        # Same budget policy admission applies at submit — one clamp,
        # shared, so the two entry points can never drift.
        self.admission.clamp_budget(req)
        self.admission.count_admit(tenant=req.tenant)
        req.submitted_at = now
        req.queued_since = now
        req.admitted_at = now
        req.tokens = list(orig.tokens)
        # The branch inherits the stream mid-flight: its next token is
        # a continuation, not a first token — no fresh TTFT.
        req.first_token_at = orig.first_token_at
        req.admit_index = self._admit_counter
        self._admit_counter += 1
        free.request = req
        free.state = _SlotState.ACTIVE
        free.produced = src.produced
        free.input_token = src.input_token
        free.prefill_pos = src.prefill_pos
        free.last_progress = now
        free.last_token_at = src.last_token_at
        self._spec_start(free)
        self._emit('serve.admit', request_id=req.id, slot=free.index,
                   queue_wait=0.0, prompt_len=len(req.prompt),
                   requeues=0, fork_of=orig.id, tenant=req.tenant)
        return req

    def _evict_longest_idle(self, exclude=()):
        """Rung two of the ladder: evict the busy slot that has gone
        longest without progress (ties → oldest admission), if it has
        been idle at least ``min_evict_idle``. The evicted request
        terminates with status ``'evicted'`` and its partial tokens.
        ``exclude``: slot indices never chosen (the page-deficit ladder
        evicts OTHERS to free pages before preempting the needy one).

        With a policy armed (serve/policy.py), a DOOMED slot — one
        whose request is predicted to miss its deadline anyway, from
        the remaining budget and the live inter-token-gap percentile —
        is preferred over the longest-idle one: the evicted stream was
        already lost, the survivor may still retire in-SLO."""
        now = self.clock()
        busy = [s for s in self._slots if s.state is not _SlotState.FREE
                and s.index not in exclude]
        if not busy:
            return False
        victim = None
        if self._policy is not None:
            victim = self._policy.eviction_victim(
                [(s, s.request, s.produced) for s in busy], now,
                self._gap_estimate())
        if victim is None:
            victim = max(busy,
                         key=lambda s: (now - s.last_progress,
                                        -(s.request.admit_index or 0)))
            if now - victim.last_progress < self.cfg.min_evict_idle:
                return False
        self._finish(victim, 'evicted')
        return True

    def _gap_estimate(self):
        """The live inter-token pace (policy's finish predictor): the
        configured percentile of ``serve.token_seconds``, NaN until
        the first gap lands (the policy then refuses to call anyone
        doomed — no pace signal, no guess)."""
        return self._h_token.percentile(
            self._policy.cfg.gap_percentile)

    def _record_dropped(self, dropped):
        for req in dropped:
            if req.cancelled:
                self._c['abandoned'].inc()
                self._finalize_request(req, 'abandoned')
            else:
                # Counted by the admission controller already.
                self._finalize_request(req, 'rejected',
                                       RejectReason.DEADLINE_EXCEEDED)

    def _place_paged(self, slot: _Slot, req: Request):
        """Paged admission: attach the shared prefix (refcount++, tail
        copy) and RESERVE every page the prompt's prefill plus first
        decode append need (``len(prompt)`` rows past the prefix:
        ``len−1`` prefill appends + the first decode append) — chunked
        prefill can then never fail mid-prompt. Returns ``'ok'``,
        ``'wait'`` (pool exhausted — head-of-line waits, slot left
        clean) or ``'rejected'`` (the prefix vanished while queued, or
        the request can NEVER be placed — finalized with the typed
        reason)."""
        eng = self.engine
        # Cheap headroom check BEFORE any device work: a head-of-line
        # wait must not re-do an attach tail copy plus a page zeroing
        # every tick while the pool refills. Exact page count: the
        # attach's private tail copy (one page when the prefix ends
        # mid-page) plus the fresh pages the prompt reserve opens past
        # the prefix's coverage.
        plen = req.prefix_len
        covered = eng.pool.pages_for_rows(plen)
        need = ((1 if plen % eng.page_size else 0)
                + eng.pool.pages_for_rows(plen + len(req.prompt))
                - covered)
        if need > eng.pool.pages - eng.pinned_pages:
            # Statically unservable HERE AND FOREVER: registry-pinned
            # prefix pages never free while registered, so even a
            # fully drained pool cannot supply the attach tail copy
            # plus the prompt's fresh pages (admission.validate can't
            # see the pin — it only knows raw pool capacity). Waiting
            # would stall the head of the line for every later
            # request; reject with the typed reason instead.
            self.admission.count_reject(RejectReason.CACHE_EXHAUSTED,
                                        tenant=req.tenant)
            self._finalize_request(req, 'rejected',
                                   RejectReason.CACHE_EXHAUSTED)
            return 'rejected'
        if eng.free_pages < need:
            return 'wait'
        if req.prefix_id is not None:
            try:
                attached = eng.start_with_prefix(slot.index,
                                                 req.prefix_id)
            except KeyError:
                # Unregistered while the request sat queued: a typed
                # terminal, never a KeyError crashing the tick.
                self.admission.count_reject(
                    RejectReason.PREFIX_UNREGISTERED, tenant=req.tenant)
                self._finalize_request(
                    req, 'rejected', RejectReason.PREFIX_UNREGISTERED)
                return 'rejected'
            except PageCorruptionError as exc:
                # Standalone-engine safety net (a topology's router
                # verifies at routing time and heals through its
                # ledger, pre-empting this): quarantine the dirty
                # pages, drop the poisoned prefix, typed terminal —
                # never a token decoded off a page that fails its
                # checksum.
                eng.quarantine_pages(exc.pages)
                eng.unregister_prefix(req.prefix_id)
                self.admission.count_reject(
                    RejectReason.KV_CORRUPT, tenant=req.tenant)
                self._finalize_request(req, 'rejected',
                                       RejectReason.KV_CORRUPT)
                return 'rejected'
            if not attached:
                return 'wait'
        if not eng.reserve_rows(slot.index, len(req.prompt)):
            eng.reset(slot.index)       # releases a prefix attach too
            return 'wait'
        return 'ok'

    def _policy_chooser(self):
        """The fair-share selection hook ``pop_ready`` calls with the
        live queue, or None for FIFO. The weighted-share table is read
        from the CURRENT slot occupancy — recomputed per pop, so two
        slots filled in one tick see each other's placements."""
        if self._policy is None:
            return None
        held: Dict[str, int] = {}
        for s in self._slots:
            if s.request is not None:
                held[s.request.tenant] = held.get(s.request.tenant,
                                                  0) + 1
        return lambda live: self._policy.select(live, held)

    def _admit_into_free_slots(self):
        for slot in self._slots:
            if slot.state is not _SlotState.FREE:
                continue
            # A statically-rejected request must not burn this slot's
            # turn: the SAME slot keeps popping until something places
            # (or the queue drains / the head has to wait for pages,
            # which stops admission for the whole tick).
            while True:
                req, dropped = self.admission.pop_ready(
                    chooser=self._policy_chooser())
                self._record_dropped(dropped)
                if req is None:
                    return
                if not self._paged:
                    break
                placed = self._place_paged(slot, req)
                if placed == 'ok':
                    break
                if placed == 'wait':
                    # Admission is BY FREE PAGES: head-of-line waits
                    # (its queue position and wait clock intact) until
                    # running sequences retire pages.
                    queued_since = req.queued_since
                    self.admission.push_front(req)
                    req.queued_since = queued_since
                    return
                # 'rejected': typed terminal already recorded — the
                # slot is still free, try the next queued request.
            req.admit_index = self._admit_counter
            self._admit_counter += 1
            slot.request = req
            slot.produced = 0
            slot.prefill_pos = 0
            slot.last_token_at = None
            now = self.clock()
            slot.last_progress = now
            # Queue wait: submit (or quarantine-requeue) → slot. Stamped
            # into the admit event so the timeline reconstruction and
            # the histogram agree by construction.
            queued_since = (req.queued_since if req.queued_since
                            is not None else req.submitted_at)
            wait = max(0.0, now - queued_since)
            req.admitted_at = now
            self._h_queue.observe(wait)
            self._tenant_hist('serve.queue_wait_seconds',
                              req.tenant).observe(wait)
            self._emit('serve.admit', request_id=req.id,
                       slot=slot.index, queue_wait=wait,
                       prompt_len=len(req.prompt),
                       requeues=req.requeues, tenant=req.tenant)
            if len(req.prompt) == 1:
                slot.state = _SlotState.ACTIVE
                slot.input_token = int(req.prompt[-1])
                self._spec_start(slot)
            else:
                slot.state = _SlotState.PREFILL

    def _pressure_info(self):
        """``(pressure, source)``: the backpressure signal plus which
        stream dominates it (``'queue'`` / ``'page_pool'``) — the
        reason stamped on ``serve.degrade`` events."""
        pressure, source = self.admission.pressure, 'queue'
        if self._paged:
            stats = self.engine.cache_stats()
            pool = stats['pages_used'] / max(1, stats['pages'])
            if pool > pressure:
                pressure, source = pool, 'page_pool'
        return pressure, source

    def _pressure(self):
        """Backpressure signal: queue depth, and on paged engines the
        page-pool fill — whichever is higher. A nearly-full pool caps
        new budgets and downgrades readiness exactly like a nearly-
        full queue (shorter streams → fewer pages committed)."""
        return self._pressure_info()[0]

    def _update_readiness(self):
        if self.health.liveness is Liveness.STALLED or self._closed:
            return      # the watchdog owns NOT_READY during a stall
        if self.admission.full:
            self.health.set_readiness(Readiness.NOT_READY, 'queue full')
        elif self._pressure() >= self.cfg.degrade_watermark:
            self.health.set_readiness(Readiness.DEGRADED,
                                      'queue or page-pool pressure')
        else:
            self.health.set_readiness(Readiness.READY, 'serving')

    def _commit_token(self, slot: _Slot, tok: int, now) -> bool:
        """Append ONE committed token to the slot's stream with the
        full per-token bookkeeping — counters, TTFT/gap observations
        stamped into the serve.decode event, abandon/deadline/eos/
        budget terminal checks. Shared verbatim by the plain n=1 tick
        and the verify-k commit loop, so the two paths' bookkeeping
        cannot drift. Returns True when the token finished the request
        (slot freed) — a verify commit stops there."""
        req = slot.request
        req.tokens.append(tok)
        slot.produced += 1
        slot.input_token = tok
        slot.last_progress = now
        self._c['tokens_generated'].inc()
        # Timeline observations, stamped into the decode event: TTFT
        # on the stream's first token, inter-token gap on the rest
        # (both on the scheduler clock). Tokens a verify step commits
        # together stamp zero gaps — that IS the amortization.
        token_fields = dict(request_id=req.id, slot=slot.index,
                            token_index=slot.produced - 1, token=tok)
        if req.first_token_at is None:
            req.first_token_at = now
            ttft = max(0.0, now - req.submitted_at)
            self._h_ttft.observe(ttft)
            self._tenant_hist('serve.ttft_seconds',
                              req.tenant).observe(ttft)
            self._ttft_dirty = True
            token_fields['ttft'] = ttft
        elif slot.last_token_at is not None:
            gap = max(0.0, now - slot.last_token_at)
            self._h_token.observe(gap)
            self._tenant_hist('serve.token_seconds',
                              req.tenant).observe(gap)
            token_fields['gap'] = gap
        slot.last_token_at = now
        if self._tick_device is not None:
            # Device share of the dispatch this token rode (REAL
            # seconds, the whole batch's program — per-token division
            # is the reader's policy choice, not the log's).
            token_fields['device_seconds'] = self._tick_device
        self._emit('serve.decode', **token_fields)
        if req.cancelled or (
                self.injector is not None
                and self.injector.should_abandon(
                    req.admit_index, slot.produced)):
            self._finish(slot, 'abandoned')
        elif req.deadline is not None and req.deadline <= now:
            self._finish(slot, 'deadline_expired')
        elif (self.cfg.eos_id is not None
                and tok == self.cfg.eos_id):
            self._finish(slot, 'completed')
        elif slot.produced >= req.max_new_tokens:
            self._finish(slot, 'completed')
        else:
            return False
        return True

    def _propose(self, lens):
        """Collect this tick's proposals: per ACTIVE slot, cap the
        verify width by the remaining token budget (a verify commits
        up to cap+1 tokens — never past max_new_tokens) and the cache
        headroom, hand the proposer the committed history, and emit a
        spec.propose event per slot that got guesses. Returns
        ``{slot_index: [token, ...]}``."""
        k = self.cfg.spec_k
        reqs = []
        for slot in self._slots:
            if slot.state is not _SlotState.ACTIVE:
                continue
            req = slot.request
            cap = min(k, req.max_new_tokens - slot.produced - 1,
                      self.engine.t_max - int(lens[slot.index]) - 1)
            if cap <= 0:
                continue
            reqs.append((slot.index,
                         list(req.prompt) + req.tokens, cap))
        if not reqs:
            return {}
        caps = {s: c for s, _, c in reqs}
        props = self._proposer.propose_batch(reqs, k) or {}
        props = {s: list(p)[:caps[s]] for s, p in props.items()
                 if s in caps and len(p)}
        if self._paged:
            # Reserve each spec slot's verify-width pages up front; on
            # exhaustion DROP the slot's proposals (it rides the tick
            # as a plain n=1 decode, whose single append the
            # _ensure_pages ladder already made writable) — spec is an
            # accelerator, never a reason to preempt someone.
            for s in list(props):
                if not self.engine.reserve_rows(s, len(props[s]) + 1):
                    del props[s]
        for slot in self._slots:
            p = props.get(slot.index)
            if p:
                self._emit('spec.propose', request_id=slot.request.id,
                           slot=slot.index, proposed=len(p),
                           proposer=type(self._proposer).__name__)
        return props

    def _spec_tick(self, active, poison, request_ids, props, lens):
        """One mixed spec/non-spec verify tick: every active slot
        rides ONE fused verify program — row 0 its input token, rows
        1.. its proposals (none for non-spec slots, counts[i] = 1).
        Greedy acceptance commits the longest matching prefix plus the
        free token through the SAME per-token bookkeeping as a plain
        tick, then one batched rollback truncates every continuing
        slot's cache to its accepted prefix."""
        eng = self.engine
        w = self.cfg.spec_k + 1
        tokens = np.zeros((eng.slots, w), np.int32)
        counts = np.zeros(eng.slots, np.int64)
        for slot in self._slots:
            if slot.state is not _SlotState.ACTIVE:
                continue
            p = props.get(slot.index, [])
            tokens[slot.index, 0] = slot.input_token
            tokens[slot.index, 1:1 + len(p)] = p
            counts[slot.index] = 1 + len(p)
        dev0 = eng.program_seconds
        toks, finite = eng.verify_step(tokens, counts, active, poison,
                                       request_ids=request_ids)
        self._tick_device = eng.program_seconds - dev0
        self.health.beat()   # the step returned: not stuck
        self._c['decode_steps'].inc()
        now = self.clock()
        targets = np.full(eng.slots, np.iinfo(np.int32).max, np.int64)
        for slot in self._slots:
            if slot.state is not _SlotState.ACTIVE:
                continue
            req = slot.request
            if not finite[slot.index]:
                self._quarantine(slot)
                continue
            p = props.get(slot.index, [])
            row = toks[slot.index]
            acc = 0
            while acc < len(p) and p[acc] == int(row[acc]):
                acc += 1
            if p:
                self._h_spec_prop.observe(len(p))
                self._h_spec_acc.observe(acc)
                self._emit('spec.verify', request_id=req.id,
                           slot=slot.index, proposed=len(p),
                           accepted=acc)
            committed = []
            finished = False
            for tok in row[:acc + 1]:
                committed.append(int(tok))
                if self._commit_token(slot, int(tok), now):
                    finished = True
                    break
            if not finished:
                # Truncate the cache to the accepted prefix: the next
                # input token (the free one) is appended by the NEXT
                # step, like every committed token before it.
                targets[slot.index] = int(lens[slot.index]) + 1 + acc
                self._proposer.commit(slot.index, committed, acc)
        eng.rollback(targets)
        self._proposer.end_step()

    def load(self):
        """Placement signal for a router (serve/router.py): in-flight
        work and headroom, read without device sync. ``accepting`` is
        the router's admission probe — a False here means a submit
        would shed QUEUE_FULL, so the router tries another replica (or
        sheds typed NO_REPLICA) INSTEAD of letting this scheduler
        reject: a routed request must leave exactly one lifecycle in
        exactly one replica's log, never a reject in one and an admit
        in another."""
        busy = sum(s.state is not _SlotState.FREE for s in self._slots)
        out = {'queued': self.admission.depth, 'busy': busy,
               'free_slots': self.engine.slots - busy,
               'accepting': not self.admission.full and not self._closed,
               # Policy-relevant backlog shape (router placement and
               # the controller's scale/shed decisions): who is
               # queued, and how urgent the head of the backlog is.
               'queued_by_tenant': self.admission.queued_by_tenant(),
               'oldest_deadline': self.admission.oldest_deadline()}
        if self._paged:
            out['free_pages'] = self.engine.free_pages
        return out

    # -- control-plane actuation (serve/control.py) --------------------
    def set_watermark(self, value):
        """Move the degradation watermark (controller actuation):
        admission's threshold and the readiness ladder's move together
        — the two copies can never drift. Returns the clamped value."""
        value = min(1.0, max(0.05, float(value)))
        self.cfg.degrade_watermark = value
        self.admission.degrade_watermark = value
        return value

    def set_queue_limit(self, limit):
        """Resize the admission bound (controller actuation): a
        tightened bound flips ``accepting`` sooner, which is what
        spills new arrivals to a standby replica through the router's
        least-loaded ladder. Already-queued requests are never shed by
        a shrink — the bound gates PUSHES only. Mirrors into
        ``cfg.queue_limit`` like :meth:`set_watermark` does, so a
        post-mortem bundle's introspection reports the bound the
        incident actually ran under. Returns the clamped value."""
        limit = max(1, int(limit))
        self.cfg.queue_limit = limit
        self.admission.queue_limit = limit
        return limit

    def drain(self):
        """Preempt every in-flight request and empty the queue —
        the scale-down arc (serve/control.py): each busy slot emits
        ``serve.preempt`` (``requeued=True, drain=True``) and its
        request resets to a fresh attempt (tokens regenerate
        deterministically, same as a quarantine requeue — but the
        drain charges no requeue budget: it is an operator action,
        not a fault). Returns the drained requests in admission order
        for the caller (the router) to resubmit elsewhere; expired/
        cancelled queue entries finalize here with their typed
        reasons, exactly as a tick would have."""
        drained = []
        for slot in self._slots:
            if slot.state is _SlotState.FREE:
                continue
            req = slot.request
            self._emit('serve.preempt', request_id=req.id,
                       slot=slot.index, requeued=True, drain=True)
            self._clear_slot(slot)
            req.tokens = []
            req.first_token_at = None
            drained.append(req)
        while True:
            req, dropped = self.admission.pop_ready()
            self._record_dropped(dropped)
            if req is None:
                break
            drained.append(req)
        self._g_active.set(0)
        self._update_readiness()
        return drained

    # -- corruption containment (serve/router.py) ----------------------
    def requests_on_slots(self, slot_indices):
        """Request ids currently decoding on the given slots — the
        victims of a page-level fault (the router maps dirty pages to
        slots via the engine's reverse table, then to streams here)."""
        wanted = {int(i) for i in slot_indices}
        return [slot.request.id for slot in self._slots
                if slot.state is not _SlotState.FREE
                and slot.index in wanted]

    def queued_with_prefix(self, prefix_ids):
        """Queued request ids pinned to one of the given prefixes —
        riders that would attach poisoned pages the moment a slot
        frees. They never held the pages, but their placement plan is
        dirty, so corruption containment expels them too."""
        wanted = set(prefix_ids)
        return [req.id for req in self.admission._queue
                if req.prefix_id in wanted]

    def expel(self, request_id):
        """Forcibly remove one request — slot or queue — WITHOUT a
        terminal: the caller (the router's corruption handler) owns
        the request's fate (ledger replay on a clean replica, or a
        typed reject past budget). A slot expulsion follows the drain
        arc (``serve.preempt`` with ``expel=True``, slot cleared,
        tokens reset for a deterministic regeneration); a queue
        expulsion just unlinks. Returns the Request, or None when the
        id is not live here (already retired — nothing to heal)."""
        for slot in self._slots:
            if slot.state is _SlotState.FREE \
                    or slot.request.id != request_id:
                continue
            req = slot.request
            self._emit('serve.preempt', request_id=req.id,
                       slot=slot.index, requeued=True, expel=True)
            self._clear_slot(slot)
            req.tokens = []
            req.first_token_at = None
            self._g_active.set(sum(s.state is not _SlotState.FREE
                                   for s in self._slots))
            self._update_readiness()
            return req
        for i, req in enumerate(self.admission._queue):
            if req.id == request_id:
                # del by index, not remove(req): deque.remove falls
                # back to Request's field-wise __eq__ past the
                # identity check, and comparing numpy prompt arrays
                # raises on any request queued AHEAD of the victim.
                del self.admission._queue[i]
                self.admission._update_depth()
                self._update_readiness()
                return req
        return None

    # -- the loop -------------------------------------------------------
    def step(self) -> bool:
        """One scheduler tick (admit → prefill chunk → decode step →
        retire). Returns True while work remains. An unhandled
        exception escaping the tick dumps a post-mortem bundle (the
        state that crashed the loop, captured before unwinding
        destroys it) and re-raises — the flight recorder observes
        failures, it never absorbs them."""
        try:
            return self._step_impl()
        except Exception as e:
            if self.cfg.flight_dump_on_exception:
                self._flight_dump(
                    'exception',
                    reason=f'{type(e).__name__}: {e}')
            raise

    def _step_impl(self) -> bool:
        # Dispatch-floor anchors: REAL tick start and the engine's
        # cumulative program-seconds odometer. Ticks that run a decode
        # dispatch close the loop at the bottom of this method —
        # tick wall time minus the program delta IS the host overhead.
        tick_t0 = time.perf_counter()
        dev_anchor = self.engine.program_seconds
        toks_anchor = self._c['tokens_generated'].value
        ran_decode = False
        now = self.clock()
        self.health.beat()
        self._admit_into_free_slots()

        # Prefill interleave width, ONCE per tick: the policy's boost
        # reads the TTFT p99 (a reservoir sort — not a per-slot cost),
        # and only when a target is armed; everything else rides the
        # stock one-chunk interleave.
        chunks = 1
        if self._policy is not None \
                and self._policy.cfg.target_ttft is not None:
            chunks = self._policy.prefill_chunks(
                self._h_ttft.percentile(99))
        for slot in self._slots:
            if slot.state is not _SlotState.PREFILL:
                continue
            req = slot.request
            if req.cancelled:
                self._finish(slot, 'abandoned')
                continue
            if req.deadline is not None and req.deadline <= now:
                self._finish(slot, 'deadline_expired')
                continue
            # ONE chunk per tick per slot: long prompts interleave with
            # decoding instead of monopolizing the loop. A policy with
            # target_ttft armed may boost that to several chunks while
            # the live TTFT p99 runs hot (serve/policy.py) — prompts
            # reach their first token sooner, and the boost collapses
            # back to 1 as soon as TTFT recovers.
            for _ in range(chunks):
                end = min(slot.prefill_pos + self.engine.prefill_chunk,
                          len(req.prompt) - 1)
                if end <= slot.prefill_pos:
                    break
                self.engine.prefill(slot.index,
                                    req.prompt[slot.prefill_pos:end],
                                    request_id=req.id)
                slot.prefill_pos = end
                slot.last_progress = now
                self._emit('serve.prefill', request_id=req.id,
                           slot=slot.index, pos=end)
            if slot.prefill_pos >= len(req.prompt) - 1:
                slot.state = _SlotState.ACTIVE
                slot.input_token = int(req.prompt[-1])
                self._spec_start(slot)

        if self._paged:
            self._ensure_pages()
        active = np.array([s.state is _SlotState.ACTIVE
                           for s in self._slots])
        if active.any():
            if self.injector is not None:
                self.injector.on_decode_step(self._step_idx)
            poison = (self.injector.poison_slots(self._step_idx,
                                                 len(self._slots))
                      if self.injector is not None else None)
            # Request-id labels only materialize when spans are on —
            # the disabled default must stay allocation-free per step.
            request_ids = ([s.request.id if s.request is not None
                            else None for s in self._slots]
                           if obs_spans.enabled() else None)
            # Speculative tick: collect proposals first; a tick where
            # no slot got a guess rides the plain n=1 program (zero
            # verify overhead when the proposer has nothing).
            props = None
            if self._proposer is not None:
                lens = self.engine.lengths()
                props = self._propose(lens)
            ran_decode = True
            t0 = time.perf_counter()
            if props:
                with span('serve.decode_step', step=self._step_idx,
                          spec=True):
                    self._spec_tick(active, poison, request_ids, props,
                                    lens)
                self._h_step.observe(time.perf_counter() - t0)
            else:
                tokens_in = np.array(
                    [s.input_token for s in self._slots], np.int32)
                dev0 = self.engine.program_seconds
                with span('serve.decode_step', step=self._step_idx):
                    toks, finite = self.engine.step(
                        tokens_in, active, poison,
                        request_ids=request_ids)
                self._tick_device = self.engine.program_seconds - dev0
                self._h_step.observe(time.perf_counter() - t0)
                self.health.beat()   # the step returned: not stuck
                self._c['decode_steps'].inc()
                now = self.clock()
                for slot in self._slots:
                    if slot.state is not _SlotState.ACTIVE:
                        continue
                    if not finite[slot.index]:
                        self._quarantine(slot)
                        continue
                    tok = int(toks[slot.index])
                    finished = self._commit_token(slot, tok, now)
                    # props == {} (not None) means the proposer DID
                    # draft this tick but every proposal was dropped
                    # (nothing guessed, or paged reservation shed them
                    # all): a stateful proposer (the draft engine) has
                    # speculatively appended rows it must roll back to
                    # the committed stream — the same commit/end_step
                    # protocol a verify tick runs, with 0 accepted.
                    # Finished slots skip it: retirement already reset
                    # the proposer's slot state.
                    if props is not None and not finished:
                        self._proposer.commit(slot.index, [tok], 0)
                if props is not None:
                    self._proposer.end_step()
            self._step_idx += 1

        self._g_active.set(sum(s.state is not _SlotState.FREE
                               for s in self._slots))
        if self._paged:
            stats = self.engine.cache_stats()
            self._g_pages_used.set(stats['pages_used'])
            self._g_pages_free.set(stats['pages_free'])
            self._g_shared.set(stats['shared_pages'])
        self._maybe_profile()
        # Flight-recorder sample (throttled inside to REAL seconds;
        # the shared null recorder makes the disabled path one method
        # call, no allocation) and the anomaly watchdog's evaluation
        # pass (same real-time throttle).
        obs_flight.recorder().sample()
        if self._anomaly is not None:
            try:
                self._anomaly.tick()
            except Exception as e:
                # A broken detector must never down the serving loop.
                tracing.log_exception('scheduler.anomaly_tick', e,
                                      registry=self.registry)
        self._update_readiness()
        if ran_decode:
            # Close the dispatch-floor loop for this tick: the REAL
            # wall time the whole tick body took vs the slice spent
            # inside compiled programs (prefill chunks included — they
            # are device work this tick paid for). Emitted per tick,
            # not per token: the floor is a loop property; critpath
            # divides by tokens when it reports per-token overhead.
            tick_s = time.perf_counter() - tick_t0
            dev_s = max(0.0, self.engine.program_seconds - dev_anchor)
            overhead = max(0.0, tick_s - dev_s)
            self._h_device.observe(dev_s)
            self._h_dispatch.observe(overhead)
            self._emit('serve.dispatch', step=self._step_idx - 1,
                       tick_seconds=tick_s, device_seconds=dev_s,
                       overhead=overhead,
                       tokens=self._c['tokens_generated'].value
                       - toks_anchor)
        self._tick_device = None
        if self.on_tick is not None:
            self.on_tick(self)
        return bool(self.admission.depth) or any(
            s.state is not _SlotState.FREE for s in self._slots)

    def _maybe_profile(self):
        """Adaptive capture trigger: when armed (cfg.profile_ttft_p99 +
        a profiler) and the ttft p99 over the reservoir exceeds the
        threshold, begin ONE bounded trace capture. Checked only on
        ticks that observed a fresh TTFT (the p99 recompute sorts the
        reservoir — not a per-tick cost), rate-limited by a REAL-time
        cooldown (captures are real however the scheduler clock runs),
        and skipped while a capture is already in flight."""
        if not self._ttft_dirty:
            return
        self._ttft_dirty = False
        prof, threshold = self.profiler, self.cfg.profile_ttft_p99
        if prof is None or threshold is None:
            return
        now = time.monotonic()
        if (self._last_capture_at is not None
                and now - self._last_capture_at
                < self.cfg.profile_cooldown):
            return
        p99 = self._h_ttft.percentile(99)
        if not p99 > threshold:
            return
        if getattr(prof, 'busy', False):
            return
        try:
            prof.start(self.cfg.profile_seconds,
                       trigger='serve.ttft_p99',
                       event_log=self.event_log, ttft_p99=p99,
                       threshold=threshold)
        except CaptureInFlight:
            # Expected contention, not a fault: an HTTP /profile hit
            # can land between our busy-check and start(). Skip
            # quietly like the busy-check above — no exception event.
            return
        except Exception as e:
            # A failing profiler must never take the serving loop down.
            tracing.log_exception('scheduler.profile_trigger', e,
                                  registry=self.registry)
            return
        self._last_capture_at = now
        self._c_profile.inc()

    def run_until_idle(self, max_ticks=100_000):
        """Drive ticks until queue and slots are empty. ``max_ticks``
        bounds runaway loops (a bug, not load, is the only way to hit
        it)."""
        ticks = 0
        while self.step():
            ticks += 1
            if ticks >= max_ticks:
                raise RuntimeError(
                    f'scheduler still busy after {max_ticks} ticks: '
                    f'queue={self.admission.depth} slots='
                    f'{[s.state.value for s in self._slots]}')
        return self.results

    def close(self):
        """Stop the watchdog and mark the surface STOPPED."""
        if not self._closed:
            self._closed = True
            obs_flight.remove_provider('scheduler',
                                       self._introspection_hook)
            self.health.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
