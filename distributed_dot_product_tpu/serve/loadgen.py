# -*- coding: utf-8 -*-
"""
Seeded open-loop traffic generator for the serving scheduler — the
measurement half of ROADMAP item 5 (production traffic simulation).

The fault cocktail and fixed bursts exercise the scheduler's FAILURE
paths; nobody had ever offered it realistic LOAD. This module
generates a reproducible request trace and drives the scheduler with
it in-process, entirely in **virtual time**:

- **Open loop**: arrivals follow the configured process (Poisson, or a
  two-state ON/OFF bursty modulation) regardless of how the server is
  doing — the load does not politely wait for completions, which is
  exactly what makes queue growth, rejection and goodput measurable.
- **Heavy-tailed mixes**: prompt and output lengths come from a
  bounded-Pareto sample per tenant (most requests short, a fat tail of
  long ones — the shape real serving traffic has, and the one that
  breaks schedulers tuned on uniform bursts).
- **Tenants**: each request carries a tenant label drawn by per-tenant
  rate shares; the label threads through admission → scheduler →
  events → metrics, so per-tenant goodput is derivable offline
  (obs/slo.py) and live (/metrics).
- **Fully seeded and replayable**: one integer seed determines the
  whole trace (arrival times, tenants, prompts, budgets). The driver
  runs on a :class:`VirtualClock` injected into the scheduler, so a
  test serves minutes of simulated traffic in milliseconds of wall
  time and the SAME seed yields the IDENTICAL goodput report.

Usage::

    cfg = LoadGenConfig(seed=7, rate=300.0, requests=64)
    res = run_load(cfg, engine=KernelEngine(slots=4, t_max=128),
                   event_log=EventLog('load.jsonl'))
    # then: obs.slo.goodput('load.jsonl', SloSpec(ttft=0.2, ...))
"""

import dataclasses
import json
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from distributed_dot_product_tpu.serve.admission import RejectedError
from distributed_dot_product_tpu.serve.errors import ServeContractError
from distributed_dot_product_tpu.serve.scheduler import (
    Scheduler, ServeConfig,
)

__all__ = ['TenantSpec', 'LoadGenConfig', 'Arrival', 'VirtualClock',
           'generate_trace', 'run_trace', 'run_load', 'LoadResult',
           'default_tenants', 'TRACE_SCHEMA', 'save_trace',
           'load_trace', 'ChaosSchedule']

# determlint: the driving loop lives on the virtual clock — real time
# may only appear as the reporting-only wall_seconds accounting
# (declared in determlint.REAL_TIME_CONTRACT).
GRAPHLINT_TICK_ROOTS = ('run_trace',)


class VirtualClock:
    """Deterministic injectable clock: calling it reads the time,
    :meth:`advance` moves it. The scheduler's deadline/idleness clock
    and the event log's ``ts`` stamps both take a callable, so one
    instance makes an entire serving run virtual-time."""

    def __init__(self, start=0.0):
        self._t = float(start)

    def __call__(self):
        return self._t

    def advance(self, dt):
        if dt < 0:
            raise ValueError(f'clock cannot go backwards (dt={dt})')
        self._t += dt
        return self._t


@dataclasses.dataclass
class TenantSpec:
    """One tenant's traffic shape. ``share`` is its relative weight of
    the aggregate arrival rate. Lengths are bounded-Pareto sampled in
    ``[lo, hi]`` with tail index ``alpha`` (smaller = heavier tail).
    ``deadline_s``: optional per-request deadline (seconds after
    arrival) submitted with every request — None for no deadline."""
    name: str
    share: float = 1.0
    prompt_lo: int = 2
    prompt_hi: int = 24
    new_lo: int = 4
    new_hi: int = 24
    alpha: float = 1.5
    deadline_s: Optional[float] = None


def default_tenants(n=2):
    """The stock mix: ``t0`` interactive (short prompts, short outputs,
    2/3 of traffic) and ``t1`` batchy (longer both ways); further
    tenants split the remainder evenly with t1's shape."""
    specs = [TenantSpec('t0', share=2.0, prompt_lo=2, prompt_hi=12,
                        new_lo=4, new_hi=12),
             TenantSpec('t1', share=1.0, prompt_lo=4, prompt_hi=24,
                        new_lo=8, new_hi=24)]
    for i in range(2, n):
        specs.append(dataclasses.replace(specs[1], name=f't{i}'))
    return specs[:max(1, n)]


@dataclasses.dataclass
class LoadGenConfig:
    """Knobs of the generator. ``rate`` is the aggregate offered rate
    (requests per virtual second); ``arrival='poisson'`` draws i.i.d.
    exponential inter-arrivals, ``'bursty'`` modulates them with a
    two-state ON/OFF process (ON bursts at ``rate * burst_factor``,
    exponential dwells sized so the AVERAGE offered rate stays
    ``rate``). ``'ramp'`` climbs the instantaneous rate linearly from
    ``rate`` to ``rate * ramp_factor`` across the trace; ``'step'``
    jumps it from ``rate`` to ``rate * ramp_factor`` at the
    ``step_at`` fraction of the requests — the two deterministic
    shapes that exercise elastic scale-up/scale-down
    (serve/control.py). ``tick_seconds`` is the virtual duration of
    one scheduler tick — the simulated cost of the compiled decode
    step."""
    seed: int = 0
    rate: float = 200.0
    requests: int = 64
    arrival: str = 'poisson'   # 'poisson' | 'bursty' | 'ramp' | 'step'
    burst_factor: float = 4.0
    burst_dwell_s: float = 0.25     # mean ON-state dwell
    ramp_factor: float = 4.0        # peak rate multiple (ramp/step)
    step_at: float = 0.5            # 'step': jump after this fraction
    tenants: List[TenantSpec] = dataclasses.field(
        default_factory=default_tenants)
    vocab: int = 64
    tick_seconds: float = 0.002

    def validate(self):
        if self.rate <= 0 or self.requests < 1:
            raise ValueError(f'need rate > 0 and requests >= 1, got '
                             f'{self.rate}/{self.requests}')
        if self.arrival not in ('poisson', 'bursty', 'ramp', 'step'):
            raise ValueError(f"arrival must be 'poisson', 'bursty', "
                             f"'ramp' or 'step', got {self.arrival!r}")
        if self.arrival == 'bursty' and not self.burst_factor > 1.0:
            # The OFF dwell is sized from (burst_factor - 1); <= 1
            # would ask for a negative exponential scale deep inside
            # the generator — reject it here, typed.
            raise ValueError(f'bursty arrivals need burst_factor > 1, '
                             f'got {self.burst_factor}')
        if self.arrival in ('ramp', 'step') and not self.ramp_factor > 0:
            raise ValueError(f'{self.arrival} arrivals need '
                             f'ramp_factor > 0, got {self.ramp_factor}')
        if self.arrival == 'step' and not 0.0 <= self.step_at <= 1.0:
            raise ValueError(f'step_at must sit in [0, 1], got '
                             f'{self.step_at}')
        if not self.tenants:
            raise ValueError('need at least one TenantSpec')


@dataclasses.dataclass
class Arrival:
    """One scheduled request of a trace (virtual arrival time)."""
    at: float
    request_id: str
    tenant: str
    prompt: np.ndarray
    max_new_tokens: int
    deadline_s: Optional[float] = None


def _pareto_int(rng, lo, hi, alpha):
    """Bounded-Pareto integer in [lo, hi]: heavy-tailed (most draws
    near ``lo``, occasional ones out at ``hi``), closed under the
    bounds so a draw can never overflow the cache budget math."""
    lo, hi = int(lo), int(hi)
    if hi <= lo:
        return lo
    u = rng.random()
    ratio = (lo / hi) ** alpha
    x = lo / (1.0 - u * (1.0 - ratio)) ** (1.0 / alpha)
    return int(min(hi, max(lo, round(x))))


def generate_trace(cfg: LoadGenConfig) -> List[Arrival]:
    """The deterministic trace for ``cfg``: same seed, same trace,
    byte for byte — what makes a goodput report replayable."""
    cfg.validate()
    rng = np.random.default_rng(cfg.seed)
    shares = np.array([max(0.0, t.share) for t in cfg.tenants])
    if shares.sum() <= 0:
        raise ValueError('tenant shares sum to zero')
    shares = shares / shares.sum()
    trace = []
    t = 0.0
    # Bursty = ON/OFF modulated Poisson: ON bursts at rate*factor,
    # OFF emits nothing; dwell means sized so ON occupies 1/factor of
    # the time and the long-run offered rate stays cfg.rate.
    on = True
    state_left = (rng.exponential(cfg.burst_dwell_s)
                  if cfg.arrival == 'bursty' else float('inf'))
    for i in range(cfg.requests):
        if cfg.arrival == 'poisson':
            t += rng.exponential(1.0 / cfg.rate)
        elif cfg.arrival in ('ramp', 'step'):
            # Deterministic rate SHAPE over the request index: 'ramp'
            # climbs linearly to rate*ramp_factor at the last arrival,
            # 'step' jumps there after the step_at fraction. Each gap
            # is exponential at the instantaneous rate — a seeded
            # inhomogeneous-Poisson stand-in that round-trips through
            # save_trace/load_trace unchanged (only times serialize).
            if cfg.arrival == 'ramp':
                frac = i / max(1, cfg.requests - 1)
                r = cfg.rate * (1.0 + (cfg.ramp_factor - 1.0) * frac)
            else:
                r = (cfg.rate if i < cfg.requests * cfg.step_at
                     else cfg.rate * cfg.ramp_factor)
            t += rng.exponential(1.0 / r)
        else:
            # `gap` is ON-time until the next arrival (arrivals only
            # happen in the ON state, at rate*factor); OFF dwells are
            # dead time inserted whenever the gap crosses a state edge.
            gap = rng.exponential(1.0 / (cfg.rate * cfg.burst_factor))
            while not on or gap > state_left:
                t += state_left
                if on:
                    gap -= state_left
                state_left = rng.exponential(
                    cfg.burst_dwell_s * (cfg.burst_factor - 1.0)
                    if on else cfg.burst_dwell_s)
                on = not on
            t += gap
            state_left -= gap
        ti = int(rng.choice(len(cfg.tenants), p=shares))
        spec = cfg.tenants[ti]
        plen = _pareto_int(rng, spec.prompt_lo, spec.prompt_hi,
                           spec.alpha)
        prompt = rng.integers(0, cfg.vocab, size=plen).astype(np.int32)
        trace.append(Arrival(
            at=t, request_id=f'{spec.name}-{i:04d}', tenant=spec.name,
            prompt=prompt,
            max_new_tokens=_pareto_int(rng, spec.new_lo, spec.new_hi,
                                       spec.alpha),
            deadline_s=spec.deadline_s))
    return trace


TRACE_SCHEMA = 1


def save_trace(path, trace: List[Arrival], *, note=None):
    """Serialize a generated trace to schema-versioned JSON so the
    IDENTICAL request stream can drive two systems — the router
    topology and its single-process twin — byte for byte, or replay a
    recorded incident's load later. Floats round-trip exactly through
    JSON (repr-based), so ``load_trace(save_trace(t)) == t`` to the
    last bit; prompts serialize as plain int lists."""
    payload = {
        'schema': TRACE_SCHEMA,
        'arrivals': [
            {'at': a.at, 'request_id': a.request_id,
             'tenant': a.tenant,
             'prompt': [int(t) for t in a.prompt],
             'max_new_tokens': int(a.max_new_tokens),
             'deadline_s': a.deadline_s}
            for a in trace],
    }
    if note:
        payload['note'] = str(note)
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(payload, f, separators=(',', ':'), allow_nan=False)
        f.write('\n')
    return path


def load_trace(path) -> List[Arrival]:
    """Read a :func:`save_trace` file back into the arrival list.
    Typed errors on an unknown schema version or a malformed arrival
    — a trace drives SLO-graded runs, silently coercing a broken one
    would grade garbage."""
    with open(path, encoding='utf-8') as f:
        payload = json.load(f)
    schema = payload.get('schema') if isinstance(payload, dict) else None
    if schema != TRACE_SCHEMA:
        raise ValueError(f'{path}: trace schema {schema!r} '
                         f'(supported: {TRACE_SCHEMA}) — regenerate '
                         f'the trace with this version\'s save_trace')
    trace = []
    for i, a in enumerate(payload.get('arrivals', [])):
        try:
            deadline = a.get('deadline_s')
            trace.append(Arrival(
                at=float(a['at']),
                request_id=str(a['request_id']),
                tenant=str(a['tenant']),
                prompt=np.asarray(a['prompt'], np.int32).reshape(-1),
                max_new_tokens=int(a['max_new_tokens']),
                deadline_s=None if deadline is None
                else float(deadline)))
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(
                f'{path}: arrival {i} is malformed '
                f'({type(e).__name__}: {e})') from e
    return trace


@dataclasses.dataclass
class LoadResult:
    """One load run's in-process accounting. The authoritative SLO
    verdict comes from the EVENT LOG (obs/slo.py goodput()); this is
    the driver's own view for quick printing and cross-checks."""
    submitted: List[Tuple[str, str]]          # (request_id, tenant)
    rejected_at_submit: Dict[str, object]     # rid -> RejectReason
    results: Dict[str, object]                # rid -> RequestResult
    virtual_seconds: float
    wall_seconds: float
    offered_rate: float
    ticks: int

    @property
    def accounted(self):
        """True iff every submitted request has a terminal record —
        the zero-dropped-without-reason serving contract."""
        return all(rid in self.results or rid in self.rejected_at_submit
                   for rid, _ in self.submitted)


def run_trace(scheduler: Scheduler, trace: List[Arrival],
              clock: VirtualClock,
              tick_seconds: float = 0.002,
              on_tick=None) -> LoadResult:
    """Drive ``scheduler`` (constructed on ``clock``) through
    ``trace`` open-loop: each tick submits every arrival whose time
    has come, runs ONE scheduler step, and advances virtual time by
    ``tick_seconds``; an idle scheduler jumps straight to the next
    arrival. Returns when the trace is exhausted and the scheduler
    has drained. ``on_tick()`` (no arguments) runs after every step —
    how a :class:`~distributed_dot_product_tpu.serve.control
    .Controller` rides a router-driven run (a plain Scheduler's own
    ``on_tick`` hook covers the single-scheduler case)."""
    if tick_seconds <= 0:
        raise ServeContractError(
            f'tick_seconds must be > 0, got {tick_seconds}')
    t0 = time.perf_counter()
    start = clock()
    submitted, rejected = [], {}
    i = 0
    ticks = 0
    busy = True
    while i < len(trace) or busy:
        now = clock()
        while i < len(trace) and trace[i].at <= now:
            a = trace[i]
            i += 1
            submitted.append((a.request_id, a.tenant))
            deadline = (None if a.deadline_s is None
                        else a.at + a.deadline_s)
            try:
                scheduler.submit(a.prompt,
                                 max_new_tokens=a.max_new_tokens,
                                 deadline=deadline,
                                 request_id=a.request_id,
                                 tenant=a.tenant)
            except RejectedError as e:
                rejected[a.request_id] = e.reason
        busy = scheduler.step()
        ticks += 1
        if on_tick is not None:
            on_tick()
        clock.advance(tick_seconds)
        if not busy and i < len(trace) and trace[i].at > clock():
            # Idle gap: jump to the next arrival instead of spinning
            # empty ticks through it (open-loop, but not busy-waiting).
            clock.advance(trace[i].at - clock())
    n = len(trace)
    span = (trace[-1].at - trace[0].at) if n > 1 else 0.0
    return LoadResult(
        submitted=submitted, rejected_at_submit=rejected,
        results=dict(scheduler.results),
        virtual_seconds=clock() - start,
        wall_seconds=time.perf_counter() - t0,
        offered_rate=(n / span if span > 0 else float('inf')),
        ticks=ticks)


class ChaosSchedule:
    """Seeded chaos timing for a :func:`run_trace` drive: counts the
    loop's ticks and fires the plan's replica crash at EXACTLY its
    tick. Tick indices are virtual-time coordinates — nothing here
    reads a clock — so the same plan over the same serialized trace
    replays the crash at the same virtual instant every run, which is
    what makes the chaos benchmark's recovered-vs-twin token
    comparison a bit-identity check instead of a flake. Use as the
    run's ``on_tick``::

        chaos = ChaosSchedule(ChaosInjector(plan), router)
        run_trace(router, trace, clock, on_tick=chaos)

    The kill lands on the MEMBER (``DecodeReplica.kill`` — its event
    log tears mid-record); the ROUTER is told nothing. Its liveness
    probes must detect the silence and declare the loss, exactly as
    with a real dead process. An inner ``on_tick`` (a controller's)
    chains after the crash check.

    The same discipline covers the other two seams: a planned
    prefill-pool crash kills the pool member directly
    (``PrefillPool.kill``), and a planned page corruption flips one
    bit of the victim engine's KV pool HOST-SIDE (device buffer
    round-trip, outside every compiled program) — the router learns of
    either only through its own probes/checksums. The flip's page spec
    indexes the victim's tracked (registry) pages in sorted order and
    defers to the first tick that has any, so the same seeded trace
    poisons the same prefix page every replay — including on a
    checksums-off twin, which is what makes the silent-wrong-token
    comparison measurable."""

    def __init__(self, injector, router, on_tick=None):
        self.injector = injector
        self.router = router
        self.on_tick = on_tick
        self.tick = 0
        self.killed = []
        self.corrupted = []          # (replica, page, tick) flips landed
        self._pending_corrupt = None

    def __call__(self):
        victim = self.injector.crash_due(self.tick)
        if victim is not None:
            replica = next((r for r in self.router.pool.replicas
                            if r.name == victim), None)
            if replica is not None and replica.alive:
                replica.kill()
                self.killed.append(victim)
        if self.injector.prefill_crash_due(self.tick):
            prefill = self.router.pool.prefill
            if prefill is not None and prefill.alive:
                prefill.kill()
        due = self.injector.corrupt_due(self.tick)
        if due is not None:
            self._pending_corrupt = due
        if self._pending_corrupt is not None:
            self._pending_corrupt = self._flip(self._pending_corrupt)
        self.tick += 1
        if self.on_tick is not None:
            self.on_tick()

    def _flip(self, pending):
        """Land (or defer) a planned bit flip. Returns the pending spec
        when the victim has no tracked page yet, None once landed (or
        when the victim left the pool). Page ids come from the
        engine's own tracked-page enumeration, so under ``kv_shards``
        the index resolves over GLOBAL (stacked-row) ids and the flip
        lands inside whichever shard owns that page — the detection
        path then names that shard in ``kv.corrupt``."""
        name, index = pending
        replica = next((r for r in self.router.pool.replicas
                        if r.name == name), None)
        if replica is None:
            return None
        eng = replica.engine
        tracked = eng.tracked_pages()
        if not tracked:
            return pending
        page = tracked[index % len(tracked)]
        # Flips an EXPONENT bit of the page's first K value (byte 3 of
        # a little-endian float32): the corruption is semantically
        # loud — an undetected flip changes delivered tokens, which is
        # exactly what the no-integrity twin must demonstrate. The
        # checksum does not care which bit flipped; the comparison
        # row does.
        eng.flip_page_bit(page)
        self.corrupted.append((name, page, self.tick))
        return None


def run_load(cfg: LoadGenConfig, *, engine, serve_config=None,
             registry=None, event_log=None, fault_injector=False,
             clock=None, policy=None, control=None) -> LoadResult:
    """One-call surface: generate the trace for ``cfg``, build a
    virtual-clock :class:`Scheduler` over ``engine`` (watchdog off —
    real-time heartbeats are meaningless in virtual time), run it, and
    close it. ``event_log`` should share the virtual clock so its
    ``ts`` stamps line up with the scheduler's observations (pass an
    EventLog built with ``clock=VirtualClock`` or let this function
    re-point it). ``fault_injector=False`` = explicitly unfaulted
    (the default trace is a LOAD experiment, not a fault one); pass an
    injector to combine both.

    Closed-loop extras: ``policy`` (a :class:`~distributed_dot_product
    _tpu.serve.policy.PolicyConfig`) arms fair-share/priority
    admission and deadline-aware eviction; ``control`` (a
    :class:`~distributed_dot_product_tpu.serve.control.ControlConfig`)
    builds a :class:`~distributed_dot_product_tpu.serve.control
    .Controller` on the run's virtual clock — its stock anomaly
    watchdog and every knob change then replay bit-identically with
    the seed."""
    cfg.validate()
    clock = clock or VirtualClock()
    if event_log is not None:
        # One time base for stamps and envelopes: goodput math uses
        # the stamped observations, but operators correlate on ts.
        event_log.clock = clock
    serve_config = serve_config or ServeConfig(
        queue_limit=16, max_new_tokens=max(t.new_hi
                                           for t in cfg.tenants))
    if serve_config.watchdog or (policy is not None
                                 and serve_config.policy is None):
        serve_config = dataclasses.replace(
            serve_config, watchdog=False,
            policy=serve_config.policy or policy)
    trace = generate_trace(cfg)
    sched = Scheduler(engine, serve_config, clock=clock,
                      registry=registry, event_log=event_log,
                      fault_injector=fault_injector)
    if control is not None:
        from distributed_dot_product_tpu.serve.control import Controller
        controller = Controller(scheduler=sched, config=control,
                                clock=clock, event_log=event_log)
        sched.on_tick = lambda _s: controller.tick()
    try:
        return run_trace(sched, trace, clock,
                         tick_seconds=cfg.tick_seconds)
    finally:
        sched.close()
