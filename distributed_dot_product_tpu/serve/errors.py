# -*- coding: utf-8 -*-
"""
Typed narrowings of builtin exceptions on the serving host surface —
the classes flowlint's ``typed-escape`` rule admits through a serving
root (see ``analysis/flowlint.py``'s ``TYPED_CONTRACT``).

The contract: every exception leaving ``Scheduler.step/submit``,
``Router.step/submit``, ``KernelEngine.step/prefill/verify_step`` or
``run_trace`` carries a type the operator can dispatch on.
``RejectedError`` (typed reasons) and ``PageCorruptionError``
(integrity verdicts) already did; the remaining escapes were bare
``ValueError``/``KeyError`` caller-contract raises. These subclasses
keep every existing ``except ValueError`` / ``except KeyError``
caller working (they ARE the builtin) while making the serving stack's
own raises distinguishable from a stray builtin leaking out of library
code — the distinction the PR 17 ``deque.remove`` bug hid behind.
"""

__all__ = ['ServeContractError', 'UnknownReplicaError']


class ServeContractError(ValueError):
    """The caller broke a serving-surface contract (an unsupported
    argument combination, a mis-shaped batch, a paged-only feature on
    a slab engine). A subclass of ValueError so existing callers'
    ``except ValueError`` handlers keep working."""


class UnknownReplicaError(KeyError):
    """A replica name that is not (or no longer) a pool member. A
    subclass of KeyError so existing ``except KeyError`` callers keep
    working; ``str()`` renders the message without KeyError's repr
    quoting."""

    def __str__(self):
        return self.args[0] if self.args else ''
