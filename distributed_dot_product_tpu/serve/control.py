# -*- coding: utf-8 -*-
"""
Closed-loop SLO control: the plane that ACTS on the signals the
observatory already measures. PR 9 shipped the loadgen + goodput
grading, PR 10 the online anomaly detectors, PR 11 the replica pool
and router — this module closes the loop from observed latency back
into admission, eviction and replica count:

- **Watchdog-driven watermark actuation**: the :class:`Controller`
  evaluates an :class:`~distributed_dot_product_tpu.obs.anomaly
  .AnomalyWatchdog` (queue depth, pages free, TTFT p99, reject rate)
  plus a direct pressure probe of every scheduler on its own cadence.
  A breach TIGHTENS admission — the degradation watermark drops (new
  requests degrade to capped budgets sooner) and the queue bound
  shrinks (a full queue flips ``accepting`` sooner, spilling new
  arrivals to a standby replica through the router's least-loaded
  ladder). Sustained headroom RELAXES both, stepwise, back to the
  configured ceiling.
- **Elastic decode autoscaling** (router mode): sustained backlog
  (queued per slot across the pool) scales decode replicas up;
  sustained idleness scales down — the victim replica is DRAINED
  first (``Scheduler.drain``: every in-flight request preempts with
  ``serve.preempt requeued=true drain=true`` and resubmits through
  the router onto the remaining replicas), so no stream is ever
  dropped without a typed reason.
- **Every action is a closed-vocabulary event** (``control.adjust``,
  ``control.scale``, ``control.drain`` — obs/events.py): a run's
  entire control history reconstructs from the JSONL alone, and
  ``obs doctor`` folds the control arcs into its incident evidence.

Determinism: the controller reads ONLY its injected clock and the
schedulers' live state; pairing it with the loadgen's
:class:`~distributed_dot_product_tpu.serve.loadgen.VirtualClock` (and
handing the watchdog the same clock) makes a seeded trace's breach
sequence — and therefore its control history — replay bit-identically,
which is what lets CI gate the controlled config's goodput against
``SLO_BASELINE.json``.
"""

import dataclasses
import time
from typing import Optional

from distributed_dot_product_tpu.obs import events as obs_events

__all__ = ['ControlConfig', 'Controller']

# determlint: the evaluation loop runs from the scheduler tick — every
# decision derives from the injected clock and the probed state.
GRAPHLINT_TICK_ROOTS = ('Controller.tick',)

# Watchdog watches whose breach tightens admission (the stock catalog
# names — obs/anomaly.py default_watches).
TIGHTEN_WATCHES = frozenset(
    {'queue_depth', 'pages_free', 'ttft_p99', 'reject_rate'})


@dataclasses.dataclass
class ControlConfig:
    """Knobs of the control loop. All times on the controller's
    (injected) clock. ``interval`` is the evaluation cadence;
    ``tighten_pressure``/``relax_pressure`` bound the direct probe's
    hysteresis band; ``relax_after`` healthy evaluations undo one
    tighten step. Scaling (router mode only): ``scale_up_backlog``
    queued-per-slot across the pool for ``scale_up_after`` consecutive
    evaluations adds a replica (to ``max_replicas``);
    ``scale_down_backlog`` for ``scale_down_after`` drains the
    least-loaded one (to ``min_replicas``)."""
    interval: float = 0.02
    # watermark actuation
    min_watermark: float = 0.3
    max_watermark: Optional[float] = None   # None = the config's own
    tighten_step: float = 0.15
    relax_step: float = 0.05
    relax_after: int = 6
    tighten_pressure: float = 0.9
    relax_pressure: float = 0.5
    # queue-bound actuation (the router-spill knob)
    queue_scale_min: float = 0.25
    queue_scale_step: float = 0.5
    # elastic decode scaling
    scale: bool = True
    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_backlog: float = 1.0
    scale_up_after: int = 2
    scale_down_backlog: float = 0.05
    scale_down_after: int = 10

    def validate(self):
        if self.interval <= 0:
            raise ValueError(f'interval must be > 0, got '
                             f'{self.interval}')
        if not 0 < self.min_watermark <= 1.0:
            raise ValueError(f'min_watermark must be in (0, 1], got '
                             f'{self.min_watermark}')
        if not 0 < self.queue_scale_min <= 1.0 \
                or not 0 < self.queue_scale_step < 1.0:
            raise ValueError('queue scale knobs must sit in (0, 1]')
        if self.min_replicas < 1 \
                or self.max_replicas < self.min_replicas:
            raise ValueError(f'need 1 <= min_replicas <= max_replicas, '
                             f'got {self.min_replicas}/'
                             f'{self.max_replicas}')


class Controller:
    """Drive scheduler knobs and the replica count from observed
    signals (see module docstring). Exactly one of ``scheduler`` (a
    single :class:`~distributed_dot_product_tpu.serve.scheduler
    .Scheduler`) or ``router`` (a :class:`~distributed_dot_product_tpu
    .serve.router.Router` over a ReplicaPool — arms autoscaling) is
    given. ``watchdog``: an :class:`~distributed_dot_product_tpu.obs
    .anomaly.AnomalyWatchdog` evaluated each controller tick; in
    scheduler mode the stock catalog is built automatically over the
    scheduler's registry ON THE CONTROLLER'S CLOCK. Call :meth:`tick`
    from the serving loop (``scheduler.on_tick`` / after
    ``router.step``) — it self-throttles to ``cfg.interval``."""

    def __init__(self, *, scheduler=None, router=None, config=None,
                 watchdog=None, clock=time.monotonic, event_log=None,
                 registry=None):
        if (scheduler is None) == (router is None):
            raise ValueError('Controller needs exactly one of '
                             'scheduler= or router=')
        self.scheduler = scheduler
        self.router = router
        self.cfg = config or ControlConfig()
        self.cfg.validate()
        self.clock = clock
        self.event_log = event_log
        if registry is None:
            registry = (scheduler.registry if scheduler is not None
                        else router.registry)
        self.registry = registry
        if watchdog is None and scheduler is not None:
            from distributed_dot_product_tpu.obs.anomaly import (
                AnomalyWatchdog, default_watches,
            )
            watchdog = AnomalyWatchdog(
                scheduler.registry,
                default_watches(queue_limit=scheduler.cfg.queue_limit,
                                paged=scheduler._paged,
                                cooldown=self.cfg.interval),
                event_log=event_log, min_interval=0.0, clock=clock)
        self.watchdog = watchdog
        # Knob state: ONE controller-wide target applied to every
        # scheduler (replicas joining mid-run inherit it), so a knob
        # change is one control.adjust event, not one per replica.
        base = self._schedulers()[0].cfg
        ceiling = (self.cfg.max_watermark
                   if self.cfg.max_watermark is not None
                   else base.degrade_watermark)
        self._watermark_ceiling = ceiling
        self._watermark = min(ceiling, base.degrade_watermark)
        self._queue_base = base.queue_limit
        self._queue_scale = 1.0
        self._last_eval = None
        self._healthy = 0
        self._busy_evals = 0
        self._idle_evals = 0
        self.actions = []       # every action dict, run-lifetime
        self._g_watermark = registry.gauge('control.watermark')
        self._g_watermark.set(self._watermark)
        self._g_replicas = registry.gauge('control.replicas')
        self._g_replicas.set(len(self._schedulers()))
        self._c_adjust = registry.counter('control.adjusts')
        self._c_scale = registry.counter('control.scales')

    # -- plumbing -------------------------------------------------------
    def _schedulers(self):
        if self.scheduler is not None:
            return [self.scheduler]
        return [r.scheduler for r in self.router.pool.replicas]

    def _emit(self, event, **fields):
        log = (self.event_log if self.event_log is not None
               else obs_events.get_active())
        if log is not None:
            log.emit(event, **fields)

    def _record(self, action):
        self.actions.append(action)
        return action

    # -- the evaluation loop --------------------------------------------
    def tick(self, now=None):
        """One control evaluation (self-throttled to ``cfg.interval``
        on the controller clock). Returns the actions taken this
        evaluation as a list of dicts (empty between intervals)."""
        now = self.clock() if now is None else now
        if self._last_eval is not None \
                and now - self._last_eval < self.cfg.interval:
            return []
        self._last_eval = now
        taken = []
        breaches = (self.watchdog.tick(force=True)
                    if self.watchdog is not None else [])
        breach_names = {w.name for w, _ in breaches}
        # Highest pressure across the fleet, WITH its source (queue /
        # page_pool) — the source rides the adjust reason so a
        # post-mortem (obs doctor) can tell pool-driven tightening
        # from queue-driven.
        pressure, source = 0.0, 'queue'
        for sched in self._schedulers():
            p, src = sched._pressure_info()
            if p > pressure:
                pressure, source = p, src
        tighten = bool(breach_names & TIGHTEN_WATCHES) \
            or pressure >= self.cfg.tighten_pressure
        if tighten:
            self._healthy = 0
            reason = ('breach:' + ','.join(
                sorted(breach_names & TIGHTEN_WATCHES))
                if breach_names & TIGHTEN_WATCHES
                else f'pressure:{source}:{pressure:.2f}')
            taken += self._tighten(reason)
        elif pressure <= self.cfg.relax_pressure:
            self._healthy += 1
            if self._healthy >= self.cfg.relax_after:
                self._healthy = 0
                taken += self._relax('sustained_headroom')
        else:
            self._healthy = 0
        if self.router is not None and self.cfg.scale:
            taken += self._maybe_scale()
        return taken

    # -- watermark / queue-bound actuation ------------------------------
    def _apply_knobs(self, scheduler):
        """Push the controller's current targets onto one scheduler
        (every knob change, and every replica the controller adds)."""
        scheduler.set_watermark(self._watermark)
        scheduler.set_queue_limit(
            max(1, round(self._queue_base * self._queue_scale)))

    def _adjust(self, knob, value, previous, reason):
        for sched in self._schedulers():
            self._apply_knobs(sched)
        self._c_adjust.inc()
        self._emit('control.adjust', knob=knob, value=value,
                   reason=reason, previous=previous)
        return self._record({'action': 'adjust', 'knob': knob,
                             'value': value, 'previous': previous,
                             'reason': reason})

    def _tighten(self, reason):
        out = []
        new = max(self.cfg.min_watermark,
                  self._watermark - self.cfg.tighten_step)
        if new != self._watermark:
            prev, self._watermark = self._watermark, new
            self._g_watermark.set(new)
            out.append(self._adjust('degrade_watermark', new, prev,
                                    reason))
        new_scale = max(self.cfg.queue_scale_min,
                        self._queue_scale * self.cfg.queue_scale_step)
        if new_scale != self._queue_scale:
            prev_limit = max(1, round(self._queue_base
                                      * self._queue_scale))
            self._queue_scale = new_scale
            limit = max(1, round(self._queue_base * new_scale))
            if limit != prev_limit:
                out.append(self._adjust('queue_limit', limit,
                                        prev_limit, reason))
        return out

    def _relax(self, reason):
        out = []
        new = min(self._watermark_ceiling,
                  self._watermark + self.cfg.relax_step)
        if new != self._watermark:
            prev, self._watermark = self._watermark, new
            self._g_watermark.set(new)
            out.append(self._adjust('degrade_watermark', new, prev,
                                    reason))
        if self._queue_scale != 1.0:
            prev_limit = max(1, round(self._queue_base
                                      * self._queue_scale))
            self._queue_scale = min(
                1.0, self._queue_scale / self.cfg.queue_scale_step)
            limit = max(1, round(self._queue_base * self._queue_scale))
            if limit != prev_limit:
                out.append(self._adjust('queue_limit', limit,
                                        prev_limit, reason))
        return out

    # -- elastic decode scaling -----------------------------------------
    def _maybe_scale(self):
        pool = self.router.pool
        loads = {r.name: r.load() for r in pool.replicas}
        slots = sum(r.engine.slots for r in pool.replicas)
        queued = sum(ld['queued'] for ld in loads.values())
        busy = sum(ld['busy'] for ld in loads.values())
        backlog = queued / max(1, slots)
        if backlog >= self.cfg.scale_up_backlog:
            self._busy_evals += 1
            self._idle_evals = 0
        elif queued == 0 and busy / max(1, slots) \
                <= self.cfg.scale_down_backlog:
            self._idle_evals += 1
            self._busy_evals = 0
        else:
            self._busy_evals = self._idle_evals = 0
        out = []
        if self._busy_evals >= self.cfg.scale_up_after \
                and len(pool.replicas) < self.cfg.max_replicas:
            self._busy_evals = 0
            replica = self.router.add_replica()
            self._apply_knobs(replica.scheduler)
            n = len(pool.replicas)
            self._g_replicas.set(n)
            self._c_scale.inc()
            reason = f'backlog:{backlog:.2f}'
            self._emit('control.scale', direction='up', replicas=n,
                       reason=reason, target=replica.name)
            out.append(self._record(
                {'action': 'scale', 'direction': 'up', 'replicas': n,
                 'target': replica.name, 'reason': reason}))
        elif self._idle_evals >= self.cfg.scale_down_after \
                and len(pool.replicas) > self.cfg.min_replicas:
            self._idle_evals = 0
            # Drain the least-loaded member (fewest in-flight — the
            # cheapest preempt+requeue bill), newest name on ties so
            # the original r0 is the last to go.
            victim = min(pool.replicas,
                         key=lambda r: (loads[r.name]['queued']
                                        + loads[r.name]['busy'],
                                        -int(r.name.lstrip('r') or 0)))
            requeued = self.router.drain_replica(victim.name)
            n = len(pool.replicas)
            self._g_replicas.set(n)
            self._c_scale.inc()
            self._emit('control.drain', target=victim.name,
                       requeued=requeued)
            reason = 'sustained_idle'
            self._emit('control.scale', direction='down', replicas=n,
                       reason=reason, target=victim.name)
            out.append(self._record(
                {'action': 'scale', 'direction': 'down', 'replicas': n,
                 'target': victim.name, 'requeued': requeued,
                 'reason': reason}))
        return out
