# -*- coding: utf-8 -*-
"""
Watchdog and health surface for the decode serving loop.

A compiled decode step that hangs (wedged runtime, pathological retrace,
dead interconnect) blocks the scheduler thread on the device — the loop
itself can't report that it's stuck. So liveness is judged from OUTSIDE
the loop: the scheduler heartbeats (:meth:`HealthMonitor.beat`) every
tick, and a daemon watchdog thread flips liveness to STALLED when the
last beat ages past ``stall_timeout``. The serving layer's contract:

- **Liveness** (is the loop making progress): ``ALIVE`` ↔ ``STALLED``.
  A stall marks readiness NOT_READY (drain traffic away) and counts a
  ``serve.watchdog_stalls`` event; the NEXT beat recovers liveness and
  the scheduler's own readiness logic re-asserts READY — the soak test
  pins "readiness restored after the stall clears".
- **Readiness** (should a load balancer send traffic): ``STARTING →
  READY`` with ``DEGRADED`` (pressure-capped admissions) and
  ``NOT_READY`` (queue full / stalled) excursions, ``STOPPED`` at
  close. Set by the scheduler; the watchdog only forces NOT_READY.
- Every transition is recorded (state, reason, timestamp) and mirrored
  to gauges in the :mod:`~distributed_dot_product_tpu.utils.tracing`
  registry, next to the scheduler's queue-depth and step-latency
  metrics — one snapshot serves a health endpoint.

The watchdog measures REAL time (``time.monotonic``) independently of
the scheduler's injectable clock: a virtual-clock test must not
self-trigger stalls, and a real stall must fire even when the
scheduler's clock is frozen.
"""

import enum
import threading
import time
from typing import Callable, List, Optional, Tuple

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.utils import tracing

__all__ = ['Liveness', 'Readiness', 'HealthMonitor']


class Liveness(enum.Enum):
    ALIVE = 'alive'
    STALLED = 'stalled'


class Readiness(enum.Enum):
    STARTING = 'starting'
    READY = 'ready'
    DEGRADED = 'degraded'
    NOT_READY = 'not_ready'
    STOPPED = 'stopped'


_READINESS_CODE = {Readiness.STARTING: 0, Readiness.READY: 1,
                   Readiness.DEGRADED: 2, Readiness.NOT_READY: 3,
                   Readiness.STOPPED: 4}


class HealthMonitor:
    """Heartbeat-driven liveness + scheduler-driven readiness.

    Use::

        mon = HealthMonitor(stall_timeout=0.5)
        mon.start()                  # spawns the watchdog daemon thread
        ...
        mon.beat()                   # scheduler, every tick
        mon.set_readiness(Readiness.READY)
        ...
        mon.stop()

    ``on_stall`` (optional) is called from the watchdog thread when a
    stall is detected — keep it cheap and thread-safe.
    """

    def __init__(self, *, stall_timeout=2.0, poll_interval=None,
                 registry: Optional[tracing.MetricsRegistry] = None,
                 on_stall: Optional[Callable] = None, event_log=None):
        if stall_timeout <= 0:
            raise ValueError(f'stall_timeout must be > 0, '
                             f'got {stall_timeout}')
        self.stall_timeout = stall_timeout
        self.poll_interval = poll_interval or min(0.05, stall_timeout / 4)
        self.registry = registry or tracing.get_registry()
        self.on_stall = on_stall
        self.event_log = event_log
        self._lock = threading.Lock()
        self._last_beat: Optional[float] = None  # guarded-by: self._lock
        self._liveness = Liveness.ALIVE          # guarded-by: self._lock
        self._readiness = Readiness.STARTING     # guarded-by: self._lock
        self._transitions: List[Tuple[float, str, str, str]] = []  # guarded-by: self._lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._c_stalls = self.registry.counter('serve.watchdog_stalls')
        self._c_recovered = self.registry.counter(
            'serve.watchdog_recoveries')
        self._g_ready = self.registry.gauge('serve.readiness')
        self._g_live = self.registry.gauge('serve.liveness')
        self._g_live.set(1)

    # -- lifecycle ------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch,
                                        name='serve-watchdog',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5 * self.poll_interval + 1.0)
            self._thread = None
        self.set_readiness(Readiness.STOPPED, 'monitor stopped')

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _emit(self, event, **fields):
        """Transition → the explicit event log, else the active one.
        NEVER called while holding ``self._lock`` (the log does I/O)."""
        log = (self.event_log if self.event_log is not None
               else obs_events.get_active())
        if log is not None:
            log.emit(event, **fields)

    # -- heartbeat / state ---------------------------------------------
    def beat(self):
        """Scheduler tick heartbeat. Recovers liveness after a stall —
        readiness stays NOT_READY until the scheduler re-asserts it
        (the next readiness update), so recovery is an explicit
        transition, not a silent flag flip."""
        recovered = False
        with self._lock:
            self._last_beat = time.monotonic()
            if self._liveness is Liveness.STALLED:
                self._liveness = Liveness.ALIVE
                self._g_live.set(1)
                self._c_recovered.inc()
                self._transitions.append(
                    (self._last_beat, 'liveness', Liveness.ALIVE.value,
                     'heartbeat resumed'))
                recovered = True
        if recovered:
            self._emit('health.liveness', state=Liveness.ALIVE.value,
                       reason='heartbeat resumed')

    def set_readiness(self, state: Readiness, reason=''):
        with self._lock:
            if state is self._readiness:
                return
            self._readiness = state
            self._g_ready.set(_READINESS_CODE[state])
            self._transitions.append(
                (time.monotonic(), 'readiness', state.value, reason))
        self._emit('health.readiness', state=state.value, reason=reason)

    @property
    def liveness(self) -> Liveness:
        with self._lock:
            return self._liveness

    @property
    def readiness(self) -> Readiness:
        with self._lock:
            return self._readiness

    @property
    def transitions(self):
        """``[(monotonic_time, 'liveness'|'readiness', value, reason)]``
        — the audit trail the health tests assert on."""
        with self._lock:
            return list(self._transitions)

    @property
    def stall_events(self):
        return self._c_stalls.value

    def last_beat_age(self):
        with self._lock:
            if self._last_beat is None:
                return None
            return time.monotonic() - self._last_beat

    def snapshot(self):
        """One JSON-able dict for a health endpoint: liveness,
        readiness, beat age, stall counters, and the full metrics
        registry snapshot (queue depth, step latency, ...)."""
        age = self.last_beat_age()
        with self._lock:
            live, ready = self._liveness, self._readiness
            n_trans = len(self._transitions)
        return {
            'liveness': live.value,
            'readiness': ready.value,
            'last_beat_age_s': age,
            'stall_events': self._c_stalls.value,
            'stall_recoveries': self._c_recovered.value,
            'transitions': n_trans,
            'metrics': self.registry.snapshot(),
        }

    # -- watchdog thread ------------------------------------------------
    def _watch(self):
        while not self._stop.wait(self.poll_interval):
            with self._lock:
                beat = self._last_beat
                live = self._liveness
            if beat is None or live is Liveness.STALLED:
                continue
            age = time.monotonic() - beat
            if age <= self.stall_timeout:
                continue
            with self._lock:
                # Re-check under the lock: a beat may have landed.
                if self._last_beat is None or \
                        time.monotonic() - self._last_beat \
                        <= self.stall_timeout:
                    continue
                self._liveness = Liveness.STALLED
                self._g_live.set(0)
                self._c_stalls.inc()
                self._transitions.append(
                    (time.monotonic(), 'liveness', Liveness.STALLED.value,
                     f'no heartbeat for {age:.2f}s '
                     f'(timeout {self.stall_timeout:.2f}s)'))
            self._emit('health.liveness', state=Liveness.STALLED.value,
                       reason=f'no heartbeat for {age:.2f}s')
            self.set_readiness(Readiness.NOT_READY, 'watchdog stall')
            if self.on_stall is not None:
                try:
                    self.on_stall()
                except Exception as e:
                    # A broken callback must not kill the watchdog —
                    # but its failure has to stay observable.
                    tracing.log_exception('health.on_stall_callback', e,
                                          registry=self.registry)
