# -*- coding: utf-8 -*-
"""
Resilient decode serving layer: continuous batching over the per-slot
KV-cache kernels with admission control, backpressure, a watchdog
health surface, and per-slot NaN quarantine.

Composition (each piece standalone-testable):

- :mod:`~distributed_dot_product_tpu.serve.engine` — the compiled
  substrate: greedy decode over ``models/decode.py``'s per-slot cache.
- :mod:`~distributed_dot_product_tpu.serve.admission` — bounded queue,
  typed :class:`RejectedError` shedding, deadlines, token budgets,
  degradation.
- :mod:`~distributed_dot_product_tpu.serve.scheduler` — the
  continuous-batching loop (admit → chunked prefill → batched decode →
  retire) with the evict-before-reject ladder and quarantine/requeue.
- :mod:`~distributed_dot_product_tpu.serve.health` — heartbeat
  watchdog, liveness/readiness transitions, metrics snapshot.
- :mod:`~distributed_dot_product_tpu.serve.loadgen` — seeded open-loop
  traffic generator (Poisson/bursty arrivals, heavy-tailed length
  mixes, tenant shares) driving the scheduler on a virtual clock; the
  measurement substrate for SLO/goodput accounting (obs/slo.py).
- :mod:`~distributed_dot_product_tpu.serve.replica` — disaggregated
  substrate: the sequence-sharded prefill pool (KV computed across the
  mesh, handed off as pool pages) and the data-parallel decode replica
  pool, each replica a Scheduler+KernelEngine with its own log/metrics.
- :mod:`~distributed_dot_product_tpu.serve.router` — the front end:
  admission (typed NO_REPLICA), prefix-cache-aware and session-affine
  placement, prefill→decode handoff orchestration, elastic pool
  membership (add/drain replicas without dropping a stream).
- :mod:`~distributed_dot_product_tpu.serve.policy` — the scheduling
  policy layer: priority classes, per-tenant weighted fair share,
  deadline-aware eviction, TTFT-tuned prefill/decode interleaving.
- :mod:`~distributed_dot_product_tpu.serve.control` — the closed-loop
  controller: watchdog-driven admission-watermark actuation and
  elastic decode autoscaling with drain-by-preempt+requeue, every
  action a closed-vocabulary ``control.*`` event.
"""

from distributed_dot_product_tpu.serve.admission import (  # noqa: F401
    AdmissionController, RejectReason, RejectedError, Request,
    RequestResult,
)
from distributed_dot_product_tpu.serve.control import (  # noqa: F401
    ControlConfig, Controller,
)
from distributed_dot_product_tpu.serve.engine import (  # noqa: F401
    KernelEngine, PageCorruptionError,
)
from distributed_dot_product_tpu.serve.errors import (  # noqa: F401
    ServeContractError, UnknownReplicaError,
)
from distributed_dot_product_tpu.serve.health import (  # noqa: F401
    HealthMonitor, Liveness, Readiness,
)
from distributed_dot_product_tpu.serve.loadgen import (  # noqa: F401
    Arrival, ChaosSchedule, LoadGenConfig, LoadResult, TenantSpec,
    VirtualClock, default_tenants, generate_trace, load_trace,
    run_load, run_trace, save_trace,
)
from distributed_dot_product_tpu.serve.policy import (  # noqa: F401
    PolicyConfig, SchedulingPolicy, TenantPolicy,
)
from distributed_dot_product_tpu.serve.replica import (  # noqa: F401
    DecodeReplica, PrefillPool, ReplicaPool, TopologyConfig,
    maybe_init_distributed, parse_topology,
)
from distributed_dot_product_tpu.serve.router import (  # noqa: F401
    Router, RouterConfig, build_serving,
)
from distributed_dot_product_tpu.serve.scheduler import (  # noqa: F401
    Scheduler, ServeConfig,
)

__all__ = ['AdmissionController', 'RejectReason', 'RejectedError',
           'Request', 'RequestResult', 'KernelEngine', 'HealthMonitor',
           'Liveness', 'Readiness', 'Scheduler', 'ServeConfig',
           'Arrival', 'LoadGenConfig', 'LoadResult', 'TenantSpec',
           'VirtualClock', 'default_tenants', 'generate_trace',
           'run_load', 'run_trace', 'save_trace', 'load_trace',
           'DecodeReplica', 'PrefillPool', 'ReplicaPool',
           'TopologyConfig', 'maybe_init_distributed',
           'parse_topology', 'Router', 'RouterConfig',
           'build_serving', 'PolicyConfig', 'TenantPolicy',
           'SchedulingPolicy', 'ControlConfig', 'Controller',
           'ChaosSchedule', 'PageCorruptionError',
           'ServeContractError', 'UnknownReplicaError']
