# -*- coding: utf-8 -*-
"""
Admission control and backpressure for the decode serving layer.

A serving process dies from its edges, not its kernels: an unbounded
queue OOMs the host, an oversized prompt wedges prefill, and a request
that can never meet its deadline burns decode slots other requests need.
This module owns the request boundary:

- **Bounded queue**: ``queue_limit`` pending requests, hard. Past it the
  scheduler sheds load (after trying eviction — scheduler.py's ladder).
- **Typed rejection**: every shed request raises/records a
  :class:`RejectedError` carrying a :class:`RejectReason` — operators
  alarm on reasons, not on string-matching log lines, and the soak
  invariant "zero dropped-without-reason" becomes checkable.
- **Per-request deadlines**: absolute wall-clock points (injectable
  clock for tests). Checked at submit (don't queue the doomed), while
  queued (don't prefill the expired), and mid-stream (free the slot).
- **Token budgets**: ``max_new_tokens`` clamped to the config cap and
  to the cache capacity ``t_max - len(prompt)``; a prompt that leaves
  no room to generate even one token is PROMPT_TOO_LONG.
- **Graceful degradation**: above ``degrade_watermark`` queue pressure,
  new requests are admitted with a REDUCED token budget
  (``degraded_max_new_tokens``) instead of being rejected — trade
  per-request depth for admission, shed only when that fails.
"""

import collections
import dataclasses
import enum
import itertools
import time
from typing import List, Optional, Tuple

import numpy as np

from distributed_dot_product_tpu.obs import events as obs_events

__all__ = ['RejectReason', 'RejectedError', 'Request', 'RequestResult',
           'AdmissionController']


class RejectReason(enum.Enum):
    """Why a request was shed. The complete taxonomy — a rejection never
    carries free text alone."""
    QUEUE_FULL = 'queue_full'
    DEADLINE_EXCEEDED = 'deadline_exceeded'
    PROMPT_TOO_LONG = 'prompt_too_long'
    # Paged KV pool (scheduler over a cache_mode='paged' engine): the
    # request needs more pool pages than the pool can EVER provide, or
    # mid-stream page exhaustion outlasted its preemption retries.
    CACHE_EXHAUSTED = 'cache_exhausted'
    # The request names a shared prefix that is not (or no longer)
    # registered — at submit, or unregistered while it sat queued.
    PREFIX_UNREGISTERED = 'prefix_unregistered'
    # Disaggregated serving (serve/router.py): no decode replica in the
    # pool can accept the request — every replica's admission queue is
    # at its bound (or the pool is empty). The router-level analog of
    # QUEUE_FULL, shed BEFORE any replica's ladder runs.
    NO_REPLICA = 'no_replica'
    # Disaggregated serving: the decode replica holding this in-flight
    # stream died, and the router could not re-place it — no surviving
    # replica, or the per-request ``max_recoveries`` budget is spent.
    # Terminal: the recovery ledger entry is finalized under this reason.
    REPLICA_LOST = 'replica_lost'
    # KV page integrity: the stream's context touched a pool page that
    # failed checksum verification, and the router could not heal it —
    # recovery budget spent, or no clean replica to replay on. Terminal
    # under the same ledger discipline as REPLICA_LOST; the page(s)
    # stay quarantined.
    KV_CORRUPT = 'kv_corrupt'


class RejectedError(Exception):
    """A request was refused admission (or expired in the queue).
    ``reason`` is always a :class:`RejectReason`."""

    def __init__(self, reason: RejectReason, message: str):
        super().__init__(f'[{reason.value}] {message}')
        self.reason = reason


_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request plus its runtime bookkeeping (owned by the
    scheduler once admitted). ``deadline`` is an ABSOLUTE clock value on
    the scheduler's clock, or None for no deadline."""
    prompt: np.ndarray
    max_new_tokens: int
    deadline: Optional[float] = None
    id: str = ''
    submitted_at: float = 0.0
    # Tenant label for multi-tenant accounting: stamped on every
    # admit/reject event (EVENT_SCHEMA v2) and keyed into the
    # tenant-labeled metrics series, so per-tenant goodput is derivable
    # both live (/metrics) and offline (obs/slo.py).
    tenant: str = 'default'
    # Paged serving: id of a registered shared prefix the prompt
    # CONTINUES (the prompt tokens come after it), and its length —
    # admission budgets against prefix_len + len(prompt).
    prefix_id: Optional[int] = None
    prefix_len: int = 0
    # -- runtime state (scheduler-owned) --------------------------------
    tokens: List[int] = dataclasses.field(default_factory=list)
    requeues: int = 0
    degraded: bool = False
    cancelled: bool = False
    admit_index: Optional[int] = None   # admission order, fault-stable
    # -- timeline anchors (scheduler clock; observability) --------------
    queued_since: Optional[float] = None    # last enqueue time
    admitted_at: Optional[float] = None     # last slot assignment
    first_token_at: Optional[float] = None  # TTFT anchor

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if not self.id:
            self.id = f'req-{next(_ids)}'
        self.tenant = str(self.tenant or 'default')


@dataclasses.dataclass
class RequestResult:
    """Terminal record for one request. ``status`` is one of
    ``'completed' | 'deadline_expired' | 'evicted' | 'abandoned' |
    'failed_nan' | 'rejected'``; ``reason`` is the typed
    :class:`RejectReason` when ``status == 'rejected'`` (else None).
    Partial tokens are kept for every non-completed terminal state —
    an evicted or expired stream still delivers what it produced."""
    id: str
    status: str
    tokens: List[int]
    prompt_len: int
    reason: Optional[RejectReason] = None
    requeues: int = 0
    degraded: bool = False
    finished_at: float = 0.0
    tenant: str = 'default'


class AdmissionController:
    """Bounded admission queue with validation, degradation and typed
    shedding. The scheduler composes this with the slot engine; tests
    drive it standalone with a virtual clock."""

    def __init__(self, *, queue_limit, t_max, max_new_tokens,
                 degrade_watermark=0.75, degraded_max_new_tokens=None,
                 clock=time.monotonic, registry=None, event_log=None,
                 capacity_tokens=None):
        if queue_limit < 1:
            raise ValueError(f'queue_limit must be >= 1, got {queue_limit}')
        self.queue_limit = queue_limit
        self.t_max = t_max
        # Paged pool: most rows ONE request can ever hold (pool pages ×
        # page size, capped by t_max). None = slab (t_max governs).
        self.capacity_tokens = capacity_tokens
        self.max_new_tokens = max_new_tokens
        self.degrade_watermark = degrade_watermark
        self.degraded_max_new_tokens = (degraded_max_new_tokens
                                        or max(1, max_new_tokens // 4))
        self.clock = clock
        self.event_log = event_log
        self._queue = collections.deque()
        self._registry = registry
        if registry is not None:
            self._c_admit = registry.counter('serve.admitted')
            self._c_degraded = registry.counter('serve.degraded')
            self._c_reject = {r: registry.counter(f'serve.rejected.{r.value}')
                              for r in RejectReason}
            self._g_depth = registry.gauge('serve.queue_depth')
        else:
            self._c_admit = self._c_degraded = self._g_depth = None
            self._c_reject = {}

    def _count_tenant(self, name, tenant):
        """Bump the tenant-labeled twin of an admit/reject counter —
        same family name, ``tenant=`` label (the exporter renders both;
        external Prometheus computes per-tenant goodput from the
        labeled series)."""
        if self._registry is not None and tenant is not None:
            self._registry.counter(name, labels={'tenant': tenant}).inc()

    # -- introspection --------------------------------------------------
    @property
    def depth(self):
        return len(self._queue)

    @property
    def full(self):
        return len(self._queue) >= self.queue_limit

    @property
    def pressure(self):
        """Queue fullness in [0, 1] — the degradation ladder's input."""
        return len(self._queue) / self.queue_limit

    def queued_by_tenant(self):
        """``{tenant: queued count}`` over the live queue — the
        policy-relevant placement signal ``Scheduler.load()`` exposes
        (fair-share routing and the controller's per-tenant view)."""
        out: dict = {}
        for req in self._queue:
            out[req.tenant] = out.get(req.tenant, 0) + 1
        return out

    def oldest_deadline(self):
        """Earliest absolute deadline among queued requests, or None
        when nothing queued carries one — how urgent the backlog is."""
        deadlines = [req.deadline for req in self._queue
                     if req.deadline is not None]
        return min(deadlines) if deadlines else None

    def _update_depth(self):
        if self._g_depth is not None:
            self._g_depth.set(len(self._queue))

    def _emit(self, event, **fields):
        log = (self.event_log if self.event_log is not None
               else obs_events.get_active())
        if log is not None:
            log.emit(event, **fields)

    def _reject(self, reason: RejectReason, message: str,
                request_id=None, tenant=None):
        if reason in self._c_reject:
            self._c_reject[reason].inc()
        self._count_tenant(f'serve.rejected.{reason.value}',
                           tenant or 'default')
        if request_id is not None:
            # Submit-time shed: the request's entire recorded lifecycle
            # is this one typed event.
            self._emit('serve.reject', request_id=request_id,
                       reason=reason.value, queued=False,
                       tenant=tenant or 'default')
        raise RejectedError(reason, message)

    def reject(self, reason: RejectReason, message: str,
               request_id=None, tenant=None):
        """Public typed shed: counter + submit-time event + raise —
        for reject conditions the CALLER owns (the scheduler's paged
        checks), so they account exactly like queue/deadline sheds."""
        self._reject(reason, message, request_id=request_id,
                     tenant=tenant)

    def reject_count(self, reason: RejectReason):
        c = self._c_reject.get(reason)
        return c.value if c is not None else 0

    def count_reject(self, reason: RejectReason, tenant=None):
        """Count a scheduler-owned shed that is FINALIZED rather than
        raised (tick-time rejects of already-queued requests): same
        counters as submit-time sheds, no exception — dashboards see
        every typed reject however it was delivered."""
        if reason in self._c_reject:
            self._c_reject[reason].inc()
        self._count_tenant(f'serve.rejected.{reason.value}',
                           tenant or 'default')

    # -- admission ------------------------------------------------------
    def validate(self, request: Request, now=None):
        """Typed-reject anything that can never be served: an expired
        deadline, a prompt leaving no room to generate one token, or —
        paged — a sequence no pool-sized allocation can ever hold.
        Clamps the token budget to the config cap and cache capacity."""
        now = self.clock() if now is None else now
        if request.deadline is not None and request.deadline <= now:
            self._reject(RejectReason.DEADLINE_EXCEEDED,
                         f'request {request.id}: deadline already passed '
                         f'at submit', request_id=request.id,
                         tenant=request.tenant)
        full_len = request.prefix_len + len(request.prompt)
        room = self.t_max - full_len
        if len(request.prompt) < 1 or room < 1:
            self._reject(RejectReason.PROMPT_TOO_LONG,
                         f'request {request.id}: prompt of '
                         f'{full_len} tokens (prefix included) leaves '
                         f'no room to generate in a t_max={self.t_max} '
                         f'cache', request_id=request.id,
                         tenant=request.tenant)
        if self.capacity_tokens is not None \
                and full_len + 1 > self.capacity_tokens:
            # Statically impossible however long it waits: the POOL
            # cannot hold the prompt plus one generated token.
            self._reject(RejectReason.CACHE_EXHAUSTED,
                         f'request {request.id}: {full_len} prompt rows '
                         f'+ 1 exceed the page pool\'s '
                         f'{self.capacity_tokens}-row capacity',
                         request_id=request.id, tenant=request.tenant)
        self.clamp_budget(request)

    def clamp_budget(self, request: Request):
        """Clamp the token budget to the config cap and the cache/pool
        capacity. This is the ONE place the budget policy lives:
        submit-time :meth:`validate` and the scheduler's ``fork`` (which
        places a branch without queueing) both apply it, so a forked
        branch can never hold a slot or commit pool pages past what a
        submitted request could."""
        full_len = request.prefix_len + len(request.prompt)
        room = self.t_max - full_len
        if self.capacity_tokens is not None:
            room = min(room, self.capacity_tokens - full_len)
        request.max_new_tokens = max(1, min(request.max_new_tokens,
                                            self.max_new_tokens, room))

    def count_admit(self, tenant=None):
        """Count an admission that never crossed the queue (the
        scheduler's ``fork`` places the branch straight into a slot):
        same counter as queued admissions, so in-flight accounting over
        admitted − terminal stays balanced when fork is used."""
        if self._c_admit is not None:
            self._c_admit.inc()
        self._count_tenant('serve.admitted', tenant)

    def maybe_degrade(self, request: Request, pressure=None,
                      reason=None):
        """Above the pressure watermark, cap the request's token budget
        instead of rejecting it — rung one of the degradation ladder.
        ``pressure`` overrides the queue-depth default (the scheduler
        passes max(queue, page-pool) pressure on paged engines, so page
        exhaustion degrades before it evicts before it rejects).
        ``reason`` names the pressure source (``queue`` /
        ``page_pool``) on the ``serve.degrade`` event — the rung used
        to engage SILENTLY; now every degraded admission is a
        closed-vocabulary record the timeline and doctor can see."""
        pressure = self.pressure if pressure is None else pressure
        if pressure >= self.degrade_watermark \
                and request.max_new_tokens > self.degraded_max_new_tokens:
            request.max_new_tokens = self.degraded_max_new_tokens
            request.degraded = True
            if self._c_degraded is not None:
                self._c_degraded.inc()
            self._emit('serve.degrade', request_id=request.id,
                       watermark=self.degrade_watermark,
                       reason=reason or 'queue', pressure=pressure,
                       tenant=request.tenant)

    def push(self, request: Request):
        """Enqueue an ADMITTED request; caller has already resolved the
        queue-full ladder (this raises QUEUE_FULL as the last resort)."""
        if self.full:
            self._reject(RejectReason.QUEUE_FULL,
                         f'request {request.id}: queue at limit '
                         f'{self.queue_limit}', request_id=request.id,
                         tenant=request.tenant)
        request.queued_since = self.clock()
        self._queue.append(request)
        if self._c_admit is not None:
            self._c_admit.inc()
        self._count_tenant('serve.admitted', request.tenant)
        self._update_depth()

    def push_front(self, request: Request):
        """Requeue already-admitted work (NaN-quarantine retry) at the
        FRONT, bypassing the bound: admitted work is never dropped by
        capacity — that would convert a fault into a silent loss."""
        request.queued_since = self.clock()
        self._queue.appendleft(request)
        self._update_depth()

    def pop_ready(self, now=None, chooser=None) -> Tuple[
            Optional[Request], List[Request]]:
        """Next serviceable request plus any that expired while queued
        (the caller finalizes those as typed DEADLINE_EXCEEDED
        rejections — queue death is never silent). ``chooser`` is the
        policy hook (serve/policy.py): called with the FULL list of
        live queued requests, it returns the index to admit — the
        whole queue is deadline-swept first, so a policy pick never
        skips past (and thereby hides) an expired request. Without a
        chooser, FIFO semantics are byte-identical to before: only
        the head's expired prefix is swept."""
        now = self.clock() if now is None else now
        expired = []
        if chooser is None:
            while self._queue:
                req = self._queue.popleft()
                if req.cancelled:
                    expired.append(req)   # caller records 'abandoned'
                    continue
                if req.deadline is not None and req.deadline <= now:
                    if RejectReason.DEADLINE_EXCEEDED in self._c_reject:
                        self._c_reject[
                            RejectReason.DEADLINE_EXCEEDED].inc()
                    expired.append(req)
                    continue
                self._update_depth()
                return req, expired
            self._update_depth()
            return None, expired
        live = []
        for req in self._queue:
            if req.cancelled:
                expired.append(req)
            elif req.deadline is not None and req.deadline <= now:
                if RejectReason.DEADLINE_EXCEEDED in self._c_reject:
                    self._c_reject[RejectReason.DEADLINE_EXCEEDED].inc()
                expired.append(req)
            else:
                live.append(req)
        if not live:
            self._queue.clear()
            self._update_depth()
            return None, expired
        picked = live.pop(chooser(live))
        self._queue = collections.deque(live)
        self._update_depth()
        return picked, expired
