# -*- coding: utf-8 -*-
"""
Scheduling policy for the serving loop — the layer that decides WHO is
served when capacity is contested, extending the mechanical
degrade→evict→reject ladder (scheduler.py) with intent:

- **Priority classes + per-tenant fair share** (:meth:`SchedulingPolicy
  .select`): when free slots pull from the admission queue, the next
  request comes from the highest-priority class present; within a
  class, from the tenant holding the smallest weighted share of slots
  (held / weight — the classic weighted-fair-queueing argmin over the
  live slot table); within a tenant, FIFO. A burst from one tenant can
  no longer starve another of its share, and a carpool-lane tenant
  (higher ``priority``) always boards first.
- **Deadline-aware eviction** (:meth:`SchedulingPolicy
  .eviction_victim`): when the ladder must evict (queue full, page
  deficit), predict each running request's finish time from its
  remaining token budget and the LIVE inter-token-gap percentile, and
  evict one that will miss its deadline anyway — a stream that was
  already lost, instead of the longest-idle one that might still be
  delivered in-SLO. Falls back to longest-idle when nobody is
  provably doomed (the mechanical rung is unchanged as rung two).
- **Chunked-prefill/decode interleaving tuned against the measured
  TTFT histogram** (:meth:`SchedulingPolicy.prefill_chunks`): the
  scheduler normally appends ONE prompt chunk per slot per tick; when
  the live TTFT p99 runs past ``target_ttft``, prefilling slots get up
  to ``max_prefill_boost`` chunks per tick — prompts reach their first
  token sooner at a bounded cost to inter-token gaps, and the boost
  collapses back to 1 the moment TTFT recovers.

Everything here is a pure function of the injected inputs (the queue,
the slot table, the clock reading, histogram percentiles) — no wall
clock, no host randomness — so a policy-scheduled run replays
bit-identically under the loadgen's virtual clock, and the CI goodput
gate grades policy changes deterministically.
"""

import dataclasses
import math
from typing import Dict, Optional

__all__ = ['TenantPolicy', 'PolicyConfig', 'SchedulingPolicy']

# determlint: selection and eviction run inside the scheduler tick —
# they derive everything from the injected clock/queue/histograms.
GRAPHLINT_TICK_ROOTS = ('SchedulingPolicy.select',
                        'SchedulingPolicy.eviction_victim',
                        'SchedulingPolicy.prefill_chunks')


@dataclasses.dataclass
class TenantPolicy:
    """One tenant's service class. ``priority``: strict class — higher
    admits first, whatever the shares say. ``weight``: fair-share
    weight within a priority class (a weight-2 tenant is entitled to
    twice the slots of a weight-1 tenant under contention)."""
    priority: int = 0
    weight: float = 1.0

    def validate(self, name):
        if not self.weight > 0:
            raise ValueError(f'tenant {name!r}: weight must be > 0, '
                             f'got {self.weight}')


@dataclasses.dataclass
class PolicyConfig:
    """Knobs of the policy layer. ``tenants`` maps tenant name →
    :class:`TenantPolicy`; unnamed tenants get ``default``.
    ``fair_share=False`` keeps FIFO admission (priority classes and
    eviction/interleaving still apply). ``deadline_eviction=False``
    keeps the mechanical longest-idle rung. ``target_ttft`` (seconds,
    scheduler clock) arms the prefill-interleave boost; None disables
    it. ``gap_percentile`` picks which live gap percentile predicts a
    stream's pace (p50 = typical; p99 = conservative)."""
    tenants: Dict[str, TenantPolicy] = dataclasses.field(
        default_factory=dict)
    default: TenantPolicy = dataclasses.field(
        default_factory=TenantPolicy)
    fair_share: bool = True
    deadline_eviction: bool = True
    target_ttft: Optional[float] = None
    max_prefill_boost: int = 4
    gap_percentile: int = 50

    def validate(self):
        for name, t in self.tenants.items():
            t.validate(name)
        self.default.validate('default')
        if self.max_prefill_boost < 1:
            raise ValueError(f'max_prefill_boost must be >= 1, got '
                             f'{self.max_prefill_boost}')
        if not 0 < self.gap_percentile <= 100:
            raise ValueError(f'gap_percentile must be in (0, 100], got '
                             f'{self.gap_percentile}')


class SchedulingPolicy:
    """The policy engine the scheduler consults (see module
    docstring). Stateless between calls — every decision is recomputed
    from the live inputs, so there is no drift to reconcile after
    preemptions, drains or controller knob changes."""

    def __init__(self, config: Optional[PolicyConfig] = None):
        self.cfg = config or PolicyConfig()
        self.cfg.validate()

    def tenant(self, name) -> TenantPolicy:
        return self.cfg.tenants.get(name, self.cfg.default)

    # -- fair-share admission -------------------------------------------
    def select(self, queued, held_by_tenant) -> int:
        """Index into ``queued`` (live, deadline-checked Requests in
        FIFO order) of the next request to admit. ``held_by_tenant``
        maps tenant → slots currently held (the scheduler's live slot
        table). Strict priority first; then the smallest weighted
        share ``held / weight``; then FIFO."""
        if not queued:
            raise ValueError('select() needs a non-empty queue')
        if not self.cfg.fair_share and not self.cfg.tenants:
            return 0

        def key(i):
            req = queued[i]
            pol = self.tenant(req.tenant)
            share = (held_by_tenant.get(req.tenant, 0) / pol.weight
                     if self.cfg.fair_share else 0.0)
            return (-pol.priority, share, i)

        return min(range(len(queued)), key=key)

    # -- deadline-aware eviction ----------------------------------------
    def predicted_finish(self, now, produced, max_new_tokens,
                         gap_estimate):
        """When the stream's LAST token lands, predicted from the
        remaining budget at the live pace."""
        remaining = max(0, max_new_tokens - produced)
        return now + remaining * max(0.0, gap_estimate)

    def eviction_victim(self, candidates, now, gap_estimate):
        """Among ``candidates`` — ``(slot, request, produced)`` tuples
        for busy slots — the one whose request is predicted to miss
        its deadline anyway (largest predicted overshoot wins: the
        most-lost stream frees capacity for streams still in SLO), or
        None when nobody is provably doomed (caller falls back to
        longest-idle). A finite gap estimate is required to call a
        stream doomed — with no pace signal yet, predicting a miss
        would evict on a guess."""
        if not self.cfg.deadline_eviction or not candidates \
                or not math.isfinite(gap_estimate):
            return None
        doomed = []
        for slot, req, produced in candidates:
            if req.deadline is None:
                continue
            finish = self.predicted_finish(now, produced,
                                           req.max_new_tokens,
                                           gap_estimate)
            if finish > req.deadline:
                doomed.append((finish - req.deadline, slot))
        if not doomed:
            return None
        return max(doomed, key=lambda ds: (ds[0], ds[1].index))[1]

    # -- prefill/decode interleaving ------------------------------------
    def prefill_chunks(self, ttft_p99) -> int:
        """Prompt chunks each prefilling slot may append this tick: 1
        normally; scaled up toward ``max_prefill_boost`` as the live
        TTFT p99 runs past ``target_ttft`` (2x the target saturates
        the boost). NaN p99 (no TTFT observed yet) stays at 1."""
        target = self.cfg.target_ttft
        if target is None or ttft_p99 is None \
                or not math.isfinite(ttft_p99) or ttft_p99 <= target:
            return 1
        frac = min(1.0, (ttft_p99 - target) / target)
        return 1 + int(round(frac * (self.cfg.max_prefill_boost - 1)))
